"""Bucket-aligned sort-merge equi-join on device.

The read-side hot path: the analog of Spark's SortMergeJoinExec running
WITHOUT a ShuffleExchange on bucketed relations — the entire value
proposition of the reference's JoinIndexRule
(index/rules/JoinIndexRule.scala:38-52,124-153). Design:

- both sides arrive as [B, L] bucket-major padded arrays whose key lanes
  are integer codes (int32 where ranks fit — TPU-native — else int64)
  from a shared, order-preserving factorization (the executor guarantees
  this); pads carry the dtype's max value as the sentinel;
- per bucket, the join is the classic sorted expansion: for each left row,
  `searchsorted(right, key, left/right)` bounds its match run — XLA compiles
  this to a fused vectorized binary search, the TPU-friendly formulation of
  the data-dependent merge advance (SURVEY.md §7 "hardest parts" #1);
- match-count phase and expansion phase are separate jits: the host reads
  the total, rounds the output capacity up to a power of two (bounding
  recompiles), and the expansion emits (left row, right row) index pairs;
- `vmap` runs every bucket in parallel in ONE compiled kernel; because
  bucket(key) is a pure function of the key, per-bucket joins concatenated
  are exactly the global join — zero collectives, matching the reference's
  zero-exchange SMJ;
- **distributed**: with a mesh, the bucket dimension is sharded under
  `shard_map` — device d owns the same contiguous bucket range the build
  gave it, counts/expands/compacts its buckets locally, and NO collective
  ever runs (the analog of the reference's cluster-parallel zero-exchange
  SMJ across Spark executors, JoinIndexRule.scala:124-153).

Invariants assumed by these kernels (the plan validator,
analysis/validator.py, rejects plans that cannot satisfy them — e.g.
join sides bucketed with mismatched counts or hash dtype domains never
reach the aligned path):
- key codes are non-decreasing within each bucket on BOTH sides;
- pads carry the key dtype's max value (sentinel_for), strictly above
  every real code;
- both sides' codes come from ONE shared order-preserving factorization,
  so equal codes mean equal key values.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from hyperspace_tpu.compat import jit, shard_map

SENTINEL = np.iinfo(np.int64).max


def sentinel_for(dtype) -> int:
    """Pad value that sorts after every real key code of `dtype`."""
    return np.iinfo(np.dtype(dtype)).max


def _sort_bucket(keys: jnp.ndarray) -> jnp.ndarray:
    return jnp.sort(keys)


def _count_one(lk, rk):
    """Match-count phase for one sorted bucket (shared by the single-device
    and bucket-sharded kernels)."""
    start = jnp.searchsorted(rk, lk, side="left").astype(jnp.int32)
    end = jnp.searchsorted(rk, lk, side="right").astype(jnp.int32)
    real = lk < jnp.iinfo(lk.dtype).max  # dtype's own sentinel
    cnt = jnp.where(real, end - start, 0)
    cum = jnp.cumsum(cnt).astype(jnp.int32)
    return start, cum, cum[-1] if cum.shape[0] else jnp.int32(0)


@jit
def join_counts(lkeys: jnp.ndarray, rkeys: jnp.ndarray):
    """Per-bucket match counts. lkeys/rkeys: [B, L]/[B, R] sorted integer
    codes padded with their dtype's max (sentinel_for). Returns
    (start [B,L], cum [B,L], totals [B])."""
    return jax.vmap(_count_one)(lkeys, rkeys)


@functools.partial(jit, static_argnames=("cap",))
def join_expand(start: jnp.ndarray, cum: jnp.ndarray, totals: jnp.ndarray, cap: int):
    """Emit (li, ri, valid) of shape [B, cap] from the count phase."""

    def one(st, cm, total):
        t = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.searchsorted(cm, t, side="right").astype(jnp.int32)
        li_c = jnp.minimum(li, cm.shape[0] - 1)
        prev = jnp.where(li_c > 0, cm[jnp.maximum(li_c - 1, 0)], 0)
        within = t - prev
        ri = st[li_c] + within
        valid = t < total
        return li_c, ri, valid

    return jax.vmap(one)(start, cum, totals)


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


def pack_shift(l_len: int, r_len: int) -> int | None:
    """Bits for the right index when an (li, ri) pair fits one uint32
    (asymmetric split: ceil(log2 L) + ceil(log2 R) ≤ 32), else None."""
    bits_l = max(int(l_len - 1).bit_length(), 1)
    bits_r = max(int(r_len - 1).bit_length(), 1)
    if bits_l + bits_r <= 32:
        return bits_r
    return None


@functools.partial(jit, static_argnames=("m_pad", "shift"))
def _compact_pairs(li, ri, totals, m_pad: int, shift: int | None):
    """[B, cap] padded match pairs → dense bucket-major [m_pad] arrays.

    Output position p belongs to bucket b with offs[b] <= p < offs[b+1]
    (valid entries of a bucket are exactly its first totals[b] slots).
    Runs on device so the host downloads ONLY real matches — on tunneled
    TPUs device→host bandwidth dominates the whole join otherwise. With
    `shift` set (the two sides' index bits fit 32 together) the pair
    downloads as ONE uint32 per match, halving the transfer again."""
    num_b, cap = li.shape
    offs = jnp.concatenate(
        [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(totals).astype(jnp.int32)]
    )
    p = jnp.arange(m_pad, dtype=jnp.int32)
    b = jnp.clip(jnp.searchsorted(offs, p, side="right").astype(jnp.int32) - 1, 0, num_b - 1)
    t = jnp.clip(p - offs[b], 0, cap - 1)
    lf, rf = li[b, t], ri[b, t]
    if shift is not None:
        return (lf.astype(jnp.uint32) << shift) | rf.astype(jnp.uint32)
    return lf, rf


def _unpack_pairs(packed: np.ndarray, shift: int):
    return (
        (packed >> shift).astype(np.int32),
        (packed & np.uint32((1 << shift) - 1)).astype(np.int32),
    )


def _rank_codes_to_int32(lkeys_np: np.ndarray, rkeys_np: np.ndarray):
    """Order-preserving re-rank of 64-bit key codes into int32 (device
    lanes stay 32-bit native; the process-wide x64 flag is never touched).
    The 64-bit sentinel maps to the int32 sentinel."""
    # Each side's pads carry ITS dtype's max — mark them before the merge
    # (mixed int32/int64 inputs have different sentinels).
    is_pad = np.concatenate([
        (lkeys_np == sentinel_for(lkeys_np.dtype)).reshape(-1),
        (rkeys_np == sentinel_for(rkeys_np.dtype)).reshape(-1),
    ])
    allv = np.concatenate([
        lkeys_np.reshape(-1).astype(np.int64),
        rkeys_np.reshape(-1).astype(np.int64),
    ])
    uniq, inv = np.unique(allv, return_inverse=True)
    if len(uniq) >= np.iinfo(np.int32).max:
        raise ValueError(f"{len(uniq)} distinct join keys exceed the int32 code space")
    codes = inv.astype(np.int32)
    codes[is_pad] = sentinel_for(np.int32)
    nl = lkeys_np.size
    return codes[:nl].reshape(lkeys_np.shape), codes[nl:].reshape(rkeys_np.shape)


@functools.partial(jit, static_argnames=("cap", "m_pad", "shift"))
def _fused_join(lk, rk, cap: int, m_pad: int, shift: int | None):
    """count → expand → compact in ONE program with speculative static
    capacities, plus an overflow flag. One dispatch, one readback."""
    start, cum, totals = join_counts(lk, rk)
    overflow = (jnp.max(totals) > cap) | (jnp.sum(totals) > m_pad)
    li, ri, _valid = join_expand(start, cum, totals, cap)
    if shift is not None:
        out = _compact_pairs(li, ri, totals, m_pad, shift)
        return out, None, totals, overflow
    lf, rf = _compact_pairs(li, ri, totals, m_pad, None)
    return lf, rf, totals, overflow


# Speculative (cap, m_pad) per key-array shape: repeated queries over the
# same index sync ONCE instead of twice (each device_get round-trip costs
# ~0.3-1s of latency on tunneled TPUs). Bounded + lock-guarded: one entry
# per distinct shape accrues for the process lifetime otherwise, and
# concurrent executors share it.
import threading

_cap_cache: dict[tuple, tuple[int, int]] = {}
_cap_lock = threading.Lock()
_CAP_CACHE_MAX = 256


def _cap_get(key):
    with _cap_lock:
        return _cap_cache.get(key)


def _cap_set(key, value) -> None:
    with _cap_lock:
        if key in _cap_cache:
            _cap_cache.pop(key)
        elif len(_cap_cache) >= _CAP_CACHE_MAX:
            _cap_cache.pop(next(iter(_cap_cache)))  # oldest insertion
        _cap_cache[key] = value


def merge_join(lkeys_np: np.ndarray, rkeys_np: np.ndarray):
    """Host wrapper. lkeys_np/rkeys_np: [B, L]/[B, R] sorted int32/int64
    code arrays padded with their dtype's max (sentinel_for). Returns
    (li_flat, ri_flat, totals): bucket-major dense local row indices —
    bucket b's matches occupy [cumsum(totals)[b-1], cumsum(totals)[b])."""
    from hyperspace_tpu.execution.device_cache import device_put_cached

    if lkeys_np.dtype.itemsize > 4 or rkeys_np.dtype.itemsize > 4:
        lkeys_np, rkeys_np = _rank_codes_to_int32(lkeys_np, rkeys_np)
    # Stable (frozen index-derived) key arrays serve from the HBM cache
    # on repeat queries — the [B, L] upload happens once per version.
    lk = device_put_cached(lkeys_np)
    rk = device_put_cached(rkeys_np)
    shift = pack_shift(lkeys_np.shape[1], rkeys_np.shape[1])
    shape_key = (lkeys_np.shape, rkeys_np.shape, str(lkeys_np.dtype))

    guess = _cap_get(shape_key)
    if guess is not None:
        cap, m_pad = guess
        a, b, totals, overflow = _fused_join(lk, rk, cap, m_pad, shift)
        if shift is not None:
            packed, totals_h, ov = jax.device_get((a, totals, overflow))
            if not bool(ov):
                total = int(np.asarray(totals_h).sum())
                li_flat, ri_flat = _unpack_pairs(np.asarray(packed)[:total], shift)
                return li_flat, ri_flat, np.asarray(totals_h)
        else:
            lf, rf, totals_h, ov = jax.device_get((a, b, totals, overflow))
            if not bool(ov):
                total = int(np.asarray(totals_h).sum())
                return (
                    np.asarray(lf)[:total],
                    np.asarray(rf)[:total],
                    np.asarray(totals_h),
                )

    # Exact two-phase path (first run for this shape, or guess overflowed).
    start, cum, totals = join_counts(lk, rk)
    totals_h = np.asarray(jax.device_get(totals))
    cap = next_pow2(int(totals_h.max()) if totals_h.size else 1)
    li, ri, _valid = join_expand(start, cum, totals, cap)
    total = int(totals_h.sum())
    m_pad = next_pow2(max(total, 1))
    _cap_set(shape_key, (cap, m_pad))
    if shift is not None:
        packed = np.asarray(jax.device_get(_compact_pairs(li, ri, totals, m_pad, shift)))[:total]
        li_flat, ri_flat = _unpack_pairs(packed, shift)
        return li_flat, ri_flat, totals_h
    li_flat, ri_flat = _compact_pairs(li, ri, totals, m_pad, None)
    return (
        np.asarray(jax.device_get(li_flat))[:total],
        np.asarray(jax.device_get(ri_flat))[:total],
        totals_h,
    )


# -- distributed (bucket-sharded) path ---------------------------------------

def _count_local(lk, rk):
    """Per-bucket counts for one device's bucket range [b_loc, L]/[b_loc, R]."""
    return jax.vmap(_count_one)(lk, rk)


@functools.lru_cache(maxsize=64)
def _make_sharded_count(mesh: Mesh, axes: tuple):
    spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec), out_specs=spec, check_vma=False
    )
    def fn(lk, rk):
        _, _, totals = _count_local(lk, rk)
        return totals

    return jit(fn, key="ops.join.sharded_count")


@functools.lru_cache(maxsize=64)
def _make_sharded_emit(mesh: Mesh, axes: tuple, cap: int, out_cap: int, shift: int | None):
    """Count + expand + compact, all bucket-local per device. Each device
    emits a dense [out_cap] bucket-major segment of its own matches — the
    concatenated segments are the global bucket-major match list. Zero
    collectives anywhere."""
    spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec), out_specs=(spec, spec), check_vma=False
    )
    def fn(lk, rk):
        start, cum, totals = _count_local(lk, rk)
        li, ri, _valid = join_expand(start, cum, totals, cap)
        b_loc = totals.shape[0]
        offs = jnp.concatenate(
            [jnp.zeros(1, dtype=jnp.int32), jnp.cumsum(totals).astype(jnp.int32)]
        )
        p = jnp.arange(out_cap, dtype=jnp.int32)
        b = jnp.clip(jnp.searchsorted(offs, p, side="right").astype(jnp.int32) - 1, 0, b_loc - 1)
        t = jnp.clip(p - offs[b], 0, cap - 1)
        lf, rf = li[b, t], ri[b, t]
        if shift is not None:
            return ((lf.astype(jnp.uint32) << shift) | rf.astype(jnp.uint32)), totals
        # Unpacked: stack into one [2, out_cap]-style pair via int64-free
        # encoding — emit two rows packed along dim 0 is not possible with
        # one spec'd output, so interleave (even = left, odd = right).
        inter = jnp.stack([lf, rf], axis=1).reshape(-1)  # [2*out_cap]
        return inter, totals

    return jit(fn, key="ops.join.sharded_emit")


def merge_join_sharded(lkeys_np: np.ndarray, rkeys_np: np.ndarray, mesh: Mesh):
    """Distributed merge_join: bucket dim sharded over `mesh` (device d owns
    a contiguous bucket range), zero collectives. Same contract as
    merge_join. The caller guarantees B % mesh_size == 0."""
    from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

    from hyperspace_tpu.execution.device_cache import device_put_cached

    if lkeys_np.dtype.itemsize > 4 or rkeys_np.dtype.itemsize > 4:
        lkeys_np, rkeys_np = _rank_codes_to_int32(lkeys_np, rkeys_np)
    d = mesh_size(mesh)
    num_b = lkeys_np.shape[0]
    if d == 1 or num_b % d != 0:
        return merge_join(lkeys_np, rkeys_np)
    axes = mesh_axes(mesh)
    lk = device_put_cached(lkeys_np)
    rk = device_put_cached(rkeys_np)

    totals = _make_sharded_count(mesh, axes)(lk, rk)
    totals_h = np.asarray(jax.device_get(totals))
    cap = next_pow2(int(totals_h.max()) if totals_h.size else 1)
    seg = totals_h.reshape(d, num_b // d).sum(axis=1)  # per-device match counts
    out_cap = next_pow2(int(seg.max()) if seg.size else 1)
    shift = pack_shift(lkeys_np.shape[1], rkeys_np.shape[1])

    out, _totals2 = _make_sharded_emit(mesh, axes, cap, out_cap, shift)(lk, rk)
    out_h = np.asarray(jax.device_get(out))
    if shift is not None:
        segs = [out_h[i * out_cap : i * out_cap + int(seg[i])] for i in range(d)]
        packed = np.concatenate(segs) if segs else out_h[:0]
        li_flat, ri_flat = _unpack_pairs(packed, shift)
        return li_flat, ri_flat, totals_h
    stride = 2 * out_cap
    li_parts, ri_parts = [], []
    for i in range(d):
        segment = out_h[i * stride : (i + 1) * stride].reshape(out_cap, 2)
        li_parts.append(segment[: int(seg[i]), 0])
        ri_parts.append(segment[: int(seg[i]), 1])
    return (
        np.concatenate(li_parts).astype(np.int32),
        np.concatenate(ri_parts).astype(np.int32),
        totals_h,
    )
