"""Bucket-aligned sort-merge equi-join on device.

The read-side hot path: the analog of Spark's SortMergeJoinExec running
WITHOUT a ShuffleExchange on bucketed relations — the entire value
proposition of the reference's JoinIndexRule
(index/rules/JoinIndexRule.scala:38-52,124-153). Design:

- both sides arrive as [B, L] bucket-major padded arrays whose key lanes
  are int64 codes from a shared, order-preserving factorization (the
  executor guarantees this); pads carry the int64 max sentinel;
- per bucket, the join is the classic sorted expansion: for each left row,
  `searchsorted(right, key, left/right)` bounds its match run — XLA compiles
  this to a fused vectorized binary search, the TPU-friendly formulation of
  the data-dependent merge advance (SURVEY.md §7 "hardest parts" #1);
- match-count phase and expansion phase are separate jits: the host reads
  the total, rounds the output capacity up to a power of two (bounding
  recompiles), and the expansion emits (left row, right row) index pairs;
- `vmap` runs every bucket in parallel in ONE compiled kernel; because
  bucket(key) is a pure function of the key, per-bucket joins concatenated
  are exactly the global join — zero collectives, matching the reference's
  zero-exchange SMJ.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

SENTINEL = np.iinfo(np.int64).max


def _sort_bucket(keys: jnp.ndarray) -> jnp.ndarray:
    return jnp.sort(keys)


@jax.jit
def join_counts(lkeys: jnp.ndarray, rkeys: jnp.ndarray):
    """Per-bucket match counts. lkeys/rkeys: [B, L]/[B, R] sorted int64
    with SENTINEL pads. Returns (start [B,L], cum [B,L], totals [B])."""

    def one(lk, rk):
        start = jnp.searchsorted(rk, lk, side="left").astype(jnp.int32)
        end = jnp.searchsorted(rk, lk, side="right").astype(jnp.int32)
        real = lk < SENTINEL
        cnt = jnp.where(real, end - start, 0)
        cum = jnp.cumsum(cnt).astype(jnp.int32)
        return start, cum, cum[-1] if cum.shape[0] else jnp.int32(0)

    return jax.vmap(one)(lkeys, rkeys)


@functools.partial(jax.jit, static_argnames=("cap",))
def join_expand(start: jnp.ndarray, cum: jnp.ndarray, totals: jnp.ndarray, cap: int):
    """Emit (li, ri, valid) of shape [B, cap] from the count phase."""

    def one(st, cm, total):
        t = jnp.arange(cap, dtype=jnp.int32)
        li = jnp.searchsorted(cm, t, side="right").astype(jnp.int32)
        li_c = jnp.minimum(li, cm.shape[0] - 1)
        prev = jnp.where(li_c > 0, cm[jnp.maximum(li_c - 1, 0)], 0)
        within = t - prev
        ri = st[li_c] + within
        valid = t < total
        return li_c, ri, valid

    return jax.vmap(one)(start, cum, totals)


def next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (int(n - 1).bit_length())


def merge_join(lkeys_np: np.ndarray, rkeys_np: np.ndarray):
    """Host wrapper. lkeys_np/rkeys_np: [B, L]/[B, R] sorted int64 code
    arrays with SENTINEL pads. Returns (li, ri, valid) numpy arrays of
    shape [B, cap]."""
    from hyperspace_tpu.parallel.mesh import ensure_x64

    # int64 codes (SENTINEL = int64 max) silently truncate under default
    # 32-bit mode — x64 must be on before the first upload.
    ensure_x64()
    lk = jnp.asarray(lkeys_np)
    rk = jnp.asarray(rkeys_np)
    start, cum, totals = join_counts(lk, rk)
    totals_h = np.asarray(jax.device_get(totals))
    cap = next_pow2(int(totals_h.max()) if totals_h.size else 1)
    li, ri, valid = join_expand(start, cum, totals, cap)
    return (
        np.asarray(jax.device_get(li)),
        np.asarray(jax.device_get(ri)),
        np.asarray(jax.device_get(valid)),
    )
