"""Distributed hash-bucketize: the build-time shuffle, TPU-native.

This is the framework's equivalent of Spark's ShuffleExchangeExec + Netty
block transfer (reference hot path: `repartition(numBuckets, indexedCols)`
at actions/CreateActionBase.scala:110-112). Design per SURVEY.md §2.3:

- the mesh axis ("x") spans the devices; device d owns the contiguous
  bucket range [d*B/D, (d+1)*B/D) for B buckets over D devices;
- each device sorts its local rows by destination device, scatters them
  into a padded [D, C] send buffer, and ONE `lax.all_to_all` over ICI moves
  every row to its owner — no Netty, no host round-trip;
- a per-(src,dst) capacity C bounds the padded transfer; overflow is
  detected on device and reported back so the host can retry with a larger
  capacity factor (skew mitigation, SURVEY.md §7 step 3);
- after the exchange each device lex-sorts its received rows by
  (bucket, key columns) — giving bucket-grouped, key-sorted shards ready
  for per-bucket persistence.

Rows are carried as a stack of int32/uint32/float32-compatible columns; the
caller is responsible for representing every column as a jax-compatible
array (ColumnTable guarantees this).

Invariants (enforced statically where possible — analysis/validator.py
checks bucket specs at plan level; analysis/lint.py keeps the jax import
surface on compat.py):
- num_buckets is a positive multiple of the mesh size (checked here);
- bucket ids are a pure function of the key VALUES under the canonical
  row hash, so per-device bucket ranges partition the key space;
- invalid rows carry the 2^30 sentinel bucket and sink to shard tails.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from hyperspace_tpu.compat import jit, shard_map

AXIS = "x"


def _exchange_one_device(
    cols: list,
    bucket: jnp.ndarray,
    valid: jnp.ndarray,
    num_devices: int,
    buckets_per_device: int,
    capacity: int,
    num_key_cols: int,
    axes=(AXIS,),
):
    """Per-device body run under shard_map. `cols` are the local columns
    [R, ...] (first `num_key_cols` are sort keys, rest payloads); `bucket`
    the per-row bucket id; `valid` marks real rows. Returns
    (recv_cols, recv_bucket, recv_valid, overflowed) with received rows
    lex-sorted by (bucket, key cols) — the exchange AND the local sort run
    in one fused device program."""
    r = bucket.shape[0]
    dest = jnp.where(valid, bucket // buckets_per_device, num_devices)  # invalid → sentinel D

    # Stable sort rows by dest so each destination's rows are contiguous.
    order = lax.sort((dest.astype(jnp.int32), jnp.arange(r, dtype=jnp.int32)), num_keys=1, is_stable=True)[1]
    dest_sorted = dest[order]
    bucket_sorted = bucket[order]

    # Per-destination group extents.
    counts = jnp.bincount(dest_sorted, length=num_devices + 1)
    offsets = jnp.concatenate([jnp.zeros(1, jnp.int32), jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    overflowed = jnp.max(counts[:num_devices]) > capacity

    # Build the [D, C] send buffer by GATHER (TPU-friendly; scatters
    # serialize): slot (d, c) reads sorted row offsets[d] + c when real.
    slot_dst = jnp.repeat(jnp.arange(num_devices, dtype=jnp.int32), capacity)
    slot_within = jnp.tile(jnp.arange(capacity, dtype=jnp.int32), num_devices)
    slot_ok = slot_within < counts[slot_dst]
    src = jnp.where(slot_ok, offsets[slot_dst] + slot_within, 0)

    def fill_slots(col_sorted, fill):
        """Gather per-ROW values into the [D, C] slot layout."""
        vals = jnp.where(slot_ok, col_sorted[src], fill)
        return vals.reshape(num_devices, capacity)

    send_valid = slot_ok.astype(jnp.int32).reshape(num_devices, capacity)
    send_bucket = fill_slots(bucket_sorted, -1)
    send_cols = [fill_slots(c[order], 0) for c in cols]

    # THE exchange: one all_to_all over the mesh axes (ICI within a
    # slice; ICI+DCN on a multi-slice mesh).
    recv_valid = lax.all_to_all(send_valid, axes, 0, 0, tiled=True)
    recv_bucket = lax.all_to_all(send_bucket, axes, 0, 0, tiled=True)
    recv_cols = [lax.all_to_all(c, axes, 0, 0, tiled=True) for c in send_cols]

    # Flatten [D, C] → [D*C]; invalid rows get the sentinel bucket so they
    # sink to the end, then ONE stable lex-sort by (bucket, key cols).
    rv = recv_valid.reshape(-1)
    rb = jnp.where(rv > 0, recv_bucket.reshape(-1), jnp.int32(2**30))
    rc = [c.reshape(-1) for c in recv_cols]
    sorted_arrays = lax.sort((rb, *rc, rv), num_keys=1 + num_key_cols, is_stable=True)
    rb = sorted_arrays[0]
    rc = list(sorted_arrays[1:-1])
    rv = sorted_arrays[-1]
    return rc, rb, rv, overflowed


@functools.lru_cache(maxsize=64)
def make_bucketize_fn(
    mesh: Mesh,
    num_cols: int,
    num_buckets: int,
    capacity: int,
    num_key_cols: int,
):
    """Build the jitted shard_map'd exchange+sort for a fixed column layout.

    Works on a 1-D ("x") or 2-D ("dcn", "x") mesh: the exchange runs over
    the COMBINED axes, so on a multi-slice mesh XLA routes the
    within-slice portion over ICI and the cross-slice portion over DCN.
    Device order (and therefore contiguous bucket ownership) follows the
    flattened mesh order."""
    from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

    axes = mesh_axes(mesh)
    num_devices = mesh_size(mesh)
    if num_buckets % num_devices != 0:
        raise ValueError(f"num_buckets {num_buckets} must be a multiple of mesh size {num_devices}")
    buckets_per_device = num_buckets // num_devices
    spec = P(axes)  # dim 0 sharded over the combined mesh axes

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(tuple(spec for _ in range(num_cols)), spec, spec),
        out_specs=(tuple(spec for _ in range(num_cols)), spec, spec, P()),
        check_vma=False,
    )
    def fn(cols, bucket, valid):
        rc, rb, rv, overflow = _exchange_one_device(
            list(cols), bucket, valid, num_devices, buckets_per_device, capacity,
            num_key_cols, axes,
        )
        # overflow is a per-device scalar; reduce with OR (max) across mesh.
        overflow = lax.pmax(overflow.astype(jnp.int32), axes)
        return tuple(rc), rb, rv, overflow[None] if overflow.ndim == 0 else overflow

    return jit(fn, key="ops.bucketize.exchange")


@functools.lru_cache(maxsize=64)
def make_bucketize_perm_fn(
    mesh: Mesh,
    lane_dtypes: tuple,
    num_buckets: int,
    capacity: int,
):
    """Exchange + lex-sort that returns ONLY (permutation, counts).

    The full-row variant above downloads every exchanged column; on
    tunneled TPUs device→host readback is the build bottleneck
    (~20 MB/s), so this program keeps payloads off the device entirely:
    inputs are the key LANES (ops/sortkeys.py) + per-row bucket id, the
    global row id is generated on device (iota + axis offset), and the
    outputs are the key-sorted global row permutation [n_pad] plus
    per-device per-bucket valid-row counts [D, num_buckets]. The host
    gathers payload columns by the permutation and carves by the counts —
    one int32-per-row readback total."""
    from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

    axes = mesh_axes(mesh)
    num_devices = mesh_size(mesh)
    if num_buckets % num_devices != 0:
        raise ValueError(f"num_buckets {num_buckets} must be a multiple of mesh size {num_devices}")
    buckets_per_device = num_buckets // num_devices
    num_lanes = len(lane_dtypes)
    spec = P(axes)
    axis_sizes = {ax: mesh.shape[ax] for ax in axes}

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(tuple(spec for _ in range(num_lanes)), spec, P()),
        out_specs=(spec, P(axes, None), P()),
        check_vma=False,
    )
    def fn(lanes, bucket, n_rows):
        r = bucket.shape[0]
        flat_idx = jnp.int32(0)
        for ax in axes:
            flat_idx = flat_idx * axis_sizes[ax] + lax.axis_index(ax)
        gid = flat_idx * r + jnp.arange(r, dtype=jnp.int32)
        valid = (gid < n_rows[0]).astype(jnp.int32)
        rc, rb, rv, overflow = _exchange_one_device(
            list(lanes) + [gid], bucket, valid, num_devices, buckets_per_device,
            capacity, num_lanes, axes,
        )
        perm = rc[-1]
        # Valid rows carry their true bucket; invalid rows carry the 2^30
        # sentinel, which bincount's bounded scatter drops.
        counts = jnp.bincount(rb, length=num_buckets).astype(jnp.int32)
        overflow = lax.pmax(overflow.astype(jnp.int32), axes)
        return perm, counts[None, :], overflow[None] if overflow.ndim == 0 else overflow

    return jit(fn, key="ops.bucketize.perm")


def bucketize_perm(
    mesh: Mesh,
    lanes: list,
    bucket,
    n: int,
    num_buckets: int,
    capacity_factor: float = 2.0,
):
    """Host wrapper for the permutation-only exchange (overflow retry as in
    `bucketize`). `lanes`/`bucket` are host arrays padded to a multiple of
    the mesh size; rows past `n` are pads. Returns (order [n] int32 global
    row ids in (bucket, key) order, bucket_rows [num_buckets])."""
    import numpy as _np

    from hyperspace_tpu.parallel.mesh import mesh_size

    num_devices = mesh_size(mesh)
    n_pad = bucket.shape[0]
    if n_pad >= 2**31:
        raise ValueError("bucketize_perm row ids exceed int32")
    per_dev = n_pad // num_devices
    lane_dtypes = tuple(str(_np.dtype(l.dtype)) for l in lanes)
    n_arr = jnp.asarray(_np.array([n], dtype=_np.int32))
    dev_lanes = tuple(jnp.asarray(l) for l in lanes)
    dev_bucket = jnp.asarray(bucket)
    while True:
        capacity = max(1, math.ceil(per_dev / num_devices * capacity_factor))
        capacity = min(capacity, per_dev)
        fn = make_bucketize_perm_fn(mesh, lane_dtypes, num_buckets, capacity)
        perm, counts, overflow = fn(dev_lanes, dev_bucket, n_arr)
        # ONE fused readback (overflow + perm + counts): every device_get
        # round-trip costs ~0.3-1s of latency on tunneled TPUs, and
        # overflow is rare enough that optimistically downloading perm
        # alongside it wins on average.
        perm_h, counts_h, overflow_h = jax.device_get((perm, counts, overflow))
        if not bool(_np.asarray(overflow_h).max()):
            break
        if capacity >= per_dev:
            # Typed (not assert): the invariant breaking would cross the
            # action API surface, and asserts vanish under -O.
            from hyperspace_tpu.exceptions import HyperspaceError

            raise HyperspaceError("bucketize overflow with full capacity — impossible")
        capacity_factor *= 2.0
    perm_h = _np.asarray(perm_h)
    counts_h = _np.asarray(counts_h)  # [D, num_buckets]
    # Each shard's output is its flattened [D, capacity] recv buffer
    # (valid rows sorted to the front), so the global array is [D * D*cap].
    shard_len = num_devices * capacity
    valid_per_shard = counts_h.sum(axis=1)
    parts = [
        perm_h[i * shard_len : i * shard_len + int(valid_per_shard[i])]
        for i in range(num_devices)
    ]
    order = _np.concatenate(parts) if parts else perm_h[:0]
    return order, counts_h.sum(axis=0)


def bucketize(
    mesh: Mesh,
    cols: list,
    bucket: jnp.ndarray,
    valid: jnp.ndarray,
    num_buckets: int,
    capacity_factor: float = 2.0,
    num_key_cols: int | None = None,
):
    """Host wrapper with overflow retry (doubling the capacity factor).

    Inputs are global arrays whose leading dim is a multiple of the mesh
    size (caller pads). The first `num_key_cols` of `cols` (default: all
    but the last) are sort keys after the exchange. Returns
    (cols, bucket, valid) where rows live on their owning device,
    lex-sorted by (bucket, keys) with invalid rows sunk to each shard's
    tail under the sentinel bucket."""
    from hyperspace_tpu.parallel.mesh import mesh_size

    num_devices = mesh_size(mesh)
    n = bucket.shape[0]
    per_dev = n // num_devices
    if num_key_cols is None:
        num_key_cols = max(0, len(cols) - 1)
    while True:
        capacity = max(1, math.ceil(per_dev / num_devices * capacity_factor))
        capacity = min(capacity, per_dev)  # no point exceeding local rows
        fn = make_bucketize_fn(mesh, len(cols), num_buckets, capacity, num_key_cols)
        out_cols, out_bucket, out_valid, overflow = fn(tuple(cols), bucket, valid)
        if not bool(jax.device_get(overflow).max()):
            return list(out_cols), out_bucket, out_valid
        if capacity >= per_dev:
            # Typed (not assert): the invariant breaking would cross the
            # action API surface, and asserts vanish under -O.
            from hyperspace_tpu.exceptions import HyperspaceError

            raise HyperspaceError("bucketize overflow with full capacity — impossible")
        capacity_factor *= 2.0
