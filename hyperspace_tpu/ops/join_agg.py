"""Fused join + aggregation: aggregate over an equi-join WITHOUT
materializing the joined pairs.

The expansion phase of a sort-merge join emits one (left, right) index
pair per match — for full-table TPC-H joins that is the whole output and
its readback/gather dominates. But every standard aggregate over the
join decomposes over each primary row's match RUN [st_i, en_i) in the
sorted secondary side:

    count(*)                += (en_i - st_i)                per primary row
    sum(primary expr v)     += v_i * (en_i - st_i)
    sum(secondary expr u)   += P[en_i] - P[st_i]            (P = prefix sum)

so the aggregation needs only the run bounds (two searchsorteds — the
count phase the join already runs) plus cumsum/gather/segment-sum, all
on device, and downloads K per-group scalars instead of millions of
pairs. Runs under scoped x64 (jax.enable_x64) for 53-bit accumulation;
the global flag is never touched.

Fused kernel ladder (docs/architecture.md "device data path"): with
``hyperspace.device.fusedKernels`` = auto and an eligible shape, the run
bounds come from the tiled Pallas searchsorted
(ops/sortkeys.pallas_run_bounds — the secondary row resident in VMEM,
one vectorized compare-and-count per tile) and feed the same lax
epilogue; bounds are integers, so results are byte-identical to the
all-lax path by construction. Ineligible shapes or failed lowerings
fall back transparently (`device.kernel.fused`/`device.kernel.fallbacks`
count the split).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu import stats
from hyperspace_tpu.compat import jit
from hyperspace_tpu.obs import trace as obs_trace


def _seg_scan_extremum(vals, new_seg, op):
    """Segmented inclusive prefix min/max along the last axis: the scan
    restarts where `new_seg` is True. Standard associative segmented-scan
    operator — maps to one `lax.associative_scan` (log-depth on device)."""

    def comb(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, op(va, vb))

    _, out = jax.lax.associative_scan(comb, (new_seg, vals), axis=-1)
    return out


def _one_bucket(pkb, skb, pvb, svb, gidb, stb, enb, num_segments: int, channels: tuple):
    """Per-bucket channel reduction given the run bounds [stb, enb)."""
    real = pkb < jnp.iinfo(pkb.dtype).max
    matched = real & (enb > stb)
    runlen = jnp.where(real, enb - stb, 0).astype(jnp.float64)
    p_prefix = None
    if svb.shape[0] and any(ch[0] == "s" for ch in channels):
        p_prefix = jnp.concatenate(
            [jnp.zeros((svb.shape[0], 1), svb.dtype), jnp.cumsum(svb, axis=-1)],
            axis=-1,
        )
    new_key = None
    if any(ch[0] in ("smin", "smax") for ch in channels):
        new_key = jnp.concatenate(
            [jnp.ones(1, bool), skb[1:] != skb[:-1]]
        )
    outs = []
    for ch in channels:
        kind = ch[0]
        if kind == "star":
            outs.append(jax.ops.segment_sum(runlen, gidb, num_segments))
        elif kind == "p":
            outs.append(jax.ops.segment_sum(pvb[ch[1]] * runlen, gidb, num_segments))
        elif kind == "s":
            pj = p_prefix[ch[1]]
            w = jnp.where(real, pj[enb] - pj[stb], 0.0)
            outs.append(jax.ops.segment_sum(w, gidb, num_segments))
        else:
            is_min = kind.endswith("min")
            ident = jnp.inf if is_min else -jnp.inf
            seg_red = jax.ops.segment_min if is_min else jax.ops.segment_max
            if kind[0] == "p":
                w = jnp.where(matched, pvb[ch[1]], ident)
            else:
                m = _seg_scan_extremum(
                    svb[ch[1]], new_key, jnp.minimum if is_min else jnp.maximum
                )
                w = jnp.where(matched, m[jnp.maximum(enb - 1, 0)], ident)
            outs.append(seg_red(w, gidb, num_segments))
    return jnp.stack(outs)


def _combine_buckets(per_bucket, channels: tuple):
    """Fold the vmapped [B, C, K] per-bucket partials across buckets (a
    group's rows can span buckets only via the primary side's bucketing;
    sums add, extrema fold with their own op)."""
    combined = []
    for c, ch in enumerate(channels):
        if ch[0] == "pmin" or ch[0] == "smin":
            combined.append(jnp.min(per_bucket[:, c], axis=0))
        elif ch[0] == "pmax" or ch[0] == "smax":
            combined.append(jnp.max(per_bucket[:, c], axis=0))
        else:
            combined.append(jnp.sum(per_bucket[:, c], axis=0))
    return jnp.stack(combined)  # [C, num_segments]


@functools.partial(jit, static_argnames=("num_segments", "channels"))
def _fused_join_agg(pk, sk, pvals, svals, gid, num_segments: int, channels: tuple):
    """pk/sk: [B, Lp]/[B, Ls] per-bucket sorted int32 codes (pads carry
    the dtype max). pvals [Ap, B, Lp] / svals [As, B, Ls]: float64
    per-row channel values (nulls and pads pre-zeroed for sum channels,
    pre-set to the ±inf identity for extremum channels). gid [B, Lp]:
    group ids (pads → num_segments-1). channels: ('star',) | ('p'|'s', j)
    sum channels | ('pmin'|'pmax'|'smin'|'smax', j) run-extremum channels
    (an equi-join match run IS one key segment of the sorted secondary,
    so its extremum is the segmented prefix scan value at the run end).
    Returns [len(channels), num_segments] float64."""

    def one(pkb, skb, pvb, svb, gidb):
        st = jnp.searchsorted(skb, pkb, side="left").astype(jnp.int32)
        en = jnp.searchsorted(skb, pkb, side="right").astype(jnp.int32)
        return _one_bucket(pkb, skb, pvb, svb, gidb, st, en, num_segments, channels)

    per_bucket = jax.vmap(one)(pk, sk, pvals.transpose(1, 0, 2), svals.transpose(1, 0, 2), gid)
    return _combine_buckets(per_bucket, channels)


@functools.partial(jit, static_argnames=("num_segments", "channels"))
def _fused_join_agg_bounds(
    pk, sk, st, en, pvals, svals, gid, num_segments: int, channels: tuple
):
    """Same program as :func:`_fused_join_agg` with the run bounds
    precomputed (the Pallas run-bounds kernel feeds this variant)."""

    def one(pkb, skb, stb, enb, pvb, svb, gidb):
        return _one_bucket(pkb, skb, pvb, svb, gidb, stb, enb, num_segments, channels)

    per_bucket = jax.vmap(one)(
        pk, sk, st, en, pvals.transpose(1, 0, 2), svals.transpose(1, 0, 2), gid
    )
    return _combine_buckets(per_bucket, channels)


def fused_join_aggregate(
    pk: np.ndarray,
    sk: np.ndarray,
    pvals: np.ndarray,
    svals: np.ndarray,
    gid: np.ndarray,
    num_groups: int,
    channels: tuple,
    fused: str = "off",
) -> np.ndarray:
    """Host wrapper: pads the group dimension (+1 dead segment for pads)
    and runs the fused device program on the persistent x64 worker thread
    (parallel/x64.py). Returns [C, num_groups] float64. `fused` = "auto"
    tries the Pallas run-bounds kernel first (identical integer bounds,
    so identical results), with the lax searchsorted as the fallback."""
    from hyperspace_tpu.execution.device_cache import device_put_cached
    from hyperspace_tpu.ops.sortkeys import pallas_run_bounds
    from hyperspace_tpu.parallel.x64 import run_x64

    k_seg = 1 << max(int(num_groups).bit_length(), 1)  # >= num_groups+1

    def call():
        # Stable (frozen, identity-cached) inputs serve from the HBM
        # cache on repeat queries; the upload keys carry the active x64
        # scope, so the float64 channels stay float64.
        pk_dev = device_put_cached(pk)
        sk_dev = device_put_cached(sk)
        bounds = None
        if fused == "auto":
            with obs_trace.span(
                "device.kernel", kernel="pallas-run-bounds",
                buckets=pk.shape[0], secondary=sk.shape[1],
            ):
                bounds = pallas_run_bounds(pk_dev, sk_dev)
            if bounds is not None:
                stats.increment("device.kernel.fused")
            else:
                stats.increment("device.kernel.fallbacks")
        if bounds is not None:
            out = _fused_join_agg_bounds(
                pk_dev, sk_dev, bounds[0], bounds[1],
                device_put_cached(pvals),
                device_put_cached(svals),
                device_put_cached(gid),
                k_seg,
                channels,
            )
        else:
            out = _fused_join_agg(
                pk_dev,
                sk_dev,
                device_put_cached(pvals),
                device_put_cached(svals),
                device_put_cached(gid),
                k_seg,
                channels,
            )
        return np.asarray(jax.device_get(out))

    return run_x64(call)[:, :num_groups]
