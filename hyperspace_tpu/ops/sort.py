"""Multi-key lexicographic sort on device.

The analog of the per-bucket sort in the reference's bucketed write
(index/DataFrameWriterExtensions.scala:49-66, bucketBy == sortBy). XLA's
`lax.sort` with `num_keys` performs a fused lexicographic sort of all
operands in one compiled op — this is exactly the "let XLA do it" path; no
hand-written kernel needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def lex_sort_tables(key_arrays: list, payload_arrays: list) -> tuple[list, list]:
    """Sort rows by the key columns (lexicographic), carrying payloads.

    Returns (sorted_keys, sorted_payloads)."""
    operands = tuple(key_arrays) + tuple(payload_arrays)
    out = lax.sort(operands, num_keys=len(key_arrays), is_stable=True)
    return list(out[: len(key_arrays)]), list(out[len(key_arrays) :])


def sort_indices_by_keys(key_arrays: list) -> jnp.ndarray:
    """Permutation that sorts by the key columns (stable)."""
    n = key_arrays[0].shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    out = lax.sort(tuple(key_arrays) + (iota,), num_keys=len(key_arrays), is_stable=True)
    return out[-1]
