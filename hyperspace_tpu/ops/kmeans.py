"""Device k-means: the coarse quantizer for the vector index.

The analog of the covering index's hash-bucketize step for embedding
columns (BASELINE config 5): rows are partitioned by nearest centroid so a
query probes only its closest partitions. Everything is MXU work — the
distance matrix is one [n, d] @ [d, C] matmul per Lloyd iteration, and the
centroid update is the one-hot-assignment matmul [C, n] @ [n, d] — so the
whole trainer is a handful of big batched matmuls, exactly what the
systolic array wants.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu.compat import jit

_TRAIN_SAMPLE = 131_072
_ASSIGN_CHUNK = 262_144


@functools.partial(jit, static_argnames=("iters",))
def _lloyd(x: jnp.ndarray, init: jnp.ndarray, iters: int) -> jnp.ndarray:
    """x [n, d] f32, init [C, d] f32 → trained centroids [C, d]."""
    xsq = jnp.sum(x * x, axis=1, keepdims=True)  # [n, 1]

    def step(c, _):
        d2 = xsq - 2.0 * (x @ c.T) + jnp.sum(c * c, axis=1)[None, :]  # [n, C]
        assign = jnp.argmin(d2, axis=1)  # [n]
        onehot = jax.nn.one_hot(assign, c.shape[0], dtype=x.dtype)  # [n, C]
        sums = onehot.T @ x  # [C, d] — MXU
        counts = jnp.sum(onehot, axis=0)[:, None]  # [C, 1]
        new_c = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), c)
        return new_c, None

    out, _ = jax.lax.scan(step, init, None, length=iters)
    return out


def train_centroids(
    x: np.ndarray, num_partitions: int, iters: int = 8, seed: int = 0
) -> np.ndarray:
    """Train `num_partitions` centroids on (a sample of) x [n, d]."""
    n = len(x)
    rng = np.random.default_rng(seed)
    if n > _TRAIN_SAMPLE:
        sample = x[rng.choice(n, _TRAIN_SAMPLE, replace=False)]
    else:
        sample = x
    init_idx = rng.choice(len(sample), min(num_partitions, len(sample)), replace=False)
    init = sample[init_idx].astype(np.float32)
    if len(init) < num_partitions:  # degenerate tiny input: repeat rows
        reps = -(-num_partitions // len(init))
        init = np.tile(init, (reps, 1))[:num_partitions]
    out = _lloyd(jnp.asarray(sample, dtype=jnp.float32), jnp.asarray(init), iters)
    return np.asarray(jax.device_get(out))


@jit
def _assign(x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
    d2 = (
        jnp.sum(x * x, axis=1, keepdims=True)
        - 2.0 * (x @ c.T)
        + jnp.sum(c * c, axis=1)[None, :]
    )
    return jnp.argmin(d2, axis=1).astype(jnp.int32)


def assign_partitions(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Nearest-centroid partition id per row, chunked to bound HBM."""
    c = jnp.asarray(centroids, dtype=jnp.float32)
    out = []
    for lo in range(0, len(x), _ASSIGN_CHUNK):
        chunk = jnp.asarray(x[lo : lo + _ASSIGN_CHUNK], dtype=jnp.float32)
        out.append(np.asarray(jax.device_get(_assign(chunk, c))))
    return np.concatenate(out) if out else np.zeros(0, np.int32)
