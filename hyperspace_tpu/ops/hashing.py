"""Deterministic vectorized hashing for bucket assignment.

The analog of Spark's HashPartitioning under `repartition(numBuckets, cols)`
(reference hot path actions/CreateActionBase.scala:108-112): every row is
assigned `bucket = hash(key columns) % num_buckets`. The hash must be

- identical on host (numpy) and device (jax.numpy), so build-time bucketing
  (device) and query-time bucket pruning (host) agree;
- dictionary-independent for strings: the hash is a function of the string
  BYTES (per-dictionary hashes gathered through codes), never of the codes,
  so two tables bucket identically regardless of their dictionaries;
- 32-bit only: TPUs strongly prefer 32-bit lanes; int64 inputs are split
  into hi/lo words and mixed (murmur3 finalizer).
"""

from __future__ import annotations

import hashlib

import numpy as np

_U32 = np.uint32


def _mix32(x, xp):
    """murmur3 fmix32 — avalanche a uint32 lane."""
    x = x.astype(xp.uint32) if hasattr(x, "astype") else xp.uint32(x)
    x = x ^ (x >> xp.uint32(16))
    x = x * xp.uint32(0x85EBCA6B)
    x = x ^ (x >> xp.uint32(13))
    x = x * xp.uint32(0xC2B2AE35)
    x = x ^ (x >> xp.uint32(16))
    return x


def hash_int_column(arr, xp):
    """Hash an integer/bool/float column to uint32.

    int64/float64 are viewed as two 32-bit words and both words mixed;
    32-bit types mix directly. Works with numpy or jax.numpy via `xp`.
    The numpy path dispatches to the threaded C++ kernel when built
    (hyperspace_tpu/native — bit-identical by construction and test).
    """
    dtype = arr.dtype
    if dtype in (np.dtype(np.float32),):
        arr = arr.view(np.int32) if xp is np else arr.view(xp.int32)
        dtype = arr.dtype
    if dtype in (np.dtype(np.float64),):
        arr = arr.view(np.int64) if xp is np else arr.view(xp.int64)
        dtype = arr.dtype
    if dtype in (np.dtype(np.bool_),):
        arr = arr.astype(np.int32 if xp is np else xp.int32)
        dtype = arr.dtype
    if dtype in (np.dtype(np.int64), np.dtype(np.uint64)):
        if xp is np:
            from hyperspace_tpu import native

            out = native.hash_i64(arr.view(np.int64))
            if out is not None:
                return out
        lo = (arr & 0xFFFFFFFF).astype(xp.uint32)
        hi = ((arr >> 32) & 0xFFFFFFFF).astype(xp.uint32)
        return _mix32(lo ^ (_mix32(hi, xp) * xp.uint32(0x9E3779B1)), xp)
    # 32-bit lane
    if xp is np:
        from hyperspace_tpu import native

        out = native.hash_i32(arr.view(np.int32) if arr.dtype != np.int32 else arr)
        if out is not None:
            return out
    return _mix32(arr.astype(xp.uint32), xp)


def string_dict_hashes(dictionary: np.ndarray) -> np.ndarray:
    """uint32 hash per dictionary entry, a pure function of the bytes
    (md5 prefix) — stable across processes and dictionaries."""
    from hyperspace_tpu import native

    out = native.md5_prefix(dictionary)
    if out is not None:
        return out
    out = np.empty(len(dictionary), dtype=np.uint32)
    for i, s in enumerate(dictionary):
        h = hashlib.md5(str(s).encode("utf-8")).digest()
        out[i] = int.from_bytes(h[:4], "little")
    return out


def combine_hashes(hashes: list, xp):
    """Order-dependent combine of per-column uint32 hashes."""
    acc = hashes[0]
    if xp is np and len(hashes) > 1:
        from hyperspace_tpu import native

        for h in hashes[1:]:
            nat = native.combine(acc, h)
            if nat is None:
                acc = _mix32(acc * xp.uint32(31) + h, xp)
            else:
                acc = nat
        return acc
    for h in hashes[1:]:
        acc = _mix32(acc * xp.uint32(31) + h, xp)
    return acc


def bucket_ids(hashes, num_buckets: int, xp):
    """Map uint32 hashes to bucket ids [0, num_buckets) as int32."""
    return (hashes % xp.uint32(num_buckets)).astype(xp.int32)
