"""Order-preserving 32-bit lane decomposition of key columns.

The device plane sorts rows by key with `lax.sort` on native 32-bit lanes
(TPU emulates 64-bit). Instead of ranking values to int32 codes with a
host `np.unique` pass (O(n log n) host work, impossible to stream), each
logical key column decomposes into 1-3 int32/uint32 lanes whose
lexicographic order equals the logical order of the column:

- int8/16/32, date32, bool  → one int32 lane;
- int64, timestamp          → (hi int32, lo uint32) word pair;
- uint64                    → (hi uint32, lo uint32);
- float32                   → one uint32 lane via the IEEE-754 total-order
  bit flip (negatives reversed, sign bit toggled);
- float64                   → the same flip on 64 bits, split hi/lo;
- strings                   → the table's sorted-dictionary codes (already
  rank codes; only valid WITHIN one table/dictionary);
- nullable columns          → a leading validity lane (0 null, 1 valid),
  so nulls sort first — matching the query plane's null-first codes.

This is the streaming-safe scheme VERDICT.md round 1 asked for: lanes are
a pure per-row function of the value, so chunks of any size decompose
independently. The reference gets the analogous property for free from
Spark's typed sort (index/DataFrameWriterExtensions.scala:49-66 sorts
raw column values, not ranks).

Invariant: lane decomposition is only defined for dtypes with a total
order — the plan validator (analysis/validator.py, rule unsortable-key)
rejects sort/window-order keys over vector columns before execution
reaches the HyperspaceError below.
"""

from __future__ import annotations

import functools

import numpy as np

from hyperspace_tpu import stats
from hyperspace_tpu.exceptions import HyperspaceError


def _flip32(v: np.ndarray) -> np.ndarray:
    """IEEE-754 int32 bit pattern → uint32 whose unsigned order equals the
    float order (negatives reversed, sign toggled)."""
    mask = (v >> 31) | np.int32(-(2**31))  # v>=0: 0x80000000, v<0: 0xFFFFFFFF
    return (v ^ mask).view(np.uint32)


def _flip64(v: np.ndarray) -> np.ndarray:
    mask = (v >> 63) | np.int64(-(2**63))
    return (v ^ mask).view(np.uint64)


def _split64(u: np.ndarray) -> list[np.ndarray]:
    """uint64 → (hi uint32, lo uint32) lanes (unsigned lexicographic)."""
    return [(u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)]


def value_lanes(arr: np.ndarray) -> list[np.ndarray]:
    """Decompose one physical array into order-preserving 32-bit lanes."""
    dt = np.dtype(arr.dtype)
    if dt == np.bool_:
        return [arr.astype(np.int32)]
    if dt.kind == "i" and dt.itemsize <= 4:
        return [arr.astype(np.int32, copy=False)]
    if dt.kind == "u" and dt.itemsize < 4:
        return [arr.astype(np.int32)]
    if dt == np.uint32:
        return [arr]
    if dt == np.int64:
        return [(arr >> 32).astype(np.int32), (arr & 0xFFFFFFFF).astype(np.uint32)]
    if dt == np.uint64:
        return _split64(arr)
    if dt == np.float32:
        return [_flip32(arr.view(np.int32))]
    if dt == np.float64:
        return _split64(_flip64(arr.view(np.int64)))
    raise HyperspaceError(f"unsupported key dtype {dt}")


def column_lanes(table, name: str, force_validity: bool = False) -> list[np.ndarray]:
    """Lanes for a named column of a ColumnTable (validity lane first when
    the column has nulls; null slots zeroed so output is deterministic).
    `force_validity` emits the validity lane even for null-free columns so
    lane layouts match across tables (batched sorts)."""
    f = table.schema.field(name)
    arr = table.columns[f.name]
    lanes: list[np.ndarray] = []
    valid = table.valid_mask(name)
    if valid is not None:
        lanes.append(valid.astype(np.int32))
        zero = np.zeros((), dtype=arr.dtype)
        arr = np.where(valid, arr, zero)
    elif force_validity:
        lanes.append(np.ones(len(arr), dtype=np.int32))
    if f.is_string:
        lanes.append(np.ascontiguousarray(arr, dtype=np.int32))
        return lanes
    lanes.extend(value_lanes(arr))
    return lanes


def key_lanes(table, key_columns: list[str], force_validity: bool = False) -> list[np.ndarray]:
    """All lanes for a key-column list, in sort-significance order."""
    out: list[np.ndarray] = []
    for c in key_columns:
        out.extend(column_lanes(table, c, force_validity=force_validity))
    return out


def lanes_as_unsigned(lanes: list[np.ndarray]) -> np.ndarray:
    """[L, n] uint32 matrix whose unsigned lexicographic order equals the
    lanes' mixed signed/unsigned order (signed lanes get the sign bit
    flipped) — the layout the native host sort kernel consumes."""
    out = np.empty((len(lanes), len(lanes[0]) if lanes else 0), dtype=np.uint32)
    for i, l in enumerate(lanes):
        if l.dtype == np.uint32:
            out[i] = l
        else:
            out[i] = l.astype(np.int32).view(np.uint32) ^ np.uint32(0x80000000)
    return out


def lexsort_lanes(lanes: list[np.ndarray]) -> np.ndarray:
    """Host (numpy) stable argsort by the lanes — the reference ordering
    the device sort must reproduce. np.lexsort keys are LAST-significant
    first, so reverse."""
    if not lanes:
        return np.arange(0)
    return np.lexsort(tuple(reversed(lanes)))


def invert_lane(lane: np.ndarray) -> np.ndarray:
    """Order-reversing bijection on a lane (~x flips both int32 signed
    order and uint32 unsigned order) — implements DESC sort keys. A
    flipped validity lane also lands nulls last, matching SQL's
    nulls-first-ASC / nulls-last-DESC convention."""
    return ~lane


def order_lanes(table, by: list[tuple[str, bool]]) -> list[np.ndarray]:
    """Lanes for an ORDER BY (column, ascending) list."""
    out: list[np.ndarray] = []
    for c, asc in by:
        lanes = column_lanes(table, c, force_validity=True)
        if not asc:
            lanes = [invert_lane(l) for l in lanes]
        out.extend(lanes)
    return out


def device_lanes_perm(lanes: list[np.ndarray]) -> np.ndarray:
    """Stable permutation sorting rows by pre-decomposed 32-bit lanes —
    ONE device lax.sort (pads to a power of two; a leading is_pad lane
    sinks pads). This is the fused bucket+key encode the query-time
    re-grouping uses instead of a separate host np.lexsort pass: callers
    stack e.g. [bucket lane, *key lanes] and get the grouped order in a
    single device dispatch."""
    import jax
    import jax.numpy as jnp

    n = len(lanes[0]) if lanes else 0
    if n <= 1:
        return np.arange(n)
    l_pad = 1 << (int(n - 1).bit_length())
    is_pad = np.zeros((1, l_pad), np.int32)
    is_pad[0, n:] = 1
    ops = [jnp.asarray(is_pad)]
    for l in lanes:
        buf = np.zeros((1, l_pad), l.dtype)
        buf[0, :n] = l
        ops.append(jnp.asarray(buf))
    iota = np.arange(l_pad, dtype=np.int32)[None, :]
    ops.append(jnp.asarray(iota))
    fn = _make_batch_sort(len(ops), 1 + len(lanes))
    perm = np.asarray(jax.device_get(fn(*ops)))
    return perm[0, :n]


def device_order_perm(table, by: list[tuple[str, bool]]) -> np.ndarray:
    """Stable permutation ordering `table` by the (column, ascending)
    keys — one device lax.sort over the decomposed lanes."""
    if table.num_rows <= 1:
        return np.arange(table.num_rows)
    return device_lanes_perm(order_lanes(table, by))


# -- fused Pallas run bounds --------------------------------------------------
# Batched searchsorted for the fused join-aggregate: every (bucket,
# primary-row tile) program holds the bucket's WHOLE sorted secondary
# key row in VMEM and counts `sk < pk` / `sk <= pk` with one vectorized
# compare-and-sum — exactly searchsorted left/right on a sorted row,
# integer-exact by construction (so results stay byte-identical to the
# lax path), without the per-element binary-search while_loop XLA lowers
# jnp.searchsorted to. Generalizes the ops/topk.py tiling (grid over
# tiles, whole-reduction rows resident in VMEM).
_RB_TILE = 128
# The secondary row must fit VMEM beside the (tile, Ls) compare block.
_RB_MAX_SECONDARY = 8192
# Interpret mode (CPU tests) pays a python-level grid loop per program:
# bound total compare work so the fused path never engages where the
# brute-force O(Lp*Ls) sweep would dwarf the O(Lp log Ls) lax path.
_RB_INTERPRET_WORK = 1 << 24

import threading as _threading

_pallas_rb_bad: set = set()
_pallas_rb_bad_lock = _threading.Lock()


@functools.lru_cache(maxsize=32)
def _make_run_bounds_kernel(tile: int, ls_pad: int, interpret: bool):
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.compat import jit, resolve_pallas

    pl = resolve_pallas()

    def kernel(pk_ref, sk_ref, st_ref, en_ref):
        pk = pk_ref[0, :]  # (tile,) int32, sorted or not — bounds are per-element
        sk = sk_ref[0, :]  # (ls_pad,) int32, sorted (pads carry dtype max)
        cmp = sk[None, :] < pk[:, None]
        st_ref[0, :] = jnp.sum(cmp.astype(jnp.int32), axis=1)
        en_ref[0, :] = jnp.sum((sk[None, :] <= pk[:, None]).astype(jnp.int32), axis=1)

    def run(pk, sk):  # pk [B, lp_pad], sk [B, ls_pad]; lp_pad % tile == 0
        b, lp = pk.shape
        return pl.pallas_call(
            kernel,
            grid=(b, lp // tile),
            in_specs=[
                pl.BlockSpec((1, tile), lambda i, j: (i, j)),
                pl.BlockSpec((1, ls_pad), lambda i, j: (i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, tile), lambda i, j: (i, j)),
                pl.BlockSpec((1, tile), lambda i, j: (i, j)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, lp), jnp.int32),
                jax.ShapeDtypeStruct((b, lp), jnp.int32),
            ],
            interpret=interpret,
        )(pk, sk)

    return jit(run, key="ops.sortkeys.pallas_run_bounds")


def pallas_run_bounds(pk, sk):
    """(st, en) device arrays — per-row searchsorted left/right of the
    bucket-batched primary codes `pk` [B, Lp] into the sorted secondary
    codes `sk` [B, Ls] — via the fused Pallas kernel, or None when the
    shape is ineligible or the lowering failed (caller keeps the lax
    searchsorted path; results are identical either way). Lp must be a
    multiple of the tile (the caller pads with sentinels)."""
    import jax

    b, lp = pk.shape
    ls = sk.shape[1]
    if ls > _RB_MAX_SECONDARY or lp % _RB_TILE or lp == 0 or ls == 0:
        return None
    interpret = jax.default_backend() == "cpu"
    if interpret and b * lp * ls > _RB_INTERPRET_WORK:
        return None
    with _pallas_rb_bad_lock:
        if (_RB_TILE, ls) in _pallas_rb_bad:
            return None
    try:
        run = _make_run_bounds_kernel(_RB_TILE, ls, interpret)
        out = run(pk, sk)
        stats.increment("device.kernel.fused")
        return out
    except Exception:  # noqa: BLE001 — fall back to the lax searchsorted
        with _pallas_rb_bad_lock:
            _pallas_rb_bad.add((_RB_TILE, ls))
        stats.increment("device.kernel.fallbacks")
        return None


@functools.lru_cache(maxsize=32)
def _make_batch_sort(num_operands: int, num_keys: int):
    import jax
    from jax import lax

    def f(*ops):
        return lax.sort(ops, num_keys=num_keys, is_stable=True)[-1]

    from hyperspace_tpu.compat import jit

    return jit(f, key="ops.sortkeys.batch_sort")


@functools.lru_cache(maxsize=16)
def _make_sharded_topn(mesh, axes, n: int):
    """Per-shard first-n selection by a (hi, lo) uint32 key pair: one
    lax.sort per device under shard_map, zero collectives; the sharded
    outputs concatenate to the D*n global candidate list."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from hyperspace_tpu.compat import shard_map

    spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec), out_specs=(spec, spec, spec),
        check_vma=False,
    )
    def fn(hi, lo, idx):
        s = lax.sort((hi, lo, idx), num_keys=2, is_stable=True)
        return s[0][:n], s[1][:n], s[2][:n]

    from hyperspace_tpu.compat import jit

    return jit(fn, key="ops.sortkeys.sharded_topn")


@functools.lru_cache(maxsize=16)
def _make_sharded_le(mesh, axes):
    """Elementwise (hi, lo) <= (thr_hi, thr_lo) over the sharded rows."""
    import jax
    from jax.sharding import PartitionSpec as P

    from hyperspace_tpu.compat import shard_map

    spec = P(axes)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, P(), P()), out_specs=spec,
        check_vma=False,
    )
    def fn(hi, lo, thi, tlo):
        return (hi < thi) | ((hi == thi) & (lo <= tlo))

    from hyperspace_tpu.compat import jit

    return jit(fn, key="ops.sortkeys.sharded_le")


def distributed_top_n_candidates(lanes_u32: np.ndarray, n: int, mesh) -> np.ndarray | None:
    """Candidate row indices provably containing the global top-n by the
    packed 64-bit key prefix, computed SPMD over the mesh (the ORDER BY
    participation the reference gets from Spark's TakeOrderedAndProject
    running on every executor): each device selects its shard's first n
    by one local lax.sort; the n-th smallest prefix over the D*n union
    is an inclusive threshold; a sharded elementwise pass emits every
    row at or below it (prefix ties stay in — the exact candidate-set
    sort settles total order). Returns None when the mesh cannot help."""
    import jax
    import jax.numpy as jnp

    from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

    d = mesh_size(mesh)
    n_rows = lanes_u32.shape[1]
    if d <= 1 or n <= 0 or n_rows < 2 * n * d:
        return None
    hi = lanes_u32[0]
    lo = lanes_u32[1] if lanes_u32.shape[0] > 1 else np.zeros(n_rows, np.uint32)
    n_pad = 1 << (int(n_rows - 1).bit_length())
    if n_pad % d:
        n_pad = ((n_pad + d - 1) // d) * d
    if n_pad // d < n:
        return None

    def pad(a, fill):
        out = np.full(n_pad, fill, dtype=a.dtype)
        out[:n_rows] = a
        return out

    axes = mesh_axes(mesh)
    hi_p = jnp.asarray(pad(hi, np.uint32(0xFFFFFFFF)))
    lo_p = jnp.asarray(pad(lo, np.uint32(0xFFFFFFFF)))
    idx = jnp.asarray(np.arange(n_pad, dtype=np.int32))
    chi, clo, cidx = jax.device_get(_make_sharded_topn(mesh, axes, n)(hi_p, lo_p, idx))
    valid = cidx < n_rows
    chi, clo = chi[valid], clo[valid]
    if len(chi) < n:
        return None  # fewer real rows than n across shards: caller sorts all
    order = np.lexsort((clo, chi))
    thr_hi, thr_lo = chi[order[n - 1]], clo[order[n - 1]]
    mask = np.asarray(
        jax.device_get(
            _make_sharded_le(mesh, axes)(
                hi_p, lo_p, jnp.uint32(thr_hi), jnp.uint32(thr_lo)
            )
        )
    )[:n_rows]
    return np.flatnonzero(mask)


def device_sort_perms(tables, key_columns: list[str]) -> list[np.ndarray]:
    """Batched per-table stable key-sort permutation on device.

    Pads every table to a common power-of-two length; a leading is_pad
    lane sinks pads unambiguously (a lane-max pad value could collide
    with real data). ONE lax.sort call sorts all tables (lax.sort
    batches over leading dims), one readback returns all permutations —
    this is the streaming build's phase-2 device kernel."""
    import jax
    import jax.numpy as jnp

    if not tables:
        return []
    lens = [t.num_rows for t in tables]
    lanes_list = [key_lanes(t, key_columns, force_validity=True) for t in tables]
    num_lanes = len(lanes_list[0])
    b = len(tables)
    mx = max(max(lens), 1)
    l_pad = 1 << (int(mx - 1).bit_length()) if mx > 1 else 1
    is_pad = np.zeros((b, l_pad), np.int32)
    for i, n in enumerate(lens):
        is_pad[i, n:] = 1
    stacked = []
    for j in range(num_lanes):
        dt = lanes_list[0][j].dtype
        buf = np.zeros((b, l_pad), dt)
        for i, lanes in enumerate(lanes_list):
            buf[i, : lens[i]] = lanes[j]
        stacked.append(buf)
    iota = np.broadcast_to(np.arange(l_pad, dtype=np.int32), (b, l_pad))
    ops = [jnp.asarray(is_pad)] + [jnp.asarray(s) for s in stacked] + [jnp.asarray(np.ascontiguousarray(iota))]
    fn = _make_batch_sort(len(ops), 1 + num_lanes)
    perm = np.asarray(jax.device_get(fn(*ops)))
    return [perm[i, : lens[i]] for i in range(len(tables))]
