"""Grouped aggregation on device.

One of the engine-side operators the reference left to Spark
(SURVEY.md §2.2 — HashAggregateExec inside WholeStageCodegen); the TPU
build owns it. Group identity is factorized on host (tiny), the
reduction runs as one jitted segment-reduce on device, and only the
K-sized per-group results come back — aggregation queries never pay the
match/row readback that dominates tunneled-TPU transfers.

Staging (docs/architecture.md "device data path"): channel preparation
(null masking, the indicator channels, the [A, n_pad] float64 stack)
and the group-id pad route through the identity caches for stable
(frozen index-cache) inputs, and the device uploads go through
DEVICE_CACHE — a repeat aggregation over the same index version costs
one kernel launch plus a [A, K] readback, not a re-staging of every
channel (BENCH_VENUES group_agg was 1.06x warm-over-cold before this).

Fused kernel: when the group count is small enough for the whole [C, K]
accumulator to live in VMEM, ALL channels reduce in ONE tiled Pallas
program (generalizing the ops/topk.py tiling — grid over row tiles, the
revisited output block accumulates across sequential grid steps). The
fused kernel only engages when byte-identical results are PROVABLE —
extremum channels always (order-independent), sum channels only when
every value is integral and the absolute sum fits float64's exact range
— because its within-tile reduction order differs from the sequential
host bincount. Everything else takes the always-available jitted lax
path; `device.kernel.fused` / `device.kernel.fallbacks` count the
split, `hyperspace.device.fusedKernels` = off disables it.

SQL semantics: null inputs are ignored by sum/min/max/mean and count(col);
count(*) counts rows; a group whose inputs are all null yields NULL
(validity mask); null group keys form their own group.
"""

from __future__ import annotations

import functools
import threading

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu import stats
from hyperspace_tpu.compat import jit
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.plan.expr import Col, evaluate
from hyperspace_tpu.schema import Schema


def _pow2(n: int) -> int:
    return 1 << max(int(n - 1).bit_length(), 0) if n > 1 else 1


# -- fused Pallas segment reduce ---------------------------------------------
# Row-tile size of the fused kernel (grid dimension), and the largest
# padded segment count whose [C, K] accumulator stays comfortably in
# VMEM alongside a (tile, K) one-hot block.
_PALLAS_SEG_TILE = 256
_PALLAS_MAX_SEGMENTS = 2048
# Interpret mode (CPU tests) materializes every (tile, K) block in
# numpy: bound the total work so the fused path never engages on shapes
# where the python-level grid loop would dominate.
_PALLAS_INTERPRET_WORK = 1 << 24
# The exactness bound for fused sums: every partial sum of integral
# values with |total| below 2^52 is exactly representable in float64,
# so ANY reduction order produces the identical bits.
_EXACT_SUM_BOUND = float(2**52)

# (fns, k_pad, tile) combos whose Pallas lowering failed — those fall
# back permanently (same ladder as ops/topk.py). Lock-guarded: serve
# workers record failures concurrently.
_pallas_agg_bad: set = set()
_pallas_agg_bad_lock = threading.Lock()


@functools.lru_cache(maxsize=32)
def _make_pallas_segment_reduce(fns: tuple, k_pad: int, tile: int, interpret: bool):
    """Fused multi-channel segment reduce: grid streams row tiles, the
    [C, k_pad] output block (constant index map) accumulates across the
    SEQUENTIAL grid steps — one program for every channel instead of one
    dispatch per channel. Channel c reduces vals[c] by `fns[c]` over the
    shared group ids."""
    from hyperspace_tpu.compat import resolve_pallas

    pl = resolve_pallas()
    c_num = len(fns)

    def kernel(gid_ref, vals_ref, out_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            for c, fn in enumerate(fns):
                ident = 0.0 if fn == "sum" else (np.inf if fn == "min" else -np.inf)
                out_ref[c, :] = jnp.full((k_pad,), ident, out_ref.dtype)

        gid = gid_ref[0, :]
        onehot = gid[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (tile, k_pad), 1
        )
        for c, fn in enumerate(fns):
            v = vals_ref[c, :]
            if fn == "sum":
                out_ref[c, :] += jnp.sum(jnp.where(onehot, v[:, None], 0.0), axis=0)
            elif fn == "min":
                out_ref[c, :] = jnp.minimum(
                    out_ref[c, :], jnp.min(jnp.where(onehot, v[:, None], jnp.inf), axis=0)
                )
            else:
                out_ref[c, :] = jnp.maximum(
                    out_ref[c, :], jnp.max(jnp.where(onehot, v[:, None], -jnp.inf), axis=0)
                )

    def run(gid2d, vals):
        n_pad = vals.shape[1]
        return pl.pallas_call(
            kernel,
            grid=(n_pad // tile,),
            in_specs=[
                pl.BlockSpec((1, tile), lambda i: (0, i)),
                pl.BlockSpec((c_num, tile), lambda i: (0, i)),
            ],
            out_specs=pl.BlockSpec((c_num, k_pad), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((c_num, k_pad), vals.dtype),
            interpret=interpret,
        )(gid2d, vals)

    return jit(run, key="ops.aggregate.pallas_segment_reduce")


@functools.partial(jit, static_argnames=("num_segments", "fns"))
def _segment_reduce_many(vals, gid, num_segments: int, fns: tuple):
    """One device program reducing several (value, fn) pairs over shared
    segment ids. vals: [A, n_pad]; returns [A, num_segments]."""
    outs = []
    for i, fn in enumerate(fns):
        v = vals[i]
        if fn == "sum":
            outs.append(jax.ops.segment_sum(v, gid, num_segments))
        elif fn == "min":
            outs.append(jax.ops.segment_min(v, gid, num_segments))
        elif fn == "max":
            outs.append(jax.ops.segment_max(v, gid, num_segments))
        else:
            raise ValueError(fn)
    return jnp.stack(outs)


@functools.lru_cache(maxsize=32)
def _make_sharded_segment_reduce(mesh, axes: tuple, num_segments: int, fns: tuple):
    """Mesh-distributed segment reduce: the row dimension shards across
    devices, each shard reduces locally, and ONE collective per channel
    (psum for sums, pmin/pmax for extrema) combines the [A, K] partials —
    the distributed HashAggregate the reference gets from Spark's partial
    + final aggregation (SURVEY.md §2.2), expressed as XLA collectives
    over ICI.

    Invariant: `fns` only contains channels with a commutative device
    reduction over NUMERIC lanes — string inputs never reach here (the
    plan validator rejects sum/mean over string expressions, rule
    dtype-incompatible-aggregate)."""
    from jax.sharding import PartitionSpec as P

    from hyperspace_tpu.compat import shard_map

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axes), P(axes)),
        out_specs=P(None, None),
        check_vma=False,
    )
    def fn(vals, gid):
        local = _segment_reduce_many.__wrapped__(vals, gid, num_segments, fns)
        outs = []
        for i, f in enumerate(fns):
            if f == "sum":
                outs.append(jax.lax.psum(local[i], axes))
            elif f == "min":
                outs.append(jax.lax.pmin(local[i], axes))
            elif f == "max":
                outs.append(jax.lax.pmax(local[i], axes))
            else:
                raise ValueError(f)
        return jnp.stack(outs)

    return jit(fn, key="ops.aggregate.sharded_reduce")


def _dense_codes(arr: np.ndarray, valid) -> tuple[np.ndarray, int] | None:
    """O(n) factorization for integer columns whose value range is small
    relative to n (join keys, dict codes, dates): rank via a presence
    table instead of np.unique's O(n log n) argsort. Returns
    (codes [n] int64 with 0 reserved for nulls, cardinality incl. the
    null slot) in VALUE-sorted code order, or None when out of range."""
    if not np.issubdtype(arr.dtype, np.integer) or len(arr) == 0:
        return None
    vv = arr if valid is None else arr[valid]
    if len(vv) == 0:
        return np.zeros(len(arr), np.int64), 1
    lo, hi = int(vv.min()), int(vv.max())
    span = hi - lo + 1
    if span > max(4 * len(arr), 1 << 16):
        return None
    offs = arr.astype(np.int64) - lo
    if valid is not None:
        offs = np.where(valid, offs, 0)
    present = np.zeros(span, dtype=bool)
    present[offs[valid] if valid is not None else offs] = True
    ids = np.cumsum(present, dtype=np.int64)  # 1-based rank among present
    codes = ids[offs]
    if valid is not None:
        codes[~valid] = 0
    return codes, int(present.sum()) + 1


def _column_codes(table: ColumnTable, c: str) -> tuple[np.ndarray, int]:
    """(codes [n] int64 with 0 = null, cardinality) for one group column,
    codes in value-sorted order."""
    f = table.schema.field(c)
    arr = table.columns[f.name]
    if arr.ndim != 1:
        raise HyperspaceError(f"cannot group by vector column {c!r}")
    valid = table.valid_mask(c)
    dense = _dense_codes(arr, valid)
    if dense is not None:
        return dense
    _, inv = np.unique(arr, return_inverse=True)
    inv = inv.astype(np.int64) + 1
    card = int(inv.max()) + 1 if len(inv) else 1
    if valid is not None:
        inv[~valid] = 0
    return inv, card


def _compress(codes: np.ndarray) -> tuple[np.ndarray, int, np.ndarray]:
    """Combined codes → (gid [n] in [0, K), K, first_idx [K]) with gid
    order following code order."""
    dense = _dense_codes(codes, None)
    if dense is not None:
        gid = dense[0] - 1  # no nulls at this stage; drop the reserved 0
        k = dense[1] - 1
    else:
        uniq, gid = np.unique(codes, return_inverse=True)
        gid = gid.reshape(-1).astype(np.int64)
        k = len(uniq)
    # Any representative row per group works (the key values are equal);
    # a vectorized last-write gives one without a sort.
    rep = np.empty(k, dtype=np.int64)
    rep[gid] = np.arange(len(gid), dtype=np.int64)
    return gid, k, rep


def group_ids(table: ColumnTable, group_by: list[str]):
    """Host factorization of the group-key tuples. Returns
    (gid [n] int64, K, first_idx [K] — a representative row per group).
    O(n) for integer/dict/date keys of reasonable range (the common
    case: join keys, flags); np.unique fallback otherwise."""
    n = table.num_rows
    if not group_by:
        return np.zeros(n, np.int64), 1, np.zeros(1 if n else 0, np.int64)
    if len(group_by) == 1 and n:
        # Dictionary-coded string group column with no nulls: the codes
        # already ARE compact ranks in value order (the dictionary is
        # sorted) — one bincount decides whether any dictionary entry is
        # unused, and the whole multi-pass rank machinery collapses to at
        # most one small-table gather (at SF100 this was ~40% of the
        # fused join-aggregate's wall on BOTH venues).
        f = table.schema.field(group_by[0])
        if f.is_string and table.valid_mask(group_by[0]) is None:
            codes = np.asarray(table.columns[f.name])
            k_dict = len(table.dictionaries[f.name])
            if k_dict:
                cnt = np.bincount(codes, minlength=k_dict)
                used = cnt > 0
                if used.all():
                    gid = codes.astype(np.int64, copy=False)
                    k = k_dict
                else:
                    lookup = np.cumsum(used, dtype=np.int64) - 1
                    gid = lookup[codes]
                    k = int(used.sum())
                rep = np.empty(k, dtype=np.int64)
                rep[gid] = np.arange(n, dtype=np.int64)
                return gid, k, rep
    codes0, card0 = _column_codes(table, group_by[0])
    combined = codes0
    total = card0
    for c in group_by[1:]:
        codes, card = _column_codes(table, c)
        if total * card >= np.iinfo(np.int64).max:
            raise HyperspaceError(
                f"group-by key cardinalities overflow the int64 code space"
            )
        combined = combined * np.int64(card) + codes
        total *= card
    return _compress(combined)


def _case_input(table: ColumnTable, e) -> tuple[np.ndarray, np.ndarray | None]:
    """CASE WHEN inside an aggregate: conditions evaluate with FULL
    predicate semantics (string literals, 3-valued nulls — a null
    condition does not take its branch) via the filter mask machinery;
    value legs are numeric. Validity follows the branch actually taken."""
    from hyperspace_tpu.ops.filter import eval_predicate_mask

    out, valid = _expr_input(table, e.default)
    out = _full(np.asarray(out, dtype=np.float64), table.num_rows)
    for cond, val in reversed(e.branches):
        m = eval_predicate_mask(table, cond)
        v, vvalid = _expr_input(table, val)
        v = _full(np.asarray(v, dtype=np.float64), table.num_rows)
        out = np.where(m, v, out)
        if valid is not None or vvalid is not None:
            va = np.ones(table.num_rows, bool) if valid is None else valid
            vb = np.ones(table.num_rows, bool) if vvalid is None else vvalid
            valid = np.where(m, vb, va)
    return out, valid


def _full(vals: np.ndarray, n: int) -> np.ndarray:
    return np.full(n, vals) if vals.ndim == 0 else vals


def _expr_input(table: ColumnTable, e) -> tuple[np.ndarray, np.ndarray | None]:
    """Recursive (values, validity) for an aggregate expression. Case
    nodes keep their branch-following validity ANYWHERE in the tree (a
    null condition takes the ELSE leg, it does not poison the row);
    everything else ANDs the validity of what it actually reads. Values
    may be 0-d (literals) until the caller broadcasts."""
    from hyperspace_tpu.plan.expr import Case, Lit as _Lit

    if isinstance(e, Case):
        return _case_input(table, e)
    if isinstance(e, Col):
        f = table.schema.field(e.name)
        if f.is_string:
            raise HyperspaceError(f"aggregate expression over string column {f.name!r}")
        return table.columns[f.name], table.valid_mask(e.name)
    if isinstance(e, _Lit):
        return np.asarray(e.value), None
    from hyperspace_tpu.plan.expr import DatePart as _DatePart
    from hyperspace_tpu.plan.expr import eval_date_part

    if isinstance(e, _DatePart):
        vals, valid = _expr_input(table, e.child)
        return eval_date_part(e.part, _full(np.asarray(vals), table.num_rows), np), valid
    from hyperspace_tpu.plan.expr import BinOp as _BinOp

    if isinstance(e, _BinOp):
        a, av = _expr_input(table, e.left)
        b, bv = _expr_input(table, e.right)
        vals = np.asarray(
            evaluate(
                _BinOp(e.op, Col("__a__"), Col("__b__")),
                lambda name: a if name == "__a__" else b,
                np,
            )
        )
        if av is None:
            valid = bv
        elif bv is None:
            valid = av
        else:
            valid = av & bv
        return vals, valid
    from hyperspace_tpu.plan.expr import MathFn as _MathFn

    if isinstance(e, _MathFn):
        vals, valid = _expr_input(table, e.child)
        out = evaluate(
            _MathFn(e.fn, Col("__a__")), lambda name: np.asarray(vals), np
        )
        return np.asarray(out), valid
    raise HyperspaceError(f"cannot aggregate over expression {type(e).__name__}")


def _numeric_input(table: ColumnTable, e) -> tuple[np.ndarray, np.ndarray | None]:
    """Full-length numeric (values, validity) for an aggregate expression."""
    vals, valid = _expr_input(table, e)
    return _full(vals, table.num_rows), valid


def agg_input(table: ColumnTable, spec) -> tuple[np.ndarray, np.ndarray | None, bool]:
    """(values, valid mask or None, is_string_codes) for one AggSpec."""
    from hyperspace_tpu.plan.expr import Case

    if spec.expr is None:  # count(*)
        return np.ones(table.num_rows, np.int64), None, False
    if isinstance(spec.expr, Case):
        vals, valid = _case_input(table, spec.expr)
        return vals, valid, False
    if isinstance(spec.expr, Col):
        f = table.schema.field(spec.expr.name)
        valid = table.valid_mask(spec.expr.name)
        if f.is_string:
            if spec.fn not in ("min", "max", "count"):
                raise HyperspaceError(f"{spec.fn} over string column {f.name!r}")
            return table.columns[f.name], valid, True
        return table.columns[f.name], valid, False
    vals, valid = _numeric_input(table, spec.expr)
    return vals, valid, False


def aggregate_arrays_host(
    inputs: list[tuple[np.ndarray, np.ndarray | None, str]],
    gid: np.ndarray,
    num_groups: int,
):
    """Host (numpy) venue of the segment reduce: bincount sums and
    sorted-reduceat min/max in exact float64. The inputs are host-resident
    and the [A, K] result is tiny, so on slow-transfer deployments (or
    chips without native f64) this beats uploading every channel to the
    device; semantics are pinned identical to aggregate_arrays."""
    n = len(gid)
    order = None
    group_rows = np.bincount(gid, minlength=num_groups).astype(np.int64)
    results: list[np.ndarray] = []
    counts: list[np.ndarray] = []
    for vals, valid, fn in inputs:
        v = np.asarray(vals, dtype=np.float64)
        if fn == "sum":
            if valid is not None:
                v = np.where(valid, v, 0.0)
            res = np.bincount(gid, weights=v, minlength=num_groups)
        else:
            identity = np.inf if fn == "min" else -np.inf
            if order is None:
                order = np.argsort(gid, kind="stable")
                starts = np.searchsorted(gid[order], np.arange(num_groups))
            sv = v[order]
            if valid is not None:
                sv = np.where(valid[order], sv, identity)
            nonempty = group_rows > 0
            res = np.full(num_groups, identity)
            if n and nonempty.any():
                op = np.minimum if fn == "min" else np.maximum
                # reduceat only over NON-EMPTY groups: an empty group's
                # start equals the next group's, so including it (or
                # clamping start == n) would shrink a neighbour's segment.
                # A non-empty group's segment runs to the next listed
                # start, which is exactly its true end.
                res[nonempty] = op.reduceat(sv, starts[nonempty])
        cnt = (
            group_rows.astype(np.float64)
            if valid is None
            else np.bincount(gid, weights=valid.astype(np.float64), minlength=num_groups)
        )
        results.append(res)
        counts.append(cnt)
    return np.stack(results), np.stack(counts)


def aggregate_arrays(
    inputs: list[tuple[np.ndarray, np.ndarray | None, str]],
    gid: np.ndarray,
    num_groups: int,
    venue: str = "device",
    mesh=None,
    fused: str = "off",
    exact_sums: list | None = None,
):
    """Segment-reduce of (values, valid, fn) triples sharing group
    ids. fn ∈ sum/min/max (count/mean are composed by the caller).
    Returns (results [A, K] float64-ish np arrays, counts [A, K]).
    With a multi-device mesh the row dimension shards across devices
    (partial reduce + one collective per channel).

    `fused` = "auto" engages the fused Pallas segment reduce when the
    shape is eligible AND byte-identity with the host reference is
    provable; `exact_sums` carries the per-input integral-sum proof
    (computed once in the cached channel prep — None means unproven,
    which keeps the lax path). Channel staging and uploads route
    through the identity caches for stable inputs."""
    if not inputs:  # DISTINCT: group keys only, nothing to reduce
        return np.zeros((0, num_groups)), np.zeros((0, num_groups))
    if venue == "host":
        return aggregate_arrays_host(inputs, gid, num_groups)
    from hyperspace_tpu.execution import device_cache as dcache
    from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

    d = mesh_size(mesh) if mesh is not None else 1
    n = len(gid)
    n_pad = _pow2(max(n, 1))
    if d > 1 and n_pad % d:
        n_pad = ((n_pad + d - 1) // d) * d
    k_seg = _pow2(num_groups + 1)  # +1 dead segment for pads

    def build_gid_pad() -> np.ndarray:
        g = np.full(n_pad, num_groups, np.int32)
        g[:n] = gid
        return g

    if dcache.is_stable(gid):
        gid_p = dcache.derived(
            ("gidpad1", id(gid), n_pad, num_groups), (gid,), build_gid_pad
        )
    else:
        gid_p = build_gid_pad()

    fns: list[str] = []
    chan_exact: list[bool] = []
    for i, (_vals, _valid, fn) in enumerate(inputs):
        fns.append(fn)
        chan_exact.append(
            True if fn in ("min", "max")
            else bool(exact_sums[i]) if exact_sums is not None else False
        )
        fns.append("sum")  # the per-input non-null count channel
        chan_exact.append(True)  # 0/1 indicators: exact in any order

    def build_channels() -> np.ndarray:
        vals_list: list[np.ndarray] = []
        for vals, valid, fn in inputs:
            v = np.asarray(vals, dtype=np.float64)
            if fn == "sum":
                if valid is not None:
                    v = np.where(valid, v, 0.0)
            elif fn == "min":
                v = np.where(valid, v, np.inf) if valid is not None else v
            elif fn == "max":
                v = np.where(valid, v, -np.inf) if valid is not None else v
            vals_list.append(np.pad(v, (0, n_pad - n)) if fn == "sum" else _pad_const(v, n_pad, fn))
            # Every input also gets a non-null count (for mean/null results).
            cnt = np.ones(n, np.float64) if valid is None else valid.astype(np.float64)
            vals_list.append(np.pad(cnt, (0, n_pad - n)))
        return np.stack(vals_list)

    stable = dcache.is_stable(gid) and all(
        dcache.is_stable(v) and (m is None or dcache.is_stable(m))
        for v, m, _fn in inputs
    )
    if stable:
        ids = tuple((id(v), id(m) if m is not None else None) for v, m, _fn in inputs)
        refs = tuple(
            a for v, m, _fn in inputs for a in ((v, m) if m is not None else (v,))
        )
        stacked = dcache.derived(
            ("aggstack", ids, tuple(fns), n_pad), refs, build_channels
        )
    else:
        stacked = build_channels()
    # 53-bit accumulation on the persistent x64 worker thread — the
    # process-wide flag is never touched (round 1 weakness #8).
    from hyperspace_tpu.parallel.x64 import run_x64

    out = None
    if d == 1 and fused == "auto":
        out = _try_pallas_reduce(stacked, gid_p, k_seg, tuple(fns), chan_exact, n_pad)
    if out is None:
        if fused == "auto":
            stats.increment("device.kernel.fallbacks")
        if d > 1:
            reduce_fn = _make_sharded_segment_reduce(mesh, mesh_axes(mesh), k_seg, tuple(fns))
            out = np.asarray(
                run_x64(
                    lambda: jax.device_get(reduce_fn(jnp.asarray(stacked), jnp.asarray(gid_p)))
                )
            )
        else:
            reduce_fn = functools.partial(
                _segment_reduce_many, num_segments=k_seg, fns=tuple(fns)
            )
            # Stable stacks/pads serve the upload from the HBM cache on
            # repeat queries — the staging tax is paid once per version.
            out = np.asarray(
                run_x64(
                    lambda: jax.device_get(
                        reduce_fn(
                            dcache.device_put_cached(stacked),
                            dcache.device_put_cached(gid_p),
                        )
                    )
                )
            )
    out = out[:, :num_groups]
    results = out[0::2]
    counts = out[1::2]
    return results, counts


def _try_pallas_reduce(
    stacked: np.ndarray, gid_p: np.ndarray, k_seg: int, fns: tuple,
    chan_exact: list, n_pad: int,
):
    """One fused Pallas launch for ALL channels, or None when ineligible
    (shape, unprovable exactness, prior lowering failure, interpret-work
    bound) or when lowering fails (recorded, permanent fallback)."""
    from hyperspace_tpu.execution import device_cache as dcache
    from hyperspace_tpu.parallel.x64 import run_x64

    k_pad = max(k_seg, 128)  # lane-width floor for the TPU lowering
    if k_pad > _PALLAS_MAX_SEGMENTS or not all(chan_exact):
        return None
    tile = min(_PALLAS_SEG_TILE, n_pad)
    interpret = jax.default_backend() == "cpu"
    if interpret and n_pad * k_pad > _PALLAS_INTERPRET_WORK:
        return None
    with _pallas_agg_bad_lock:
        if (fns, k_pad, tile) in _pallas_agg_bad:
            return None

    def build_gid2d() -> np.ndarray:
        return np.ascontiguousarray(gid_p.reshape(1, n_pad))

    if dcache.is_stable(gid_p):
        gid2d = dcache.derived(("gid2d", id(gid_p)), (gid_p,), build_gid2d)
    else:
        gid2d = build_gid2d()
    try:
        run = _make_pallas_segment_reduce(fns, k_pad, tile, interpret)
        with obs_trace.span(
            "device.kernel", kernel="pallas-segment-reduce",
            channels=len(fns), segments=k_pad,
        ):
            out = np.asarray(
                run_x64(
                    lambda: jax.device_get(
                        run(
                            dcache.device_put_cached(gid2d),
                            dcache.device_put_cached(stacked),
                        )
                    )
                )
            )
    except Exception:  # noqa: BLE001 — fall back to the lax path
        with _pallas_agg_bad_lock:
            _pallas_agg_bad.add((fns, k_pad, tile))
        return None
    stats.increment("device.kernel.fused")
    return out


def _pad_const(v: np.ndarray, n_pad: int, fn: str) -> np.ndarray:
    fill = np.inf if fn == "min" else -np.inf
    out = np.full(n_pad, fill, np.float64)
    out[: len(v)] = v
    return out


def finalize_agg_values(vals: np.ndarray, empty: np.ndarray, dtype) -> np.ndarray:
    """Per-group aggregate values → output column. Float outputs keep
    legitimately non-finite results (NaN inputs, overflowing sums —
    Spark/the reference return NaN/Infinity here); only empty (all-NULL)
    groups are zero-backed, and their validity mask marks them NULL.
    Integer outputs coerce non-finite before the cast (undefined
    otherwise; such values only arise for empty groups anyway)."""
    if np.dtype(dtype).kind == "f":
        safe = np.where(empty, 0, vals)
    else:
        safe = np.where(empty, 0, np.where(np.isfinite(vals), vals, 0))
    return safe.astype(dtype)


def _spec_identity(table: ColumnTable, spec):
    """(refs, id-parts) over every array one AggSpec reads — the
    identity key of its prepared channels. (None, None) when any input
    is unstable (per-query table: nothing to memoize against)."""
    from hyperspace_tpu.execution import device_cache as dc

    names = sorted({r.lower() for r in spec.references()}) if spec.expr is not None else []
    refs: list = []
    parts: list = []
    for nm in names:
        f = table.schema.field(nm)
        for a in (table.columns[f.name], table.dictionaries.get(f.name), table.validity.get(f.name)):
            if a is None:
                parts.append(None)
                continue
            if not dc.is_stable(a):
                return None, None
            refs.append(a)
            parts.append(id(a))
    return tuple(refs), tuple(parts)


def _sum_exactness(vals) -> bool:
    """True when a sum channel's values are provably order-independent
    in float64: finite, integral, absolute total below 2^52 — every
    partial sum is then exactly representable, so ANY reduction order
    (the fused kernel's tile sums included) yields the host reference's
    bits."""
    v = np.asarray(vals, dtype=np.float64)
    if not len(v):
        return True
    with np.errstate(all="ignore"):
        if not bool(np.isfinite(v).all()):
            return False
        if not bool((v == np.trunc(v)).all()):
            return False
        return float(np.abs(v).sum()) < _EXACT_SUM_BOUND


def prepared_agg_input(table: ColumnTable, spec):
    """(vals, valid, fn, exact) channels for one AggSpec — the masked
    value array, its validity, the reduce fn, and the fused-kernel
    exactness proof — memoized per (expression, input identity) for
    stable tables so repeat queries skip the channel prep entirely."""
    import json

    from hyperspace_tpu.execution import device_cache as dc

    def build_raw():
        vals, valid, _is_str = agg_input(table, spec)
        fn = {"count": "sum", "mean": "sum"}.get(spec.fn, spec.fn)
        if spec.fn == "count":
            vals = np.ones(table.num_rows, np.float64) if valid is None else valid.astype(np.float64)
            valid = None
            exact = True  # 0/1 indicators sum exactly in any order
        elif fn == "sum":
            exact = _sum_exactness(vals)
        else:
            exact = True  # extrema are order-independent
        return vals, valid, fn, exact

    refs, parts = _spec_identity(table, spec)
    if refs is None:
        return build_raw()
    if spec.expr is None:
        # count(*): the channel depends only on the row count.
        key = ("aggprep", "count_star", table.num_rows)
    else:
        key = (
            "aggprep",
            spec.fn,
            json.dumps(spec.expr.to_json(), sort_keys=True),
            table.num_rows,
            parts,
        )

    def build():
        vals, valid, fn, exact = build_raw()
        vals = dc.freeze(np.asarray(vals))
        if valid is not None:
            valid = dc.freeze(np.asarray(valid))
        nbytes = int(vals.nbytes) + (int(valid.nbytes) if valid is not None else 0)
        return (vals, valid, fn, exact), nbytes

    return dc.HOST_DERIVED.get_or_build(key, refs, build)


def aggregate_table(
    table: ColumnTable, group_by: list[str], aggs: list, out_schema: Schema,
    venue: str = "device",
    mesh=None,
    groups: tuple | None = None,
    fused: str = "off",
) -> ColumnTable:
    """Execute a grouped aggregation over a materialized table.
    `groups` optionally passes a precomputed (gid, K, first_idx)
    factorization so callers sharing one key layout across several
    aggregations (distinct expansion, grouping sets) don't re-factorize.
    `fused` gates the fused Pallas segment reduce (see aggregate_arrays)."""
    gid, k, first_idx = groups if groups is not None else group_ids(table, group_by)

    inputs = []
    exact_sums: list[bool] = []
    string_dicts: dict[int, np.ndarray] = {}
    for i, spec in enumerate(aggs):
        if isinstance(spec.expr, Col):
            f = table.schema.field(spec.expr.name)
            if f.is_string:
                string_dicts[i] = table.dictionaries[f.name]
        vals, valid, fn, exact = prepared_agg_input(table, spec)
        inputs.append((vals, valid, fn))
        exact_sums.append(exact)

    if k == 0:
        return ColumnTable.empty(out_schema)
    results, counts = aggregate_arrays(
        inputs, gid, k, venue=venue, mesh=mesh, fused=fused, exact_sums=exact_sums
    )

    cols: dict[str, np.ndarray] = {}
    dicts: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for c in group_by:
        f = table.schema.field(c)
        out_f = out_schema.field(c)
        cols[out_f.name] = table.columns[f.name][first_idx]
        if f.name in table.dictionaries:
            dicts[out_f.name] = table.dictionaries[f.name]
        gv = table.valid_mask(c)
        if gv is not None:
            validity[out_f.name] = gv[first_idx]
    for i, spec in enumerate(aggs):
        out_f = out_schema.field(spec.alias)
        res, cnt = results[i], counts[i]
        if spec.fn == "count":
            cols[out_f.name] = res.astype(np.int64)
            continue
        if spec.fn == "mean":
            with np.errstate(invalid="ignore", divide="ignore"):
                vals = res / cnt
        else:
            vals = res
        empty = cnt == 0  # all inputs null ⇒ NULL result
        if i in string_dicts:
            codes = np.where(empty, 0, vals).astype(np.int32)
            cols[out_f.name] = codes
            dicts[out_f.name] = string_dicts[i]
        else:
            cols[out_f.name] = finalize_agg_values(vals, empty, out_f.device_dtype)
        if empty.any():
            validity[out_f.name] = ~empty
    return ColumnTable(out_schema, cols, dicts, validity)
