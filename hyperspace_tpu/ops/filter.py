"""Device-side predicate evaluation.

The analog of Spark's WholeStageCodegen'd filter/project over the index scan
(SURVEY.md §2.2): the whole predicate tree evaluates as ONE jitted XLA
computation over the columns — XLA fuses the comparisons/boolean algebra
into a single pass over HBM, which is the TPU equivalent of the JVM's fused
codegen operator.

Device compute stays 32-bit native (TPU lanes are 32-bit; the process-wide
`jax_enable_x64` flag is never touched). 64-bit columns are handled by
*pairing*: each comparison against an int64/float64 column is lowered to an
equivalent boolean expression over two virtual uint32 columns — the hi/lo
words of an order-preserving 64-bit key (sign-flipped for ints, IEEE
total-order mapped for floats) — with the literal split the same way on
host. Comparisons XLA can't express this way (64-bit arithmetic, exotic
mixed-type shapes) fall back to one vectorized numpy evaluation on host.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Col,
    DatePart,
    Expr,
    InList,
    IsNull,
    Like,
    Lit,
    Not,
    Or,
    Substr,
    evaluate,
)

# Virtual-column name pieces for the 64-bit pair lowering. "\x00" cannot
# appear in a real column name, so these never collide with the schema.
_SEP = "\x00"
_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}


class _HostFallback(Exception):
    """Raised by the lowering pass when the predicate needs host numpy."""


@dataclasses.dataclass(eq=False, repr=True)
class _Cmp3(Expr):
    """A 3-valued comparison: `value` is the device boolean expression,
    `null` (optional) an expression over virtual is-null columns — when it
    is true the comparison's outcome is UNKNOWN (SQL semantics: any
    comparison with NULL is neither true nor false)."""

    value: Expr
    null: Expr | None

    def references(self):
        refs = self.value.references()
        return refs | self.null.references() if self.null is not None else refs


def _null_expr(table: ColumnTable, names: list[str]) -> Expr | None:
    """OR of is-null virtual columns for the given base columns (only those
    that actually carry validity masks); None when none do."""
    out: Expr | None = None
    for name in names:
        if table.valid_mask(name) is None:
            continue
        c = Col(f"{table.schema.field(name).name}{_SEP}nul")
        out = c if out is None else Or(out, c)
    return out


def _or_chain(parts: list[Expr]) -> Expr:
    """BALANCED disjunction (depth log2 n): a left-deep chain overflows
    every recursive walker past a few hundred terms."""
    if len(parts) == 1:
        return parts[0]
    mid = len(parts) // 2
    return Or(_or_chain(parts[:mid]), _or_chain(parts[mid:]))


# Above this many runs the desugared comparison tree stops being a win
# (hundreds of fused comparisons per row); a code->bool lookup table is
# one gather instead.
_MAX_CODE_RUNS = 64


@dataclasses.dataclass(eq=False, repr=True)
class _DictLut(Expr):
    """Internal leaf: boolean lookup over a string column's dictionary
    codes (lut[code]); produced by translate_predicate when a LIKE/IN
    match set is too scattered for range desugaring. Never serialized —
    it exists only between translation and evaluation."""

    col: Col
    lut: "np.ndarray"  # bool, [dictionary size]

    def references(self):
        return self.col.references()


@dataclasses.dataclass(eq=False, repr=True)
class _StrColCmp(Expr):
    """Internal leaf: comparison between two STRING-VALUED sides (columns
    or substrings of columns) whose dictionaries differ. Each side's codes
    map through `lmap`/`rmap` into one MERGED sorted dictionary, where
    integer comparison equals string comparison. Raw code comparison
    across two dictionaries is meaningless — this leaf is what
    translate_predicate rewrites it into. Host-evaluated (the lowering
    pass falls back)."""

    op: str
    left: Col
    right: Col
    lmap: "np.ndarray"  # [left dict size] int32 positions in the merged dict
    rmap: "np.ndarray"

    def references(self):
        return self.left.references() | self.right.references()


def _string_valued(table: ColumnTable, e: Expr):
    """(column name, per-code string values) when `e` is a string column
    or SUBSTRING of one; None otherwise."""
    if isinstance(e, Col):
        try:
            f = table.schema.field(e.name)
        except Exception:
            return None
        if f.is_string:
            return f.name, np.asarray(table.dictionaries[f.name], dtype=object)
        return None
    if isinstance(e, Substr) and isinstance(e.child, Col):
        f = table.schema.field(e.child.name)
        if f.is_string:
            name, vals = _substr_values(table, e)
            return name, np.asarray(vals, dtype=object)
    return None


def _codes_runs_expr(col: Col, codes: "np.ndarray", dict_size: int) -> Expr:
    """Matched dictionary codes (sorted int array) → the equivalent
    predicate in the code domain: an OR of contiguous code ranges (a
    prefix LIKE over a SORTED dictionary is always ONE range), or a
    dictionary lookup table when the match set is scattered (NOT LIKE
    over near-unique comments). All forms are device-lowerable and
    null-aware via the normal _Cmp3 machinery."""
    if len(codes) == 0:
        # No dictionary value matches: always-false but still UNKNOWN for
        # null inputs (-1 is never a real code).
        return BinOp("eq", col, Lit(np.int32(-1)))
    codes = np.asarray(codes, dtype=np.int64)
    breaks = np.flatnonzero(np.diff(codes) > 1)
    if len(breaks) + 1 > _MAX_CODE_RUNS:
        lut = np.zeros(dict_size, dtype=bool)
        lut[codes] = True
        return _DictLut(col, lut)
    starts = np.concatenate([[0], breaks + 1])
    ends = np.concatenate([breaks, [len(codes) - 1]])
    parts: list[Expr] = []
    for s, t in zip(starts, ends):
        a, b = int(codes[s]), int(codes[t])
        if a == b:
            parts.append(BinOp("eq", col, Lit(np.int32(a))))
        else:
            parts.append(
                And(BinOp("ge", col, Lit(np.int32(a))), BinOp("le", col, Lit(np.int32(b))))
            )
    return _or_chain(parts)


def like_regex(pattern: str):
    """Compiled regex for a SQL LIKE pattern (% = any run, _ = one char)."""
    import re

    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("".join(out), re.DOTALL)


def _like_codes(table: ColumnTable, colname: str, pattern: str) -> "np.ndarray":
    f = table.schema.field(colname)
    if not f.is_string:
        from hyperspace_tpu.exceptions import HyperspaceError

        raise HyperspaceError(f"LIKE requires a string column, got {colname!r}")
    rx = like_regex(pattern)
    d = table.dictionaries[f.name]
    return np.flatnonzero([rx.fullmatch(str(s)) is not None for s in d])


def _substr_values(table: ColumnTable, sub: Substr) -> tuple[str, "np.ndarray"]:
    """(column name, per-dictionary-entry substring values)."""
    from hyperspace_tpu.exceptions import HyperspaceError

    if not isinstance(sub.child, Col):
        raise HyperspaceError("SUBSTRING applies to a column")
    f = table.schema.field(sub.child.name)
    if not f.is_string:
        raise HyperspaceError(f"SUBSTRING requires a string column, got {sub.child.name!r}")
    lo = sub.start - 1
    d = table.dictionaries[f.name]
    return f.name, np.array([str(s)[lo : lo + sub.length] for s in d], dtype=object)


_NP_CMP = {"eq": "__eq__", "ne": "__ne__", "lt": "__lt__", "le": "__le__", "gt": "__gt__", "ge": "__ge__"}


def translate_predicate(table: ColumnTable, e: Expr) -> Expr:
    """Rewrite string-column comparisons against literals into the code
    domain of `table`'s dictionaries (order-preserving), and desugar the
    SQL predicate extensions — IN, LIKE, SUBSTRING comparisons, date-part
    comparisons — into plain comparison trees the device lowering and the
    host fallback both evaluate. Pure — returns a new tree, never mutates
    the plan's predicate."""
    if isinstance(e, BinOp) and e.is_comparison:
        l, r = e.left, e.right
        ls, rs = _string_valued(table, l), _string_valued(table, r)
        if ls is not None and rs is not None:
            # String-valued vs string-valued: codes from two different
            # dictionaries must NOT compare directly — remap both into
            # one merged sorted dictionary first (q19/q46's
            # city/zip-prefix inequality shapes).
            lname, lvals = ls
            rname, rvals = rs
            ls_str = lvals.astype(str)
            rs_str = rvals.astype(str)
            merged = np.unique(np.concatenate([ls_str, rs_str]))
            lmap = np.searchsorted(merged, ls_str).astype(np.int32)
            rmap = np.searchsorted(merged, rs_str).astype(np.int32)
            return _StrColCmp(e.op, Col(lname), Col(rname), lmap, rmap)
        if (ls is None) != (rs is None):
            other = r if ls is not None else l
            if not isinstance(other, Lit):
                from hyperspace_tpu.exceptions import HyperspaceError

                raise HyperspaceError(
                    "cannot compare a string column with a non-string expression"
                )
        if isinstance(r, (Substr, DatePart)) and isinstance(l, Lit):
            l, r = r, l
            e = BinOp(_FLIP[e.op], l, r)
        if isinstance(l, Substr) and isinstance(r, Lit):
            name, vals = _substr_values(table, l)
            cmp = getattr(vals.astype(str), _NP_CMP[e.op])
            codes = np.flatnonzero(cmp(str(r.value)))
            return _codes_runs_expr(Col(name), codes, len(vals))
        if isinstance(l, DatePart) and isinstance(r, Lit):
            t = _translate_date_part_cmp(e.op, l, r.value)
            if t is not None:
                return t
            return e  # month/day shapes: host evaluation
        if isinstance(l, Col) and isinstance(r, Lit) and table.schema.field(l.name).is_string:
            return BinOp(e.op, l, Lit(table.translate_literal(l.name, r.value, e.op)))
        if isinstance(r, Col) and isinstance(l, Lit) and table.schema.field(r.name).is_string:
            return translate_predicate(table, BinOp(_FLIP[e.op], r, l))
        return e
    if isinstance(e, InList):
        child = e.child
        if isinstance(child, Substr):
            name, vals = _substr_values(table, child)
            want = {str(v) for v in e.values}
            codes = np.flatnonzero([v in want for v in vals])
            return _codes_runs_expr(Col(name), codes, len(vals))
        if isinstance(child, Col):
            if table.schema.field(child.name).is_string:
                codes = []
                d = table.dictionaries[table.schema.field(child.name).name]
                for v in e.values:
                    pos = int(np.searchsorted(d, v))
                    if pos < len(d) and d[pos] == v:
                        codes.append(pos)
                return _codes_runs_expr(
                    child, np.sort(np.unique(codes)) if codes else np.array([]), len(d)
                )
            return _or_chain([BinOp("eq", child, Lit(v)) for v in e.values])
        return e  # DatePart / arithmetic probes: host evaluation
    if isinstance(e, Like):
        from hyperspace_tpu.exceptions import HyperspaceError

        if not isinstance(e.child, Col):
            raise HyperspaceError("LIKE applies to a column")
        f = table.schema.field(e.child.name)
        return _codes_runs_expr(
            Col(f.name),
            _like_codes(table, e.child.name, e.pattern),
            len(table.dictionaries[f.name]),
        )
    if isinstance(e, And):
        return And(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Or):
        return Or(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Not):
        return Not(translate_predicate(table, e.child))
    return e


def _days(y: int, m: int, d: int) -> int:
    import datetime

    return (datetime.date(y, m, d) - datetime.date(1970, 1, 1)).days


def _translate_date_part_cmp(op: str, dp: DatePart, value) -> Expr | None:
    """year(col) OP literal → the equivalent day-range comparison on the
    raw date column (device-lowerable; feeds min/max range pruning).
    month/day parts are not interval-shaped over days — return None."""
    if dp.part != "year" or not isinstance(dp.child, Col):
        return None
    if isinstance(value, (bool, np.bool_)) or not isinstance(value, (int, np.integer)):
        return None
    col = dp.child
    y = int(value)
    if y < 1 or y > 9998:  # keep datetime.date in range
        return None
    first, next_first = _days(y, 1, 1), _days(y + 1, 1, 1)
    if op == "eq":
        return And(BinOp("ge", col, Lit(first)), BinOp("lt", col, Lit(next_first)))
    if op == "ne":
        return Or(BinOp("lt", col, Lit(first)), BinOp("ge", col, Lit(next_first)))
    if op == "lt":
        return BinOp("lt", col, Lit(first))
    if op == "le":
        return BinOp("lt", col, Lit(next_first))
    if op == "ge":
        return BinOp("ge", col, Lit(first))
    if op == "gt":
        return BinOp("ge", col, Lit(next_first))
    return None


# -- 64-bit pair lowering ----------------------------------------------------

def _col_kind(table: ColumnTable, name: str) -> tuple[str, int]:
    """('i'|'f'|'b', byte width) of a column's device array."""
    f = table.schema.field(name)
    dt = np.dtype(f.device_dtype)
    if dt == np.bool_:
        return "b", 1
    return ("f" if dt.kind == "f" else "i"), dt.itemsize


def _ordered_u64(arr: np.ndarray, domain: str) -> np.ndarray:
    """Map a column to uint64 keys whose unsigned order equals the value
    order of `domain` ('i' = int64 order, 'f' = float64 total order with
    -0.0 canonicalized and NaN above +inf)."""
    if domain == "i":
        a = arr.astype(np.int64, copy=False)
        return a.view(np.uint64) ^ np.uint64(1 << 63)
    a = arr.astype(np.float64, copy=False)
    a = np.where(a == 0.0, 0.0, a)  # -0.0 → +0.0 so == matches IEEE
    a = np.where(np.isnan(a), np.nan, a)  # negative NaNs → canonical NaN,
    # so EVERY NaN keys above +inf and the guards catch them uniformly
    u = a.view(np.uint64)
    neg = (u >> np.uint64(63)).astype(bool)
    return np.where(neg, ~u, u | np.uint64(1 << 63))


def _key_parts(value: float | int, domain: str) -> tuple[np.uint32, np.uint32] | None:
    """hi/lo uint32 words of one literal's ordered key (None = NaN)."""
    if domain == "f":
        v = np.float64(value)
        if np.isnan(v):
            return None
        u = int(_ordered_u64(np.array([v]), "f")[0])
    else:
        u = int(_ordered_u64(np.array([int(value)], dtype=np.int64), "i")[0])
    return np.uint32(u >> 32), np.uint32(u & 0xFFFFFFFF)


def _pair_cols(name: str, domain: str) -> tuple[Col, Col]:
    return Col(f"{name}{_SEP}{domain}hi"), Col(f"{name}{_SEP}{domain}lo")


def _pair_cmp(op: str, hi, lo, hi2, lo2) -> Expr:
    """Lexicographic (hi, lo) comparison as a boolean expression. Operands
    are Col/Lit exprs over uint32 values."""
    if op == "eq":
        return And(BinOp("eq", hi, hi2), BinOp("eq", lo, lo2))
    if op == "ne":
        return Or(BinOp("ne", hi, hi2), BinOp("ne", lo, lo2))
    strict = {"lt": "lt", "le": "lt", "gt": "gt", "ge": "gt"}[op]
    inner = {"lt": "lt", "le": "le", "gt": "gt", "ge": "ge"}[op]
    return Or(
        BinOp(strict, hi, hi2),
        And(BinOp("eq", hi, hi2), BinOp(inner, lo, lo2)),
    )


_INT32_MIN, _INT32_MAX = np.iinfo(np.int32).min, np.iinfo(np.int32).max
_INT64_MIN, _INT64_MAX = np.iinfo(np.int64).min, np.iinfo(np.int64).max


def _normalize_int_literal(value, op: str):
    """Reduce a numeric literal compared against an INTEGER column to an
    int literal + op, or a constant bool when the comparison is decided.

    Returns ("const", bool) | ("cmp", op, int_value)."""
    if isinstance(value, (bool, np.bool_)):
        value = int(value)
    if isinstance(value, (float, np.floating)):
        f = float(value)
        if math.isnan(f):
            return ("const", op == "ne")
        if f == math.inf:
            return ("const", op in ("lt", "le", "ne"))
        if f == -math.inf:
            return ("const", op in ("gt", "ge", "ne"))
        if f == int(f):
            value = int(f)
        else:
            # x OP non-integral f over integers decides by floor/ceil.
            if op == "eq":
                return ("const", False)
            if op == "ne":
                return ("const", True)
            if op in ("lt", "le"):
                return ("cmp", "le", math.floor(f))
            return ("cmp", "ge", math.ceil(f))  # gt, ge
    v = int(value)
    if v > _INT64_MAX:
        return ("const", op in ("lt", "le", "ne"))
    if v < _INT64_MIN:
        return ("const", op in ("gt", "ge", "ne"))
    return ("cmp", op, v)


def _lower_col_lit(table: ColumnTable, op: str, colname: str, value) -> Expr:
    """Lower `col OP literal` to a device-safe expression."""
    kind, width = _col_kind(table, colname)
    if kind == "b":
        if isinstance(value, (bool, np.bool_)):
            return BinOp(op, Col(colname), Lit(np.bool_(value)))
        raise _HostFallback  # bool vs numeric literal: numpy int semantics
    if kind == "i":
        if isinstance(value, (float, np.floating)) and width > 4:
            # numpy compares int64 arrays with float scalars in float64,
            # ROUNDING the column above 2^53 — match it by comparing in the
            # float64 key domain (the pair prep casts the column the same
            # lossy way numpy does).
            return _float_domain_cmp(colname, op, value)
        norm = _normalize_int_literal(value, op)
        if norm[0] == "const":
            return Lit(np.bool_(norm[1]))
        _, op, v = norm
        if width <= 4:
            # int32 → float64 is exact, so floor/ceil normalization of a
            # float literal is equivalent to numpy's float64 comparison.
            if _INT32_MIN <= v <= _INT32_MAX:
                return BinOp(op, Col(colname), Lit(np.int32(v)))
            return Lit(np.bool_(op in ("lt", "le", "ne") if v > _INT32_MAX else op in ("gt", "ge", "ne")))
        hi, lo = _key_parts(v, "i")
        chi, clo = _pair_cols(colname, "i")
        return _pair_cmp(op, chi, clo, Lit(hi), Lit(lo))
    # float column
    if width <= 4:
        weak = type(value) in (int, float, bool) or isinstance(value, (np.bool_, np.float32))
        if weak:
            # numpy weak-scalar promotion (NEP 50): a python scalar against
            # a float32 array compares IN float32 — round the literal.
            return BinOp(op, Col(colname), Lit(np.float32(value)))
        # Strong 64-bit numpy scalar: numpy promotes to float64; widen the
        # column to the float64 pair domain (float32→float64 is exact).
    return _float_domain_cmp(colname, op, value)


def _float_domain_cmp(colname: str, op: str, value) -> Expr:
    """`col OP literal` in the float64 ordered-key pair domain."""
    parts = _key_parts(value, "f")
    if parts is None:  # NaN literal: IEEE says everything compares false
        return Lit(np.bool_(op == "ne"))
    hi, lo = parts
    chi, clo = _pair_cols(colname, "f")
    out = _pair_cmp(op, chi, clo, Lit(hi), Lit(lo))
    if op in ("gt", "ge"):
        # NaN keys sort above +inf; gt/ge must exclude them (IEEE: false).
        ihi, ilo = _key_parts(math.inf, "f")
        out = And(out, _pair_cmp("le", chi, clo, Lit(ihi), Lit(ilo)))
    return out


def _lower_col_col(table: ColumnTable, op: str, lname: str, rname: str) -> Expr:
    lkind, lwidth = _col_kind(table, lname)
    rkind, rwidth = _col_kind(table, rname)
    if lkind == "b" or rkind == "b":
        if lkind == rkind:
            return BinOp(op, Col(lname), Col(rname))
        raise _HostFallback
    if lwidth <= 4 and rwidth <= 4 and lkind == rkind:
        return BinOp(op, Col(lname), Col(rname))
    # Widen both sides into a shared ordered-key domain: int-int compares in
    # int64 order; anything involving a float compares in float64 order
    # (ints cast to float64 — numpy's promotion does the same).
    domain = "i" if (lkind == "i" and rkind == "i") else "f"
    lhi, llo = _pair_cols(lname, domain)
    rhi, rlo = _pair_cols(rname, domain)
    out = _pair_cmp(op, lhi, llo, rhi, rlo)
    if domain == "f":
        # NaN keys (any sign, canonicalized) sort above +inf; exclude them
        # on whichever side the op could leak through (IEEE: any comparison
        # with NaN is false, != is true).
        ihi, ilo = _key_parts(math.inf, "f")
        l_finite = _pair_cmp("le", lhi, llo, Lit(ihi), Lit(ilo))
        r_finite = _pair_cmp("le", rhi, rlo, Lit(ihi), Lit(ilo))
        if op in ("gt", "ge"):
            out = And(out, l_finite)
        elif op in ("lt", "le"):
            out = And(out, r_finite)
        elif op == "eq":  # NaN == NaN must be false despite equal keys
            out = And(out, l_finite)
        elif op == "ne":  # NaN != NaN must be true despite equal keys
            out = Or(out, Not(l_finite))
    return out


def _subtree_kinds(table: ColumnTable, e: Expr) -> set[str] | None:
    """Value kinds ('i'/'f'/'b') a non-comparison subtree touches, or None
    when it can't evaluate correctly in 32-bit device mode (64-bit columns,
    literals not 32-bit exact, or int division — numpy divides ints in
    float64, jnp in float32, so threshold comparisons could diverge)."""
    if isinstance(e, Col):
        kind, width = _col_kind(table, e.name)
        return {kind} if width <= 4 else None
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, (bool, np.bool_)):
            return {"b"}
        if isinstance(v, (int, np.integer)):
            return {"i"} if _INT32_MIN <= int(v) <= _INT32_MAX else None
        if isinstance(v, (float, np.floating)):
            ok = np.isnan(v) or float(np.float32(v)) == float(v)
            return {"f"} if ok else None
        return None
    if isinstance(e, BinOp):
        l = _subtree_kinds(table, e.left)
        r = _subtree_kinds(table, e.right)
        if l is None or r is None:
            return None
        kinds = l | r
        if len(kinds) > 1:
            # Mixed-kind arithmetic: numpy promotes int⊕float to float64,
            # the device would use float32 — lossy above 2^24. Host only.
            return None
        if e.op == "div" and kinds != {"f"}:
            return None  # int division: numpy float64, device float32
        return kinds
    return None


def _lower(table: ColumnTable, e: Expr) -> Expr:
    """Lower a (string-translated) predicate to a device-safe tree of
    And/Or/Not over _Cmp3 leaves (3-valued comparisons), raising
    _HostFallback where 32-bit device semantics can't match numpy."""
    if isinstance(e, And):
        return And(_lower(table, e.left), _lower(table, e.right))
    if isinstance(e, Or):
        return Or(_lower(table, e.left), _lower(table, e.right))
    if isinstance(e, Not):
        return Not(_lower(table, e.child))
    if isinstance(e, IsNull):
        # IS NULL is never UNKNOWN: it evaluates the validity lanes
        # directly (true where any referenced column is null).
        nul = _null_expr(table, sorted(e.references()))
        return _Cmp3(nul if nul is not None else Lit(np.bool_(False)), None)
    if isinstance(e, _DictLut):
        return _Cmp3(e, _null_expr(table, [e.col.name]))
    if isinstance(e, BinOp) and e.is_comparison:
        l, r = e.left, e.right
        if isinstance(l, Lit) and isinstance(r, Col):
            return _lower(table, BinOp(_FLIP[e.op], r, l))
        if isinstance(l, Col) and isinstance(r, Lit):
            value = _lower_col_lit(table, e.op, l.name, r.value)
            return _Cmp3(value, _null_expr(table, [l.name]))
        if isinstance(l, Col) and isinstance(r, Col):
            value = _lower_col_col(table, e.op, l.name, r.name)
            return _Cmp3(value, _null_expr(table, [l.name, r.name]))
        # Compound arithmetic sides: keep on device only when every piece
        # is exactly representable in 32-bit lanes AND both sides share one
        # value kind (mixed int/float comparisons promote to float64 under
        # numpy but float32 on device).
        lk = _subtree_kinds(table, l)
        rk = _subtree_kinds(table, r)
        if lk is not None and rk is not None and len(lk | rk) == 1:
            # A null in ANY input makes the whole comparison unknown.
            return _Cmp3(e, _null_expr(table, sorted(e.references())))
        raise _HostFallback
    if isinstance(e, Lit) and isinstance(e.value, (bool, np.bool_)):
        return _Cmp3(e, None)
    raise _HostFallback


# -- compiled evaluation ----------------------------------------------------

def _structure_key(e: Expr, lits: list) -> tuple:
    """Structural fingerprint of an expression with literals abstracted out
    (collected into `lits` in walk order). Predicates that differ only in
    literal values share one compiled evaluator."""
    if isinstance(e, _Cmp3):
        return (
            "cmp3",
            _structure_key(e.value, lits),
            _structure_key(e.null, lits) if e.null is not None else None,
        )
    if isinstance(e, _DictLut):
        # The lut enters as a traced array argument: same-structure
        # predicates over different dictionaries share the compiled fn.
        lits.append(e.lut)
        return ("dictlut", e.col.name.lower())
    if isinstance(e, Lit):
        lits.append(e.value)
        return ("lit",)
    if isinstance(e, Col):
        return ("col", e.name.lower())
    if isinstance(e, BinOp):
        return ("binop", e.op, _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, And):
        return ("and", _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, Or):
        return ("or", _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, Not):
        return ("not", _structure_key(e.child, lits))
    raise ValueError(f"cannot fingerprint {e!r}")


def _eval_with_args(e: Expr, cols: dict, lit_iter) -> object:
    """Evaluate against traced column arrays and traced literal scalars
    (consumed in the same walk order _structure_key used)."""
    if isinstance(e, Lit):
        return next(lit_iter)
    if isinstance(e, _DictLut):
        lut = next(lit_iter)
        return lut[cols[e.col.name.lower()]]
    if isinstance(e, Col):
        return cols[e.name.lower()]
    if isinstance(e, BinOp):
        a = _eval_with_args(e.left, cols, lit_iter)
        b = _eval_with_args(e.right, cols, lit_iter)
        return evaluate(BinOp(e.op, Lit(a), Lit(b)), None, jnp)
    if isinstance(e, And):
        return jnp.logical_and(_eval_with_args(e.left, cols, lit_iter), _eval_with_args(e.right, cols, lit_iter))
    if isinstance(e, Or):
        return jnp.logical_or(_eval_with_args(e.left, cols, lit_iter), _eval_with_args(e.right, cols, lit_iter))
    if isinstance(e, Not):
        return jnp.logical_not(_eval_with_args(e.child, cols, lit_iter))
    raise ValueError(f"cannot evaluate {e!r}")


def _eval3(e: Expr, cols: dict, lit_iter):
    """Kleene evaluation → (definitely-true, definitely-false) mask pair.
    Unknown = neither. This is how SQL's 3-valued logic stays a pair of
    plain boolean lanes the TPU fuses for free."""
    if isinstance(e, _Cmp3):
        v = _eval_with_args(e.value, cols, lit_iter)
        if e.null is None:
            return v, jnp.logical_not(v)
        n = _eval_with_args(e.null, cols, lit_iter)
        known = jnp.logical_not(n)
        return jnp.logical_and(v, known), jnp.logical_and(jnp.logical_not(v), known)
    if isinstance(e, And):
        t1, f1 = _eval3(e.left, cols, lit_iter)
        t2, f2 = _eval3(e.right, cols, lit_iter)
        return jnp.logical_and(t1, t2), jnp.logical_or(f1, f2)
    if isinstance(e, Or):
        t1, f1 = _eval3(e.left, cols, lit_iter)
        t2, f2 = _eval3(e.right, cols, lit_iter)
        return jnp.logical_or(t1, t2), jnp.logical_and(f1, f2)
    if isinstance(e, Not):
        t, f = _eval3(e.child, cols, lit_iter)
        return f, t
    raise ValueError(f"cannot 3-value evaluate {e!r}")


# (structure, column layout, literal dtypes, padded length) → jitted fn.
# Literals enter as traced scalars and shapes are padded to powers of two,
# so repeated point lookups with different keys / different bucket sizes
# hit the XLA compile cache instead of re-tracing per query. Lock-guarded
# for concurrent serve workers (a racing double-trace is harmless but the
# insert must not tear the dict).
import threading

_MASK_FN_CACHE: dict = {}
_MASK_FN_LOCK = threading.Lock()


def _pow2(n: int) -> int:
    return 1 << max(1, (n - 1)).bit_length() if n > 1 else 1


def _resolve_column(table: ColumnTable, name: str, memo: dict) -> np.ndarray:
    """A physical or virtual (pair-lowered hi/lo, is-null) column as a
    host array. Virtual columns derived from STABLE (frozen, cached)
    base columns are memoized across queries — repeat filters over the
    same index version skip the 64-bit key derivation entirely."""
    from hyperspace_tpu.execution import device_cache as dc

    if _SEP not in name:
        return table.columns[table.schema.field(name).name]
    base, tag = name.split(_SEP, 1)
    if tag == "nul":
        valid = table.valid_mask(base)
        if dc.is_stable(valid):
            return dc.derived(("nul", id(valid)), (valid,), lambda: ~valid)
        return ~valid
    domain, word = tag[0], tag[1:]
    base_arr = table.columns[table.schema.field(base).name]
    key = (base.lower(), domain)
    u = memo.get(key)
    if u is None:
        if dc.is_stable(base_arr):
            u = dc.derived(
                ("u64", id(base_arr), domain), (base_arr,),
                lambda: _ordered_u64(base_arr, domain),
            )
        else:
            u = _ordered_u64(base_arr, domain)
        memo[key] = u
    if word == "hi":
        compute = lambda: (u >> np.uint64(32)).astype(np.uint32)  # noqa: E731
    else:
        compute = lambda: (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)  # noqa: E731
    if dc.is_stable(u):
        return dc.derived(("word", id(u), word), (u,), compute)
    return compute()


def _host_mask(table: ColumnTable, predicate: Expr) -> np.ndarray:
    """Vectorized numpy fallback: full 64-bit semantics + Kleene logic.
    Returns the definitely-true mask (what a SQL filter keeps)."""

    def resolve(name: str):
        return table.columns[table.schema.field(name).name]

    n_rows = table.num_rows

    def known_mask(e: Expr) -> np.ndarray:
        """True where every column input of `e` is non-null."""
        known = np.ones(n_rows, dtype=bool)
        for name in e.references():
            valid = table.valid_mask(name)
            if valid is not None:
                known = known & valid
        return known

    def tri(e: Expr):
        if isinstance(e, And):
            t1, f1 = tri(e.left)
            t2, f2 = tri(e.right)
            return t1 & t2, f1 | f2
        if isinstance(e, Or):
            t1, f1 = tri(e.left)
            t2, f2 = tri(e.right)
            return t1 | t2, f1 & f2
        if isinstance(e, Not):
            t, f = tri(e.child)
            return f, t
        if isinstance(e, IsNull):
            known = known_mask(e.child)
            return ~known, known  # IS NULL is never UNKNOWN
        if isinstance(e, _DictLut):
            v = e.lut[resolve(e.col.name)]
            known = known_mask(e)
            return v & known, ~v & known
        if isinstance(e, _StrColCmp):
            fn = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
                  "le": np.less_equal, "gt": np.greater, "ge": np.greater_equal}[e.op]
            lv = e.lmap[resolve(e.left.name)]
            rv = e.rmap[resolve(e.right.name)]
            v = fn(lv, rv)
            known = known_mask(e)
            return v & known, ~v & known
        # Leaf comparison/expression: any null input makes it unknown.
        with np.errstate(all="ignore"):
            v = np.broadcast_to(np.asarray(evaluate(e, resolve, np), dtype=bool), (n_rows,))
        known = known_mask(e)
        return v & known, ~v & known

    t, _ = tri(predicate)
    return t


def eval_predicate_mask(
    table: ColumnTable, predicate: Expr, mesh=None, venue: str = "auto"
) -> np.ndarray:
    """Evaluate the predicate; returns a host bool mask. Venue-aware: the
    mask must land on host and the columns start there, so below the link
    floor the exact numpy evaluation (_host_mask — the same one
    unliftable predicates already use) beats the device round-trip. On
    device, with a mesh the row dimension is sharded across it (purely
    elementwise — zero collectives; the analog of the reference keeping
    full scan parallelism, FilterIndexRule.scala:114-120)."""
    predicate = translate_predicate(table, predicate)
    if venue == "auto":
        from hyperspace_tpu.parallel.bandwidth import pick_venue

        prefer_device = False
        if mesh is not None:
            from hyperspace_tpu.parallel.mesh import mesh_size

            prefer_device = mesh_size(mesh) > 1
        venue = pick_venue(
            "auto", 200.0,
            prefer_device=prefer_device,
            what="hyperspace.filter.venue",
            needs_native=False,
        )
    if venue == "host":
        return _host_mask(table, predicate)
    try:
        lowered = _lower(table, predicate)
    except _HostFallback:
        return _host_mask(table, predicate)

    lits: list = []
    struct = _structure_key(lowered, lits)
    names = sorted(lowered.references())

    n = table.num_rows
    n_pad = _pow2(n)
    sharding = None
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        from hyperspace_tpu.parallel.mesh import mesh_axes, mesh_size

        if mesh_size(mesh) > 1 and n_pad % mesh_size(mesh) == 0:
            sharding = NamedSharding(mesh, PartitionSpec(mesh_axes(mesh)))
    from hyperspace_tpu.execution.device_cache import device_put_padded

    arrays = []
    layout = []
    memo: dict = {}
    for name in names:
        arr = _resolve_column(table, name, memo)
        # Stable (frozen index-cache or derived) columns upload through
        # the device cache: repeat queries serve from HBM, no re-staging.
        arrays.append(device_put_padded(arr, n_pad, sharding))
        layout.append((name.lower(), arr.dtype.str))
    lit_args = [np.asarray(v) for v in lits]

    key = (struct, tuple(layout), tuple(a.dtype.str for a in lit_args), n_pad)
    with _MASK_FN_LOCK:
        fn = _MASK_FN_CACHE.get(key)
    if fn is None:
        lowered_names = [nm for nm, _ in layout]

        def raw(cols_tuple, lits_tuple, expr=lowered):
            cols = dict(zip(lowered_names, cols_tuple))
            t, _f = _eval3(expr, cols, iter(lits_tuple))
            return jnp.broadcast_to(t, (n_pad,))

        from hyperspace_tpu.compat import jit

        fn = jit(raw, key="ops.filter.mask")
        with _MASK_FN_LOCK:
            _MASK_FN_CACHE[key] = fn

    mask = fn(tuple(arrays), tuple(jnp.asarray(v) for v in lit_args))
    return np.asarray(jax.device_get(mask)).astype(bool)[:n]


def apply_filter(
    table: ColumnTable, predicate: Expr, mesh=None, venue: str = "auto"
) -> ColumnTable:
    if table.num_rows == 0:
        return table
    mask = eval_predicate_mask(table, predicate, mesh=mesh, venue=venue)
    return table.filter_mask(mask)
