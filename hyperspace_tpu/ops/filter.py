"""Device-side predicate evaluation.

The analog of Spark's WholeStageCodegen'd filter/project over the index scan
(SURVEY.md §2.2): the whole predicate tree evaluates as ONE jitted XLA
computation over the columns — XLA fuses the comparisons/boolean algebra
into a single pass over HBM, which is the TPU equivalent of the JVM's fused
codegen operator.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.expr import And, BinOp, Col, Expr, Lit, Not, Or, evaluate


def translate_predicate(table: ColumnTable, e: Expr) -> Expr:
    """Rewrite string-column comparisons against literals into the code
    domain of `table`'s dictionaries (order-preserving). Pure — returns a
    new tree, never mutates the plan's predicate."""
    if isinstance(e, BinOp) and e.is_comparison:
        l, r = e.left, e.right
        if isinstance(l, Col) and isinstance(r, Lit) and table.schema.field(l.name).is_string:
            return BinOp(e.op, l, Lit(table.translate_literal(l.name, r.value, e.op)))
        if isinstance(r, Col) and isinstance(l, Lit) and table.schema.field(r.name).is_string:
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}
            return translate_predicate(table, BinOp(flip[e.op], r, l))
        return e
    if isinstance(e, And):
        return And(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Or):
        return Or(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Not):
        return Not(translate_predicate(table, e.child))
    return e


def _structure_key(e: Expr, lits: list) -> tuple:
    """Structural fingerprint of an expression with literals abstracted out
    (collected into `lits` in walk order). Predicates that differ only in
    literal values share one compiled evaluator."""
    if isinstance(e, Lit):
        lits.append(e.value)
        return ("lit",)
    if isinstance(e, Col):
        return ("col", e.name.lower())
    if isinstance(e, BinOp):
        return ("binop", e.op, _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, And):
        return ("and", _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, Or):
        return ("or", _structure_key(e.left, lits), _structure_key(e.right, lits))
    if isinstance(e, Not):
        return ("not", _structure_key(e.child, lits))
    raise ValueError(f"cannot fingerprint {e!r}")


def _eval_with_args(e: Expr, cols: dict, lit_iter) -> object:
    """Evaluate against traced column arrays and traced literal scalars
    (consumed in the same walk order _structure_key used)."""
    if isinstance(e, Lit):
        return next(lit_iter)
    if isinstance(e, Col):
        return cols[e.name.lower()]
    if isinstance(e, BinOp):
        a = _eval_with_args(e.left, cols, lit_iter)
        b = _eval_with_args(e.right, cols, lit_iter)
        return evaluate(BinOp(e.op, Lit(a), Lit(b)), None, jnp)
    if isinstance(e, And):
        return jnp.logical_and(_eval_with_args(e.left, cols, lit_iter), _eval_with_args(e.right, cols, lit_iter))
    if isinstance(e, Or):
        return jnp.logical_or(_eval_with_args(e.left, cols, lit_iter), _eval_with_args(e.right, cols, lit_iter))
    if isinstance(e, Not):
        return jnp.logical_not(_eval_with_args(e.child, cols, lit_iter))
    raise ValueError(f"cannot evaluate {e!r}")


# (structure, column layout, literal dtypes, padded length) → jitted fn.
# Literals enter as traced scalars and shapes are padded to powers of two,
# so repeated point lookups with different keys / different bucket sizes
# hit the XLA compile cache instead of re-tracing per query.
_MASK_FN_CACHE: dict = {}


def _pow2(n: int) -> int:
    return 1 << max(1, (n - 1)).bit_length() if n > 1 else 1


def eval_predicate_mask(table: ColumnTable, predicate: Expr) -> np.ndarray:
    """Evaluate the predicate on device; returns a host bool mask."""
    from hyperspace_tpu.parallel.mesh import ensure_x64

    # int64/float64 columns and literals must not truncate to 32-bit.
    ensure_x64()
    predicate = translate_predicate(table, predicate)
    lits: list = []
    struct = _structure_key(predicate, lits)
    names = sorted(predicate.references())

    n = table.num_rows
    n_pad = _pow2(n)
    arrays = []
    layout = []
    for name in names:
        f = table.schema.field(name)
        arr = table.columns[f.name]
        if len(arr) != n_pad:
            arr = np.concatenate([arr, np.zeros(n_pad - n, dtype=arr.dtype)])
        arrays.append(jnp.asarray(arr))
        layout.append((name.lower(), arr.dtype.str))
    lit_args = [np.asarray(v) for v in lits]

    key = (struct, tuple(layout), tuple(a.dtype.str for a in lit_args), n_pad)
    fn = _MASK_FN_CACHE.get(key)
    if fn is None:
        lowered_names = [nm for nm, _ in layout]

        def raw(cols_tuple, lits_tuple):
            cols = dict(zip(lowered_names, cols_tuple))
            return _eval_with_args(predicate, cols, iter(lits_tuple))

        fn = jax.jit(raw)
        _MASK_FN_CACHE[key] = fn

    mask = fn(tuple(arrays), tuple(jnp.asarray(v) for v in lit_args))
    return np.asarray(jax.device_get(mask)).astype(bool)[:n]


def apply_filter(table: ColumnTable, predicate: Expr) -> ColumnTable:
    if table.num_rows == 0:
        return table
    mask = eval_predicate_mask(table, predicate)
    return table.filter_mask(mask)
