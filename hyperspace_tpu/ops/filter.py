"""Device-side predicate evaluation.

The analog of Spark's WholeStageCodegen'd filter/project over the index scan
(SURVEY.md §2.2): the whole predicate tree evaluates as ONE jitted XLA
computation over the columns — XLA fuses the comparisons/boolean algebra
into a single pass over HBM, which is the TPU equivalent of the JVM's fused
codegen operator.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.expr import And, BinOp, Col, Expr, Lit, Not, Or, evaluate


def translate_predicate(table: ColumnTable, e: Expr) -> Expr:
    """Rewrite string-column comparisons against literals into the code
    domain of `table`'s dictionaries (order-preserving). Pure — returns a
    new tree, never mutates the plan's predicate."""
    if isinstance(e, BinOp) and e.is_comparison:
        l, r = e.left, e.right
        if isinstance(l, Col) and isinstance(r, Lit) and table.schema.field(l.name).is_string:
            return BinOp(e.op, l, Lit(table.translate_literal(l.name, r.value, e.op)))
        if isinstance(r, Col) and isinstance(l, Lit) and table.schema.field(r.name).is_string:
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq", "ne": "ne"}
            return translate_predicate(table, BinOp(flip[e.op], r, l))
        return e
    if isinstance(e, And):
        return And(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Or):
        return Or(translate_predicate(table, e.left), translate_predicate(table, e.right))
    if isinstance(e, Not):
        return Not(translate_predicate(table, e.child))
    return e


def eval_predicate_mask(table: ColumnTable, predicate: Expr) -> np.ndarray:
    """Evaluate the predicate on device; returns a host bool mask."""
    predicate = translate_predicate(table, predicate)
    names = sorted(predicate.references())
    resolved = {}
    for n in names:
        f = table.schema.field(n)
        arr = table.columns[f.name]
        resolved[n.lower()] = jnp.asarray(arr)

    def fn(cols):
        return evaluate(predicate, lambda name: cols[name.lower()], jnp)

    mask = jax.jit(fn)(resolved)
    return np.asarray(jax.device_get(mask)).astype(bool)


def apply_filter(table: ColumnTable, predicate: Expr) -> ColumnTable:
    if table.num_rows == 0:
        return table
    mask = eval_predicate_mask(table, predicate)
    return table.filter_mask(mask)
