"""Computed projection: evaluate named select-list expressions.

The reference gets computed select lists (SELECT a*b AS x) from
Catalyst's Project operator for free; our IR carries (alias, Expr)
entries and this op materializes them over a ColumnTable. Numeric
expressions ride the same (values, validity) evaluation the aggregate
inputs use (ops/aggregate._numeric_input — 3-valued nulls, CASE with
branch-following validity); boolean expressions ride the fused filter
mask machinery; SUBSTRING over a string column maps the (small, sorted)
dictionary and re-sorts so the engine's order-preserving-codes invariant
holds for downstream comparisons.
"""

from __future__ import annotations

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Col,
    Expr,
    InList,
    IsNull,
    Like,
    Not,
    Or,
    Substr,
    expr_dtype,
)


def _bool_column(table: ColumnTable, e: Expr) -> tuple[np.ndarray, np.ndarray | None]:
    """SQL boolean value of a predicate: True / False / NULL(unknown).
    The filter machinery computes true-masks only (unknown folds to
    False — correct for WHERE); a projected boolean additionally needs
    the false-mask to tell False from NULL."""
    from hyperspace_tpu.ops.filter import eval_predicate_mask

    tmask = eval_predicate_mask(table, e)
    fmask = eval_predicate_mask(table, Not(e))
    known = tmask | fmask
    return tmask, None if known.all() else known


def _substr_column(
    table: ColumnTable, e: Substr
) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
    """(codes, sorted dictionary, validity) for SUBSTRING(col, s, l)."""
    if not isinstance(e.child, Col):
        raise HyperspaceError("SUBSTRING projection requires a string column input")
    f = table.schema.field(e.child.name)
    if not f.is_string:
        raise HyperspaceError(f"SUBSTRING over non-string column {f.name!r}")
    d = table.dictionaries[f.name]
    lo = e.start - 1
    sub = np.array([s[lo : lo + e.length] for s in d], dtype=object)
    new_dict, inverse = np.unique(sub.astype(str), return_inverse=True)
    codes = inverse.astype(np.int32)[table.columns[f.name]]
    return codes, new_dict, table.valid_mask(f.name)


def _string_case_column(table: ColumnTable, e: Expr):
    """String-valued CASE of the TPC-DS q36/q70 shape: every branch value
    is the SAME string column or a string literal (the 'masked parent
    key' idiom — `case when grouping(x)=0 then cat end`). The dictionary
    extends with the literals (re-sorted to keep the order-preserving
    codes invariant) and branches select in code space."""
    from hyperspace_tpu.plan.expr import Case, Lit
    from hyperspace_tpu.ops.filter import eval_predicate_mask

    if not isinstance(e, Case):
        raise HyperspaceError(
            f"cannot project string-typed expression {type(e).__name__}"
        )
    src: str | None = None
    lits: set[str] = set()
    for v in [*(v for _, v in e.branches), e.default]:
        if isinstance(v, Col):
            f = table.schema.field(v.name)
            if not f.is_string:
                raise HyperspaceError("string CASE branches must be string-typed")
            if src is not None and f.name != src:
                raise HyperspaceError(
                    "string CASE supports one source column (plus literals)"
                )
            src = f.name
        elif isinstance(v, Lit) and isinstance(v.value, str):
            lits.add(v.value)
        else:
            raise HyperspaceError(
                "string CASE branches must be a string column or string literals"
            )
    base = table.dictionaries[src] if src is not None else np.zeros(0, dtype=object)
    merged = np.unique(np.concatenate([base.astype(str), np.array(sorted(lits), dtype=str)]))
    old_to_new = np.searchsorted(merged, base.astype(str)).astype(np.int32)
    lit_code = {s: int(np.searchsorted(merged, s)) for s in lits}
    n = table.num_rows

    def branch_codes(v) -> np.ndarray:
        if isinstance(v, Col):
            return old_to_new[table.columns[src]]
        return np.full(n, lit_code[v.value], np.int32)

    def branch_valid(v) -> np.ndarray | None:
        if isinstance(v, Col):
            return table.validity.get(src)
        return None

    codes = branch_codes(e.default)
    valid = branch_valid(e.default)
    for cond, v in reversed(e.branches):
        m = eval_predicate_mask(table, cond)
        codes = np.where(m, branch_codes(v), codes)
        bv = branch_valid(v)
        if valid is not None or bv is not None:
            va = np.ones(n, bool) if valid is None else valid
            vb = np.ones(n, bool) if bv is None else bv
            valid = np.where(m, vb, va)
    return codes.astype(np.int32), merged.astype(object), valid


def compute_column(
    table: ColumnTable, e: Expr, dtype: str
) -> tuple[np.ndarray, np.ndarray | None, np.ndarray | None]:
    """Evaluate one computed projection entry.

    Returns (values, dictionary or None, validity or None); values are
    physical (codes when a dictionary is returned).
    """
    from hyperspace_tpu.ops.aggregate import _numeric_input
    from hyperspace_tpu.schema import Field

    if isinstance(e, Col):
        # Column rename (SELECT c AS x) — carries codes/dict/validity.
        f = table.schema.field(e.name)
        return (
            table.columns[f.name],
            table.dictionaries.get(f.name),
            table.validity.get(f.name),
        )
    if isinstance(e, Substr):
        codes, d, valid = _substr_column(table, e)
        return codes, d, valid
    if dtype == "bool" and isinstance(e, (And, Or, Not, IsNull, InList, Like)) or (
        isinstance(e, BinOp) and e.is_comparison
    ):
        vals, valid = _bool_column(table, e)
        return vals, None, valid
    if dtype == "string":
        from hyperspace_tpu.plan.expr import Lit

        if isinstance(e, Lit) and isinstance(e.value, str):
            # Constant string column (q76's channel labels): one-entry
            # dictionary, all codes zero.
            return (
                np.zeros(table.num_rows, np.int32),
                np.array([e.value], dtype=object),
                None,
            )
        return _string_case_column(table, e)
    vals, valid = _numeric_input(table, e)
    phys = Field("_", dtype).device_dtype
    return np.asarray(vals).astype(phys, copy=False), None, valid


def project_table(table: ColumnTable, columns: list, out_schema) -> ColumnTable:
    """Execute a Project with computed entries over a host table."""
    cols: dict[str, np.ndarray] = {}
    dicts: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    for entry, field in zip(columns, out_schema.fields):
        if isinstance(entry, str):
            f = table.schema.field(entry)
            cols[field.name] = table.columns[f.name]
            if f.name in table.dictionaries:
                dicts[field.name] = table.dictionaries[f.name]
            if f.name in table.validity:
                validity[field.name] = table.validity[f.name]
            continue
        vals, d, valid = compute_column(table, entry[1], field.dtype)
        cols[field.name] = vals
        if d is not None:
            dicts[field.name] = d
        if valid is not None and not valid.all():
            validity[field.name] = np.asarray(valid, dtype=bool)
    return ColumnTable(out_schema, cols, dicts, validity)
