"""Window functions as sorted-segment computations.

The reference's environment executes windows in Spark's WindowExec
(sort by partition+order keys, then per-frame evaluation); our
formulation rides the engine's order-preserving 32-bit key lanes
(ops/sortkeys.py): one stable lexsort by (partition gid, order lanes)
yields segment/peer boundaries, and every supported function is then a
vectorized prefix/segment computation — no per-partition loop, which is
what makes 100k+ partitions (TPC-DS q67's item×store windows) cheap on
a host feed and maps to `lax.associative_scan` on device.

Frames (plan/nodes.py Window):
  - "partition": whole-partition aggregates via one bincount/reduceat;
  - "rows":  running (UNBOUNDED PRECEDING .. CURRENT ROW) prefix sums;
  - "range": the "rows" result at the LAST peer row, shared by peers
    (SQL's default frame with ORDER BY).
Running min/max ("rows"/"range" frames) are prefix maximum.accumulate
with per-segment restart via the segment-base trick.
"""

from __future__ import annotations

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.aggregate import _numeric_input, group_ids
from hyperspace_tpu.ops.sortkeys import order_lanes
from hyperspace_tpu.plan.nodes import WindowSpec


def _safe_int(vals: np.ndarray, dtype) -> np.ndarray:
    """Cast extremum results to an integer dtype: ±inf identities (rows
    whose frame holds no valid value — their validity mask marks them
    NULL) are zero-backed first so the cast is defined and silent."""
    return np.where(np.isfinite(vals), vals, 0).astype(dtype)


def _segment_starts(arrs: list[np.ndarray]) -> np.ndarray:
    """Bool [n]: row i starts a new segment (any key differs from i-1)."""
    n = len(arrs[0])
    new = np.zeros(n, dtype=bool)
    if n:
        new[0] = True
        for a in arrs:
            new[1:] |= a[1:] != a[:-1]
    return new


def _start_index(new_seg: np.ndarray) -> np.ndarray:
    """For each row, the index of its segment's first row."""
    idx = np.arange(len(new_seg), dtype=np.int64)
    return np.maximum.accumulate(np.where(new_seg, idx, 0))


def _seg_prefix_sum(vals: np.ndarray, start_idx: np.ndarray) -> np.ndarray:
    """Per-segment running sum (inclusive) via global cumsum minus the
    segment's base (everything before its first row)."""
    cs = np.cumsum(vals)
    base = cs[start_idx] - vals[start_idx]
    return cs - base


def _seg_prefix_extremum(vals: np.ndarray, new_seg: np.ndarray, fn: str) -> np.ndarray:
    """Per-segment running min/max, exactly, with no segment loop: rank
    the values once, combine (segment ordinal, rank) into one int64 key
    whose prefix maximum restarts per segment (the segment term
    dominates), then map winning ranks back to values."""
    n = len(vals)
    order = np.argsort(vals, kind="stable")
    rank = np.empty(n, dtype=np.int64)
    rank[order] = np.arange(n, dtype=np.int64)
    r = rank if fn == "max" else (n - 1) - rank  # min = max of inverted ranks
    seg = (np.cumsum(new_seg) - 1).astype(np.int64)
    acc = np.maximum.accumulate(seg * np.int64(n) + r) - seg * np.int64(n)
    if fn == "min":
        acc = (n - 1) - acc
    return vals[order][acc]


def window_table(
    table: ColumnTable,
    partition_by: list[str],
    order_by: list[tuple[str, bool]],
    funcs: list[WindowSpec],
    frame: str,
    out_schema,
) -> ColumnTable:
    n = table.num_rows
    cols = dict(table.columns)
    dicts = dict(table.dictionaries)
    validity = dict(table.validity)
    if n == 0:
        empty = ColumnTable.empty(out_schema)
        return empty

    gid, _, _ = group_ids(table, partition_by)
    lanes = order_lanes(table, order_by) if order_by else []
    # np.lexsort: last key is primary → (least-significant lanes first,
    # partition gid last). Stable, so ties keep input order (row_number
    # determinism).
    perm = np.lexsort((*reversed(lanes), gid)) if (lanes or partition_by) else np.arange(n)
    sgid = gid[perm]
    new_seg = _segment_starts([sgid])
    slanes = [l[perm] for l in lanes]
    new_peer = _segment_starts([sgid, *slanes]) if lanes else new_seg
    start_idx = _start_index(new_seg)
    idx = np.arange(n, dtype=np.int64)

    def scatter(sorted_vals: np.ndarray) -> np.ndarray:
        out = np.empty(n, dtype=sorted_vals.dtype)
        out[perm] = sorted_vals
        return out

    def peer_shared(run: np.ndarray) -> np.ndarray:
        """RANGE frame: each row takes the running value at its LAST
        peer row."""
        pg = np.cumsum(new_peer) - 1
        last = np.zeros(pg[-1] + 1 if n else 0, dtype=np.int64)
        last[pg] = idx  # ascending scan: last write per peer group wins
        return run[last[pg]]

    for spec, field in zip(funcs, out_schema.fields[len(table.schema.fields) :]):
        if spec.fn in ("lag", "lead"):
            # Partition-bounded shift along the ORDER BY: row i takes the
            # value `offset` rows before (lag) / after (lead) it within
            # its segment, NULL past the segment edge (SQL's default).
            from hyperspace_tpu.plan.expr import Col

            src_dict = None
            if isinstance(spec.expr, Col):
                src_f = table.schema.field(spec.expr.name)
                vals = np.asarray(table.columns[src_f.name])
                valid = table.validity.get(src_f.name)
                src_dict = table.dictionaries.get(src_f.name)
            else:
                vals, valid = _numeric_input(table, spec.expr)
                vals = np.full(n, vals) if np.ndim(vals) == 0 else vals
            sv = vals[perm]
            svalid = None if valid is None else np.asarray(valid)[perm]
            if spec.fn == "lag":
                src = idx - spec.offset
                in_seg = src >= start_idx
            else:
                # Last index of each segment, broadcast per row.
                seg = np.cumsum(new_seg) - 1
                seg_last = np.zeros(int(seg[-1]) + 1, dtype=np.int64)
                seg_last[seg] = idx  # ascending: last write per segment wins
                src = idx + spec.offset
                in_seg = src <= seg_last[seg]
            src_c = np.clip(src, 0, n - 1)
            shifted = sv[src_c]
            ok = in_seg if svalid is None else (in_seg & svalid[src_c])
            if field.is_string:
                # Codes shift with the source dictionary carried over.
                cols[field.name] = scatter(shifted)
                if src_dict is not None:
                    dicts[field.name] = src_dict
            else:
                cols[field.name] = scatter(shifted).astype(field.device_dtype, copy=False)
            if not ok.all():
                validity[field.name] = scatter(ok)
            continue
        if spec.fn == "row_number":
            vals = idx - start_idx + 1
            cols[field.name] = scatter(vals)
            continue
        if spec.fn == "rank":
            peer_start = np.maximum.accumulate(np.where(new_peer, idx, 0))
            cols[field.name] = scatter(peer_start - start_idx + 1)
            continue
        if spec.fn == "dense_rank":
            dense = np.cumsum(new_peer)
            cols[field.name] = scatter(dense - dense[start_idx] + 1)
            continue

        # Aggregate functions.
        if spec.expr is None:  # count(*)
            vals, valid = np.ones(n, np.int64), None
        else:
            vals, valid = _numeric_input(table, spec.expr)
            vals = np.full(n, vals) if np.ndim(vals) == 0 else vals
        sv = np.asarray(vals)[perm]
        svalid = None if valid is None else np.asarray(valid)[perm]
        ones = np.ones(n, np.int64) if svalid is None else svalid.astype(np.int64)
        is_int = field.dtype in ("int32", "int64", "bool", "date")
        acc_dtype = np.int64 if is_int and spec.fn in ("sum", "count", "min", "max") else np.float64
        contrib = sv.astype(acc_dtype, copy=False)
        if svalid is not None and spec.fn in ("sum", "mean"):
            contrib = np.where(svalid, contrib, acc_dtype(0))

        if frame == "partition":
            # One segment reduce, broadcast back over the partition.
            seg = np.cumsum(new_seg) - 1
            k = int(seg[-1]) + 1
            cnt = np.bincount(seg, weights=ones, minlength=k).astype(np.int64)
            if spec.fn == "count":
                res, res_valid = cnt, None
            elif spec.fn in ("sum", "mean"):
                if spec.fn == "sum" and is_int:
                    # Exact int64 accumulation (contrib is already in
                    # segment order): float64 bincount weights would lose
                    # integer exactness above 2^53.
                    res = np.add.reduceat(contrib, np.flatnonzero(new_seg))
                else:
                    s = np.bincount(seg, weights=contrib.astype(np.float64), minlength=k)
                    if spec.fn == "mean":
                        with np.errstate(invalid="ignore", divide="ignore"):
                            res = s / cnt
                    else:
                        res = s
                res_valid = cnt > 0
            else:  # min / max
                identity = np.inf if spec.fn == "min" else -np.inf
                sx = contrib.astype(np.float64, copy=False)
                if svalid is not None:
                    sx = np.where(svalid, sx, identity)
                starts = np.flatnonzero(new_seg)
                op = np.minimum if spec.fn == "min" else np.maximum
                res = op.reduceat(sx, starts)
                res = _safe_int(res, acc_dtype) if is_int else res
                res_valid = cnt > 0
            run = np.asarray(res)[seg]
            run_cnt_ok = None if res_valid is None else res_valid[seg]
        else:
            # Running ("rows") value, optionally peer-shared ("range").
            run_ones = _seg_prefix_sum(ones, start_idx)
            if spec.fn == "count":
                run = run_ones
                run_cnt_ok = None
            elif spec.fn in ("sum", "mean"):
                rs = _seg_prefix_sum(contrib, start_idx)
                if spec.fn == "mean":
                    with np.errstate(invalid="ignore", divide="ignore"):
                        run = rs.astype(np.float64) / run_ones
                else:
                    run = rs
                run_cnt_ok = run_ones > 0
            else:  # running min / max
                fx = contrib.astype(np.float64, copy=False)
                if svalid is not None:
                    fx = np.where(svalid, fx, np.inf if spec.fn == "min" else -np.inf)
                run = _seg_prefix_extremum(fx, new_seg, spec.fn)
                run = _safe_int(run, acc_dtype) if is_int else run
                run_cnt_ok = run_ones > 0
            if frame == "range":
                run = peer_shared(run)
                if run_cnt_ok is not None:
                    run_cnt_ok = peer_shared(run_cnt_ok)

        phys = field.device_dtype
        out_vals = scatter(np.asarray(run))
        cols[field.name] = out_vals.astype(phys, copy=False)
        if run_cnt_ok is not None and not run_cnt_ok.all():
            validity[field.name] = scatter(run_cnt_ok)

    return ColumnTable(out_schema, cols, dicts, validity)
