from hyperspace_tpu.ops.hashing import bucket_ids, combine_hashes, hash_int_column, string_dict_hashes

__all__ = ["bucket_ids", "combine_hashes", "hash_int_column", "string_dict_hashes"]
