from hyperspace_tpu.ops.hashing import bucket_ids, combine_hashes, hash_int_column, string_dict_hashes

#: Every Pallas kernel the package ships, by its jit call-site key
#: (static-analysis rule HSL026, analysis/tracedomain.py — the mirror
#: of ``faults.KNOWN_POINTS``). Each declared kernel's engagement chain
#: must statically carry the full fallback ladder: an exactness gate, a
#: permanent per-shape bad-set fallback, and both ``device.kernel.*``
#: counters. Undeclared engagements and stale entries are findings, so
#: this tuple is provably the complete kernel inventory.
KNOWN_KERNELS = (
    "ops.aggregate.pallas_segment_reduce",
    "ops.sortkeys.pallas_run_bounds",
    "ops.topk.pallas_tile",
)

__all__ = [
    "KNOWN_KERNELS",
    "bucket_ids",
    "combine_hashes",
    "hash_int_column",
    "string_dict_hashes",
]
