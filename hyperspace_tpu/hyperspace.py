"""User facade and session integration.

Reference parity: com/microsoft/hyperspace/Hyperspace.scala:24-133 (the 8
user APIs delegating to the collection manager, with a context holding the
session + caching manager) and package.scala:34-77 (enable/disable toggling
the optimizer rule batch). There is no SparkSession here; `HyperspaceSession`
owns the configuration, the device mesh, the executor, and the
enable/disable switch, and `session.run(plan)` is the query entry point
that applies the rewrite rules when enabled.
"""

from __future__ import annotations

import dataclasses
import threading
from pathlib import Path

from hyperspace_tpu.config import HyperspaceConf
from hyperspace_tpu.dataset import Dataset
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.index.collection_manager import CachingIndexCollectionManager
from hyperspace_tpu.index.index_config import IndexConfig
from hyperspace_tpu.obs import events as obs_events
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.rules.base import apply_rules

# Structured health-plane events (obs/events.py): the query plane's
# degradations become operator-visible records on /debug/events, each
# carrying the active trace id.
_EVT_FALLBACK = obs_events.declare("fallback.replan")
_EVT_QUARANTINED = obs_events.declare("index.quarantined")
_EVT_DEMOTED = obs_events.declare("advisor.routing.demoted")


def _enable_persistent_compile_cache() -> None:
    """Point XLA's persistent compilation cache at a stable directory so
    short-lived processes skip the 1-40s first-compile cost (the fixed
    overhead that dominated small-scale builds). Opt out with
    HYPERSPACE_XLA_CACHE_DIR=''. Idempotent; failures are non-fatal."""
    import os

    d = os.environ.get("HYPERSPACE_XLA_CACHE_DIR")
    if d is None:
        base = os.environ.get("HYPERSPACE_CACHE_DIR") or os.path.expanduser(
            "~/.cache/hyperspace_tpu"
        )
        d = os.path.join(base, "xla")
    if not d:
        return
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return  # user already configured one
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    except Exception as e:
        # Best-effort speedup, never fatal — but leave a trace so a
        # mysteriously slow first compile is explainable.
        import logging

        logging.getLogger("hyperspace_tpu").debug(
            "persistent compile cache unavailable: %s", e
        )


@dataclasses.dataclass
class QueryOutcome:
    """Per-query handle state: everything one `run` produced, owned by
    the caller instead of smeared across session globals. Two concurrent
    queries each get their own outcome; the session keeps a lock-guarded
    *view* of the most recent one (`last_query_stats` / `last_profile()`)
    for the single-caller API. The serving plane (docs/serving.md)
    attaches an outcome to each QueryHandle."""

    result: object  # ColumnTable
    stats: dict
    physical_plan: object
    profile: object
    replans: int = 0
    used_indexes: bool = True


class HyperspaceSession:
    """The engine session: configuration + mesh + executor + rule toggle.

    Thread-safety: `run()` may be called from N threads (the serving
    plane does exactly that). Each query's mutable state lives in a
    per-query :class:`QueryOutcome`; the shared session view
    (`last_query_stats`, `last_physical_plan`, `last_profile()`, the
    corruption-quarantine `index_health` map, lazy manager init) is
    guarded by one reentrant lock."""

    def __init__(self, system_path: str | None = None, num_buckets: int | None = None, mesh=None):
        kwargs = {}
        if system_path is not None:
            kwargs["system_path"] = str(system_path)
        if num_buckets is not None:
            kwargs["num_buckets"] = int(num_buckets)
        _enable_persistent_compile_cache()
        self.conf = HyperspaceConf(**kwargs)
        self.mesh = mesh
        self._enabled = False
        self._manager: CachingIndexCollectionManager | None = None
        # Guards the session view below + lazy manager construction.
        self._state_lock = threading.RLock()
        # Executed-plan evidence of the most recent run(): Executor.stats
        # and the executed PhysicalNode tree.
        self.last_query_stats: dict = {}
        self.last_physical_plan = None
        # QueryProfile of the most recent run() (docs/observability.md);
        # always populated — the physical-plan side of the profile costs
        # two perf_counter calls per operator even with tracing off.
        self._last_profile = None
        # Per-index health map (index root -> failure record). An index
        # that served corrupt data is quarantined from the rewrite rules
        # for the rest of the session; queries transparently fall back to
        # the source (docs/fault_tolerance.md). recover()/refresh clears.
        # Mutations go through _state_lock; per-query snapshots keep one
        # query's replan decisions consistent.
        self.index_health: dict[str, dict] = {}
        # Advisor plane (docs/advisor.md): the bounded workload ring every
        # run_query appends to, and the adaptive-routing ledger. Both lazy
        # (constructed under _state_lock on first use).
        self._workload = None
        self._routing = None

    # -- rule toggle (package.scala:46-70) --------------------------------
    def enable_hyperspace(self) -> "HyperspaceSession":
        self._enabled = True
        return self

    def disable_hyperspace(self) -> "HyperspaceSession":
        self._enabled = False
        return self

    def is_hyperspace_enabled(self) -> bool:
        return self._enabled

    # -- wiring -----------------------------------------------------------
    @property
    def manager(self) -> CachingIndexCollectionManager:
        if self._manager is None:
            with self._state_lock:
                if self._manager is None:
                    def writer_factory():
                        from hyperspace_tpu.execution.builder import DeviceIndexBuilder

                        w = DeviceIndexBuilder(
                            mesh=self.mesh,
                            memory_budget_bytes=self.conf.build_memory_budget_bytes,
                            chunk_bytes=self.conf.build_chunk_bytes or None,
                            venue=self.conf.build_venue,
                            venue_min_mbps=self.conf.join_venue_min_mbps,
                            pipeline_enabled=self.conf.build_pipeline_enabled,
                            pipeline_max_inflight_bytes=self.conf.build_pipeline_max_inflight_bytes,
                            workers=self.conf.build_workers,
                            exchange_dir=self.conf.build_exchange_dir or None,
                        )
                        self._last_writer = w
                        return w

                    self._manager = CachingIndexCollectionManager(self.conf, writer_factory)
        return self._manager

    @property
    def workload(self):
        """The session's bounded workload log (docs/advisor.md): one
        :class:`~hyperspace_tpu.advisor.workload.WorkloadRecord` per
        run_query, the advisor's learning input."""
        if self._workload is None:
            with self._state_lock:
                if self._workload is None:
                    from hyperspace_tpu.advisor.workload import WorkloadLog

                    self._workload = WorkloadLog(self.conf.advisor_workload_max_records)
        return self._workload

    def routing_ledger(self):
        """The adaptive-routing outcome ledger (advisor/routing.py);
        constructed lazily — sessions that never enable
        ``hyperspace.advisor.routing.enabled`` still get a readable view
        of it for reports."""
        if self._routing is None:
            with self._state_lock:
                if self._routing is None:
                    from hyperspace_tpu.advisor.routing import RoutingLedger

                    self._routing = RoutingLedger(self)
        return self._routing

    @property
    def last_build_stats(self) -> dict:
        """Stats of the most recent index build in this session,
        including the per-phase wall-time breakdown (decode / hash+lanes
        / partition+exchange / carve+encode+write)."""
        return dict(getattr(getattr(self, "_last_writer", None), "last_build_stats", {}) or {})

    # -- data access ------------------------------------------------------
    def parquet(self, root: str | Path) -> Scan:
        """Register a parquet dataset and return its scan plan (the
        DataFrame-equivalent; LogicalPlan carries the fluent API)."""
        return Dataset.parquet(root).scan()

    def orc(self, root: str | Path) -> Scan:
        return Dataset.orc(root).scan()

    def csv(self, root: str | Path) -> Scan:
        return Dataset.csv(root).scan()

    def json(self, root: str | Path) -> Scan:
        """Register a line-delimited JSON dataset."""
        return Dataset.json(root).scan()

    def optimized_plan(self, plan: LogicalPlan, snapshot=None) -> LogicalPlan:
        if not self._enabled:
            return plan
        from hyperspace_tpu.plan.prune import prune_columns
        from hyperspace_tpu.plan.pushdown import push_down_filters

        # Predicate pushdown + column pruning FIRST (the analog of Spark
        # running PushDownPredicate/ColumnPruning before the
        # extraOptimizations batch): side-local filters reach the join
        # sides (where the index rules cover them) and scans narrow to
        # what the query needs.
        if snapshot is not None:
            # MVCC pinned read (ingest/snapshot.py): the candidate set is
            # the entries captured at admission, NOT the live listing —
            # versions a concurrent micro-batch commits are invisible.
            indexes = snapshot.entries()
        else:
            indexes = self.manager.get_indexes()
        with self._state_lock:
            unhealthy = set(self.index_health)
        if unhealthy:
            # Indexes that served corrupt data are out of the candidate
            # set until recovered — degradation is sticky per session,
            # not re-discovered (and re-failed) on every query.
            indexes = [
                e for e in indexes
                if str(Path(e.content.root)) not in unhealthy
            ]
        return apply_rules(prune_columns(push_down_filters(plan)), indexes, conf=self.conf)

    def run(self, plan: LogicalPlan, profile_dir: str | Path | None = None, snapshot=None):
        """Execute a plan (rewriting through indexes when enabled);
        returns a ColumnTable. With `profile_dir`, the execution runs
        under jax.profiler.trace and writes an xplane artifact there
        (SURVEY.md §5: the TPU profiling story) — open with TensorBoard
        or xprof. With `snapshot` (a PinnedSnapshot from
        :meth:`pin_snapshot`), the read repeats against the pinned
        version stamp no matter what commits concurrently.

        Corruption fallback (`hyperspace.fallback.enabled`): when an
        index scan hits unreadable index data mid-query, the failing
        index is recorded in `index_health` and the query transparently
        re-plans — first through the remaining healthy indexes, then
        (if corruption persists) straight against the source data. The
        query answers either way; `hyperspace_tpu.stats` counts it."""
        outcome = self.run_query(plan, profile_dir=profile_dir, snapshot=snapshot)
        self._publish(outcome)
        return outcome.result

    def pin_snapshot(self):
        """Pin an MVCC repeatable-read view of the collection at the
        current per-index version stamp (ingest/snapshot.py,
        docs/ingestion.md "snapshot semantics"). Pass the handle to
        `run(..., snapshot=snap)`; release it (or use it as a context
        manager) when done."""
        from hyperspace_tpu.ingest.snapshot import PinnedSnapshot

        return PinnedSnapshot(self)

    def run_query(
        self,
        plan: LogicalPlan,
        profile_dir: str | Path | None = None,
        plan_cache=None,
        snapshot=None,
    ) -> QueryOutcome:
        """Execute a plan into a per-query :class:`QueryOutcome` without
        touching the session view — the concurrency-safe entry point the
        serving plane uses (docs/serving.md). `plan_cache` (a
        serve.PlanCache) memoizes `optimized_plan` per versioned plan
        key; its key includes the quarantine set, so a mid-query
        corruption replan re-optimizes under the new key instead of
        hitting the poisoned entry."""
        import time

        from hyperspace_tpu import stats
        from hyperspace_tpu.exceptions import IndexCorruptionError
        from hyperspace_tpu.execution import device_cache
        from hyperspace_tpu.execution import io as hio
        from hyperspace_tpu.execution.executor import Executor
        from hyperspace_tpu.obs import profile as obs_profile
        from hyperspace_tpu.obs import trace as obs_trace

        from hyperspace_tpu.signature import plan_signature

        cache_before = self._cache_counts(hio, device_cache)
        if snapshot is not None:
            # Pin every raw source leaf to the snapshot's file lists
            # BEFORE planning: the rewrite rules then exact-match the
            # pinned entries and any raw fallback scans the pinned
            # files — a repeatable read across concurrent commits.
            plan = snapshot.pin_plan(plan)
            stats.increment("ingest.pinned_reads")
        replans = 0
        use_indexes = True
        # Advisor plane (docs/advisor.md): the plan's structural
        # signature keys both the workload record and the routing
        # ledger. Adaptive routing (opt-in) consults measured history
        # BEFORE planning: a signature whose indexed path measured
        # slower than raw is demoted to a straight source scan.
        sig = plan_signature(plan)
        routing_on = self.conf.advisor_routing_enabled
        routed = routing_stamp = ledger = None
        if routing_on:
            from hyperspace_tpu.advisor import routing as adv_routing

            ledger = self.routing_ledger()
            # A pinned query keys the ledger on its OWN read point —
            # the live stamp moves under concurrent commits the pinned
            # view cannot see, and a moved stamp WIPES the ledger.
            routing_stamp = (
                adv_routing.snapshot_stamp(snapshot)
                if snapshot is not None
                else adv_routing.collection_stamp(self)
            )
            if self._enabled:
                routed = ledger.decide(sig, stamp=routing_stamp)
                if routed == "raw":
                    use_indexes = False
                    obs_trace.event("advisor.routing.demoted", signature=sig)
                    _EVT_DEMOTED.emit(signature=sig)
        t_start = time.perf_counter()
        with obs_trace.trace("query") as root_span:
            while True:
                executor = Executor(mesh=self.mesh, conf=self.conf)
                with obs_trace.span("plan.optimize", indexes_enabled=self._enabled):
                    if not use_indexes:
                        optimized = plan
                    elif plan_cache is not None and self._enabled:
                        optimized = plan_cache.get_or_optimize(self, plan, snapshot=snapshot)
                    else:
                        optimized = self.optimized_plan(plan, snapshot=snapshot)
                    if use_indexes and self._enabled and self.conf.scan_prefetch_enabled:
                        # Query-tail prefetch: footers + first chunk of
                        # the index files the pruner keeps start loading
                        # on a background pool NOW, so the executor's
                        # cold reads below begin warm (advisory — see
                        # execution/prefetch.py).
                        from hyperspace_tpu.execution import prefetch as _prefetch

                        _prefetch.prefetch_plan(optimized)
                try:
                    if profile_dir is not None:
                        import jax

                        with jax.profiler.trace(str(profile_dir)):
                            result = executor.execute(optimized)
                    else:
                        result = executor.execute(optimized)
                    break
                except IndexCorruptionError as e:
                    if not (self._enabled and use_indexes and self.conf.fallback_enabled):
                        raise
                    root = str(Path(e.index_root)) if e.index_root is not None else None
                    with self._state_lock:
                        newly_quarantined = root is not None and root not in self.index_health
                        if root is None or root in self.index_health:
                            # No provenance to quarantine by (or quarantining
                            # it didn't help): indexes go off wholesale for
                            # this query — the loop provably terminates.
                            use_indexes = False
                        if root is not None:
                            self.index_health[root] = {"reason": e.msg, "path": e.path}
                    stats.increment("fallback.queries")
                    replans += 1
                    obs_trace.event("fallback.replan", index=root, reason=e.msg)
                    _EVT_FALLBACK.emit(index=root, reason=e.msg)
                    if newly_quarantined:
                        _EVT_QUARANTINED.emit(index=root, reason=e.msg)
                    import logging

                    logging.getLogger("hyperspace_tpu").warning(
                        "index data unreadable (%s); re-planning query against source", e.msg
                    )
        total_s = time.perf_counter() - t_start
        with self._state_lock:
            degraded = sorted(self.index_health)
        query_stats = executor.stats
        if degraded:
            query_stats["degraded_indexes"] = degraded
        if routing_on and ledger is not None:
            # Fold the measured outcome back into the ledger (EMA per
            # signature per mode) — the demotion evidence of future runs.
            mode = "indexed" if (self._enabled and use_indexes) else "raw"
            ledger.record(sig, mode, total_s, stamp=routing_stamp)
            query_stats["advisor_routing"] = {
                "decision": mode,
                "demoted": routed == "raw",
            }
        cache_after = self._cache_counts(hio, device_cache)
        profile = obs_profile.build_profile(
            total_s=total_s,
            physical_plan=executor.physical_plan,
            stats=query_stats,
            venue=self._venue_info(),
            cache={k: cache_after[k] - cache_before[k] for k in cache_after},
            fallback={
                "replans": replans,
                "degraded_indexes": degraded,
                "used_indexes": use_indexes,
            },
            trace_root=root_span if isinstance(root_span, obs_trace.Span) else None,
        )
        from hyperspace_tpu.advisor.workload import WorkloadRecord, used_index_names

        self.workload.record(WorkloadRecord(
            signature=sig,
            plan=plan,
            total_s=total_s,
            bytes_scanned=int(query_stats.get("bytes_scanned", 0) or 0),
            used_indexes=use_indexes and self._enabled,
            index_names=used_index_names(optimized),
            profile=profile,
            routed=routed,
        ))
        return QueryOutcome(
            result=result,
            stats=query_stats,
            physical_plan=executor.physical_plan,
            profile=profile,
            replans=replans,
            used_indexes=use_indexes,
        )

    def _publish(self, outcome: QueryOutcome) -> None:
        """Install a finished query's outcome as the session view
        (`last_query_stats` / `last_physical_plan` / `last_profile()`)
        in one locked step, so a reader never sees the stats of one
        query next to the profile of another."""
        with self._state_lock:
            self.last_query_stats = outcome.stats
            self.last_physical_plan = outcome.physical_plan
            self._last_profile = outcome.profile

    @staticmethod
    def _cache_counts(hio, device_cache) -> dict:
        t = hio.table_cache_stats()
        d, h = device_cache.DEVICE_CACHE, device_cache.HOST_DERIVED
        return {
            "table_hits": t["hits"], "table_misses": t["misses"],
            "device_hits": d.hits, "device_misses": d.misses,
            "derived_hits": h.hits, "derived_misses": h.misses,
        }

    def _venue_info(self) -> dict:
        """Where this session's queries physically run (profile evidence)."""
        info: dict = {"mesh": self.mesh is not None}
        try:
            import jax

            dev = jax.devices()[0]
            info["platform"] = dev.platform
            info["device_kind"] = getattr(dev, "device_kind", None)
            info["device_count"] = jax.device_count()
        except Exception:
            info["platform"] = None
        return info

    def last_profile(self):
        """The QueryProfile of the most recent run() in this session
        (None before the first query). Render it with
        `Hyperspace.explain(plan, mode="analyze")` or inspect
        `.to_json()` (docs/observability.md). Under concurrent serving,
        per-query profiles ride the QueryHandle instead
        (docs/serving.md) — this view is only "the most recent"."""
        with self._state_lock:
            return self._last_profile

    def serve(self, **kwargs):
        """Construct a concurrent QueryServer over this session
        (docs/serving.md): bounded worker pool, admission control, and
        the versioned plan/result caches. Keyword arguments override the
        `hyperspace.serve.*` config defaults. The serving subsystem is
        otherwise off — plain `run()` callers never pay for it."""
        from hyperspace_tpu.serve import QueryServer

        return QueryServer(self, **kwargs)

    def to_pandas(self, plan: LogicalPlan):
        import pandas as pd

        return pd.DataFrame(self.run(plan).decode())


class Hyperspace:
    """The 8-method user API (Hyperspace.scala:32-104)."""

    def __init__(self, session: HyperspaceSession):
        self.session = session

    def create_index(self, plan: LogicalPlan, index_config: IndexConfig) -> None:
        self.session.manager.create(plan, index_config)

    def create_vector_index(self, plan: LogicalPlan, config) -> None:
        """Build an ANN index over an embedding column (VectorIndexConfig)."""
        self.session.manager.create_vector(plan, config)

    def ann_search(self, plan: LogicalPlan, queries, k: int, nprobe: int | None = None,
                   embedding_column: str | None = None, metric: str | None = None):
        """Top-k nearest neighbours; probes a matching vector index when
        hyperspace is enabled, else brute-forces the source (exact)."""
        from hyperspace_tpu.vector.search import ann_search

        return ann_search(self.session, plan, queries, k, nprobe, embedding_column, metric)

    def delete_index(self, name: str) -> None:
        self.session.manager.delete(name)

    def restore_index(self, name: str) -> None:
        self.session.manager.restore(name)

    def vacuum_index(self, name: str) -> None:
        self.session.manager.vacuum(name)

    def refresh_index(self, name: str, mode: str = "full") -> None:
        """Rebuild an index. mode="full" re-executes the logged lineage;
        mode="incremental" indexes only appended source files into per-
        bucket delta files (pair with optimize_index to compact)."""
        self.session.manager.refresh(name, mode)
        self._lift_quarantine(name)

    def optimize_index(self, name: str) -> None:
        self.session.manager.optimize(name)
        self._lift_quarantine(name)

    def _lift_quarantine(self, name: str) -> None:
        """A successful rebuild supersedes whatever corruption got the
        index quarantined in this session — let it serve queries again."""
        root = str(self.session.manager.path_resolver.get_index_path(name))
        with self.session._state_lock:
            self.session.index_health.pop(root, None)

    def cancel(self, name: str) -> None:
        self.session.manager.cancel(name)

    def recover(self, name: str | None = None) -> dict:
        """Crash recovery (docs/fault_tolerance.md): quarantine torn log
        entries, roll a transient latest entry to the last stable state
        (cancel semantics), refresh the latestStable pointer, and GC
        version dirs no stable entry references. With no name, every
        index under the system path is recovered. Also lifts the
        session's corruption quarantine (`session.index_health`) so
        repaired indexes serve queries again. Idempotent."""
        mgr = self.session.manager
        if name is not None:
            report = mgr.recover(name)
            root = str(mgr.path_resolver.get_index_path(name))
            with self.session._state_lock:
                self.session.index_health.pop(root, None)
            return report
        reports = {d.name: mgr.recover(d.name) for d in mgr.path_resolver.list_index_paths()}
        with self.session._state_lock:
            self.session.index_health.clear()
        return reports

    def indexes(self):
        return self.session.manager.indexes()

    # -- advisor (docs/advisor.md) ----------------------------------------
    def recommend(self):
        """Ranked create/drop/rebucket/optimize recommendations for the
        session's observed workload — the what-if analyzer replaying
        recorded plans through the real rewrite rules against
        hypothetical indexes. Pure analysis; nothing is mutated."""
        from hyperspace_tpu.advisor.whatif import WhatIfAnalyzer

        return WhatIfAnalyzer(self.session).recommend()

    def lifecycle(self):
        """The autonomous lifecycle policy engine over this API
        (advisor/lifecycle.py). All its gates
        (`hyperspace.advisor.lifecycle.*`) default off — construct it and
        call `.sweep()` after opting in."""
        from hyperspace_tpu.advisor.lifecycle import LifecyclePolicy

        return LifecyclePolicy(self)

    def controller(self, server=None, **kwargs):
        """The self-driving operations controller over this API
        (serve/controller.py, docs/fault_tolerance.md "self-driving
        operations"): a reconciliation loop consuming SLO burn verdicts
        and the structured event ring, actuating only through the
        crash-safe protocols this facade exposes. Gated by
        `hyperspace.controller.enabled` (default off) — construct it,
        opt in, and call `.start()` (or drive `.step()` yourself)."""
        from hyperspace_tpu.serve.controller import OpsController

        return OpsController(self, server=server, **kwargs)

    def ingest(self, **kwargs):
        """The continuous-ingestion daemon over this API
        (hyperspace_tpu/ingest/, docs/ingestion.md): CDC tailing,
        micro-batch commits through the two-phase refresh action, and
        advisor-gated compaction. Register indexes with `.watch(name,
        changelog=...)`, then `.start()` / `.drain()` / `.stop()` — or
        drive `.tick()` yourself. Gated by `hyperspace.ingest.enabled`
        (default off): every tick is a no-op until you opt in."""
        from hyperspace_tpu.ingest.daemon import IngestDaemon

        return IngestDaemon(self, **kwargs)

    def explain(
        self,
        plan: LogicalPlan,
        verbose: bool = False,
        physical: bool = False,
        mode: str | None = None,
    ) -> str:
        """Rules-off/on plan diff. physical=True EXECUTES both variants
        and diffs the physical plans that actually ran (files read,
        kernels, bucket/device counts, rows per operator).
        mode="analyze" EXECUTES the query once under the session's
        current enablement and renders its QueryProfile — per-operator
        measured wall time, rows in/out, bytes, venue, cache and
        fallback outcomes (docs/observability.md)."""
        from hyperspace_tpu.explain.plan_analyzer import (
            explain_analyze,
            explain_executed,
            explain_string,
        )

        if mode == "analyze":
            return explain_analyze(plan, self.session)
        if mode not in (None, "diff"):
            raise HyperspaceError(f"unknown explain mode {mode!r} (diff|analyze)")
        if physical:
            return explain_executed(plan, self.session)
        return explain_string(plan, self.session, verbose=verbose)
