"""The device build pipeline: scan → hash-bucketize → per-shard sort → persist.

This is the framework's write hot path — the TPU-native re-design of the
reference's `df.repartition(numBuckets, indexedCols)` + bucketed sorted
Parquet write (actions/CreateActionBase.scala:99-120 and
index/DataFrameWriterExtensions.scala:49-78):

  host:   parquet → ColumnTable (strings dict-encoded) → row hashes (the
          same uint32 function the query plane uses for bucket pruning)
  device: all_to_all bucketize over the mesh (ops/bucketize.py — the
          Spark-shuffle analog, riding ICI) then ONE fused lexicographic
          lax.sort by (bucket, indexed columns) per shard
  host:   carve the bucket-grouped, key-sorted shards into one parquet
          file per bucket + a manifest of per-bucket row counts

`DeviceIndexBuilder` implements the `IndexWriter` seam consumed by
CreateAction/RefreshAction, and `compact` implements OptimizeAction's
compactor seam.
"""

from __future__ import annotations

import math
import time
from pathlib import Path

import numpy as np

from jax.sharding import Mesh

from hyperspace_tpu.config import DEFAULT_BUILD_MEMORY_BUDGET
from hyperspace_tpu.dataset import format_suffix, list_data_files
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio

# Host hash/sort helpers live in build_exchange (the jax-free module the
# pooled build's worker processes import); re-exported here for the
# query plane's historical import path (executor/exec_side/exec_scan).
from hyperspace_tpu.execution.build_exchange import (  # noqa: F401 — re-exports
    NULL_HASH,
    compute_row_hashes,
    hash_scalar_key,
)
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.parallel.mesh import enable_compile_cache, mesh_size
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan

# Pipeline telemetry (docs/observability.md): occupancy is the mean busy
# fraction of the three p2 stages over the pipeline wall (1.0 = every
# stage saturated — a longer queue window cannot help; ≪1.0 = one stage
# starves the others); queue depth is observed at each reader put.
_MET_OCCUPANCY = obs_metrics.gauge(
    "build.pipeline.occupancy",
    "mean busy fraction of the p2 read/sort/write stages over the pipeline wall",
)
_MET_QDEPTH = obs_metrics.histogram(
    "build.pipeline.queue_depth",
    "bucket-completion queue depth at each reader put",
    buckets=obs_metrics.COUNT_BUCKETS,
)
_MET_POOL_WORKERS = obs_metrics.gauge(
    "build.workers.active",
    "worker processes the pooled build currently has spawned (0 between builds)",
)


def _pad_to(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    if len(arr) == n:
        return arr
    pad = np.full((n - len(arr),) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


def _host_sort_perms(tables, indexed_columns: list[str]) -> list[np.ndarray]:
    """Per-table stable key-sort permutations via the native kernel (the
    streaming build's host sort venue; same order as device_sort_perms)."""
    from hyperspace_tpu import native
    from hyperspace_tpu.ops.sortkeys import key_lanes, lanes_as_unsigned

    perms = []
    for t in tables:
        perm = np.arange(t.num_rows, dtype=np.int64)
        native.sort_range(perm, lanes_as_unsigned(key_lanes(t, indexed_columns)))
        perms.append(perm)
    return perms


def _prefetched(it):
    """One-ahead prefetch over an iterator: the next item decodes on a
    worker thread while the caller processes the current one. Each step
    runs under a `build.p1.decode` span re-planted from the caller
    (pool workers start with an empty contextvar context)."""
    from concurrent.futures import ThreadPoolExecutor

    sentinel = object()
    it = iter(it)

    def step():
        with obs_trace.span("build.p1.decode"):
            return next(it, sentinel)

    step = obs_trace.wrap(step)
    with ThreadPoolExecutor(max_workers=1) as ex:
        fut = ex.submit(step)
        while True:
            cur = fut.result()
            if cur is sentinel:
                return
            fut = ex.submit(step)
            yield cur


class DeviceIndexBuilder:
    """IndexWriter over a device mesh (defaults to all local devices).

    Two build paths, chosen by the parquet footers' uncompressed-size
    estimate against `memory_budget_bytes`:

    - **in-memory** (fits): one host decode, one fused device
      exchange+sort returning just the row permutation, one host gather,
      threaded per-bucket write;
    - **streaming** (doesn't fit): the out-of-core pipeline the reference
      gets from Spark's pipelined scan (actions/CreateActionBase.scala:
      99-120 scans sources of any size) — chunked row-group decode
      (prefetch-overlapped) → per-chunk host bucket partition → per-bucket
      spill row groups → batched device key-sort per bucket → final files.
      Host memory is bounded by `chunk_bytes`, never the source size.
    """

    def __init__(
        self,
        mesh: Mesh | None = None,
        capacity_factor: float = 2.0,
        memory_budget_bytes: int | None = None,
        chunk_bytes: int | None = None,
        venue: str = "auto",
        venue_min_mbps: float = 200.0,
        pipeline_enabled: bool = True,
        pipeline_max_inflight_bytes: int = 0,
        workers: int = 0,
        exchange_dir: str | None = None,
    ):
        self._mesh = mesh
        self.capacity_factor = capacity_factor
        if memory_budget_bytes is None:
            memory_budget_bytes = DEFAULT_BUILD_MEMORY_BUDGET
        self.memory_budget_bytes = memory_budget_bytes
        self.chunk_bytes = chunk_bytes or max(16 << 20, memory_budget_bytes // 8)
        self.venue = venue
        self.venue_min_mbps = venue_min_mbps
        # Streaming-build pipeline (hyperspace.build.pipeline.*): False
        # restores the serial two-phase build — the byte-for-byte
        # reference the pipeline is verified against (bench.py --smoke).
        self.pipeline_enabled = pipeline_enabled
        self.pipeline_max_inflight_bytes = pipeline_max_inflight_bytes
        # Scale-out pooled build (hyperspace.build.workers, docs/
        # architecture.md "scale-out build"): 0 = in-process (the paths
        # above, unchanged); N > 0 splits the build across N spawned
        # worker processes exchanging rows through per-owner spill files
        # — byte-identical to the serial streaming reference.
        self.workers = int(workers)
        self.exchange_dir = str(exchange_dir) if exchange_dir else None
        self.last_build_stats: dict = {}
        self._last_phases: dict = {}
        enable_compile_cache()

    def _sort_venue(self, mesh) -> str:
        """Where the bucketize+sort permutation is computed. The sort's
        only output is a row-id permutation that must land on host; on a
        slow device→host link (tunneled TPU) the readback dominates, so
        auto picks the threaded C++ counting-sort + per-bucket key sort
        when a single device would run the exchange anyway. A real
        multi-device mesh keeps the device all_to_all path in auto mode
        (the distributed exchange is the point); a forced venue wins."""
        from hyperspace_tpu.parallel.bandwidth import pick_venue

        return pick_venue(
            self.venue, self.venue_min_mbps,
            prefer_device=mesh_size(mesh) > 1,
            what="hyperspace.build.venue",
        )

    def _mesh_for(self, num_buckets: int) -> Mesh:
        # Shrink to the largest device count dividing num_buckets
        # (dropping any multi-slice structure — correctness first).
        from hyperspace_tpu.parallel.mesh import mesh_for_parallelism

        return mesh_for_parallelism(self._mesh, num_buckets)

    # -- IndexWriter -----------------------------------------------------
    def write(
        self,
        plan: LogicalPlan,
        columns: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
    ) -> None:
        if not isinstance(plan, Scan):
            raise HyperspaceError("index builds materialize scan-only plans")
        if plan.files is not None:
            files = list(plan.files)
        else:
            files = [fi.path for fi in list_data_files(plan.root, suffix=format_suffix(plan.format))]
        if self.workers > 0 and files:
            # Scale-out path: the pooled build IS a streaming build (it
            # exchanges through spill files), so it runs regardless of
            # the memory estimate and stays byte-identical to the serial
            # streaming reference at every source size.
            self._write_pooled(
                files, plan.scan_schema, columns, indexed_columns, num_buckets,
                dest_path, fmt=plan.format,
            )
            return
        if plan.format == "parquet":
            footers = hio.read_footers(files)
            est = hio.estimate_uncompressed_bytes(files, columns, footers=footers)
            if est > self.memory_budget_bytes:
                self._write_streaming(
                    files, plan.scan_schema, columns, indexed_columns, num_buckets,
                    dest_path, est, footers=footers,
                )
                return
        else:
            # Non-parquet sources: a rough on-disk-size inflate picks the
            # path; above the budget they stream too — CSV by record
            # batches, ORC by stripes, JSON at file granularity (pyarrow
            # has no incremental JSON reader, so the memory bound holds
            # per file there).
            import os

            est = sum(os.stat(f).st_size for f in files) * 4
            if est > self.memory_budget_bytes:
                self._write_streaming(
                    files, plan.scan_schema, columns, indexed_columns, num_buckets,
                    dest_path, est, fmt=plan.format,
                )
                return
        t0 = time.perf_counter()
        table = hio.read_table_files(files, plan.format, columns=columns, schema=plan.schema)
        t_decode = time.perf_counter() - t0
        self.write_table(table, indexed_columns, num_buckets, dest_path)
        phases = dict(self._last_phases)
        phases["decode"] = round(t_decode, 4)
        self.last_build_stats = {
            "path": "in-memory",
            "bytes_estimate": est,
            "rows": table.num_rows,
            "phases_s": phases,
        }

    def write_table(
        self,
        table: ColumnTable,
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
    ) -> None:
        from hyperspace_tpu.ops.bucketize import bucketize_perm
        from hyperspace_tpu.ops.sortkeys import key_lanes

        mesh = self._mesh_for(num_buckets)
        d = mesh_size(mesh)
        n = table.num_rows
        t0 = time.perf_counter()

        # Host: bucket assignment from the canonical row hash.
        row_hash = compute_row_hashes(table, indexed_columns)
        bucket = bucket_ids(row_hash, num_buckets, np)

        # Host: decompose key columns into order-preserving 32-bit lanes
        # (ops/sortkeys.py — no np.unique rank pass; streaming-safe).
        # Payload bytes never touch the device: the exchange+sort emits a
        # row-id permutation and the host gathers columns by it.
        key_names = [table.schema.field(c).name for c in indexed_columns]
        lanes = key_lanes(table, indexed_columns)
        t_hash = time.perf_counter()

        sort_fn = None
        if self._sort_venue(mesh) == "host":
            # Host venue: C++ counting-sort by bucket now; each bucket's
            # key sort runs INSIDE its carve task (sort_fn) so sorting
            # pipelines with the parquet encode of other buckets — no
            # device round-trip (the permutation is the sort's only
            # output and it must land on host).
            from hyperspace_tpu import native
            from hyperspace_tpu.ops.sortkeys import lanes_as_unsigned

            order, bucket_rows = native.bucket_perm(bucket, num_buckets)
            lanes_u = lanes_as_unsigned(lanes)

            def sort_fn(p: int, sel: np.ndarray) -> np.ndarray:
                native.sort_range(sel, lanes_u)
                return sel
        else:
            # Pad rows to a multiple of the mesh size; rows past n are pads
            # (the device derives validity from the global row id).
            n_pad = max(d, math.ceil(max(n, 1) / d) * d)
            bucket_p = _pad_to(bucket, n_pad)
            lanes_p = [_pad_to(l, n_pad) for l in lanes]

            # Device: the exchange (Spark-shuffle analog, single all_to_all)
            # fused with the per-shard lex sort by (bucket, key lanes); ONE
            # int32-per-row readback (the permutation).
            order, bucket_rows = bucketize_perm(
                mesh, lanes_p, bucket_p, n, num_buckets, self.capacity_factor
            )
        if len(order) != n:
            raise HyperspaceError(
                f"row count changed through exchange: {n} → {len(order)}"
            )
        t_exchange = time.perf_counter()
        compact_bucket = np.repeat(
            np.arange(num_buckets, dtype=np.int32), bucket_rows
        )

        # Host: carve into per-bucket files, gathering each bucket's rows
        # by its slice of the permutation INSIDE the write threads (the
        # gather overlaps the parquet encode — and, host venue, the key
        # sort — of other buckets). Devices own contiguous bucket ranges
        # in mesh order and each shard is bucket-sorted, so the compacted
        # global bucket array is sorted.
        field_names = [f.name for f in table.schema.fields]
        payload_names = [c for c in field_names if c not in key_names]
        hio.carve_and_write(
            Path(dest_path), table.select(key_names + payload_names),
            compact_bucket, num_buckets, indexed_columns,
            order=order, sort_fn=sort_fn,
        )
        t_done = time.perf_counter()
        # Phase wall times. On the host venue the per-bucket KEY sort
        # runs inside the carve tasks (pipelined with parquet encode), so
        # it lands in carve_encode_write by design.
        self._last_phases = {
            "hash_lanes": round(t_hash - t0, 4),
            "partition_exchange": round(t_exchange - t_hash, 4),
            "carve_encode_write": round(t_done - t_exchange, 4),
        }

    # -- streaming out-of-core build -------------------------------------
    def _write_streaming(
        self,
        files: list[str],
        schema,
        columns: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
        est_bytes: int,
        footers=None,
        fmt: str = "parquet",
    ) -> None:
        import shutil
        from concurrent.futures import ThreadPoolExecutor

        import pyarrow.parquet as pq

        from hyperspace_tpu.ops.sortkeys import device_sort_perms

        dest = Path(dest_path)
        spill = dest.parent / (dest.name + ".spill")
        if spill.exists():
            shutil.rmtree(spill)
        spill.mkdir(parents=True, exist_ok=True)
        sub_schema = schema.select(columns)
        key_names = [sub_schema.field(c).name for c in indexed_columns]
        payload_names = [f.name for f in sub_schema.fields if f.name not in key_names]
        ordered = key_names + payload_names

        pipelined = self.pipeline_enabled
        writers: dict[int, pq.ParquetWriter] = {}
        spill_bytes: dict[int, int] = {}
        total_rows = 0
        n_chunks = 0
        pipe_info: dict | None = None
        try:
            # Phase 1: stream decoded chunks (format-aware iterator);
            # decode of chunk i+1 overlaps the hash/partition/spill of
            # chunk i via the one-ahead prefetcher. Pipelined mode also
            # fans the per-bucket spill encodes of chunk i out to pool
            # workers (waiting out chunk i−1's first, so per-bucket write
            # order stays chunk order and host memory stays ≤ two
            # chunks) — decode ‖ hash ‖ encode instead of decode ‖ rest.
            t_p1 = time.perf_counter()
            decode_wait = 0.0
            gen = _prefetched(
                self._decoded_chunks(files, fmt, columns, schema, footers=footers)
            )
            _SENTINEL = object()
            def _encode_chunk(parts: list) -> None:
                # One pool task per CHUNK (not per bucket): per-bucket
                # futures cost more churn than the encodes they cover.
                with obs_trace.span("build.p1.spill", parts=len(parts)):
                    for w, part in parts:
                        w.write_table(part)

            _encode_chunk_w = obs_trace.wrap(_encode_chunk)
            with ThreadPoolExecutor(max_workers=2) as p1_pool:
                spill_fut = None
                while True:
                    tw = time.perf_counter()
                    at = next(gen, _SENTINEL)
                    decode_wait += time.perf_counter() - tw
                    if at is _SENTINEL:
                        break
                    n_chunks += 1
                    ct = ColumnTable.from_arrow(at, sub_schema).select(ordered)
                    total_rows += ct.num_rows
                    bucket = bucket_ids(
                        compute_row_hashes(ct, indexed_columns), num_buckets, np
                    )
                    order = np.argsort(bucket, kind="stable")
                    sb = bucket[order]
                    starts = np.searchsorted(sb, np.arange(num_buckets + 1))
                    arrow_sorted = ct.take(order).to_arrow()
                    parts: list = []
                    for b in range(num_buckets):
                        lo, hi = int(starts[b]), int(starts[b + 1])
                        if hi <= lo:
                            continue
                        w = writers.get(b)
                        if w is None:
                            # Spill is engine-private scratch: the cheap codec
                            # (see io.INDEX_WRITE_COMPRESSION) beats snappy on
                            # encode CPU, which bounds phase 1 on small hosts,
                            # and dictionary encoding stays strings-only for
                            # the same reason write_bucket's does.
                            w = pq.ParquetWriter(
                                spill / hio.bucket_file_name(b),
                                arrow_sorted.schema,
                                compression=hio.INDEX_WRITE_COMPRESSION,
                                # Stats skipped like write_bucket's: spill
                                # footers are only read for sizes.
                                write_statistics=False,
                                use_dictionary=[
                                    f.name for f in sub_schema.select(ordered).fields if f.is_string
                                ],
                            )
                            writers[b] = w
                        part = arrow_sorted.slice(lo, hi - lo)
                        # Decoded-size ledger: the pipeline's p2 window
                        # admits buckets by these bytes, so no spill
                        # footer is ever re-opened (io.footer_cache
                        # dedupes the rest).
                        spill_bytes[b] = spill_bytes.get(b, 0) + part.nbytes
                        parts.append((w, part))
                    if pipelined:
                        # Waiting out chunk i−1 HERE (after chunk i's
                        # hash/partition) keeps per-writer chunk order —
                        # the spill bytes stay identical to the serial
                        # path's — while chunk i−1's encode overlapped
                        # this chunk's decode and hash.
                        if spill_fut is not None:
                            spill_fut.result()
                        spill_fut = p1_pool.submit(_encode_chunk_w, parts)
                    else:
                        for w, part in parts:
                            w.write_table(part)
                if spill_fut is not None:
                    spill_fut.result()
            if not pipelined:
                for w in writers.values():
                    w.close()
            t_p2 = time.perf_counter()

            # Phase 2. Pipelined: writer closes feed a bounded
            # bucket-completion queue; spill-read of bucket b+1 overlaps
            # the key sort of b overlaps the final write of b−1 (see
            # _p2_pipelined). Serial: the original batched two-step.
            dest.mkdir(parents=True, exist_ok=True)
            bucket_rows = [0] * num_buckets
            key_stats: list = [None] * num_buckets
            col_stats: list = [None] * num_buckets
            stat_cols = [
                f.name
                for f in sub_schema.select(ordered).fields
                if not f.is_vector and f.name != sub_schema.field(indexed_columns[0]).name
            ]
            sort_venue = self._sort_venue(self._mesh_for(num_buckets))
            if pipelined:
                pipe_info = self._p2_pipelined(
                    writers, spill, spill_bytes, dest, sub_schema, ordered,
                    indexed_columns, num_buckets, stat_cols, sort_venue,
                    bucket_rows, key_stats, col_stats,
                )
            else:
                # Batches are planned from the SPILL FOOTERS (uncompressed
                # bytes per bucket), so at most ~chunk_bytes of bucket data
                # is resident at once — the memory bound holds end to end,
                # not just in phase 1. Within a batch, reads and writes are
                # threaded; the sort is one device call.
                spill_files = {
                    b: str(spill / hio.bucket_file_name(b))
                    for b in range(num_buckets)
                    if (spill / hio.bucket_file_name(b)).exists()
                }
                spill_footers = hio.read_footers(list(spill_files.values()))
                bucket_bytes = {
                    b: hio.estimate_uncompressed_bytes([p], footers={p: spill_footers[p]})
                    for b, p in spill_files.items()
                }
                batches: list[list[int]] = []
                cur: list[int] = []
                cur_bytes = 0
                for b in sorted(spill_files):
                    if cur and cur_bytes + bucket_bytes[b] > self.chunk_bytes:
                        batches.append(cur)
                        cur, cur_bytes = [], 0
                    cur.append(b)
                    cur_bytes += bucket_bytes[b]
                if cur:
                    batches.append(cur)

                with ThreadPoolExecutor(max_workers=8) as pool:
                    empty = ColumnTable.empty(sub_schema.select(ordered))
                    for b in range(num_buckets):
                        if b not in spill_files:
                            hio.write_bucket(dest, b, empty)
                    for ids in batches:
                        tables = list(pool.map(lambda b: hio.read_parquet([spill_files[b]]), ids))
                        if sort_venue == "host":
                            perms = _host_sort_perms(tables, indexed_columns)
                        else:
                            perms = device_sort_perms(tables, indexed_columns)
                        futs = [
                            pool.submit(hio.write_bucket, dest, b, t.take(p))
                            for b, t, p in zip(ids, tables, perms)
                        ]
                        for b, t in zip(ids, tables):
                            bucket_rows[b] = t.num_rows
                            key_stats[b] = hio.bucket_key_stats(t, indexed_columns[0])
                            if stat_cols:
                                col_stats[b] = hio.bucket_column_stats(t, stat_cols)
                        for f in futs:
                            f.result()
            hio.write_manifest(
                dest, num_buckets, indexed_columns, bucket_rows,
                key_stats if any(s is not None for s in key_stats) else None,
                col_stats if any(s is not None for s in col_stats) else None,
            )
        finally:
            shutil.rmtree(spill, ignore_errors=True)
        t_end = time.perf_counter()
        self.last_build_stats = {
            "path": "streaming",
            "format": fmt,
            "bytes_estimate": est_bytes,
            "chunks": n_chunks,
            "rows": total_rows,
            # Phase walls: p1 = decode→hash→partition→spill (decode_wait
            # is the NON-overlapped decode stall inside it — the prefetch
            # hides the rest); p2 = spill read→key sort→final write
            # (pipelined mode overlaps its stages AND the writer closes,
            # so p2 here is the OVERLAPPED wall, not a sum of stages).
            "phases_s": {
                "p1_decode_hash_spill": round(t_p2 - t_p1, 4),
                "p1_decode_wait": round(decode_wait, 4),
                "p2_sort_encode_write": round(t_end - t_p2, 4),
            },
        }
        if pipe_info is not None:
            self.last_build_stats["pipeline"] = pipe_info

    # -- scale-out pooled build ------------------------------------------
    def _exchange_root(self, dest: Path) -> Path:
        """Where this build's cross-process spill exchange lives:
        `hyperspace.build.exchange.dir` (suffixed with the dest name so
        concurrent builds never collide), or `<dest>.exchange` next to
        the version dir (same filesystem as the output)."""
        if self.exchange_dir:
            return Path(self.exchange_dir) / f"{dest.parent.name}-{dest.name}.exchange"
        return dest.parent / (dest.name + ".exchange")

    def _write_pooled(
        self,
        files: list[str],
        schema,
        columns: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
        fmt: str = "parquet",
    ) -> None:
        """The scale-out build (docs/architecture.md "scale-out build"):
        bucket id → owner is the shard key, spill files are the
        cross-process exchange format, and the only things crossing the
        process boundary are paths plus the decoded-byte ledger.

        - **p1** — ≤ `workers` shard processes, each decoding a disjoint
          *contiguous* slice of the input files, hashing/partitioning
          rows, and appending per-bucket spill parquet into the
          destination owners' exchange dirs (build_exchange.p1_shard);
        - **p2** — ≤ min(workers, num_buckets) owner processes, each
          reading its buckets' spill in shard order (reproducing the
          global row order), key-sorting, and writing the final bucket
          files + stats in parallel (build_exchange.p2_owner);
        - **coordinator** — slices files, babysits the pools (a dead
          worker is a typed WorkerCrashed abort, never a hang), merges
          the per-owner manifest stats, and writes the manifest. The
          surrounding Action 2-phase protocol is untouched, so commit
          semantics — and the output bytes — match the in-process
          streaming build exactly.

        The exchange dir is swept in `finally`, success or abort."""
        import os
        import shutil

        from hyperspace_tpu import stats
        from hyperspace_tpu.execution import build_exchange as bx
        from hyperspace_tpu.parallel.procpool import TaskPool

        dest = Path(dest_path)
        exchange = self._exchange_root(dest)
        if exchange.exists():
            shutil.rmtree(exchange)
        exchange.mkdir(parents=True, exist_ok=True)
        try:
            sizes = [os.stat(f).st_size for f in files]
        except OSError:
            sizes = [1] * len(files)
        slices = bx.slice_files(files, sizes, self.workers)
        n_shards = len(slices)
        num_owners = max(1, min(self.workers, num_buckets))
        # Per-owner one-ahead read window: the same maxInflightBytes
        # budget the p2 pipeline uses, fed from p1's decoded-byte ledger.
        window = self.pipeline_max_inflight_bytes or max(1, 4 * self.chunk_bytes)
        total_rows = 0
        n_chunks = 0
        spill_bytes: dict[int, int] = {}
        try:
            t0 = time.perf_counter()
            with obs_trace.span("build.pool.p1", shards=n_shards):
                with TaskPool("hs-build-p1") as pool:
                    for w, slc in enumerate(slices):
                        fault_point("build.worker.spawn", str(exchange))
                        pool.submit(w, bx.p1_shard, bx.P1Task(
                            worker=w, files=slc, fmt=fmt, columns=list(columns),
                            schema=schema, indexed_columns=list(indexed_columns),
                            num_buckets=num_buckets, num_owners=num_owners,
                            chunk_bytes=self.chunk_bytes,
                            memory_budget_bytes=self.memory_budget_bytes,
                            exchange_dir=str(exchange),
                        ))
                        _MET_POOL_WORKERS.set(w + 1)
                    p1 = pool.join()
            _MET_POOL_WORKERS.set(0)
            for _, res in sorted(p1.items()):
                total_rows += res["rows"]
                n_chunks += res["chunks"]
                for b, nb in res["spill_bytes"].items():
                    spill_bytes[b] = spill_bytes.get(b, 0) + nb
            exchange_bytes = sum(spill_bytes.values())
            stats.increment("build.exchange.bytes", exchange_bytes)
            t_p2 = time.perf_counter()

            dest.mkdir(parents=True, exist_ok=True)
            with obs_trace.span("build.pool.p2", owners=num_owners):
                with TaskPool("hs-build-p2") as pool:
                    for o in range(num_owners):
                        fault_point("build.worker.spawn", str(exchange))
                        pool.submit(o, bx.p2_owner, bx.P2Task(
                            owner=o, num_owners=num_owners, n_shards=n_shards,
                            num_buckets=num_buckets, exchange_dir=str(exchange),
                            dest_dir=str(dest), columns=list(columns),
                            schema=schema, indexed_columns=list(indexed_columns),
                            spill_bytes={
                                b: nb for b, nb in spill_bytes.items()
                                if bx.owner_of(b, num_owners) == o
                            },
                            window_bytes=window,
                        ))
                        _MET_POOL_WORKERS.set(o + 1)
                    p2 = pool.join()
            _MET_POOL_WORKERS.set(0)

            fault_point("build.manifest.merge", str(dest))
            bucket_rows = [0] * num_buckets
            key_stats: list = [None] * num_buckets
            col_stats: list = [None] * num_buckets
            for _, res in sorted(p2.items()):
                for b, r in res["bucket_rows"].items():
                    bucket_rows[b] = r
                for b, s in res["key_stats"].items():
                    key_stats[b] = s
                for b, s in res["col_stats"].items():
                    col_stats[b] = s
            hio.write_manifest(
                dest, num_buckets, indexed_columns, bucket_rows,
                key_stats if any(s is not None for s in key_stats) else None,
                col_stats if any(s is not None for s in col_stats) else None,
            )
            t_end = time.perf_counter()
        finally:
            _MET_POOL_WORKERS.set(0)
            shutil.rmtree(exchange, ignore_errors=True)
        self.last_build_stats = {
            "path": "pooled",
            "format": fmt,
            "workers": self.workers,
            "p1_shards": n_shards,
            "p2_owners": num_owners,
            "rows": total_rows,
            "chunks": n_chunks,
            "exchange_bytes": exchange_bytes,
            "phases_s": {
                "p1_decode_hash_spill": round(t_p2 - t0, 4),
                "p2_sort_encode_write": round(t_end - t_p2, 4),
            },
        }

    def _p2_pipelined(
        self,
        writers,
        spill: Path,
        spill_bytes: dict[int, int],
        dest: Path,
        sub_schema,
        ordered: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        stat_cols: list[str],
        sort_venue: str,
        bucket_rows: list,
        key_stats: list,
        col_stats: list,
    ) -> dict:
        """The 3-stage phase-2 pipeline behind a bounded bucket-completion
        queue: writer CLOSES fan out to the pool and feed the queue as
        they land, the reader admits buckets under a byte-budgeted
        in-flight window and decodes them (`spill.read`), the sort stage
        (this thread) computes each bucket's key permutation, and write
        tasks gather+encode the final file — so the spill read of bucket
        b+1 overlaps the key sort of b overlaps the parquet write of b−1,
        and the first reads overlap the remaining closes (the only
        p1→p2 order that hash partitioning permits: every bucket needs
        every chunk). Crash-safe: reader failures re-raise on this
        thread via the error sentinel, writers release their window bytes
        in `finally`, and the stop flag unblocks a parked reader, so the
        spill dir's cleanup (caller's `finally`) always runs.

        Mutates bucket_rows/key_stats/col_stats in place (distinct slots
        per bucket) and returns the pipeline telemetry dict."""
        import queue as _queue
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from hyperspace_tpu.ops.sortkeys import device_sort_perms

        # The window covers buckets across ALL THREE stages (a bucket's
        # bytes release only when its final write lands), so it needs
        # headroom beyond one sort batch or the reader starves.
        window = self.pipeline_max_inflight_bytes or max(1, 4 * self.chunk_bytes)
        cv = threading.Condition()
        inflight = {"bytes": 0}
        stop = [False]
        ready: "_queue.Queue" = _queue.Queue()  # bucket ids whose spill writer closed
        sortq: "_queue.Queue" = _queue.Queue()  # (bucket, table, nbytes) | _DONE | _ERR
        _DONE, _ERR = object(), object()
        busy = {"read": 0.0, "sort": 0.0, "write": 0.0}
        busy_lock = threading.Lock()
        max_depth = [0]
        spill_ids = sorted(writers)
        n_spilled = len(spill_ids)

        def close_one(b: int) -> None:
            try:
                writers[b].close()
            finally:
                # Enqueue even on a failed close: the reader's decode of
                # the torn spill file surfaces the error (never a hang).
                ready.put(b)

        def read_loop() -> None:
            try:
                for _ in range(n_spilled):
                    b = ready.get()
                    if stop[0]:
                        return
                    nb = max(1, spill_bytes.get(b, 1))
                    with cv:
                        while not stop[0] and inflight["bytes"] > 0 and inflight["bytes"] + nb > window:
                            cv.wait()
                        if stop[0]:
                            return
                        inflight["bytes"] += nb
                    path = str(spill / hio.bucket_file_name(b))
                    fault_point("spill.read", path)
                    t0 = time.perf_counter()
                    with obs_trace.span("build.p2.read", bucket=b, bytes=nb):
                        t = hio.read_parquet([path])
                    with busy_lock:
                        busy["read"] += time.perf_counter() - t0
                    fault_point("pipeline.put", path)
                    sortq.put((b, t, nb))
                    d = sortq.qsize()
                    _MET_QDEPTH.observe(d)
                    if d > max_depth[0]:
                        max_depth[0] = d
            except BaseException:
                sortq.put(_ERR)
                raise
            sortq.put(_DONE)

        def write_one(b: int, t: ColumnTable, perm: np.ndarray, nb: int) -> None:
            try:
                t0 = time.perf_counter()
                with obs_trace.span("build.p2.write", bucket=b):
                    # Manifest stats ride the write stage (min/max is
                    # permutation-invariant, so computing them pre-gather
                    # matches the serial path exactly) — they parallelize
                    # across write workers instead of serializing the
                    # sort stage.
                    bucket_rows[b] = t.num_rows
                    key_stats[b] = hio.bucket_key_stats(t, indexed_columns[0])
                    if stat_cols:
                        col_stats[b] = hio.bucket_column_stats(t, stat_cols)
                    hio.write_bucket(dest, b, t.take(perm))
                with busy_lock:
                    busy["write"] += time.perf_counter() - t0
            finally:
                with cv:
                    inflight["bytes"] -= nb
                    cv.notify_all()

        t_start = time.perf_counter()
        wfuts: list = []
        with ThreadPoolExecutor(max_workers=8) as pool:
            empty = ColumnTable.empty(sub_schema.select(ordered))
            for b in range(num_buckets):
                if b not in writers:
                    wfuts.append(pool.submit(obs_trace.wrap(hio.write_bucket), dest, b, empty))
            for b in spill_ids:
                pool.submit(obs_trace.wrap(close_one), b)
            rfut = pool.submit(obs_trace.wrap(read_loop))
            try:
                sentinel = None
                while sentinel is None:
                    fault_point("pipeline.get")
                    item = sortq.get()
                    if item is _DONE or item is _ERR:
                        break
                    # Micro-batch: drain whatever the reader has already
                    # staged (≤8 buckets) into ONE device sort call. Each
                    # table pads and sorts independently inside the batch
                    # (ops/sortkeys.device_sort_perms), so every bucket's
                    # permutation is identical whatever batch it lands in
                    # — batching amortizes dispatch, never changes bytes.
                    batch = [item]
                    while len(batch) < 8:
                        try:
                            nxt = sortq.get_nowait()
                        except _queue.Empty:
                            break
                        if nxt is _DONE or nxt is _ERR:
                            sentinel = nxt
                            break
                        batch.append(nxt)
                    ts = [t for _, t, _ in batch]
                    t0 = time.perf_counter()
                    with obs_trace.span(
                        "build.p2.sort", buckets=len(batch), rows=sum(t.num_rows for t in ts)
                    ):
                        if sort_venue == "host":
                            perms = _host_sort_perms(ts, indexed_columns)
                        else:
                            perms = device_sort_perms(ts, indexed_columns)
                    busy["sort"] += time.perf_counter() - t0
                    for (b, t, nb), perm in zip(batch, perms):
                        wfuts.append(pool.submit(obs_trace.wrap(write_one), b, t, perm, nb))
                item = sentinel if sentinel is not None else item
                if item is _ERR:
                    rfut.result()  # re-raises the reader's failure here
            finally:
                with cv:
                    stop[0] = True
                    cv.notify_all()
            for f in wfuts:
                f.result()
        wall = time.perf_counter() - t_start
        occ = 0.0
        if wall > 0:
            occ = sum(min(v, wall) for v in busy.values()) / (3 * wall)
        _MET_OCCUPANCY.set(round(occ, 4))
        return {
            "occupancy": round(occ, 4),
            "max_queue_depth": max_depth[0],
            "window_bytes": window,
            "stage_busy_s": {k: round(v, 4) for k, v in busy.items()},
        }

    def _decoded_chunks(self, files, fmt: str, columns, schema, footers=None):
        """Yield pyarrow Tables of ≤ ~chunk_bytes decoded source data —
        the shared format-aware chunked decode in build_exchange.py (the
        pooled build's p1 shard workers drive the same generator over
        their own file slices)."""
        from hyperspace_tpu.execution.build_exchange import decoded_chunks

        yield from decoded_chunks(
            files, fmt, columns, schema,
            self.chunk_bytes, self.memory_budget_bytes, footers=footers,
        )

    # -- OptimizeAction's compactor seam ---------------------------------
    def compact(self, entry, src_paths: list[Path] | Path, dest_path: Path) -> None:
        """Merge all files of each bucket across every live version dir
        (base + incremental-refresh deltas) into one sorted file per bucket
        in the new version dir. Indexes too large for the in-memory path
        compact through the same streaming pipeline that built them."""
        from hyperspace_tpu.schema import Schema

        num_buckets = entry.derived_dataset.num_buckets
        indexed = entry.derived_dataset.indexed_columns
        if isinstance(src_paths, (str, Path)):
            src_paths = [src_paths]
        files = [fi.path for src in src_paths for fi in list_data_files(src)]
        footers = hio.read_footers(files)
        est = hio.estimate_uncompressed_bytes(files, footers=footers)
        if est > self.memory_budget_bytes:
            import pyarrow.parquet as pq

            schema = Schema.from_arrow(pq.ParquetFile(files[0]).schema_arrow)
            self._write_streaming(
                files, schema, list(schema.names), indexed, num_buckets,
                dest_path, est, footers=footers,
            )
            return
        table = hio.read_parquet(files)
        self.write_table(table, indexed, num_buckets, dest_path)
