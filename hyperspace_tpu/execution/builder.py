"""The device build pipeline: scan → hash-bucketize → per-shard sort → persist.

This is the framework's write hot path — the TPU-native re-design of the
reference's `df.repartition(numBuckets, indexedCols)` + bucketed sorted
Parquet write (actions/CreateActionBase.scala:99-120 and
index/DataFrameWriterExtensions.scala:49-78):

  host:   parquet → ColumnTable (strings dict-encoded) → row hashes (the
          same uint32 function the query plane uses for bucket pruning)
  device: all_to_all bucketize over the mesh (ops/bucketize.py — the
          Spark-shuffle analog, riding ICI) then ONE fused lexicographic
          lax.sort by (bucket, indexed columns) per shard
  host:   carve the bucket-grouped, key-sorted shards into one parquet
          file per bucket + a manifest of per-bucket row counts

`DeviceIndexBuilder` implements the `IndexWriter` seam consumed by
CreateAction/RefreshAction, and `compact` implements OptimizeAction's
compactor seam.
"""

from __future__ import annotations

import math
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from hyperspace_tpu.dataset import list_data_files
from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.bucketize import bucketize
from hyperspace_tpu.ops.hashing import bucket_ids, combine_hashes, hash_int_column, string_dict_hashes
from hyperspace_tpu.parallel.mesh import enable_compile_cache, make_mesh, mesh_size
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan


# The fixed hash contribution of a NULL key slot: nulls bucket
# deterministically (they can never match an equality literal, so bucket
# pruning by literal hash stays correct regardless).
NULL_HASH = np.uint32(0x9E3779B9)


def compute_row_hashes(table: ColumnTable, key_columns: list[str]) -> np.ndarray:
    """Host-side uint32 row hash over the key columns. Deterministic and
    dictionary-independent (ops/hashing.py), so the query plane can prune
    buckets by recomputing the same hash on a literal."""
    hashes = []
    for name in key_columns:
        f = table.schema.field(name)
        arr = table.columns[f.name]
        if f.is_string:
            dh = string_dict_hashes(table.dictionaries[f.name])
            h = dh[arr]
        else:
            h = hash_int_column(arr, np)
        valid = table.valid_mask(name)
        if valid is not None:
            h = np.where(valid, h, NULL_HASH)
        hashes.append(h)
    return combine_hashes(hashes, np)


def hash_scalar_key(values: list, fields) -> np.ndarray:
    """Hash one key tuple (for bucket pruning at query time)."""
    hs = []
    for v, f in zip(values, fields):
        if f.is_string:
            hs.append(string_dict_hashes(np.array([v], dtype=object)))
        else:
            hs.append(hash_int_column(np.array([v], dtype=f.device_dtype), np))
    return combine_hashes(hs, np)


def _fast_take(arr: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """Threaded native gather when built, numpy fancy-index otherwise."""
    from hyperspace_tpu import native

    out = native.take_rows(arr, idx)
    return out if out is not None else arr[idx]


def _pad_to(arr: np.ndarray, n: int, fill=0) -> np.ndarray:
    if len(arr) == n:
        return arr
    pad = np.full((n - len(arr),) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])


class DeviceIndexBuilder:
    """IndexWriter over a device mesh (defaults to all local devices)."""

    def __init__(self, mesh: Mesh | None = None, capacity_factor: float = 2.0):
        self._mesh = mesh
        self.capacity_factor = capacity_factor
        enable_compile_cache()

    def _mesh_for(self, num_buckets: int) -> Mesh:
        # Shrink to the largest device count dividing num_buckets
        # (dropping any multi-slice structure — correctness first).
        from hyperspace_tpu.parallel.mesh import mesh_for_parallelism

        return mesh_for_parallelism(self._mesh, num_buckets)

    # -- IndexWriter -----------------------------------------------------
    def write(
        self,
        plan: LogicalPlan,
        columns: list[str],
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
    ) -> None:
        table = self._materialize(plan, columns)
        self.write_table(table, indexed_columns, num_buckets, dest_path)

    def write_table(
        self,
        table: ColumnTable,
        indexed_columns: list[str],
        num_buckets: int,
        dest_path: Path,
    ) -> None:
        mesh = self._mesh_for(num_buckets)
        d = mesh_size(mesh)
        n = table.num_rows

        # Host: bucket assignment from the canonical row hash.
        row_hash = compute_row_hashes(table, indexed_columns)
        bucket = bucket_ids(row_hash, num_buckets, np)

        # Host: order-preserving int32 rank codes per key column. The
        # device exchange + sort run entirely in native int32 (TPU has no
        # native 64-bit sort; pushing int64/float64 payloads through a
        # variadic lax.sort is both slow to compile and slow to run).
        # Payload bytes never touch the device: the sort emits a row-id
        # permutation and the host gathers the original columns by it.
        key_names = [table.schema.field(c).name for c in indexed_columns]
        key_codes = []
        for kname in key_names:
            f = table.schema.field(kname)
            arr = table.columns[kname]
            if f.is_string:
                codes = arr.astype(np.int32)  # sorted-dict codes (copy)
            else:
                _, inv = np.unique(arr, return_inverse=True)
                codes = inv.astype(np.int32)
            valid = table.valid_mask(kname)
            if valid is not None:
                codes[~valid] = -1  # nulls sort FIRST within their bucket
            key_codes.append(codes)

        # Pad rows to a multiple of the mesh size.
        n_pad = max(d, math.ceil(max(n, 1) / d) * d)
        valid = _pad_to(np.ones(n, np.int32), n_pad)
        bucket_p = _pad_to(bucket, n_pad)
        gid = _pad_to(np.arange(n, dtype=np.int32), n_pad)
        codes_p = [_pad_to(c, n_pad) for c in key_codes]

        # Device: the exchange (Spark-shuffle analog, single all_to_all)
        # fused with the per-shard lex sort by (bucket, key codes); the
        # row-id rides along as the only payload.
        out_cols, out_bucket, out_valid = bucketize(
            mesh,
            [jnp.asarray(c) for c in codes_p] + [jnp.asarray(gid)],
            jnp.asarray(bucket_p),
            jnp.asarray(valid),
            num_buckets,
            self.capacity_factor,
            num_key_cols=len(key_names),
        )
        out_bucket_h = np.asarray(jax.device_get(out_bucket))
        gid_h = np.asarray(jax.device_get(out_cols[-1]))
        valid_mask = out_bucket_h < num_buckets  # sentinel marks invalid

        # Host: gather every column by the device-computed permutation and
        # carve into per-bucket files.
        compact_bucket = out_bucket_h[valid_mask]
        order = gid_h[valid_mask]
        if len(order) != n:
            raise HyperspaceError(
                f"row count changed through exchange: {n} → {len(order)}"
            )
        field_names = [f.name for f in table.schema.fields]
        payload_names = [c for c in field_names if c not in key_names]
        ordered = key_names + payload_names
        # Devices own contiguous bucket ranges in mesh order and each shard
        # is bucket-sorted, so the compacted global bucket array is sorted.
        result = ColumnTable(
            table.schema.select(ordered),
            {name: _fast_take(table.columns[name], order) for name in ordered},
            dict(table.dictionaries),
            {name: table.validity[name][order] for name in ordered if name in table.validity},
        )
        hio.carve_and_write(
            Path(dest_path), result, compact_bucket, num_buckets, indexed_columns
        )

    # -- OptimizeAction's compactor seam ---------------------------------
    def compact(self, entry, src_paths: list[Path] | Path, dest_path: Path) -> None:
        """Merge all files of each bucket across every live version dir
        (base + incremental-refresh deltas) into one sorted file per bucket
        in the new version dir."""
        num_buckets = entry.derived_dataset.num_buckets
        indexed = entry.derived_dataset.indexed_columns
        if isinstance(src_paths, (str, Path)):
            src_paths = [src_paths]
        files = [fi.path for src in src_paths for fi in list_data_files(src)]
        table = hio.read_parquet(files)
        self.write_table(table, indexed, num_buckets, dest_path)

    # -- helpers ---------------------------------------------------------
    def _materialize(self, plan: LogicalPlan, columns: list[str]) -> ColumnTable:
        if not isinstance(plan, Scan):
            raise HyperspaceError("index builds materialize scan-only plans")
        files = plan.files if plan.files is not None else [fi.path for fi in list_data_files(plan.root)]
        return hio.read_parquet(files, columns=columns, schema=plan.schema)
