"""Executor support: plan leaves, side descriptors, key-bound analysis,
identity caches, and the shared row-materialization helpers. Split out of
executor.py (round 5); the executor mixins import from here.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.filter import eval_predicate_mask
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.plan.expr import BinOp, Col, Expr, Lit, split_conjuncts
from hyperspace_tpu.plan.nodes import Aggregate, Join, LogicalPlan, Scan



@dataclasses.dataclass
class _TableLeaf(LogicalPlan):
    """Executor-internal leaf wrapping an already-materialized table
    (partial-aggregation pushdown splices one under a Join). Never
    serialized; never seen by the rules."""

    table: ColumnTable

    @property
    def schema(self):
        return self.table.schema

    def children(self) -> list[LogicalPlan]:
        return []

    def to_json(self):
        raise HyperspaceError("_TableLeaf is executor-internal")


@dataclasses.dataclass
class AlignedSide:
    scan: Scan
    project: list[str] | None  # columns to keep after the join gather
    # Hybrid scan: unbucketed delta scans whose rows are bucketized
    # on the fly and merged into the index buckets before the SMJ.
    # Any number of deltas is accepted (a Union of the index scan with
    # several appended-file scans, not just the canonical two-input
    # shape the rewrite rule emits today).
    deltas: tuple[Scan, ...] = ()
    # Side-local filter (JoinIndexRule keeps linear sides with filters):
    # applied per bucket BEFORE the merge, preserving bucket grouping and
    # within-bucket sort order (a filtered subsequence stays sorted).
    predicate: Expr | None = None


@dataclasses.dataclass
class SideData:
    """One join side in concatenated bucket-grouped layout: rows of bucket
    b occupy [offsets[b], offsets[b+1])."""

    table: ColumnTable
    offsets: np.ndarray  # [B+1] int64
    sorted_within: bool  # buckets key-sorted (index files are)?
    # Fields defining the bucket hash domain (the dtypes the row hash was
    # computed in) — two bucketings pair only when these are compatible.
    hash_fields: tuple | None = None


def _hash_fields_compatible(a, b) -> bool:
    """Equal key values bucket identically under both domains."""
    if a is None or b is None or len(a) != len(b):
        return False
    for fa, fb in zip(a, b):
        if fa.is_string != fb.is_string:
            return False
        if not fa.is_string and np.dtype(fa.device_dtype) != np.dtype(fb.device_dtype):
            return False
    return True


def _filter_side(side: SideData, predicate, mesh, venue: str = "auto") -> SideData:
    """Apply a side-local filter to bucket-grouped data, recomputing the
    bucket offsets over the surviving rows (grouping and within-bucket
    order are preserved — a filtered subsequence stays sorted)."""
    t = side.table
    if t.num_rows == 0:
        return side
    mask = eval_predicate_mask(t, predicate, mesh=mesh, venue=venue)
    counts = np.diff(side.offsets)
    bucket_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    new_counts = np.bincount(bucket_of[mask], minlength=len(counts))
    offsets = np.concatenate([[0], np.cumsum(new_counts)]).astype(np.int64)
    return SideData(t.filter_mask(mask), offsets, side.sorted_within)


def _bucket_sorted_codes(codes: np.ndarray, side: SideData, venue: str = "host"):
    """Ensure codes are non-decreasing within each bucket. Returns
    (sorted codes, perm) where perm maps sorted positions back to the
    side's row order (None when already sorted — the index-file case,
    verified with one vectorized pass, memoized for stable codes).
    `venue` picks where the re-grouping permutation is computed: "device"
    fuses the bucket lane and the code lanes into ONE lax.sort
    (ops/sortkeys.device_lanes_perm) instead of the host np.lexsort
    pass; both produce the identical stable permutation, so the memo
    cache never keys on the venue."""
    from hyperspace_tpu.execution import device_cache as dc

    n = len(codes)
    if n == 0:
        return codes, None
    if side.sorted_within:

        def check() -> bool:
            if n > (1 << 25):
                # Index files are sorted by CONTRACT (the builder writes
                # them that way); at 33M+ rows the O(n) belt-and-braces
                # verification costs real seconds, so sample: the LAST
                # within-bucket adjacency of every bucket (end-2, end-1 —
                # the likely spot for a builder merge bug) plus 64k
                # random adjacencies still catches systematic violations.
                rng = np.random.default_rng(0)
                bounds = np.asarray(side.offsets)
                idx = rng.integers(0, n - 1, 65_536)
                ends = bounds[1:]
                tail_probes = ends[ends >= 2] - 2  # pair (end-2, end-1)
                probes = np.concatenate([idx, tail_probes])
                probes = probes[probes + 1 < n]
                bucket_of_probe = np.searchsorted(bounds, probes, side="right") - 1
                same_bucket = bucket_of_probe == (
                    np.searchsorted(bounds, probes + 1, side="right") - 1
                )
                bad = (codes[probes + 1] < codes[probes]) & same_bucket
                return not bool(bad.any())
            counts0 = np.diff(side.offsets)
            b_of = np.repeat(np.arange(len(counts0), dtype=np.int64), counts0)
            d = np.diff(codes)
            return not np.any(d[b_of[:-1] == b_of[1:]] < 0)

        if dc.is_stable(codes):
            ok = dc.HOST_DERIVED.get_or_build(
                ("sortck", id(codes), side.offsets.tobytes()),
                (codes,),
                lambda: (check(), 1),
            )
        else:
            ok = check()
        if ok:
            return codes, None
    def build_sorted(cacheable: bool):
        counts = np.diff(side.offsets)
        bucket_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
        if venue == "device":
            from hyperspace_tpu.ops.sortkeys import device_lanes_perm, value_lanes

            lanes = value_lanes(bucket_of.astype(np.int32)) + value_lanes(codes)
            perm = device_lanes_perm(lanes).astype(np.int64)
        else:
            perm = np.lexsort((codes, bucket_of))  # stable; regroups identically
        sc = codes[perm]
        nbytes = sc.nbytes + perm.nbytes
        if cacheable and nbytes <= dc.HOST_DERIVED.budget // 4:
            # Freeze ONLY what the cache will actually keep (same rule as
            # the decoded-table cache): a frozen-but-uncached result would
            # masquerade as stable and pile dead downstream entries.
            sc, perm = dc.freeze(sc), dc.freeze(perm)
        return (sc, perm), nbytes

    if dc.is_stable(codes):
        # Stable (identity-cached) codes: memoize the sort itself, not
        # just the sortedness check — repeat queries over the same index
        # version skip the O(n log n) pass entirely, and the frozen
        # outputs keep the downstream pad/upload caches engaged.
        return dc.HOST_DERIVED.get_or_build(
            ("bsort", id(codes), side.offsets.tobytes()), (codes,),
            lambda: build_sorted(True),
        )
    return build_sorted(False)[0]


@dataclasses.dataclass
class KeyBounds:
    """Conjunct bounds on one column: lo/hi literal (None = unbounded) and
    whether each bound is strict (< / >) rather than inclusive."""

    lo: object = None
    lo_strict: bool = False
    hi: object = None
    hi_strict: bool = False


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def _conjunct_col_lit(conj) -> tuple[str, str, object] | None:
    """Destructure one conjunct as (column, op, literal), normalizing
    `lit op col` by flipping the comparison. NaN literals are rejected
    (they defeat ordered-bound reasoning: every comparison is False, but
    searchsorted treats NaN as largest). Returns None otherwise."""
    if not isinstance(conj, BinOp):
        return None
    op = conj.op
    if isinstance(conj.left, Col) and isinstance(conj.right, Lit):
        name, v = conj.left.name, conj.right.value
    elif isinstance(conj.right, Col) and isinstance(conj.left, Lit):
        name, v = conj.right.name, conj.left.value
        op = _FLIP.get(op, op)
    else:
        return None
    if v is None:
        return None
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return None
    return name, op, v


def _like_prefix(pattern: str) -> str | None:
    """The literal prefix of a prefix-shaped LIKE pattern ('PROMO%'), or
    None when the pattern isn't prefix-shaped."""
    if pattern.endswith("%") and len(pattern) > 1:
        body = pattern[:-1]
        if "%" not in body and "_" not in body:
            return body
    return None


def _prefix_upper(prefix: str) -> str | None:
    """Smallest string ABOVE every string with `prefix` (exclusive upper
    bound for prefix matching); None when the last char can't increment."""
    last = ord(prefix[-1])
    if last >= 0x10FFFF:
        return None
    return prefix[:-1] + chr(last + 1)


def _conjunct_bound_ops(conj, key: str) -> list[tuple[str, object]] | None:
    """One conjunct → literal (op, value) bounds it implies on `key`:
    plain comparisons pass through; IN gives its min/max envelope; a
    prefix LIKE gives [prefix, next-prefix). The residual filter mask
    still applies the exact predicate — bounds only need to be a valid
    superset."""
    from hyperspace_tpu.plan.expr import InList, Like

    if isinstance(conj, InList) and isinstance(conj.child, Col):
        if conj.child.name.lower() != key:
            return None
        vals = conj.values
        if any(isinstance(v, (float, np.floating)) and np.isnan(v) for v in vals):
            return None
        try:
            return [("ge", min(vals)), ("le", max(vals))]
        except TypeError:
            return None
    if isinstance(conj, Like) and isinstance(conj.child, Col):
        if conj.child.name.lower() != key:
            return None
        prefix = _like_prefix(conj.pattern)
        if prefix is None:
            if "%" not in conj.pattern and "_" not in conj.pattern:
                return [("eq", conj.pattern)]  # wildcard-free LIKE = equality
            return None
        out: list[tuple[str, object]] = [("ge", prefix)]
        upper = _prefix_upper(prefix)
        if upper is not None:
            out.append(("lt", upper))
        return out
    if isinstance(conj, BinOp) and conj.is_comparison:
        from hyperspace_tpu.ops.filter import _translate_date_part_cmp
        from hyperspace_tpu.plan.expr import DatePart

        l, r, op = conj.left, conj.right, conj.op
        if isinstance(r, DatePart) and isinstance(l, Lit):
            l, r, op = r, l, _FLIP.get(op, op)
        if isinstance(l, DatePart) and isinstance(r, Lit):
            # year(d) OP lit → the same day-range tree the filter layer
            # lowers to; recurse so the range feeds pruning too.
            t = _translate_date_part_cmp(op, l, r.value)
            if t is None:
                return None
            out: list[tuple[str, object]] = []
            for sub in split_conjuncts(t):
                pairs = _conjunct_bound_ops(sub, key)
                if pairs is None:
                    return None  # ne-shaped (an OR): not a conjunct bound
                out.extend(pairs)
            return out
    dec = _conjunct_col_lit(conj)
    if dec is None:
        return None
    name, op, v = dec
    if name.lower() != key or op not in ("eq", "lt", "le", "gt", "ge"):
        return None
    return [(op, v)]


def key_bounds(predicate: Expr, key: str) -> KeyBounds | None:
    """Extract literal comparison bounds on `key` from the predicate's
    conjuncts (key op lit / lit op key; eq pins both ends; IN gives its
    envelope; prefix LIKE gives a string range). Returns None when no
    conjunct bounds the column. Incomparable literal types are ignored
    (the residual filter mask still applies them exactly)."""
    key = key.lower()
    b = KeyBounds()
    found = False
    for conj in split_conjuncts(predicate):
        pairs = _conjunct_bound_ops(conj, key)
        if pairs is None:
            continue
        for op, v in pairs:
            try:
                if op in ("gt", "ge", "eq") and (
                    b.lo is None or v > b.lo or (v == b.lo and op == "gt")
                ):
                    b.lo, b.lo_strict = v, op == "gt"
                    found = True
                if op in ("lt", "le", "eq") and (
                    b.hi is None or v < b.hi or (v == b.hi and op == "lt")
                ):
                    b.hi, b.hi_strict = v, op == "lt"
                    found = True
            except TypeError:
                continue
    return b if found else None


def predicate_all_key_bounds(predicate: Expr, key: str) -> bool:
    """True iff EVERY conjunct is a comparable literal bound on `key`
    (eq/lt/le/gt/ge) — i.e. an exact searchsorted slice on the sorted key
    fully implements the predicate and the residual mask is redundant."""
    key = key.lower()
    for conj in split_conjuncts(predicate):
        dec = _conjunct_col_lit(conj)
        if dec is None:
            return False
        name, op, v = dec
        if name.lower() != key or op not in ("eq", "lt", "le", "gt", "ge"):
            return False
        if not isinstance(v, (int, float, bool, np.number)):
            return False
    return True


def _stats_overlap(bounds: KeyBounds, mn, mx) -> bool:
    """Can any value in [mn, mx] satisfy the bounds?"""
    try:
        if bounds.hi is not None and (mn > bounds.hi or (bounds.hi_strict and mn == bounds.hi)):
            return False
        if bounds.lo is not None and (mx < bounds.lo or (bounds.lo_strict and mx == bounds.lo)):
            return False
    except TypeError:
        return True  # incomparable stats: keep the file
    return True


def _bounds_domain(field, bounds: KeyBounds):
    """Conversion putting pruning comparisons in the SAME numeric domain
    the filter mask uses (ops/filter.py _lower_col_lit's numpy promotion):
    float32 columns compare weak scalars in float32 (the literal ROUNDS),
    and int columns compare float literals in float64. Without this,
    pruning could drop rows the mask would keep. Returns None when raw
    comparison already matches (ints vs ints, strings)."""
    dt = field.device_dtype
    vals = [v for v in (bounds.lo, bounds.hi) if v is not None]
    if dt.kind == "f":
        weak = all(
            type(v) in (int, float, bool) or isinstance(v, (np.bool_, np.float32))
            for v in vals
        )
        return np.float32 if (dt.itemsize <= 4 and weak) else np.float64
    if dt.kind in "iu" and any(isinstance(v, (float, np.floating)) for v in vals):
        return np.float64
    return None


def _convert_bounds(field, bounds: KeyBounds) -> tuple[KeyBounds, object]:
    """(bounds cast into the comparison domain, stat-value converter)."""
    conv = _bounds_domain(field, bounds)
    if conv is None:
        return bounds, lambda v: v
    try:
        cast = KeyBounds(
            conv(bounds.lo) if bounds.lo is not None else None,
            bounds.lo_strict,
            conv(bounds.hi) if bounds.hi is not None else None,
            bounds.hi_strict,
        )
    except (TypeError, ValueError, OverflowError):
        return bounds, lambda v: v
    def stat_conv(v):
        try:
            return conv(v)
        except (TypeError, ValueError, OverflowError):
            return v
    return cast, stat_conv


def _pad_bucket_major(
    codes: np.ndarray,
    offsets: np.ndarray,
    fill=None,
    width: int | None = None,
) -> np.ndarray:
    """[n] bucket-grouped values → [B, L] padded array, built with one
    vectorized gather. Default fill is the dtype's sort-last sentinel
    (key codes); value channels pass an explicit fill and width."""
    counts = np.diff(offsets)
    b = len(counts)
    lmax = width if width is not None else max(int(counts.max()) if counts.size else 1, 1)
    sentinel = join_ops.sentinel_for(codes.dtype) if fill is None else fill
    if len(codes) == 0:
        return np.full((b, lmax), sentinel, dtype=codes.dtype)
    idx = offsets[:-1, None] + np.arange(lmax, dtype=np.int64)[None, :]
    mask = np.arange(lmax)[None, :] < counts[:, None]
    return np.where(mask, codes[np.minimum(idx, len(codes) - 1)], sentinel)




def _broadcast_probe(lcodes: np.ndarray, rcodes: np.ndarray):
    """Match pairs via a broadcast hash table: the smaller side builds a
    dense code -> (start, count) table, every large-side row probes it
    with ONE vectorized gather (no binary search — random-access
    searchsorted over millions of probes is ~10x slower than a
    cache-resident table), and duplicate runs expand vectorized. The
    large side is never sorted. Null codes are side-distinct negatives
    and never match. Returns None when the shared code space is too
    sparse for a table (caller falls back to the merge kernel); else
    (lidx, ridx) in the merge path's contract."""
    swap = len(lcodes) < len(rcodes)
    build, probe = (lcodes, rcodes) if swap else (rcodes, lcodes)
    top = 0
    if len(build):
        top = max(top, int(build.max()) + 1)
    if len(probe):
        top = max(top, int(probe.max()) + 1)
    if top == 0:
        # Every key on both sides is null-coded: no row can match.
        empty = np.zeros(0, dtype=np.int64)
        return empty, empty
    if top > 8 * len(build) + 65_536:
        return None  # sparse code space: the table would dwarf the side
    bvalid = build >= 0
    counts = np.bincount(build[bvalid], minlength=top)
    starts = np.concatenate([[0], np.cumsum(counts[:-1])]) if top else np.zeros(0, np.int64)
    order = np.argsort(build, kind="stable")  # null codes sort first
    nneg = int((~bvalid).sum())
    pvalid = probe >= 0
    pc = np.where(pvalid, probe, 0)
    cnt = np.where(pvalid, counts[pc], 0)
    lo = nneg + starts[pc]
    if not counts.size or counts.max() <= 1:
        # Unique build keys (the normal dimension-table case): each probe
        # row matches 0 or 1 build rows — no run expansion at all.
        matched = cnt > 0
        probe_idx = np.flatnonzero(matched)
        build_idx = order[lo[matched]]
        if swap:
            return build_idx, probe_idx
        return probe_idx, build_idx
    total = int(cnt.sum())
    probe_idx = np.repeat(np.arange(len(probe), dtype=np.int64), cnt)
    run_starts = np.cumsum(cnt) - cnt
    within = np.arange(total, dtype=np.int64) - np.repeat(run_starts, cnt)
    build_idx = order[np.repeat(lo, cnt) + within]
    if swap:
        return build_idx, probe_idx  # build side is the LEFT input
    return probe_idx, build_idx


def _copy_field(out_f, src: ColumnTable, src_name: str, cols, dicts, val) -> None:
    """Copy src column `src_name` into output field `out_f` (dtype-cast
    for numeric mismatches — outer-join key coalescing may source the
    left-named key column from the right side)."""
    sf = src.schema.field(src_name)
    arr = src.columns[sf.name]
    if sf.name in src.dictionaries:
        dicts[out_f.name] = src.dictionaries[sf.name]
        cols[out_f.name] = arr
    else:
        want = np.dtype(out_f.device_dtype)
        cols[out_f.name] = arr if arr.ndim > 1 or arr.dtype == want else arr.astype(want)
    v = src.validity.get(sf.name)
    if v is not None:
        val[out_f.name] = v


def _null_field(out_f, n: int, dict_src: ColumnTable | None, cols, dicts, val) -> None:
    """All-null column for output field `out_f` (outer-join null
    extension). String fields reuse `dict_src`'s dictionary for that
    field when available, so concat with the matched part needs no
    dictionary merge."""
    if out_f.is_vector:
        raise HyperspaceError(
            f"outer join cannot null-extend vector column {out_f.name!r}"
        )
    if out_f.is_string:
        d = None
        if dict_src is not None:
            try:
                sf = dict_src.schema.field(out_f.name)
                d = dict_src.dictionaries.get(sf.name)
            except Exception:
                d = None
        if d is None or len(d) == 0:
            d = np.array([""], dtype=object)
        cols[out_f.name] = np.zeros(n, dtype=np.int32)
        dicts[out_f.name] = d
    else:
        cols[out_f.name] = np.zeros(n, dtype=out_f.device_dtype)
    val[out_f.name] = np.zeros(n, dtype=bool)


def _concat_side_cached(tables: list[ColumnTable]) -> ColumnTable:
    """Concatenated bucket-grouped side table, memoized on the identity
    of the per-bucket cached tables (the device plane's HBM-resident
    container rests on this stability: frozen concat => stable codes =>
    cached pads => cached uploads). Falls through for single groups (the
    cached table passes through already frozen)."""
    from hyperspace_tpu.execution import device_cache as dc

    if len(tables) == 1:
        return tables[0]
    # Only identity-stable inputs may be memoized (and only then may the
    # output be frozen): per-query tables too large for the io cache get
    # fresh ids every time — caching against those would pile dead pinned
    # entries, and freezing their concat would let every downstream cache
    # mistake per-query arrays for stable ones.
    stable = all(
        all(
            dc.is_stable(a)
            for a in (*t.columns.values(), *t.validity.values(), *t.dictionaries.values())
        )
        for t in tables
    )
    if not stable:
        return ColumnTable.concat(tables)

    def build():
        out = ColumnTable.concat(tables)
        for arr in (*out.columns.values(), *out.validity.values(), *out.dictionaries.values()):
            dc.freeze(arr)
        # _table_nbytes counts string payloads, not just object pointers —
        # the budget must see what the entry actually retains.
        return out, int(hio._table_nbytes(out))

    return dc.HOST_DERIVED.get_or_build(
        ("sidecat", tuple(id(t) for t in tables)), tuple(tables), build
    )


def _composite_keys(codes: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """(bucket << 33) + code composites: codes span int32 (±2^31) and
    buckets are small, so the shifted sum is collision-free in int64 and
    globally SORTED for bucket-major key-sorted inputs. Shared by the
    semi/anti membership probe and the fused run-extremum channels."""
    b = np.repeat(np.arange(len(offsets) - 1, dtype=np.int64), np.diff(offsets))
    return (b << np.int64(33)) + codes.astype(np.int64)


class _RunExtremum:
    """Per-primary-row extrema over the secondary match runs, shared by
    every min/max channel of one fused join-aggregation. The secondary
    side is bucket-major key-sorted, so all rows with one key form a
    contiguous run; the composite key is globally sorted and each
    primary row's run bounds come from two searchsorteds (built LAZILY —
    primary-side-only channels never pay for them). Extrema are
    multiplicity-independent, so the per-KEY extremum stands in for
    every duplicate primary row with that key."""

    def __init__(self, pri_codes, pri_offsets, pperm, sec_codes, sec_offsets, sperm, matches, n_l):
        self.sperm = sperm
        self.pperm = pperm
        self.matches = matches
        self.n_l = n_l
        self._pri = (pri_codes, pri_offsets)
        self._sec = (sec_codes, sec_offsets)
        self._runs = None

    def _run_index(self):
        if self._runs is None:
            cp = _composite_keys(*self._pri)
            cs = _composite_keys(*self._sec)
            st = np.searchsorted(cs, cp, side="left")
            en = np.searchsorted(cs, cp, side="right")
            if len(cs):
                starts = np.concatenate([[0], np.flatnonzero(np.diff(cs) != 0) + 1])
                ridx = np.clip(
                    np.searchsorted(starts, st, side="right") - 1, 0, len(starts) - 1
                )
            else:
                starts = np.zeros(0, np.int64)
                ridx = np.zeros(len(cp), np.int64)
            self._runs = (st, en, en > st, starts, ridx)
        return self._runs

    def per_primary_row(self, fn: str, side: str, secondary: str, vals, ind):
        """(row extremum, row validity) in ORIGINAL primary order for one
        channel; `vals`/`ind` are the channel's per-orig-row arrays of
        `side` (invalid slots already zeroed, `ind` marking them)."""
        identity = np.inf if fn == "min" else -np.inf
        if side == secondary:
            _st, _en, has, starts, ridx = self._run_index()
            sv = vals if self.sperm is None else vals[self.sperm]
            si = ind if self.sperm is None else ind[self.sperm]
            if not len(starts):
                return np.full(self.n_l, identity), np.zeros(self.n_l, bool)
            op = np.minimum if fn == "min" else np.maximum
            sv = np.where(si > 0, np.asarray(sv, np.float64), identity)
            key_ext = op.reduceat(sv, starts)
            key_validcnt = np.add.reduceat(np.asarray(si, np.float64), starts)
            ext_sorted = np.where(has, key_ext[ridx], identity)
            valid_sorted = has & (key_validcnt[ridx] > 0)
            if self.pperm is not None:
                ext = np.empty(self.n_l)
                ext[self.pperm] = ext_sorted
                valid = np.empty(self.n_l, bool)
                valid[self.pperm] = valid_sorted
                return ext, valid
            return ext_sorted, valid_sorted
        # Primary-side channel: extremum over the group's MATCHED rows.
        v = np.where(np.asarray(ind) > 0, np.asarray(vals, np.float64), identity)
        valid = (self.matches > 0) & (np.asarray(ind) > 0)
        return v, valid


def _desugar_count_distinct(plan: "Aggregate"):
    """count(distinct col) as a TWO-PHASE re-aggregation: the inner
    aggregate groups by (group keys, distinct column) — its rows are the
    distinct (group, value) pairs — and computes partials for every
    sibling aggregate; the outer counts the distinct column (nulls
    excluded, SQL semantics) and recombines the partials (sum of sums /
    counts, min of mins, max of maxes). The Spark analog is the planner's
    distinct-aggregate Expand rewrite. Returns (desugared plan, aliases
    of the original count specs — the caller zero-fills their NULLs)."""
    from hyperspace_tpu.plan.nodes import AggSpec, Aggregate

    # The caller routes multi-distinct / mean-sharing aggregates to
    # _distinct_aggregate; this fast path sees exactly one distinct
    # column and no mean.
    dcol = next(a.expr.name for a in plan.aggs if a.fn == "count_distinct")
    group_low = {c.lower() for c in plan.group_by}
    inner_groups = list(plan.group_by) + ([dcol] if dcol.lower() not in group_low else [])
    inner_aggs: list = []
    outer_aggs: list = []
    count_aliases: list[str] = []
    for i, a in enumerate(plan.aggs):
        if a.fn == "count_distinct":
            outer_aggs.append(AggSpec("count", Col(dcol), a.alias))
            continue
        part = f"__partial_{i}"
        if a.fn == "count":
            inner_aggs.append(AggSpec("count", a.expr, part))
            outer_aggs.append(AggSpec("sum", Col(part), a.alias))
            count_aliases.append(a.alias)
        else:  # sum / min / max recombine with themselves
            inner_aggs.append(AggSpec(a.fn, a.expr, part))
            outer_aggs.append(AggSpec(a.fn, Col(part), a.alias))
    inner = Aggregate(plan.child, inner_groups, inner_aggs)
    return Aggregate(inner, list(plan.group_by), outer_aggs), count_aliases


def _stable_table_refs(table: ColumnTable, names: set[str]):
    """(refs, id-parts) over every array the named columns touch (data,
    dictionary, validity), or (None, None) when any is unstable."""
    from hyperspace_tpu.execution import device_cache as dc

    refs: list = []
    parts: list = []
    for nm in sorted(names):
        f = table.schema.field(nm)
        for a in (table.columns[f.name], table.dictionaries.get(f.name), table.validity.get(f.name)):
            if a is None:
                parts.append(None)
                continue
            if not dc.is_stable(a):
                return None, None
            refs.append(a)
            parts.append(id(a))
    return tuple(refs), tuple(parts)


def _group_ids_cached(table: ColumnTable, group_by: list[str]):
    """group_ids memoized on the identity of the (stable) group-key
    arrays — repeat aggregations over the same index version skip the
    factorization of millions of keys."""
    from hyperspace_tpu.execution import device_cache as dc
    from hyperspace_tpu.ops.aggregate import group_ids

    if not group_by:
        return group_ids(table, group_by)
    refs, parts = _stable_table_refs(table, {c.lower() for c in group_by})
    if refs is None:
        return group_ids(table, group_by)

    def build():
        gid, k, first = group_ids(table, group_by)
        dc.freeze(gid)
        dc.freeze(first)
        return (gid, k, first), int(gid.nbytes + first.nbytes)

    return dc.HOST_DERIVED.get_or_build(
        ("gid", tuple(c.lower() for c in group_by), parts), refs, build
    )


def _agg_channels_cached(tbl: ColumnTable, spec):
    """(masked values, indicator) channels for one AggSpec, memoized per
    (expression, input identity) for stable tables."""
    import json

    from hyperspace_tpu.execution import device_cache as dc
    from hyperspace_tpu.ops.aggregate import agg_input

    def raw():
        vals, valid, _ = agg_input(tbl, spec)
        vals = np.asarray(vals, dtype=np.float64)
        if valid is not None:
            vals = np.where(valid, vals, 0.0)
        ind = np.ones(tbl.num_rows, np.float64) if valid is None else valid.astype(np.float64)
        return vals, ind

    refs, parts = _stable_table_refs(tbl, {r.lower() for r in spec.references()})
    if not refs:  # unstable or constant expression: no identity to key on
        return raw()
    key = ("aggin", json.dumps(spec.expr.to_json(), sort_keys=True), parts)

    def build():
        vals, ind = raw()
        dc.freeze(vals)
        dc.freeze(ind)
        return (vals, ind), int(vals.nbytes + ind.nbytes)

    return dc.HOST_DERIVED.get_or_build(key, refs, build)


def _factorize_keys_cached(lt: ColumnTable, rt: ColumnTable, lkeys, rkeys,
                           null_safe: bool = False):
    """Pairwise key factorization memoized on the IDENTITY of every input
    it reads (key columns, dictionaries, validity) — valid only when all
    are stable (frozen index-cache arrays). Repeat joins over the same
    index version skip ranking entirely; codes are frozen so downstream
    pad/upload caches can key on them. Returns (lcodes, rcodes)."""
    from hyperspace_tpu.execution import device_cache as dc

    lrefs, lparts = _stable_table_refs(lt, {k.lower() for k in lkeys})
    rrefs, rparts = _stable_table_refs(rt, {k.lower() for k in rkeys})
    if lrefs is None or rrefs is None:
        lc, rc = _factorize_keys([lt], [rt], lkeys, rkeys, null_safe=null_safe)
        return lc[0], rc[0]
    refs = lrefs + rrefs
    parts = (lparts, rparts, null_safe)

    def build():
        lc, rc = _factorize_keys([lt], [rt], lkeys, rkeys, null_safe=null_safe)
        out = (dc.freeze(lc[0]), dc.freeze(rc[0]))
        return out, int(lc[0].nbytes + rc[0].nbytes)

    return dc.HOST_DERIVED.get_or_build(("fact", parts), refs, build)


def _pad_bucket_major_cached(
    codes: np.ndarray, offsets: np.ndarray, fill=None, width: int | None = None
) -> np.ndarray:
    """Bucket-major pad through the derived cache when the input is
    stable (index-sorted, frozen) — the [B, L] device upload then hits
    the HBM cache too."""
    from hyperspace_tpu.execution import device_cache as dc

    if dc.is_stable(codes):
        return dc.derived(
            ("padbm", id(codes), offsets.tobytes(), repr(fill), width),
            (codes,),
            lambda: _pad_bucket_major(codes, offsets, fill=fill, width=width),
        )
    return _pad_bucket_major(codes, offsets, fill=fill, width=width)


def _stack_cached(arrs: list, empty_shape: tuple) -> np.ndarray:
    """np.stack through the derived cache when every channel is stable
    (the [A, n] float64 stack is a 100MB-scale memcpy per query)."""
    from hyperspace_tpu.execution import device_cache as dc

    if not arrs:
        return np.zeros(empty_shape)
    if all(dc.is_stable(a) for a in arrs):
        return dc.derived(
            ("stack", tuple(id(a) for a in arrs)), tuple(arrs), lambda: np.stack(arrs)
        )
    return np.stack(arrs)


def _key_null_mask(table: ColumnTable, keys: list[str]) -> np.ndarray | None:
    """True where ANY key column is null (such rows never join — SQL:
    NULL = NULL is not true). None when every key column is null-free."""
    m = None
    for k in keys:
        valid = table.valid_mask(k)
        if valid is not None:
            m = ~valid if m is None else (m | ~valid)
    return m


def _apply_null_codes(lcodes, rcodes, lnulls, rnulls):
    """Null-keyed rows get side-distinct negative codes (-2 left, -1
    right): they sort first and can never equal across sides, so the merge
    kernel drops them with zero extra work."""
    for c, m in zip(lcodes, lnulls):
        if m is not None:
            c[m] = -2
    for c, m in zip(rcodes, rnulls):
        if m is not None:
            c[m] = -1
    return lcodes, rcodes


def _factorize_keys(ltables, rtables, lkeys, rkeys, null_safe=False):
    """Map each partition's key tuples to a shared int32 rank-code space
    whose order matches the lexicographic order of the raw key tuples.
    int32 keeps the device merge-join kernels on native 32-bit lanes (TPU
    emulates 64-bit); ranks always fit (bounded by total row count).

    `null_safe` switches the NULL treatment from SQL join equality (a
    null-keyed row never matches — side-distinct negative codes) to SQL
    set/IS NOT DISTINCT FROM equality: per key column, NULL becomes one
    extra domain value SHARED across sides (code `len(uniq)`), so
    (1, NULL) matches (1, NULL) but still not (1, 0) — the physical
    zero/"" a null slot holds can no longer collide with a real value."""
    lnulls = [_key_null_mask(t, lkeys) for t in ltables]
    rnulls = [_key_null_mask(t, rkeys) for t in rtables]
    has_nulls = any(m is not None for m in lnulls + rnulls)
    # Fast path: a single integer key whose value SPAN fits int32 needs no
    # ranking — values shifted by the minimum are order-preserving codes.
    # Codes are NON-NEGATIVE by construction, so a negative code always
    # means a null-keyed row (the invariant _broadcast_probe and the
    # null-code scheme below rely on). (Skipped with nulls: raw values
    # could collide with the null codes.)
    if len(lkeys) == 1 and not has_nulls:
        lvals = [_logical_key(t, lkeys[0]) for t in ltables]
        rvals = [_logical_key(t, rkeys[0]) for t in rtables]
        if all(np.issubdtype(v.dtype, np.integer) for v in lvals + rvals):
            lo = min((int(v.min()) for v in lvals + rvals if len(v)), default=0)
            hi = max((int(v.max()) for v in lvals + rvals if len(v)), default=0)
            # Span strictly below int32 max: the sentinel pad must still
            # sort last after the shift.
            if hi - lo < np.iinfo(np.int32).max - 1:
                shift = np.int64(lo)
                return (
                    [(v.astype(np.int64) - shift).astype(np.int32) for v in lvals],
                    [(v.astype(np.int64) - shift).astype(np.int32) for v in rvals],
                )

    per_col_codes_l: list[list[np.ndarray]] = [[] for _ in ltables]
    per_col_codes_r: list[list[np.ndarray]] = [[] for _ in rtables]
    cards: list[int] = []
    for lname, rname in zip(lkeys, rkeys):
        dict_res = _dict_domain_codes(ltables, rtables, lname, rname)
        if dict_res is not None:
            # Dictionary-coded string keys factorize in the DICTIONARY
            # domain: merge the small sorted dictionaries and remap each
            # side's codes with one O(n) gather — the per-row string
            # values never inflate on host (the O(n log n) string
            # np.unique below was a top line of BENCH_SF100's
            # key-factorization tax). Order and cross-side equality are
            # preserved exactly (the merged domain is sorted and covers
            # both sides); cardinality counts dictionary entries, a
            # superset of used values — the mixed-radix combination only
            # needs an injective order-preserving code space, so a
            # larger radix is still correct.
            lvals, rvals, card = dict_res
            if null_safe and has_nulls:
                masks = [t.valid_mask(lname) for t in ltables] + [
                    t.valid_mask(rname) for t in rtables
                ]
                if any(m is not None for m in masks):
                    lvals = [v.copy() for v in lvals]
                    rvals = [v.copy() for v in rvals]
                    any_null = False
                    for v, m in zip(lvals + rvals, masks):
                        if m is not None and (~m).any():
                            v[~m] = card
                            any_null = True
                    if any_null:
                        card += 1
            cards.append(max(card, 1))
            for i, v in enumerate(lvals):
                per_col_codes_l[i].append(v)
            for i, v in enumerate(rvals):
                per_col_codes_r[i].append(v)
            continue
        lvals = [_logical_key(t, lname) for t in ltables]
        rvals = [_logical_key(t, rname) for t in rtables]
        allv = np.concatenate(lvals + rvals) if (lvals or rvals) else np.array([])
        uniq, inv = np.unique(allv, return_inverse=True)
        card = max(len(uniq), 1)
        if null_safe and has_nulls:
            # NULL = one extra per-column domain value shared across
            # sides, so the physical zero/"" a null slot holds cannot
            # alias a real value of this column.
            masks = [t.valid_mask(lname) for t in ltables] + [
                t.valid_mask(rname) for t in rtables
            ]
            if any(m is not None for m in masks):
                alln = np.concatenate([
                    (~m if m is not None else np.zeros(len(v), dtype=bool))
                    for m, v in zip(masks, lvals + rvals)
                ])
                if alln.any():
                    inv = inv.copy()
                    inv[alln] = len(uniq)
                    card = len(uniq) + 1
        cards.append(card)
        pos = 0
        for i, v in enumerate(lvals):
            per_col_codes_l[i].append(inv[pos : pos + len(v)])
            pos += len(v)
        for i, v in enumerate(rvals):
            per_col_codes_r[i].append(inv[pos : pos + len(v)])
            pos += len(v)

    def combine(per_part):
        out = []
        for codes in per_part:
            acc = np.zeros(len(codes[0]) if codes else 0, dtype=np.int64)
            for c, k in zip(codes, cards):
                acc = acc * np.int64(k) + c.astype(np.int64)
            out.append(acc)
        return out

    import math

    if math.prod(cards) >= np.iinfo(np.int64).max:
        # The int64 mixed-radix combination itself would wrap — the codes
        # in `combine` below would collide before any re-rank could help.
        raise HyperspaceError(
            f"join key cardinalities {cards} overflow the int64 code space"
        )
    lcomb, rcomb = combine(per_col_codes_l), combine(per_col_codes_r)
    int32_max = np.iinfo(np.int32).max
    # Mixed-radix codes that provably fit int32 cast directly — no
    # re-rank pass needed (math.prod is exact, arbitrary precision).
    if math.prod(cards) < int32_max:
        lc = [c.astype(np.int32) for c in lcomb]
        rc = [c.astype(np.int32) for c in rcomb]
        if null_safe:
            # NULLs are already real domain values in the codes — the
            # never-match negative-code scheme must not touch them.
            return lc, rc
        return _apply_null_codes(lc, rc, lnulls, rnulls)
    # Otherwise re-rank the combined codes down to int32 (order preserved
    # by np.unique).
    allc = np.concatenate(lcomb + rcomb) if (lcomb or rcomb) else np.zeros(0, np.int64)
    uniq, inv = np.unique(allc, return_inverse=True)
    if len(uniq) >= int32_max:
        raise HyperspaceError(
            f"join key space has {len(uniq)} distinct tuples — exceeds the "
            "int32 code space"
        )
    inv = inv.astype(np.int32)
    pos, out_l, out_r = 0, [], []
    for c in lcomb:
        out_l.append(inv[pos : pos + len(c)])
        pos += len(c)
    for c in rcomb:
        out_r.append(inv[pos : pos + len(c)])
        pos += len(c)
    if null_safe:
        return out_l, out_r
    return _apply_null_codes(out_l, out_r, lnulls, rnulls)


def _dict_domain_codes(ltables, rtables, lname, rname):
    """Dictionary-domain factorization of one string key column:
    (per-left-table codes, per-right-table codes, cardinality) in the
    merged sorted-dictionary domain, or None when the column pair is not
    dictionary-coded on every table (the value-domain np.unique path
    handles it). The merged domain is the sorted union of the SMALL
    per-table dictionaries; each table's rows remap with one gather."""
    lfs = [t.schema.field(lname) for t in ltables]
    rfs = [t.schema.field(rname) for t in rtables]
    if not all(f.is_string for f in lfs + rfs):
        return None
    pairs = [(t, t.schema.field(lname).name) for t in ltables] + [
        (t, t.schema.field(rname).name) for t in rtables
    ]
    if any(nm not in t.dictionaries for t, nm in pairs):
        return None
    dicts = [np.asarray(t.dictionaries[nm]) for t, nm in pairs]
    first = dicts[0]
    if all(len(d) == len(first) and np.array_equal(d, first) for d in dicts[1:]):
        # One shared sorted dictionary (the common single-index-version
        # case): the codes already ARE the domain ranks — zero work.
        codes = [t.columns[nm].astype(np.int64, copy=False) for t, nm in pairs]
        card = len(first)
    else:
        merged = np.unique(np.concatenate([d.astype(str) for d in dicts]))
        codes = []
        for (t, nm), d in zip(pairs, dicts):
            old_to_new = np.searchsorted(merged, d.astype(str)).astype(np.int64)
            col = t.columns[nm]
            codes.append(old_to_new[col] if len(d) else col.astype(np.int64, copy=False))
        card = len(merged)
    nl = len(ltables)
    return codes[:nl], codes[nl:], card


def _logical_key(table: ColumnTable, name: str) -> np.ndarray:
    f = table.schema.field(name)
    arr = table.columns[f.name]
    if f.is_string:
        return table.dictionaries[f.name][arr]
    return arr
