"""Scan + filter execution: cached decode, bucket pruning, range
pruning, hybrid scan reads (Executor mixin)."""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np
import pyarrow as pa

from hyperspace_tpu import stats as _ft_stats
from hyperspace_tpu.exceptions import IndexCorruptionError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.build_exchange import hash_scalar_key
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.dataset import format_suffix, list_data_files
from hyperspace_tpu.ops.filter import apply_filter
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.plan.expr import BinOp, Col, Expr, Lit, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, LogicalPlan, Scan, Union

from hyperspace_tpu.execution.exec_common import (
    KeyBounds,
    _convert_bounds,
    _stats_overlap,
    key_bounds,
    predicate_all_key_bounds,
)


def _corruption(e: BaseException, index_root: str, files: list[str]) -> IndexCorruptionError:
    """Wrap an unreadable-index-file failure with provenance (which index,
    which files) for the session's health map and fallback re-plan."""
    _ft_stats.increment("index.corruption")
    return IndexCorruptionError(
        f"unreadable index data under {index_root}: {e}",
        index_root=index_root,
        path=files[0] if files else None,
    )


# Bucket pruning reads at most this many point combinations; above it
# the (still-correct) range/mask machinery takes over.
MAX_POINT_COMBOS = 64


def scan_files(scan: Scan) -> list[str]:
    if scan.files is not None:
        return list(scan.files)
    return [fi.path for fi in list_data_files(scan.root, suffix=format_suffix(scan.format))]


def point_prune_names(scan: Scan, predicate: Expr, max_combos: int = MAX_POINT_COMBOS) -> set[str] | None:
    """Bucket file NAMES owned by the predicate's equality/IN literals on
    every bucket column, or None when the predicate does not pin them (or
    the combination count exceeds `max_combos`). Pure — shared by the
    executor's pruner and the plan-time prefetcher. The analog of
    partition pruning the reference cannot do (FilterIndexRule keeps a
    full scan, FilterIndexRule.scala:114-120); IN on the bucket column
    divides IO by numBuckets/|IN| instead of 1."""
    import itertools
    import math

    from hyperspace_tpu.plan.expr import InList

    num_buckets, bucket_cols = scan.bucket_spec
    cand: dict[str, list] = {}
    for conj in split_conjuncts(predicate):
        got: tuple[str, list] | None = None
        if isinstance(conj, BinOp) and conj.op == "eq":
            if isinstance(conj.left, Col) and isinstance(conj.right, Lit):
                got = (conj.left.name.lower(), [conj.right.value])
            elif isinstance(conj.right, Col) and isinstance(conj.left, Lit):
                got = (conj.right.name.lower(), [conj.left.value])
        elif isinstance(conj, InList) and isinstance(conj.child, Col):
            got = (conj.child.name.lower(), list(conj.values))
        if got is not None:
            name, vals = got
            # Conjunctive constraints: any one conjunct's list is a
            # valid superset of the reachable values — keep the
            # smallest.
            if name not in cand or len(vals) < len(cand[name]):
                cand[name] = vals
    try:
        lists = [cand[c.lower()] for c in bucket_cols]
    except KeyError:
        return None
    if math.prod(len(l) for l in lists) > max_combos:
        return None
    fields = [scan.scan_schema.field(c) for c in bucket_cols]
    names: set[str] = set()
    for combo in itertools.product(*lists):
        h = hash_scalar_key(list(combo), fields)
        names.add(hio.bucket_file_name(int(bucket_ids(h, num_buckets, np)[0])))
    return names


class ScanFilterMixin:
    def _scan_files(self, scan: Scan) -> list[str]:
        return scan_files(scan)

    def _cached_read(self, files: list[str], columns, schema, index_root: str | None = None) -> ColumnTable:
        """Index-file read through the decoded-table cache; files_read
        counts only physical (miss) reads. With `index_root` (the read
        serves an INDEX scan), an unreadable file — missing, truncated,
        or garbage parquet — surfaces as a typed IndexCorruptionError so
        the session can quarantine the index and re-plan against the
        source instead of failing the query.

        Index scans decode PER FILE and concatenate through the cached
        side-concat — the join-side pattern. Two wins over one
        multi-file decode: each single-chunk per-file column stages as a
        zero-copy Arrow buffer view (a 16-file concat is multi-chunk and
        can never stage — this was the whole filter/group_agg staging
        tax), and per-file cache entries are shared across queries with
        DIFFERENT surviving file subsets (pruning no longer forces a
        full re-decode). The frozen concat itself is identity-cached, so
        repeat queries skip it entirely."""
        before = hio.table_cache_stats()
        try:
            if index_root is not None and len(files) > 1:
                from concurrent.futures import ThreadPoolExecutor

                from hyperspace_tpu.execution.exec_common import _concat_side_cached
                from hyperspace_tpu.obs import trace as obs_trace

                read = obs_trace.wrap(
                    lambda f: hio.read_parquet_cached([f], columns=columns, schema=schema)
                )
                with ThreadPoolExecutor(max_workers=min(8, len(files))) as ex:
                    tables = list(ex.map(read, files))
                table = _concat_side_cached(tables)
            else:
                table = hio.read_parquet_cached(files, columns=columns, schema=schema)
        except IndexCorruptionError:
            raise
        except (OSError, pa.ArrowException) as e:
            if index_root is None:
                raise
            raise _corruption(e, index_root, files) from e
        finally:
            after = hio.table_cache_stats()
            self.stats["files_read"] += after["miss_files"] - before["miss_files"]
            self.stats["bytes_scanned"] += after["miss_bytes"] - before["miss_bytes"]
        return table

    def _scan(self, scan: Scan, columns: list[str] | None = None) -> ColumnTable:
        files = self._scan_files(scan)
        cols = columns if columns is not None else scan.scan_schema.names
        if not files:  # everything pruned away
            return ColumnTable.empty(scan.scan_schema.select(cols))
        if scan.format == "parquet":
            # ALL parquet scans ride the decoded-table cache, not just
            # index files: the cache validates per-file mtimes, so a
            # mutated source re-decodes while repeat queries over stable
            # sources (dimension tables above all) skip the decode — the
            # analog of Spark's in-memory relation cache.
            root = scan.root if scan.bucket_spec is not None else None
            return self._cached_read(files, cols, scan.scan_schema, index_root=root)
        self.stats["files_read"] += len(files)
        import os as _os

        try:
            self.stats["bytes_scanned"] += sum(_os.path.getsize(f) for f in files)
        except OSError:
            pass
        return hio.read_table_files(files, scan.format, columns=cols, schema=scan.scan_schema)

    # -- filter (with index bucket pruning) ------------------------------
    def _filter(self, plan: Filter) -> ColumnTable:
        child = plan.child
        # Per-OPERATOR pruning evidence: deltas of the query-cumulative
        # counters from this frame's start.
        fp0, rp0 = self.stats["files_pruned"], self.stats["rows_pruned"]
        mask_venue = self._filter_venue()
        mask_kernel = "host-mask" if mask_venue == "host" else "fused-xla-mask"
        if isinstance(child, Scan) and child.bucket_spec is not None:
            pruned = self._prune_bucket_files(child, plan.predicate)
            if pruned is not None:
                self._phys(
                    "IndexPointLookup",
                    files_pruned=self.stats["files_pruned"] - fp0,
                    kernel=f"bucket-hash-prune + {mask_kernel}",
                )
                table = self._cached_read(
                    pruned, child.scan_schema.names, child.scan_schema, index_root=child.root
                )
                return apply_filter(table, plan.predicate, mesh=self.mesh, venue=mask_venue)
            ranged = self._range_read(child, plan.predicate)
            if ranged is not None:
                table, exact = ranged
                if exact and predicate_all_key_bounds(plan.predicate, child.bucket_spec[1][0]):
                    # The slice IS the predicate: every conjunct bounds the
                    # sorted key, so the residual mask would be all-true —
                    # skip its evaluation (and the device round-trip).
                    self._phys(
                        "IndexRangeScan",
                        files_pruned=self.stats["files_pruned"] - fp0,
                        rows_pruned=self.stats["rows_pruned"] - rp0,
                        kernel="minmax-prune + searchsorted-slice (exact, mask skipped)",
                    )
                    return table
                self._phys(
                    "IndexRangeScan",
                    files_pruned=self.stats["files_pruned"] - fp0,
                    rows_pruned=self.stats["rows_pruned"] - rp0,
                    kernel=f"minmax-prune + searchsorted-slice + {mask_kernel}",
                )
                return apply_filter(table, plan.predicate, mesh=self.mesh, venue=mask_venue)
        if isinstance(child, Union):
            # Hybrid scan: prune the bucketed input(s), keep deltas whole.
            new_inputs: list[LogicalPlan] = []
            for inp in child.inputs:
                if isinstance(inp, Scan) and inp.bucket_spec is not None:
                    pruned = self._prune_bucket_files(inp, plan.predicate)
                    if pruned is None:
                        ranged = self._range_prune_list(inp, plan.predicate)
                        pruned = ranged[0] if ranged is not None else None  # (kept, bounds, stats)
                    if pruned is not None:
                        inp = dataclasses.replace(inp, files=pruned)
                new_inputs.append(inp)
            self._phys(
                "HybridScanFilter",
                files_pruned=self.stats["files_pruned"] - fp0,
                kernel=f"bucket/minmax-prune + {mask_kernel}",
            )
            return apply_filter(
                self._union(Union(new_inputs)), plan.predicate,
                mesh=self.mesh, venue=mask_venue,
            )
        self._phys(kernel=mask_kernel)
        return apply_filter(self._execute(child), plan.predicate, mesh=self.mesh, venue=mask_venue)

    def _prune_bucket_files(self, scan: Scan, predicate: Expr) -> list[str] | None:
        """If the predicate pins every bucket column with equality
        literals — single (eq) or multi-point (IN) — return only the
        owning buckets' files (see point_prune_names)."""
        names = point_prune_names(scan, predicate)
        if names is None:
            return None
        files = self._scan_files(scan)
        matches = [f for f in files if Path(f).name in names]
        if matches:
            self.stats["files_pruned"] += len(files) - len(matches)
            return matches
        return None

    def _range_prune_list(
        self, scan: Scan, predicate: Expr
    ) -> tuple[list[str], KeyBounds, dict] | None:
        """File-level range (min/max) pruning: drop bucket files whose
        manifest key stats cannot overlap the predicate's bounds on the
        leading indexed column. The analog of FileSourceScanExec's parquet
        min/max pruning (SURVEY.md §2.2), which the reference inherits
        from Spark. Comparisons run in the filter mask's own numeric
        domain so pruning never disagrees with it. Returns None when no
        literal bounds or no stats exist."""
        key = scan.bucket_spec[1][0]
        bounds = key_bounds(predicate, key)
        files = self._scan_files(scan)
        stats = hio.file_key_stats(files) if bounds is not None else {}
        if bounds is not None and stats:
            bounds, stat_conv = _convert_bounds(scan.scan_schema.field(key), bounds)
        else:
            stat_conv = None
        # Included-column pruning: any OTHER referenced column with
        # manifest columnStats and literal bounds prunes too (the
        # reference gets this from parquet per-column min/max via
        # FileSourceScanExec, SURVEY.md §2.2).
        refs = {r.lower() for r in predicate.references()}
        extra: list[tuple[KeyBounds, object, dict]] = []
        for c in scan.scan_schema.names:
            if c.lower() == key.lower() or c.lower() not in refs:
                continue
            b = key_bounds(predicate, c)
            if b is None:
                continue
            cstats = hio.file_column_stats(files, c)
            if not cstats:
                continue
            cb, cconv = _convert_bounds(scan.scan_schema.field(c), b)
            extra.append((cb, cconv, cstats))
        if stat_conv is None and not extra:
            return None
        kept: list[str] = []
        for f in files:
            keep = True
            if stat_conv is not None and f in stats:
                s = stats[f]
                # s is None ⇔ bucket empty or all-null key: no row can
                # satisfy a literal comparison (3VL), safe to skip.
                keep = s is not None and _stats_overlap(bounds, stat_conv(s[0]), stat_conv(s[1]))
            for cb, cconv, cstats in extra:
                if not keep:
                    break
                if f in cstats:
                    s = cstats[f]
                    keep = s is not None and _stats_overlap(cb, cconv(s[0]), cconv(s[1]))
            if keep:
                kept.append(f)
        if stat_conv is None and len(kept) == len(files):
            # Included-column stats pruned nothing and the key gives no
            # slicing bounds: stay on the plain scan path (whole cached
            # bucket files — the device upload cache keys on them).
            return None
        self.stats["files_pruned"] += len(files) - len(kept)
        return kept, (bounds if stat_conv is not None else None), stats

    def _range_read(self, scan: Scan, predicate: Expr) -> tuple[ColumnTable, bool] | None:
        """File-level range pruning + within-file searchsorted slicing
        (each surviving file is key-sorted by construction, so qualifying
        rows form one contiguous run). Dictionary codes are not
        value-ordered across files and null prefixes break sortedness —
        both fall back to reading the file whole (mask handles the rest).
        Returns (table, exact): exact ⇔ every row returned provably
        satisfies the key bounds (all parts sliced on a sorted, null-free,
        stats-backed key)."""
        from concurrent.futures import ThreadPoolExecutor

        pruned = self._range_prune_list(scan, predicate)
        if pruned is None:
            return None
        kept, bounds, stats_files = pruned
        schema = scan.scan_schema
        field = schema.field(scan.bucket_spec[1][0])
        if not kept:
            return ColumnTable.empty(schema), True
        before = hio.table_cache_stats()
        try:
            with ThreadPoolExecutor(max_workers=min(8, len(kept))) as pool:
                tables = list(
                    pool.map(
                        lambda fp: hio.read_parquet_cached([fp], columns=schema.names, schema=schema),
                        kept,
                    )
                )
        except IndexCorruptionError:
            raise
        except (OSError, pa.ArrowException) as e:
            raise _corruption(e, scan.root, kept) from e
        finally:
            after = hio.table_cache_stats()
            self.stats["files_read"] += after["miss_files"] - before["miss_files"]
            self.stats["bytes_scanned"] += after["miss_bytes"] - before["miss_bytes"]
        parts: list[ColumnTable] = []
        # Float keys can hold NaN VALUES (sorted last by the build); a
        # lower-bound-only slice would include them while the mask drops
        # them — never claim exactness for float key columns. bounds is
        # None when only included-column stats pruned: no key slicing.
        exact = bounds is not None and field.device_dtype.kind != "f"
        for fp, t in zip(kept, tables):
            if t.num_rows == 0:
                continue
            sliceable = (
                bounds is not None
                and not field.is_string
                and t.valid_mask(field.name) is None
                and fp in stats_files  # stats-backed ⇒ written key-sorted
            )
            if sliceable:
                colv = t.columns[field.name]
                lo_i, hi_i = 0, t.num_rows
                if bounds.lo is not None:
                    lo_i = int(np.searchsorted(colv, bounds.lo, side="right" if bounds.lo_strict else "left"))
                if bounds.hi is not None:
                    hi_i = int(np.searchsorted(colv, bounds.hi, side="left" if bounds.hi_strict else "right"))
                if hi_i <= lo_i:
                    self.stats["rows_pruned"] += t.num_rows
                    continue
                if lo_i > 0 or hi_i < t.num_rows:
                    self.stats["rows_pruned"] += t.num_rows - (hi_i - lo_i)
                    t = t.take(np.arange(lo_i, hi_i))
            else:
                exact = False
            parts.append(t)
        if not parts:
            return ColumnTable.empty(schema), True
        out = ColumnTable.concat(parts) if len(parts) > 1 else parts[0]
        return out, exact

    # -- join ------------------------------------------------------------
