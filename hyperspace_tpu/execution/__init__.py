from hyperspace_tpu.execution.table import ColumnTable

__all__ = ["ColumnTable"]
