"""Aggregate execution: segment reduce, partial-agg pushdown, distinct
expansion, and grouping-set re-folds (Executor mixin)."""

from __future__ import annotations


import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.expr import Col, Lit
from hyperspace_tpu.plan.nodes import Aggregate, Join, LogicalPlan

from hyperspace_tpu.execution.exec_common import (
    _TableLeaf,
    _copy_field,
    _desugar_count_distinct,
    _group_ids_cached,
    _null_field,
)


class AggregateMixin:
    def _aggregate(self, plan: "Aggregate") -> ColumnTable:
        from hyperspace_tpu.ops.aggregate import aggregate_table

        if plan.grouping_sets is not None:
            return self._grouping_sets_aggregate(plan)
        if any(a.fn == "count_distinct" for a in plan.aggs):
            for a in plan.aggs:
                if a.fn == "count_distinct" and not isinstance(a.expr, Col):
                    raise HyperspaceError("count_distinct requires a plain column")
            dcols = {a.expr.name.lower() for a in plan.aggs if a.fn == "count_distinct"}
            if len(dcols) == 1 and not any(a.fn == "mean" for a in plan.aggs):
                # Single distinct column, no mean: the plan-level two-phase
                # desugar keeps the inner aggregate eligible for the fused
                # Aggregate(Join) path.
                self._phys("CountDistinctReaggregate")
                plan2, count_aliases = _desugar_count_distinct(plan)
                out = self._execute(plan2)
                # SQL count is never NULL: the outer SUM of count partials
                # yields NULL over zero inner rows — restore the 0.
                for alias in count_aliases:
                    f = out.schema.field(alias)
                    v = out.validity.pop(f.name, None)
                    if v is not None:
                        out.columns[f.name] = np.where(v, out.columns[f.name], 0)
                return out
            return self._distinct_aggregate(plan, sorted(dcols))
        venue = self._agg_venue()
        pushed = self._try_partial_agg_pushdown(plan)
        if isinstance(pushed, ColumnTable):
            return pushed
        if pushed is not None:
            # Pushdown bailed AFTER materializing the left side: continue
            # with the spliced plan so nothing below re-executes it.
            plan = pushed
        # Fuse Aggregate(Join) on both venues: the device run-prefix
        # kernel avoids the match-pair readback; the host C++
        # merge+accumulate avoids materializing the pairs at all.
        fused = self._try_fused_join_aggregate(plan)
        if fused is not None:
            self._phys(
                "FusedJoinAggregate",
                join_path=self.stats["join_path"],
                kernel=self.stats["join_kernel"],
                buckets=self.stats["num_buckets"],
            )
            return fused
        table = self._execute(plan.child)
        self.stats["agg_path"] = f"segment-reduce-{venue}"
        mesh = self.mesh if venue == "device" else None
        if mesh is not None:
            from hyperspace_tpu.parallel.mesh import mesh_size

            self.stats["agg_devices"] = mesh_size(mesh)
        self._phys(
            "SegmentReduceAggregate",
            venue=venue,
            groups=len(plan.group_by),
            aggs=len(plan.aggs),
            devices=self.stats.get("agg_devices", 1),
        )
        return aggregate_table(
            table, plan.group_by, plan.aggs, plan.schema, venue=venue, mesh=mesh,
            # Identity-cached factorization: repeat aggregations over a
            # stable index version skip re-factorizing the keys.
            groups=_group_ids_cached(table, plan.group_by),
            fused=self._fused_kernels(),
        )

    def _try_partial_agg_pushdown(self, plan: "Aggregate") -> "ColumnTable | Aggregate | None":
        """Partial aggregation pushdown (Spark's PartialAggregate /
        aggregate-through-join analog): for Aggregate(Join(L, R)) where
        every aggregate reads only the L side — optionally inside a
        CASE whose CONDITION reads only the R side (the q43/q59 weekly
        pivot shape; R attributes are constant per join-key run, so the
        case splits into the outer re-aggregation) — pre-aggregate L by
        (join keys + L group columns), join the FEW partial rows, and
        re-fold. Adaptive: bails when the partial grouping would not
        actually shrink L (measured, not guessed), in which case the
        normal fused path re-executes the (cheap, cached) L side."""
        from hyperspace_tpu.ops.aggregate import aggregate_table
        from hyperspace_tpu.plan.expr import Case, Lit
        from hyperspace_tpu.plan.nodes import AggSpec

        child = plan.child
        if not isinstance(child, Join) or child.how != "inner" or child.condition is not None:
            return None
        if isinstance(child.left, _TableLeaf) or isinstance(child.right, _TableLeaf):
            return None  # already pushed (recursion guard)
        lnames = {n.lower() for n in child.left.schema.names}
        rnames = {n.lower() for n in child.right.schema.names}
        g_l = [c for c in plan.group_by if c.lower() in lnames]
        g_r = [c for c in plan.group_by if c.lower() not in lnames]
        if any(c.lower() not in rnames for c in g_r):
            return None

        partial_specs: list[AggSpec] = []
        outer_specs: list[AggSpec] = []
        mean_parts: dict[str, tuple[str, str]] = {}  # alias -> (sum, cnt) temp names
        count_aliases: list[str] = []
        uses_r = bool(g_r)
        for i, a in enumerate(plan.aggs):
            refs = {r.lower() for r in a.references()}
            if a.fn == "count" and a.expr is None:
                partial_specs.append(AggSpec("count", None, f"__pp{i}"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}"), a.alias))
                count_aliases.append(a.alias)
                continue
            if a.fn in ("sum", "count", "min", "max") and refs and refs <= lnames:
                partial_specs.append(AggSpec(a.fn, a.expr, f"__pp{i}"))
                fn2 = "sum" if a.fn in ("sum", "count") else a.fn
                outer_specs.append(AggSpec(fn2, Col(f"__pp{i}"), a.alias))
                if a.fn == "count":
                    count_aliases.append(a.alias)
                continue
            if a.fn == "mean" and refs and refs <= lnames:
                partial_specs.append(AggSpec("sum", a.expr, f"__pp{i}s"))
                partial_specs.append(AggSpec("count", a.expr, f"__pp{i}c"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}s"), f"__po{i}s"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}c"), f"__po{i}c"))
                mean_parts[a.alias] = (f"__po{i}s", f"__po{i}c")
                continue
            if (
                a.fn == "sum"
                and isinstance(a.expr, Case)
                and len(a.expr.branches) == 1
                and isinstance(a.expr.default, Lit)
                and a.expr.default.value in (0, 0.0)
            ):
                cond, val = a.expr.branches[0]
                crefs = {r.lower() for r in cond.references()}
                vrefs = {r.lower() for r in val.references()}
                if crefs and crefs <= rnames and vrefs <= lnames:
                    uses_r = True
                    partial_specs.append(AggSpec("sum", val, f"__pp{i}"))
                    from hyperspace_tpu.plan.expr import when as _when

                    outer_specs.append(
                        AggSpec("sum", _when(cond, Col(f"__pp{i}")).otherwise(0.0), a.alias)
                    )
                    continue
            return None
        if not uses_r:
            # The aggregate never needs R beyond the join's filtering
            # effect — the fused path already handles that shape better.
            return None

        pkeys: list[str] = list(child.left_on)
        pk_low = {c.lower() for c in pkeys}
        for c in g_l:
            if c.lower() not in pk_low:
                pkeys.append(c)
                pk_low.add(c.lower())

        lt = self._execute(child.left)
        gid, k, rep = _group_ids_cached(lt, pkeys)
        if k > max(64, lt.num_rows // 8):
            # Less than ~8x shrink: the extra factorize + re-fold beats
            # nothing the fused path doesn't already do better. When the
            # left side is a deep subtree, it is already MATERIALIZED —
            # hand back a plan with it spliced in so nothing below
            # re-executes it. An index-aligned scan side stays a PLAN:
            # splicing would knock it off the zero-exchange aligned path
            # (and its DPP pruning), which beats the re-execution it
            # avoids (the scan is cache-served anyway).
            if self._aligned_side(child.left) is not None:
                return None
            return Aggregate(
                Join(_TableLeaf(lt), child.right, child.left_on, child.right_on,
                     child.how, condition=child.condition),
                list(plan.group_by),
                list(plan.aggs),
            )

        from hyperspace_tpu.plan.nodes import Aggregate as _Agg

        pschema = _Agg(_TableLeaf(lt), pkeys, partial_specs).schema
        venue = self._agg_venue()
        partial = aggregate_table(
            lt, pkeys, partial_specs, pschema, venue=venue, groups=(gid, k, rep),
            fused=self._fused_kernels(),
        )
        self._phys(
            "PartialAggPushdown",
            partial_rows=partial.num_rows,
            input_rows=lt.num_rows,
            keys=pkeys,
        )
        outer_plan: LogicalPlan = _Agg(
            Join(_TableLeaf(partial), child.right, child.left_on, child.right_on, "inner"),
            list(plan.group_by),
            outer_specs,
        )
        out = self._execute(outer_plan)
        # Re-shape to the original output: means recompose from their
        # sum/count partials (NULL when no valid input), counts restore
        # SQL's never-NULL zero, columns return in declared order.
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for f in plan.schema.fields:
            low = f.name.lower()
            if low in {c.lower() for c in plan.group_by}:
                _copy_field(f, out, f.name, cols, dicts, validity)
                continue
            if f.name in mean_parts or low in {a.lower() for a in mean_parts}:
                s_name, c_name = mean_parts[f.name]
                s = out.column(s_name).astype(np.float64)
                c = out.column(c_name).astype(np.float64)
                with np.errstate(invalid="ignore", divide="ignore"):
                    cols[f.name] = np.where(c > 0, s / np.maximum(c, 1), 0.0)
                if (c == 0).any():
                    validity[f.name] = c > 0
                continue
            _copy_field(f, out, f.name, cols, dicts, validity)
            if f.name in count_aliases:
                v = validity.pop(f.name, None)
                if v is not None:
                    cols[f.name] = np.where(v, cols[f.name], 0)
        return ColumnTable(plan.schema, cols, dicts, validity)

    def _distinct_aggregate(self, plan: "Aggregate", dcols: list[str]) -> ColumnTable:
        """General distinct expansion (the Spark planner's Expand analog
        for multi-distinct aggregates, q38/q87 shapes): execute the child
        ONCE, factorize the group keys ONCE, run the non-distinct specs
        as a normal segment reduce sharing that factorization, and count
        each distinct column by factorizing (group keys, column) pairs —
        the representative row of each pair maps back to its outer group,
        so a bincount over pair representatives IS the distinct count.
        No join, no per-spec re-execution; mean shares freely."""
        from hyperspace_tpu.ops.aggregate import aggregate_table, group_ids
        from hyperspace_tpu.schema import Schema

        ct = self._execute(plan.child)
        venue = self._agg_venue()
        gid, k, rep = _group_ids_cached(ct, plan.group_by)
        self._phys(
            "DistinctExpandAggregate",
            distinct_cols=dcols,
            groups=len(plan.group_by),
            venue=venue,
        )
        out_schema = plan.schema
        if k == 0 or (ct.num_rows == 0 and plan.group_by):
            return ColumnTable.empty(out_schema)
        regular = [a for a in plan.aggs if a.fn != "count_distinct"]
        reg_fields = [out_schema.field(c) for c in plan.group_by]
        reg_fields += [out_schema.field(a.alias) for a in regular]
        base = aggregate_table(
            ct, plan.group_by, regular, Schema(tuple(reg_fields)),
            venue=venue, groups=(gid, k, rep), fused=self._fused_kernels(),
        )
        cols = dict(base.columns)
        dicts = dict(base.dictionaries)
        validity = dict(base.validity)
        pair_counts: dict[str, np.ndarray] = {}
        for d in dcols:
            pgid, pk, prep = group_ids(ct, [*plan.group_by, d])
            del pgid, pk
            outer = gid[prep]
            vd = ct.valid_mask(d)
            if vd is not None:
                outer = outer[vd[prep]]  # SQL: distinct counts exclude NULL
            pair_counts[d] = np.bincount(outer, minlength=k).astype(np.int64)
        for a in plan.aggs:
            if a.fn == "count_distinct":
                cols[out_schema.field(a.alias).name] = pair_counts[a.expr.name.lower()]
        return ColumnTable(out_schema, cols, dicts, validity)

    def _grouping_sets_aggregate(self, plan: "Aggregate") -> ColumnTable:
        """ROLLUP / CUBE / GROUPING SETS as ONE finest-grain aggregate
        (which gets the fused Aggregate(Join) path when it applies) plus
        cheap re-aggregations of its partials per set — the two-phase
        machinery the count_distinct desugar introduced, generalized.
        The union null-extends group columns a set aggregates away;
        grouping() flags tell data NULLs from subtotal NULLs."""
        from hyperspace_tpu.ops.aggregate import aggregate_table
        from hyperspace_tpu.plan.expr import Col
        from hyperspace_tpu.plan.nodes import AggSpec
        from hyperspace_tpu.schema import Field, Schema

        if any(a.fn == "count_distinct" for a in plan.aggs):
            # Distinct counts do not compose from partials (the same value
            # in two finest groups of one coarser group would double
            # count), so the re-fold below cannot serve them: materialize
            # the child ONCE and aggregate each set directly over it —
            # the plain-aggregate path owns the distinct machinery.
            return self._grouping_sets_distinct(plan)

        # Phase 1: finest grain over the full group_by, means split into
        # sum+count partials so coarser sets can recompose them exactly.
        base_specs: list[AggSpec] = []
        for a in plan.aggs:
            if a.fn == "grouping":
                continue
            if a.fn == "mean":
                base_specs.append(AggSpec("sum", a.expr, f"__gs_sum_{a.alias}"))
                base_specs.append(AggSpec("count", a.expr, f"__gs_cnt_{a.alias}"))
            else:
                base_specs.append(AggSpec(a.fn, a.expr, a.alias))
        base = Aggregate(plan.child, plan.group_by, base_specs)
        bt = self._execute(base)

        out_schema = plan.schema
        venue = self._agg_venue()
        self._phys(
            "GroupingSetsReaggregate",
            sets=[list(s) for s in plan.grouping_sets],
            venue=venue,
        )

        def refold(a: AggSpec) -> list[AggSpec]:
            """Phase-2 spec(s) re-aggregating a phase-1 partial column."""
            if a.fn == "mean":
                return [
                    AggSpec("sum", Col(f"__gs_sum_{a.alias}"), f"__gs_sum_{a.alias}"),
                    AggSpec("sum", Col(f"__gs_cnt_{a.alias}"), f"__gs_cnt_{a.alias}"),
                ]
            fn2 = "sum" if a.fn in ("sum", "count") else a.fn
            return [AggSpec(fn2, Col(a.alias), a.alias)]

        # ROLLUP's sets are prefixes of group_by: the mixed-radix combined
        # key of a prefix is a monotone quotient of the full key's, so ONE
        # factorize+sort of the finest key serves EVERY level (q67's
        # 9-level refold was 9 independent factorizations before this).
        prefix_groups = self._prefix_chain_groups(bt, plan.group_by, plan.grouping_sets)

        parts: list[ColumnTable] = []
        for s in plan.grouping_sets:
            specs2 = [sp for a in plan.aggs if a.fn != "grouping" for sp in refold(a)]
            fields = [bt.schema.field(c) for c in s]
            for sp in specs2:
                src = bt.schema.field(sp.expr.name)
                dtype = src.dtype if sp.fn in ("min", "max") else (
                    "int64" if src.dtype in ("int32", "int64", "bool", "date") else "float64"
                )
                fields.append(Field(sp.alias, dtype))
            sub = aggregate_table(
                bt, list(s), specs2, Schema(tuple(fields)), venue=venue,
                groups=None if prefix_groups is None else prefix_groups.get(len(s)),
                fused=self._fused_kernels(),
            )

            def agg_col(f, spec, cols, dicts, validity, sub=sub):
                if spec.fn == "mean":
                    ssum = sub.column(f"__gs_sum_{spec.alias}").astype(np.float64)
                    scnt = sub.column(f"__gs_cnt_{spec.alias}").astype(np.float64)
                    sv = sub.valid_mask(f"__gs_sum_{spec.alias}")
                    with np.errstate(invalid="ignore", divide="ignore"):
                        cols[f.name] = np.where(scnt > 0, ssum / np.maximum(scnt, 1), 0.0)
                    if sv is not None or (scnt == 0).any():
                        ok = scnt > 0
                        validity[f.name] = ok if sv is None else (ok & sv)
                elif spec.fn == "count":
                    # COUNT is never NULL: zero-row re-folds yield a NULL
                    # sum partial — restore 0 (same rule as the
                    # count_distinct desugar's outer sum).
                    v = sub.valid_mask(spec.alias)
                    c = sub.column(spec.alias)
                    cols[f.name] = np.where(v, c, 0) if v is not None else c
                else:
                    _copy_field(f, sub, spec.alias, cols, dicts, validity)

            parts.append(self._gs_assemble(plan, out_schema, sub, s, bt, agg_col))
        return ColumnTable.concat(parts)

    @staticmethod
    def _prefix_chain_groups(bt: ColumnTable, group_by, sets):
        """Per-set (gid, K, rep) factorizations for prefix-chain grouping
        sets (ROLLUP), all derived from ONE sort. The finest combined key
        is mixed-radix over the per-column codes; a length-L prefix's key
        is its quotient by the trailing radix product — monotone, so the
        full-key sort order is already sorted for every prefix and each
        level needs only an O(n) segment mask. None when the sets are not
        a prefix chain or the radix product overflows (caller falls back
        to per-set factorization)."""
        from hyperspace_tpu.ops.aggregate import _column_codes

        gb_low = [c.lower() for c in group_by]
        lens = set()
        for s in sets:
            if [c.lower() for c in s] != gb_low[: len(s)]:
                return None
            lens.add(len(s))
        if not group_by or bt.num_rows == 0:
            return None
        codes = []
        cards = []
        for c in group_by:
            cd, card = _column_codes(bt, c)
            codes.append(cd)
            cards.append(np.int64(card))
        total = np.int64(1)
        for card in cards:
            if int(total) * int(card) >= np.iinfo(np.int64).max:
                return None
            total *= card
        combined = codes[0].astype(np.int64, copy=True)
        for cd, card in zip(codes[1:], cards[1:]):
            combined *= card
            combined += cd
        # Trailing radix products: suffix[L] divides the full key down to
        # the length-L prefix's key.
        suffix = [np.int64(1)] * (len(group_by) + 1)
        for i in range(len(group_by) - 1, -1, -1):
            suffix[i] = suffix[i + 1] * cards[i]
        perm = np.argsort(combined, kind="stable")
        sc = combined[perm]
        n = len(sc)
        out = {}
        for length in sorted(lens):
            if length == 0:
                out[0] = (np.zeros(n, np.int64), 1, np.zeros(1, np.int64))
                continue
            q = sc // suffix[length]
            newseg = np.empty(n, dtype=bool)
            newseg[0] = True
            newseg[1:] = q[1:] != q[:-1]
            seg = np.cumsum(newseg) - 1
            gid = np.empty(n, dtype=np.int64)
            gid[perm] = seg
            out[length] = (gid, int(seg[-1]) + 1, perm[np.flatnonzero(newseg)])
        return out

    def _gs_assemble(
        self, plan: "Aggregate", out_schema, sub: ColumnTable, s, dict_src, agg_col
    ) -> ColumnTable:
        """One grouping set's output part, shared by the re-fold and
        distinct grouping-set paths: group columns in `s` copy through,
        group columns aggregated away null-extend, grouping() flags
        derive from set membership, and `agg_col(field, spec, cols,
        dicts, validity)` fills the aggregate columns."""
        in_set = {c.lower() for c in s}
        gb_low = {c.lower() for c in plan.group_by}
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        nrows = sub.num_rows
        for f in out_schema.fields:
            low = f.name.lower()
            if low in gb_low:
                if low in in_set:
                    _copy_field(f, sub, f.name, cols, dicts, validity)
                else:
                    _null_field(
                        f, nrows, dict_src if f.is_string else None, cols, dicts, validity
                    )
                continue
            spec = next(a for a in plan.aggs if a.alias.lower() == low)
            if spec.fn == "grouping":
                cols[f.name] = np.full(
                    nrows, 0 if spec.expr.name.lower() in in_set else 1, np.int64
                )
            else:
                agg_col(f, spec, cols, dicts, validity)
        return ColumnTable(out_schema, cols, dicts, validity)

    def _grouping_sets_distinct(self, plan: "Aggregate") -> ColumnTable:
        """GROUPING SETS with count_distinct aggregates (q14/q18 shapes):
        the child materializes once, then every set aggregates it
        directly — per-set work instead of the partial re-fold, because
        distinct counts cannot be composed from finer partials."""

        ct = self._execute(plan.child)
        leaf = _TableLeaf(ct)
        out_schema = plan.schema
        self._phys(
            "GroupingSetsDistinct",
            sets=[list(s) for s in plan.grouping_sets],
            distinct_cols=sorted(
                a.expr.name.lower() for a in plan.aggs if a.fn == "count_distinct"
            ),
        )
        parts: list[ColumnTable] = []
        for s in plan.grouping_sets:
            specs = [a for a in plan.aggs if a.fn != "grouping"]
            sub = self._execute(Aggregate(leaf, list(s), specs))

            def agg_col(f, spec, cols, dicts, validity, sub=sub):
                _copy_field(f, sub, spec.alias, cols, dicts, validity)

            parts.append(self._gs_assemble(plan, out_schema, sub, s, ct, agg_col))
        return ColumnTable.concat(parts)

