"""Executed physical plan: what a query ACTUALLY ran.

The reference's explain compiles to Spark's executedPlan and diffs
physical operators (PlanAnalyzer.scala:163-178,
PhysicalOperatorAnalyzer.scala:39-56). Here there is no separate
compile step — the executor IS the physical layer — so the physical
plan is recorded as the query runs: one node per executed operator
carrying the evidence (files read, rows pruned, kernel/path chosen,
bucket counts, device counts, rows out). `explain(physical=True)`
executes both variants and diffs these trees.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class PhysicalNode:
    """One executed operator. `detail` holds operator-specific evidence
    (files=, rows_pruned=, path=, kernel=...); children in execution
    order."""

    op: str
    detail: dict = dataclasses.field(default_factory=dict)
    children: list = dataclasses.field(default_factory=list)
    rows_out: int | None = None
    # Measured wall time of this operator's frame (children included).
    # Deliberately NOT part of label(): explain's plan diff matches
    # labels across two runs, and wall times never match.
    wall_s: float | None = None

    def label(self) -> str:
        parts = [self.op]
        for k in sorted(self.detail):
            parts.append(f"{k}={self.detail[k]}")
        if self.rows_out is not None:
            parts.append(f"rows={self.rows_out}")
        return " ".join(str(p) for p in parts)

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()

    def to_json(self) -> dict:
        return {
            "op": self.op,
            "detail": dict(self.detail),
            "rows": self.rows_out,
            "wall_s": self.wall_s,
            "children": [c.to_json() for c in self.children],
        }
