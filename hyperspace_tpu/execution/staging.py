"""Arrow→device zero-copy staging — the host-marshalling tax collector.

BENCH_SF100's round-5 accounting put the 600M-row join at ~60s of a
401s wall: the other ~340s was host-side marshalling — bucket/source
decode, key factorization, and channel staging — paid BETWEEN the Arrow
bytes pyarrow decoded and the numpy arrays the device plane uploads.
The biggest single line item is a deliberate memcpy: `ColumnTable
.from_arrow` copied every zero-copy Arrow buffer into an owned numpy
array so that "read-only" could mean exactly one thing in the engine
(frozen by the cache layer, identity-stable).

This module removes that copy WITHOUT weakening the invariant. A
fixed-width, null-free, single-chunk Arrow column can be viewed as a
read-only numpy array over the Arrow buffer itself (`np.frombuffer` —
the view pins the buffer, so lifetime is safe). The view is only kept
on the cache-destined read path (`io.read_parquet_cached` asks for it
with ``zero_copy_ok=True`` and freezes the table moments later); a
table that turns out too large to cache is downgraded to owned writable
arrays (`ColumnTable.own_arrays`), restoring the old semantics exactly.
So "writeable=False ⇒ identity-stable" still holds for every array the
device/derived caches ever see.

Accounting: every fixed-width column that crosses the staging boundary
is counted in ``device.stage.bytes_zero_copy`` (kept as a buffer view)
or ``device.stage.bytes_copied`` (host-materialized: nulls, casts,
multi-chunk concat, unaligned views, or staging disabled). The venue
bench gates the copied-byte reduction on these counters.

Fault point ``device.stage`` fires before each zero-copy view attempt.
An injected transient fault (or any real OSError from the buffer
plumbing) degrades that column to the copied host path — the query
still answers, bytes land in the copied counter. CrashPoint passes
through untouched (BaseException — the query surface declares it).

Gated by ``hyperspace.device.staging.enabled`` (process-global, like
the faults/obs switches: the decode path has no session handle).
"""

from __future__ import annotations

import threading

import numpy as np

from hyperspace_tpu import stats
from hyperspace_tpu.faults import fault_point

# Process-global gate, flipped by config.set(DEVICE_STAGING_ENABLED).
# Benign racy read by design (same contract as faults._armed): a stale
# value steers one column down the other (equally correct) path.
_lock = threading.Lock()
_enabled = True


def set_enabled(enabled: bool) -> None:
    global _enabled
    with _lock:
        _enabled = bool(enabled)


def enabled() -> bool:
    return _enabled  # noqa: HSL013 — single-word read of a bool gate


# Arrow fixed-width primitive types that view directly as the engine's
# device dtypes. Bool is bit-packed in Arrow (no numpy view); date32 and
# timestamp[us] are reinterpreted via Arrow's zero-copy .view() upstream.
_VIEW_DTYPES = {
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "uint8": np.uint8,
    "uint16": np.uint16,
    "uint32": np.uint32,
    "uint64": np.uint64,
    "float": np.float32,
    "double": np.float64,
    "date32[day]": np.int32,
    "timestamp[us]": np.int64,
}


def count_copied(nbytes: int) -> None:
    """Account host-materialized staging bytes (the copied path)."""
    if nbytes > 0:
        stats.increment("device.stage.bytes_copied", int(nbytes))


def _buffer_view(arr, np_dtype) -> np.ndarray | None:
    """Read-only numpy view over a primitive Arrow array's data buffer,
    or None when the layout cannot be viewed (offset view misaligned to
    the lane width — the `hs_take_rows` alignment-guard class)."""
    bufs = arr.buffers()
    if len(bufs) != 2 or bufs[1] is None:
        return None
    data = bufs[1]
    dt = np.dtype(np_dtype)
    byte_off = arr.offset * dt.itemsize
    if (data.address + byte_off) % dt.itemsize:
        return None  # unaligned offset view: the memcpy path owns it
    if byte_off + len(arr) * dt.itemsize > data.size:
        return None
    out = np.frombuffer(data, dtype=dt, count=len(arr), offset=byte_off)
    # Freeze unconditionally: frombuffer over an IMMUTABLE Arrow buffer
    # (the parquet path) is already read-only, but a buffer wrapping a
    # caller's live numpy array (in-memory pa.table) stays writable —
    # and a writable staged view would let query code corrupt the shared
    # Arrow allocation. The view holds `data`, so the Arrow allocation
    # outlives the array either way.
    out.flags.writeable = False
    return out


def stage_column(arr, field) -> np.ndarray | None:
    """Zero-copy numpy view of one fixed-width Arrow column (chunked or
    plain), or None when ineligible — nulls, bool, multi-chunk, dtype
    mismatch with the schema, unaligned offset view, staging disabled,
    or an injected/real staging fault (degrades to the copied path)."""
    import pyarrow as pa

    if not _enabled:
        return None
    if isinstance(arr, pa.ChunkedArray):
        if arr.num_chunks != 1:
            return None
        arr = arr.chunk(0)
    if arr.null_count:
        return None
    want = np.dtype(field.device_dtype)
    np_dtype = _VIEW_DTYPES.get(str(arr.type))
    if np_dtype is None or np.dtype(np_dtype) != want:
        return None
    try:
        fault_point("device.stage", field.name)
        if str(arr.type) in ("date32[day]", "timestamp[us]"):
            # Arrow's .view() reinterprets the same buffer (zero-copy)
            # into the engine's physical integer domain.
            arr = arr.view(pa.int32() if want == np.int32 else pa.int64())
        view = _buffer_view(arr, np_dtype)
    except OSError:
        # Transient staging failure (injected or real): this column
        # degrades to the copied host path — the advisory contract.
        return None
    if view is None:
        return None
    stats.increment("device.stage.bytes_zero_copy", int(view.nbytes))
    return view


def validity_mask(arr) -> np.ndarray | None:
    """Host bool validity mask (True = valid) of an Arrow column,
    expanded from the PACKED validity bitmap with one vectorized
    np.unpackbits pass per chunk — not through a pyarrow compute
    round-trip that materializes an intermediate byte-per-row Arrow
    array first. Returns None when the column is null-free."""
    import pyarrow as pa

    if not arr.null_count:
        return None
    chunks = arr.chunks if isinstance(arr, pa.ChunkedArray) else [arr]
    parts: list[np.ndarray] = []
    for c in chunks:
        n = len(c)
        bufs = c.buffers()
        bitmap = bufs[0] if bufs else None
        if c.null_count == 0 or bitmap is None:
            parts.append(np.ones(n, dtype=bool))
            continue
        bits = np.frombuffer(bitmap, dtype=np.uint8)
        mask = np.unpackbits(bits, bitorder="little")[c.offset : c.offset + n]
        parts.append(mask.astype(bool))
    out = parts[0] if len(parts) == 1 else np.concatenate(parts)
    # Writable copy: the mask is a fresh host array either way (the
    # engine zeroes null slots through it), and downstream freezing is
    # the io cache's decision, not ours.
    return np.ascontiguousarray(out)
