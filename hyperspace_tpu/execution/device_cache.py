"""Device-resident array cache: the HBM half of the bucketed columnar
container (SURVEY.md §2.3).

Decoded index tables are host-cached and FROZEN (execution/io.py); their
arrays are therefore identity-stable for as long as they live. This
module keys derived artifacts on that identity — `(id(base), variant)` —
while holding a reference to the base array so the id can never be
recycled underneath an entry. Refresh/rebuild produces new host arrays
with new ids, so invalidation is automatic; eviction is LRU under a byte
budget.

Two instances cover the read hot path:
- DEVICE_CACHE: uploaded (padded, optionally sharded) `jax.Array`s —
  repeat queries over the same index version serve straight from HBM
  instead of re-staging over PCIe/the tunnel;
- HOST_DERIVED: host-side derived arrays (order-preserving 64-bit key
  words, join key codes, bucket-major pads) that would otherwise be
  recomputed per query. Entries are frozen on insert so they are
  themselves valid cache bases.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from hyperspace_tpu.obs import metrics as obs_metrics


class RefCache:
    """Identity-keyed LRU memo with a byte budget. Entries hold strong
    references to their base arrays, so id()-based keys stay valid for
    the lifetime of the entry. `name` keys the hit/miss/eviction
    counters and byte gauge in the exportable metrics registry."""

    def __init__(self, budget_bytes: int, name: str = "ref_cache"):
        self.budget = int(budget_bytes)
        self._lock = threading.Lock()
        self._entries: dict[tuple, tuple[int, tuple, object]] = {}
        # Single-flight: key -> Event set when that key's in-progress
        # build finishes (docs/serving.md — N concurrent clients missing
        # on the same cold key must not stage the same upload N times).
        self._building: dict[tuple, threading.Event] = {}
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self._met_hits = obs_metrics.counter(f"{name}.hits")
        self._met_misses = obs_metrics.counter(f"{name}.misses")
        self._met_evictions = obs_metrics.counter(f"{name}.evictions")
        self._met_bytes = obs_metrics.gauge(f"{name}.bytes", "resident cached bytes")

    def get_or_build(self, key: tuple, base_refs: tuple, build, wait_timeout: float | None = None):
        """`build() -> (value, nbytes)`; value cached under `key` while
        `base_refs` are pinned. Concurrent misses on the same key are
        single-flighted: one caller builds, the rest wait on its event
        and then hit (a waiter re-builds only if the value turned out
        too large to cache — same cost as before the dedup).

        `wait_timeout` bounds each single-flight wait: a waiter whose
        wait expires builds LOCALLY without claiming the building slot
        (the slot still belongs to the stuck builder), so an abandoned
        in-process build — a builder thread wedged in device staging, or
        killed in a way that never sets its event — cannot block waiters
        forever. None preserves the original unbounded wait."""
        while True:
            with self._lock:
                hit = self._entries.get(key)
                if hit is not None:
                    self._entries[key] = self._entries.pop(key)  # LRU touch
                    self.hits += 1
                    self._met_hits.inc()
                    return hit[2]
                ev = self._building.get(key)
                if ev is None:
                    self._building[key] = threading.Event()
                    self.misses += 1
                    break  # this caller builds
            if not ev.wait(wait_timeout):
                # Timed out on another caller's build: fall through to a
                # local build. No slot ownership — the original builder
                # (if it ever finishes) still sets and clears its event.
                with self._lock:
                    self.misses += 1
                self._met_misses.inc()
                value, nbytes = build()
                evicted = self._insert(key, base_refs, value, nbytes)
                if evicted:
                    self._met_evictions.inc(evicted)
                return value
            # Re-check: usually a hit now. If the builder failed or the
            # value was uncacheable, the building slot is free again and
            # this caller becomes the builder on the next lap.
        self._met_misses.inc()
        try:
            value, nbytes = build()
        except BaseException:
            with self._lock:
                self._building.pop(key).set()
            raise
        with self._lock:
            evicted = self._insert_locked(key, base_refs, value, nbytes)
            self._building.pop(key).set()
        if evicted:
            self._met_evictions.inc(evicted)
        return value

    def _insert(self, key: tuple, base_refs: tuple, value, nbytes: int) -> int:
        with self._lock:
            return self._insert_locked(key, base_refs, value, nbytes)

    def _insert_locked(self, key: tuple, base_refs: tuple, value, nbytes: int) -> int:
        """Admit a built value under the byte budget; returns evictions.
        Caller holds `self._lock`."""
        evicted = 0
        if nbytes <= self.budget // 4 and key not in self._entries:
            self._entries[key] = (nbytes, base_refs, value)
            self._bytes += nbytes
            while self._bytes > self.budget and self._entries:
                k = next(iter(self._entries))
                nb, _, _ = self._entries.pop(k)
                self._bytes -= nb
                evicted += 1
        self._met_bytes.set(self._bytes)
        return evicted

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.hits = 0
            self.misses = 0
            self._met_bytes.set(0)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
            }


DEVICE_CACHE = RefCache(
    int(os.environ.get("HYPERSPACE_DEVICE_CACHE_BYTES", 2 << 30)), name="device_cache"
)
HOST_DERIVED = RefCache(
    int(os.environ.get("HYPERSPACE_DERIVED_CACHE_BYTES", 1 << 30)), name="host_derived"
)


def table_footprint_bytes(table) -> int:
    """Canonical ColumnTable byte accounting for every byte-budgeted
    cache (io decoded-table cache, HOST_DERIVED side entries, the serve
    result cache). Dictionary-coded string columns count at their
    (codes + dictionary payload) footprint: the int32 code array plus
    the summed character payload (+ pointer word) of the SMALL
    dictionary — never the inflated per-row string size, and never a
    ``<U``-dtype dictionary's UTF-32-padded ``.nbytes`` (which scales
    with the LONGEST entry times the entry count). Over-counting here
    evicted dict-coded columns far too eagerly: a 4M-row dict column is
    ~16 MB of codes, not the hundreds of MB its decoded strings would
    occupy."""
    total = sum(int(v.nbytes) for v in table.columns.values())
    total += sum(int(v.nbytes) for v in table.validity.values())
    for d in table.dictionaries.values():
        total += sum(len(str(s)) for s in d.tolist()) + 8 * len(d)
    return int(total)


def is_stable(arr: np.ndarray) -> bool:
    """True when the array's identity is a valid cache key: frozen arrays
    (decoded-table cache entries and HOST_DERIVED values) never mutate
    and are pinned by the entry that caches against them."""
    return isinstance(arr, np.ndarray) and not arr.flags.writeable


def freeze(arr: np.ndarray) -> np.ndarray:
    arr.flags.writeable = False
    return arr


def _x64_now() -> bool:
    """jnp.asarray dtype resolution depends on the ACTIVE x64 scope
    (float64 downcasts to float32 outside it) — the upload key must
    distinguish the two or a cross-scope hit would return the wrong
    device dtype."""
    import jax

    return bool(jax.config.jax_enable_x64)


def device_put_padded(arr: np.ndarray, n_pad: int, sharding=None):
    """Upload `arr` padded with zeros to length n_pad (row dim), through
    DEVICE_CACHE when the base is stable. `sharding` is a
    jax.sharding.Sharding or None."""
    import jax
    import jax.numpy as jnp

    def build():
        a = arr
        if len(a) != n_pad:
            a = np.concatenate([a, np.zeros(n_pad - len(a), dtype=a.dtype)])
        dev = jnp.asarray(a) if sharding is None else jax.device_put(a, sharding)
        return dev, int(dev.nbytes)

    if not is_stable(arr):
        return build()[0]
    skey = None
    if sharding is not None:
        try:
            skey = (str(sharding.mesh.shape), str(sharding.spec))
        except Exception:
            skey = repr(sharding)
    return DEVICE_CACHE.get_or_build(
        ("pad", id(arr), n_pad, skey, _x64_now()), (arr,), build
    )


def device_put_cached(arr: np.ndarray):
    """Upload `arr` as-is, through DEVICE_CACHE when stable."""
    import jax.numpy as jnp

    def build():
        dev = jnp.asarray(arr)
        return dev, int(dev.nbytes)

    if not is_stable(arr):
        return build()[0]
    return DEVICE_CACHE.get_or_build(
        ("raw", id(arr), arr.shape, _x64_now()), (arr,), build
    )


def derived(key: tuple, base_refs: tuple, build_host):
    """Memoize a host-derived array of stable bases; the value is frozen
    so it can serve as a cache base itself. `build_host() -> np.ndarray`."""

    def build():
        out = build_host()
        return freeze(out), int(out.nbytes)

    return HOST_DERIVED.get_or_build(key, base_refs, build)


def clear_all() -> None:
    DEVICE_CACHE.clear()
    HOST_DERIVED.clear()
