"""Parquet IO: host staging between disk and the device plane.

The analog of Spark's FileSourceScanExec + vectorized Parquet read
(SURVEY.md §2.2). Reads go through pyarrow into ColumnTable (strings
dictionary-encoded); writes emit one sorted parquet file per bucket plus a
`_index_manifest.json` with per-bucket row counts — the manifest is what
enables query-time bucket pruning and hybrid-scan planning without opening
every footer.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from hyperspace_tpu import stats
from hyperspace_tpu.exceptions import HyperspaceError, IndexCorruptionError
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.schema import Schema
from hyperspace_tpu.utils import retry
from hyperspace_tpu.utils.file_utils import write_json

MANIFEST_NAME = "_index_manifest.json"

# -- decoded-table cache ------------------------------------------------------
# Index bucket files are read on every query; decoding them once and
# revalidating by mtime removes the host IO floor from the read hot path
# (round 1 weakness #4/#5). Entries are treated as immutable by callers.
# Callers read concurrently from thread pools — all cache state is guarded
# by one lock (reads/decodes themselves run unlocked).
import threading

_CACHE_BUDGET = 512 << 20
_cache: "dict[tuple, tuple[tuple, int, ColumnTable]]" = {}
_cache_bytes = 0
_cache_lock = threading.Lock()
_cache_stats = {"hits": 0, "misses": 0, "miss_files": 0, "miss_bytes": 0}

# Process-lifetime mirrors of the per-process cache dict above, in the
# exportable registry (obs/export.py renders them).
_MET_HITS = obs_metrics.counter("table_cache.hits", "decoded-table cache hits")
_MET_MISSES = obs_metrics.counter("table_cache.misses", "decoded-table cache misses")
_MET_BYTES = obs_metrics.counter("io.bytes_scanned", "bytes physically read (cache misses)")
_MET_FILES = obs_metrics.counter("io.files_read", "files physically read (cache misses)")


def set_table_cache_budget(nbytes: int) -> None:
    global _CACHE_BUDGET
    with _cache_lock:
        _CACHE_BUDGET = int(nbytes)
        _evict_locked()


def clear_table_cache() -> None:
    global _cache_bytes
    with _cache_lock:
        _cache.clear()
        _cache_bytes = 0
    # Device/derived caches key on the identity of (now-released) host
    # arrays; drop them too so the pinned references don't linger.
    from hyperspace_tpu.execution import device_cache

    device_cache.clear_all()


def table_cache_stats() -> dict:
    with _cache_lock:
        return dict(_cache_stats)


def _evict_locked() -> None:
    global _cache_bytes
    while _cache_bytes > _CACHE_BUDGET and _cache:
        k = next(iter(_cache))
        # Caller holds _cache_lock (the _locked suffix is the contract).
        _, nb, _ = _cache.pop(k)  # noqa: HSL008
        _cache_bytes -= nb


def _table_nbytes(t: ColumnTable) -> int:
    from hyperspace_tpu.execution import device_cache

    return device_cache.table_footprint_bytes(t)


def _freeze_table(t: ColumnTable) -> None:
    """Mark a table's arrays read-only before it enters the cache: the
    SAME object is returned to every caller, so an accidental in-place
    write must raise instead of corrupting every later query."""
    for arr in (*t.columns.values(), *t.validity.values(), *t.dictionaries.values()):
        arr.flags.writeable = False


def read_parquet_cached(files: list[str], columns: list[str] | None = None, schema: Schema | None = None) -> ColumnTable:
    """read_parquet through the mtime-validated decoded-table cache."""
    import os

    key = (tuple(files), tuple(columns) if columns is not None else None)
    try:
        stats_ = [os.stat(f) for f in files]
    except OSError:
        return read_parquet(files, columns=columns, schema=schema)
    mtimes = tuple(s.st_mtime_ns for s in stats_)
    with _cache_lock:
        hit = _cache.get(key)
        if hit is not None and hit[0] == mtimes:
            # Re-insert for LRU recency (dict preserves insertion order).
            _cache[key] = _cache.pop(key)
            _cache_stats["hits"] += 1
            _MET_HITS.inc()
            return hit[2]
        _cache_stats["misses"] += 1
        _cache_stats["miss_files"] += len(files)
        disk_bytes = sum(s.st_size for s in stats_)
        _cache_stats["miss_bytes"] += disk_bytes
    _MET_MISSES.inc()
    _MET_FILES.inc(len(files))
    _MET_BYTES.inc(disk_bytes)
    # Cache-destined decode: the one sanctioned caller of the zero-copy
    # staging path (execution/staging.py) — eligible columns stay
    # read-only views over the Arrow buffers, frozen into the cache
    # below (or downgraded to owned copies when the table turns out too
    # large to cache, restoring writable per-query semantics exactly).
    table = read_table_files(
        files, "parquet", columns=columns, schema=schema, zero_copy_ok=True
    )
    nb = _table_nbytes(table)
    global _cache_bytes
    cached = False
    with _cache_lock:
        if nb <= _CACHE_BUDGET // 4:
            # Freeze ONLY what actually enters the cache: frozen ⟺
            # identity-stable. A table too large to cache is re-decoded
            # per query with fresh ids — freezing it would make the
            # device/derived caches accumulate dead never-hit entries.
            _freeze_table(table)
            if key in _cache:
                _cache_bytes -= _cache.pop(key)[1]
            _cache[key] = (mtimes, nb, table)
            _cache_bytes += nb
            _evict_locked()
            cached = True
    if not cached:
        table.own_arrays()
    return table


def read_parquet(files: list[str], columns: list[str] | None = None, schema: Schema | None = None) -> ColumnTable:
    return read_table_files(files, "parquet", columns=columns, schema=schema)


_ARROW_TYPES = {
    "int32": pa.int32(),
    "int64": pa.int64(),
    "float32": pa.float32(),
    "float64": pa.float64(),
    "bool": pa.bool_(),
    "string": pa.string(),
    "date": pa.date32(),
    "timestamp": pa.timestamp("us"),
}


def _arrow_types_for(schema: Schema | None) -> dict | None:
    """name → arrow type for the registered schema's scalar fields —
    pins CSV/JSON decode to the PLANNED types instead of re-inferring
    per file (per-file inference can diverge across files and from the
    registration-time schema)."""
    if schema is None:
        return None
    out = {}
    for f in schema.fields:
        t = _ARROW_TYPES.get(f.dtype)
        if t is not None:
            out[f.name] = t
    return out or None


def _read_one_file(path: str, fmt: str, columns: list[str] | None, schema: Schema | None):
    """One file of any supported source format → pyarrow Table, with
    transient-IO retry (pyarrow's IO errors subclass OSError; only
    retryable errnos re-attempt — a missing or truncated file surfaces
    immediately). The reference gates sources to the same four formats
    (index/serde/LogicalPlanSerDeUtils.scala:225-245)."""
    return retry.retry_call(_read_one_file_once, path, fmt, columns, schema)


def _read_one_file_once(path: str, fmt: str, columns: list[str] | None, schema: Schema | None):
    fault_point("bucket.read", path)
    if fmt == "parquet":
        # ParquetFile (never the dataset API): index files live under
        # hive-looking `v__=N` version dirs, and inferring a `v__`
        # partition column would bake it into compacted files. Decoding
        # as ONE whole-file batch (instead of pq.read_table's ~128Ki-row
        # internal batches) keeps every column SINGLE-CHUNK, which is
        # what lets the zero-copy staging layer keep it as an Arrow
        # buffer view — multi-chunk columns must copy to become
        # contiguous. Measures at parity or faster than read_table.
        pf = pq.ParquetFile(path)
        n = pf.metadata.num_rows
        if columns is not None:
            # iter_batches silently IGNORES unknown columns where
            # read_table raised — keep the strict contract (an index
            # file missing a declared column is corruption, not a
            # narrower read).
            names = set(pf.schema_arrow.names)
            missing = [c for c in columns if c not in names]
            if missing:
                raise pa.lib.ArrowInvalid(
                    f"no match for column(s) {missing} in {path}"
                )
        batches = list(
            pf.iter_batches(batch_size=max(n, 1), columns=columns, use_threads=True)
        )
        if not batches:
            sch = pf.schema_arrow
            if columns is not None:
                sch = pa.schema([sch.field(c) for c in columns])
            return sch.empty_table()
        return pa.Table.from_batches(batches)
    if fmt == "orc":
        from pyarrow import orc

        return orc.ORCFile(path).read(columns=columns)
    if fmt == "csv":
        from pyarrow import csv as pcsv

        opts = pcsv.ConvertOptions(
            include_columns=columns if columns is not None else None,
            column_types=_arrow_types_for(schema),
        )
        return pcsv.read_csv(path, convert_options=opts)
    if fmt == "json":
        from pyarrow import json as pjson

        types = _arrow_types_for(schema)
        parse = None
        if types is not None and schema is not None and len(types) == len(schema.fields):
            parse = pjson.ParseOptions(
                explicit_schema=pa.schema([(f.name, types[f.name]) for f in schema.fields])
            )
        t = pjson.read_json(path, parse_options=parse)
        return t.select(columns) if columns is not None else t
    raise HyperspaceError(f"unsupported source format {fmt!r} (parquet|orc|csv|json)")


# Cold reads at or above this many on-disk bytes decode as parallel
# row-group chunks instead of one serial pq.read_table per file (only
# engaged when the file count alone cannot saturate the pool).
_CHUNKED_READ_MIN_BYTES = 32 << 20


def _read_parquet_chunked(files: list[str], columns: list[str] | None):
    """Row-group-parallel decode of a small file set, or None when the
    footer plan yields no parallelism (single row group, tiny estimate,
    unreadable footers — every fallback lands on the per-file path)."""
    from concurrent.futures import ThreadPoolExecutor

    try:
        footers = read_footers(files)
    except (OSError, pa.ArrowException):
        return None
    est = estimate_uncompressed_bytes(files, columns, footers=footers)
    if est <= 0:
        return None
    units = plan_row_group_chunks(files, max(4 << 20, est // 16), columns, footers=footers)
    if len(units) < 2:
        return None
    read = obs_trace.wrap(lambda c: read_chunk(c, columns))
    with ThreadPoolExecutor(max_workers=min(8, len(units))) as ex:
        parts = list(ex.map(read, units))
    # Units are planned in file order with row groups in order, so the
    # ordered concat reproduces the serial read's row order exactly.
    return pa.concat_tables(parts, promote_options="default")


def read_table_files(
    files: list[str],
    fmt: str = "parquet",
    columns: list[str] | None = None,
    schema: Schema | None = None,
    zero_copy_ok: bool = False,
) -> ColumnTable:
    """Format-aware multi-file read into a ColumnTable (decode released
    from the GIL and overlapped across files). `schema` is the registered
    dataset schema; CSV/JSON decode is pinned to it. `zero_copy_ok`
    opts the decode into the device-staging path — ONLY the
    cache-destined read (read_parquet_cached) may pass it (see
    ColumnTable.from_arrow)."""
    if not files:
        raise HyperspaceError("no files to read")
    import os

    try:
        nbytes = sum(os.path.getsize(f) for f in files)
    except OSError:
        nbytes = 0
    with obs_trace.span("io.read", files=len(files), fmt=fmt, bytes=nbytes):
        table = None
        if fmt == "parquet" and len(files) <= 4 and nbytes >= _CHUNKED_READ_MIN_BYTES:
            # A cold read of one (or few) big bucket files used to decode
            # serially — one pq.read_table per pool worker with most of
            # the pool idle. Split it into footer-planned row-group
            # chunks instead so the decode parallelizes within the file.
            table = _read_parquet_chunked(files, columns)
        if table is None:
            if len(files) == 1:
                tables = [_read_one_file(files[0], fmt, columns, schema)]
            else:
                from concurrent.futures import ThreadPoolExecutor

                # wrap(): pool workers start with an empty contextvar
                # context — re-plant the caller's active span so per-file
                # retry/fault events attribute to this read.
                read = obs_trace.wrap(lambda f: _read_one_file(f, fmt, columns, schema))
                with ThreadPoolExecutor(max_workers=min(8, len(files))) as ex:
                    tables = list(ex.map(read, files))
            table = pa.concat_tables(tables, promote_options="default") if len(tables) > 1 else tables[0]
    if schema is not None and columns is not None:
        schema = schema.select(columns)
    with obs_trace.span("device.stage", files=len(files), zero_copy=zero_copy_ok):
        return ColumnTable.from_arrow(table, schema, zero_copy_ok=zero_copy_ok)


def _read_footer(path: str) -> "pq.FileMetaData":
    fault_point("footer.read", path)
    return pq.ParquetFile(path).metadata


# -- footer cache -------------------------------------------------------------
# Every size estimate, chunk plan, spill batch, and stats lookup used to
# re-open footers already parsed moments earlier (the build opened each
# source footer up to three times). One mtime-validated map dedupes them;
# the prefetcher warms it so the executor's footer reads are hits.
_FOOTER_CACHE_MAX = 4096
_footer_cache: "dict[str, tuple[int, pq.FileMetaData]]" = {}
_footer_lock = threading.Lock()


def clear_footer_cache() -> None:
    with _footer_lock:
        _footer_cache.clear()


def read_footers(files: list[str]) -> dict[str, "pq.FileMetaData"]:
    """One footer parse per file, reused by the size estimate, the chunk
    planner, the spill batcher, and the query-tail prefetcher (footers
    can be remote round-trips — hence the transient-IO retry and the
    mtime-validated cache; `io.footer_cache.*` counts the dedup)."""
    import os

    from concurrent.futures import ThreadPoolExecutor

    if not files:
        return {}
    out: dict[str, "pq.FileMetaData"] = {}
    todo: list[tuple[str, int | None]] = []
    for f in files:
        try:
            mt = os.stat(f).st_mtime_ns
        except OSError:
            mt = None
        hit = None
        if mt is not None:
            with _footer_lock:
                cached = _footer_cache.get(f)
            if cached is not None and cached[0] == mt:
                hit = cached[1]
        if hit is not None:
            out[f] = hit
        else:
            todo.append((f, mt))
    if len(out):
        stats.increment("io.footer_cache.hits", len(out))
    if not todo:
        return {f: out[f] for f in files}
    stats.increment("io.footer_cache.misses", len(todo))
    if len(todo) == 1:
        mds = [retry.retry_call(_read_footer, todo[0][0])]
    else:
        with obs_trace.span("io.footers", files=len(todo)):
            read = obs_trace.wrap(lambda f: retry.retry_call(_read_footer, f))
            with ThreadPoolExecutor(max_workers=min(8, len(todo))) as ex:
                mds = list(ex.map(read, (f for f, _ in todo)))
    with _footer_lock:
        for (f, mt), md in zip(todo, mds):
            out[f] = md
            if mt is not None:
                _footer_cache[f] = (mt, md)
        while len(_footer_cache) > _FOOTER_CACHE_MAX:
            _footer_cache.pop(next(iter(_footer_cache)))
    return {f: out[f] for f in files}


def _row_group_bytes(md, rg: int, want: set | None) -> int:
    g = md.row_group(rg)
    total = 0
    for ci in range(g.num_columns):
        col = g.column(ci)
        name = col.path_in_schema.split(".")[0]
        if want is None or name.lower() in want:
            total += col.total_uncompressed_size
    return total


def estimate_uncompressed_bytes(
    files: list[str], columns: list[str] | None = None, footers=None
) -> int:
    """Uncompressed in-memory size estimate from parquet footers (no data
    read) — drives the in-memory vs streaming build decision."""
    footers = footers if footers is not None else read_footers(files)
    want = {c.lower() for c in columns} if columns is not None else None
    return sum(
        _row_group_bytes(md, rg, want)
        for f, md in footers.items()
        for rg in range(md.num_row_groups)
    )


def plan_row_group_chunks(
    files: list[str], chunk_bytes: int, columns: list[str] | None = None, footers=None
) -> list[list[tuple[str, int]]]:
    """Split (file, row-group) units into chunks of ≤ chunk_bytes
    uncompressed (each chunk holds at least one row group). The streaming
    build's host-memory unit."""
    footers = footers if footers is not None else read_footers(files)
    want = {c.lower() for c in columns} if columns is not None else None
    chunks: list[list[tuple[str, int]]] = []
    cur: list[tuple[str, int]] = []
    cur_bytes = 0
    for f in files:
        md = footers[f]
        for rg in range(md.num_row_groups):
            sz = _row_group_bytes(md, rg, want)
            if cur and cur_bytes + sz > chunk_bytes:
                chunks.append(cur)
                cur, cur_bytes = [], 0
            cur.append((f, rg))
            cur_bytes += sz
    if cur:
        chunks.append(cur)
    return chunks


def _read_chunk_file(f: str, rgs: list[int], columns: list[str] | None):
    fault_point("bucket.read", f)
    pf = pq.ParquetFile(f)
    if columns is not None:
        # Tolerate per-file schema skew: a column absent from THIS file is
        # skipped here and null-filled by the caller's promoting concat —
        # the same union semantics read_table_files gets from
        # concat_tables, and what lets the prefetcher probe any file.
        names = set(pf.schema_arrow.names)
        columns = [c for c in columns if c in names]
    return pf.read_row_groups(rgs, columns=columns)


def read_chunk(chunk: list[tuple[str, int]], columns: list[str] | None = None):
    """Decode one planned chunk to a pyarrow Table (transient-IO retried
    per file; columns missing from a file are null-filled)."""
    by_file: dict[str, list[int]] = {}
    for f, rg in chunk:
        by_file.setdefault(f, []).append(rg)
    parts = [
        retry.retry_call(_read_chunk_file, f, rgs, columns)
        for f, rgs in by_file.items()
    ]
    return pa.concat_tables(parts, promote_options="default") if len(parts) > 1 else parts[0]


def bucket_file_name(bucket: int) -> str:
    return f"bucket-{bucket:05d}.parquet"


def bucket_of_file_name(name: str) -> int | None:
    """Inverse of bucket_file_name (None for non-bucket files)."""
    if name.startswith("bucket-") and name.endswith(".parquet"):
        try:
            return int(name[len("bucket-") : -len(".parquet")])
        except ValueError:
            return None
    return None


def _json_scalar(v):
    """numpy scalar → plain JSON-serializable Python value."""
    return v.item() if hasattr(v, "item") else v


def bucket_key_stats(table: ColumnTable, key: str, sel: np.ndarray | None = None):
    """JSON-serializable [min, max] of `table[key]` over rows `sel` (all
    rows when None), ignoring nulls; None for empty/all-null/vector. The
    analog of parquet column-chunk statistics the reference gets from
    FileSourceScanExec min/max pruning (SURVEY.md §2.2) — persisted in the
    index manifest so range predicates can skip whole bucket files."""
    try:
        f = table.schema.field(key)
    except Exception:
        return None
    if f.is_vector:
        return None
    vals = table.columns[f.name]
    valid = table.valid_mask(f.name)
    if sel is not None:
        vals = vals[sel]
        valid = valid[sel] if valid is not None else None
    if valid is not None:
        vals = vals[valid]
    if len(vals) == 0:
        return None
    if f.name in table.dictionaries:
        # np.min has no ufunc loop for unicode; reduce over the (small)
        # set of used dictionary values in Python instead.
        used = np.asarray(table.dictionaries[f.name])[np.unique(vals)].tolist()
        return [min(used), max(used)]
    return [_json_scalar(vals.min()), _json_scalar(vals.max())]


def bucket_column_stats(
    table: ColumnTable, columns: list[str], sel: np.ndarray | None = None
) -> dict:
    """Per-column [min, max] stats over rows `sel` for every named scalar
    column — the included-column analog of bucket_key_stats (Spark's
    parquet reader gives the reference min/max on EVERY column; the
    manifest carries ours so non-leading predicates prune files too)."""
    out = {}
    for c in columns:
        s = bucket_key_stats(table, c, sel)
        out[c] = s
    return out


# Parquet codec for INDEX bucket files (read only by this engine; the
# source data keeps whatever codec it arrived with). lz4 encodes ~2x
# faster than the parquet default (snappy is close, zstd far slower) on
# the single-core hosts where encode IS the build's carve phase, and
# decodes at least as fast. Overridable per call for experiments.
INDEX_WRITE_COMPRESSION = "lz4"


def write_bucket(
    dest_dir: Path, bucket: int, table: ColumnTable, compression: str | None = None
) -> None:
    dest_dir.mkdir(parents=True, exist_ok=True)
    dest = dest_dir / bucket_file_name(bucket)
    fault_point("bucket.write", dest)
    # Dictionary-encode ONLY string columns: for numeric index data,
    # parquet dictionary encoding costs ~6x encode time AND grows the
    # files (high-cardinality keys, float payloads); for low-cardinality
    # strings it still wins.
    dict_cols = [f.name for f in table.schema.fields if f.is_string]
    pq.write_table(
        table.to_arrow(),
        dest,
        use_dictionary=dict_cols,
        compression=compression or INDEX_WRITE_COMPRESSION,
        # Pruning reads the MANIFEST's key/column stats (computed over the
        # gathered bucket in carve_and_write), never parquet footer
        # statistics — skipping them is ~2x on the encode of numeric
        # buckets.
        write_statistics=False,
    )
    fault_point("bucket.written", dest)


def write_manifest(
    dest_dir: Path,
    num_buckets: int,
    indexed_columns: list[str],
    bucket_rows: list[int],
    key_stats: list | None = None,
    column_stats: list | None = None,
) -> None:
    dest_dir.mkdir(parents=True, exist_ok=True)
    manifest = {
        "numBuckets": num_buckets,
        "indexedColumns": indexed_columns,
        "bucketRows": bucket_rows,
    }
    if key_stats is not None:
        # Per-bucket [min, max] of the first indexed column (None when the
        # bucket is empty or all-null) — enables file-level range pruning.
        manifest["keyStats"] = key_stats
    if column_stats is not None:
        # Per-bucket {column: [min, max] | None} for the remaining scalar
        # columns — file pruning on included-column predicates.
        manifest["columnStats"] = column_stats
    mp = dest_dir / MANIFEST_NAME
    fault_point("manifest.write", mp)
    # Atomic temp-file + os.replace (+ fsync) via write_json: a crash
    # mid-write leaves either the previous manifest or none — never a
    # torn `_index_manifest.json` that poisons every later read.
    write_json(mp, manifest)
    fault_point("manifest.written", mp)


def read_manifest(version_dir: Path) -> dict | None:
    """Version dir's manifest, or None when absent (pre-stats builds —
    planning degrades to footer counts). Garbage raises a typed
    IndexCorruptionError so callers can distinguish "no manifest" from
    "index data is damaged" and degrade/fall back deliberately."""
    p = Path(version_dir) / MANIFEST_NAME
    if not p.exists():
        return None
    fault_point("manifest.read", p)
    try:
        return json.loads(p.read_text())
    except (OSError, ValueError) as e:
        stats.increment("index.corruption")
        raise IndexCorruptionError(
            f"corrupt index manifest {p}: {e}",
            index_root=str(Path(version_dir).parent),
            path=str(p),
        ) from e


_manifest_cache: "dict[str, tuple[int, dict | None]]" = {}
_manifest_lock = threading.Lock()


def read_manifest_cached(version_dir: Path) -> dict | None:
    """read_manifest through an mtime-validated cache (manifests are
    immutable per version, but refresh can rewrite a dir's manifest)."""
    import os

    mp = Path(version_dir) / MANIFEST_NAME
    try:
        mt = os.stat(mp).st_mtime_ns
    except OSError:
        return None
    with _manifest_lock:
        cached = _manifest_cache.get(str(mp))
    if cached is not None and cached[0] == mt:
        return cached[1]
    m = read_manifest(version_dir)
    with _manifest_lock:
        _manifest_cache[str(mp)] = (mt, m)
    return m


def file_key_stats(files: list[str]) -> dict[str, list | None]:
    """Per-file [min, max] of the leading indexed column, looked up in each
    file's version-dir manifest (cached, mtime-validated). Files whose dir
    has no manifest or whose manifest has no keyStats are absent from the
    result; a present-but-None value means the bucket is empty/all-null."""
    out: dict[str, list | None] = {}
    by_dir: dict[Path, list[str]] = {}
    for f in files:
        by_dir.setdefault(Path(f).parent, []).append(f)
    for d, fs in by_dir.items():
        m = read_manifest_cached(d)
        if not m or "keyStats" not in m:
            continue
        ks = m["keyStats"]
        for f in fs:
            b = bucket_of_file_name(Path(f).name)
            if b is not None and b < len(ks):
                out[f] = ks[b]
    return out


def file_column_stats(files: list[str], column: str) -> dict[str, list | None]:
    """Per-file [min, max] of a NON-leading column from the manifests'
    columnStats (case-insensitive name match). Same present/None contract
    as file_key_stats."""
    out: dict[str, list | None] = {}
    by_dir: dict[Path, list[str]] = {}
    low = column.lower()
    for f in files:
        by_dir.setdefault(Path(f).parent, []).append(f)
    for d, fs in by_dir.items():
        m = read_manifest_cached(d)
        cs = (m or {}).get("columnStats")
        if not cs:
            continue
        for f in fs:
            b = bucket_of_file_name(Path(f).name)
            if b is None or b >= len(cs) or cs[b] is None:
                continue
            for name, s in cs[b].items():
                if name.lower() == low:
                    out[f] = s
                    break
    return out


def carve_and_write(
    dest: Path,
    table: "ColumnTable",
    sorted_partition: "np.ndarray",
    num_partitions: int,
    indexed_columns: list[str],
    order: "np.ndarray | None" = None,
    sort_fn=None,
) -> list[int]:
    """Carve `table` into one parquet file per partition + manifest.

    `sorted_partition` is the non-decreasing partition id per carved row;
    `order` (optional) maps carved row i to `table` row order[i] (identity
    when the table is already in carved order). `sort_fn(p, sel)` (optional)
    finalizes partition p's selection inside its write task — the host
    build venue passes the per-bucket native key sort here so sorting
    PIPELINES with the parquet encode of other buckets. Encode and sort
    both release the GIL, so buckets run concurrently. Returns
    per-partition row counts (also persisted in the manifest)."""
    from concurrent.futures import ThreadPoolExecutor

    dest = Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    starts = np.searchsorted(sorted_partition, np.arange(num_partitions + 1))
    rows = [int(starts[p + 1] - starts[p]) for p in range(num_partitions)]
    key_stats: list = [None] * num_partitions

    col_stats: list = [None] * num_partitions
    other_cols = [
        f.name
        for f in table.schema.fields
        if not f.is_vector and (not indexed_columns or f.name != table.schema.field(indexed_columns[0]).name)
    ]

    def write_one(p: int) -> None:
        lo, hi = int(starts[p]), int(starts[p + 1])
        sel = np.arange(lo, hi) if order is None else order[lo:hi]
        if sort_fn is not None:
            sel = sort_fn(p, sel)
        # Gather ONCE; stats read the gathered bucket (a second full
        # per-column fancy-index here measurably slows the carve phase).
        sub = table.take(sel)
        if indexed_columns:
            key_stats[p] = bucket_key_stats(sub, indexed_columns[0])
        if other_cols:
            col_stats[p] = bucket_column_stats(sub, other_cols)
        write_bucket(dest, p, sub)

    with obs_trace.span("io.carve", partitions=num_partitions):
        with ThreadPoolExecutor(max_workers=min(16, max(1, num_partitions))) as ex:
            list(ex.map(obs_trace.wrap(write_one), range(num_partitions)))
    has_stats = any(s is not None for s in key_stats)
    write_manifest(
        dest, num_partitions, indexed_columns, rows,
        key_stats if has_stats else None,
        col_stats if any(s is not None for s in col_stats) else None,
    )
    return rows
