"""Async index bucket-file prefetch — the query tail's cold-read killer.

PROFILE_Q43/Q67/Q88 attribute the TPC-DS slice's ~1x tail to host-side
marshalling: scan-bound queries pay serial cold reads of bucket files
AFTER the optimizer already knows which files survive pruning. This
module moves that IO off the critical path: while `plan.optimize` is
still running (run_query issues the prefetch as soon as the optimized
plan exists), the files the pruner keeps get their parquet FOOTERS
parsed into io's footer cache and their FIRST row-group chunk decoded
on a background pool — so by the time the executor reaches the scan,
footers are cache hits and the data read starts against a warm page
cache.

Strictly advisory: prefetch failures are counted
(`io.prefetch.errors`), never surfaced — a query can at worst miss the
warm-up. Gated by ``hyperspace.scan.prefetch.enabled``.
"""

from __future__ import annotations

import threading
from pathlib import Path

import pyarrow as pa

from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.exec_scan import point_prune_names, scan_files
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.obs import metrics as obs_metrics
from hyperspace_tpu.plan.nodes import Filter, Scan, Union

_MET_ISSUED = obs_metrics.counter("io.prefetch.issued", "prefetch jobs submitted")
_MET_ERRORS = obs_metrics.counter("io.prefetch.errors", "prefetch jobs that failed (advisory)")

# Per-query caps: a miss costs one cold read (what happens today), an
# over-eager prefetch evicts useful page cache — bound the blast radius.
_MAX_DATA_FILES = 16
_MAX_FOOTER_FILES = 256
# Decode at most this much of each file's first chunk.
_FIRST_CHUNK_BYTES = 8 << 20

# All module state below is guarded by _lock (HSL008/HSL013).
_lock = threading.Lock()
_pool = None
_pending: list = []
_issued: dict[str, int] = {}  # path -> mtime_ns of the last issued job
_ISSUED_MAX = 4096


def _get_pool():
    global _pool
    with _lock:
        if _pool is None:
            from concurrent.futures import ThreadPoolExecutor

            _pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="hs-prefetch")
        return _pool


def _job(path: str, columns: tuple[str, ...] | None, data: bool) -> None:
    """One prefetch unit: footer into the cache, optionally the first
    planned chunk (decode result discarded — the win is the warm footer
    cache + page cache). Failures are advisory by contract: the very
    same read will re-run (with retry and typed corruption handling) on
    the query path moments later, so swallowing the typed IO error here
    loses nothing."""
    try:
        footers = hio.read_footers([path])
        if data and footers:
            units = hio.plan_row_group_chunks(
                [path], _FIRST_CHUNK_BYTES, list(columns) if columns else None,
                footers=footers,
            )
            if units:
                hio.read_chunk(units[0], list(columns) if columns else None)
    except (OSError, pa.ArrowException):
        _MET_ERRORS.inc()


def _index_scans(plan) -> list[tuple[Scan, object]]:
    """(scan, predicate-or-None) pairs for every bucketed parquet scan in
    the plan, with the nearest enclosing Filter's predicate attached
    (that is what the executor's pruner will see)."""
    out: list[tuple[Scan, object]] = []

    def walk(node, pred):
        if isinstance(node, Scan):
            if node.bucket_spec is not None and node.format == "parquet":
                out.append((node, pred))
            return
        if isinstance(node, Filter):
            walk(node.child, node.predicate)
            return
        if isinstance(node, Union):
            for inp in node.inputs:
                walk(inp, pred)
            return
        for child in node.children():
            walk(child, None)

    walk(plan, None)
    return out


def prefetch_plan(plan) -> int:
    """Issue async footer + first-chunk prefetch for the index files the
    pruner will keep. Returns the number of jobs submitted (0 when the
    plan has no bucketed scans, or everything was recently issued)."""
    jobs: list[tuple[str, tuple[str, ...] | None, bool]] = []
    for scan, pred in _index_scans(plan):
        try:
            files = scan_files(scan)
        except OSError:
            continue
        names = point_prune_names(scan, pred) if pred is not None else None
        if names is not None:
            files = [f for f in files if Path(f).name in names]
        cols = tuple(scan.scan_schema.names) if scan.scan_schema is not None else None
        # Footers for everything the scan may touch (cheap, cached);
        # first-chunk decode only for a bounded set of survivors.
        for i, f in enumerate(files[:_MAX_FOOTER_FILES]):
            jobs.append((f, cols, i < _MAX_DATA_FILES))
    if not jobs:
        return 0
    import os

    submitted = 0
    pool = _get_pool()
    with _lock:
        for path, cols, data in jobs:
            try:
                # The fault point fires in the SUBMITTING thread (so it
                # is deterministic and statically reachable from the
                # run_query contract); an injected transient fault skips
                # this file's job — the advisory contract: the query
                # path re-reads with full retry/typed handling anyway.
                fault_point("prefetch.issue", path)
                mt = os.stat(path).st_mtime_ns
            except OSError:
                _MET_ERRORS.inc()
                continue
            if _issued.get(path) == mt:
                continue  # unchanged since the last issue: already warm
            _issued[path] = mt
            while len(_issued) > _ISSUED_MAX:
                _issued.pop(next(iter(_issued)))
            _pending.append(pool.submit(_job, path, cols, data))
            submitted += 1
        # Reap finished futures so _pending stays bounded.
        _pending[:] = [f for f in _pending if not f.done()]
    if submitted:
        _MET_ISSUED.inc(submitted)
    return submitted


def drain() -> None:
    """Block until every outstanding prefetch job finished (test hook —
    jobs swallow their own errors, so this never raises)."""
    with _lock:
        pending = list(_pending)
        _pending.clear()
    for f in pending:
        f.result()


def reset() -> None:
    """Forget issue history (test isolation; the pool survives)."""
    drain()
    with _lock:
        _issued.clear()
