"""Fused Aggregate(Join): the run-prefix device kernel and the host
merge+accumulate venue that never materialize the joined pairs
(Executor mixin)."""

from __future__ import annotations


import numpy as np

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.plan.nodes import Aggregate, Join, Project

from hyperspace_tpu.execution.exec_common import (
    _RunExtremum,
    _agg_channels_cached,
    _bucket_sorted_codes,
    _factorize_keys_cached,
    _group_ids_cached,
    _pad_bucket_major_cached,
    _stack_cached,
)


class FusedJoinAggMixin:
    def _try_fused_join_aggregate(self, plan: Aggregate) -> ColumnTable | None:
        """Aggregate(Join) without materializing the joined pairs
        (ops/join_agg.py). Applies when every aggregate is
        sum/count/mean/min/max over a single side's numeric expression
        and the grouping columns (if any) come from one side; cross-side
        expressions fall back to the materialized join. min/max run as
        run-extremum channels on BOTH venues (all equal-key secondary
        rows are one contiguous run of the sorted side, and extrema are
        multiplicity-independent): the host C++ pass walks runs directly;
        the device kernel takes the segmented-prefix-scan value at each
        run end and folds groups with segment_min/max."""
        from hyperspace_tpu.ops.aggregate import agg_input, finalize_agg_values, group_ids

        child = plan.child
        if isinstance(child, Project):
            child = child.child
        if not isinstance(child, Join) or child.how != "inner" or child.condition is not None:
            return None
        join = child
        lnames = {n.lower() for n in join.left.schema.names}
        rnames = {n.lower() for n in join.right.schema.names}

        def side_of(cols) -> str | None:
            cl = {c.lower() for c in cols}
            if cl and cl <= lnames:
                return "left"
            if cl and cl <= rnames:
                return "right"
            return None

        gside = None
        if plan.group_by:
            gside = side_of(plan.group_by)
            if gside is None:
                return None
        from hyperspace_tpu.plan.expr import Case

        spec_sides: list[str | None] = []
        for a in plan.aggs:
            if a.fn not in ("sum", "count", "mean", "min", "max"):
                return None
            if a.expr is None:
                spec_sides.append(None)  # count(*)
                continue
            refs = a.references()
            # Constant expressions (sum(lit(2))) and cross-side expressions
            # have no single owning side — use the materialized join.
            s = side_of(refs)
            if s is None:
                return None
            sch = join.left.schema if s == "left" else join.right.schema
            if any(sch.field(r).is_vector for r in refs):
                return None
            # Case conditions handle strings via the predicate machinery;
            # any other string reference cannot feed a numeric channel.
            if not isinstance(a.expr, Case) and any(sch.field(r).is_string for r in refs):
                return None
            spec_sides.append(s)
        primary = gside or "left"

        lside, rside, _, _ = self._join_sides(join)
        data = {"left": lside, "right": rside}
        self.stats["agg_path"] = "fused-join-agg"
        self.stats["num_buckets"] = len(data["left"].offsets) - 1

        lkeys = [data["left"].table.schema.field(c).name for c in join.left_on]
        rkeys = [data["right"].table.schema.field(c).name for c in join.right_on]
        lc0, rc0 = _factorize_keys_cached(
            data["left"].table, data["right"].table, lkeys, rkeys,
            null_safe=join.null_safe,
        )
        codes = {}
        perms = {}
        regroup_venue = self._venue(
            "sort_venue", "hyperspace.sort.venue", False, needs_native=False
        )
        codes["left"], perms["left"] = _bucket_sorted_codes(lc0, data["left"], venue=regroup_venue)
        codes["right"], perms["right"] = _bucket_sorted_codes(rc0, data["right"], venue=regroup_venue)
        secondary = "right" if primary == "left" else "left"

        # Group ids on the primary table (original row order; memoized
        # for stable index-backed sides).
        gid_orig, k, first_idx = _group_ids_cached(data[primary].table, plan.group_by)
        if k == 0:  # empty primary side
            if plan.group_by:
                return ColumnTable.empty(plan.schema)
            k, gid_orig, first_idx = 1, np.zeros(0, np.int64), np.zeros(0, np.int64)

        def spec_input(side: str, spec):
            """(masked values, indicator) per original row of `side` with
            the plain aggregate path's null semantics (ops/aggregate);
            memoized per (expression, input identity) for stable sides."""
            return _agg_channels_cached(data[side].table, spec)

        host_res = None
        if (
            self._join_venue() == "host"
            and codes[primary].dtype == np.int32
            and codes[secondary].dtype == np.int32
        ):
            host_res = self._host_fused_channels(
                plan, data, codes, perms, primary, secondary, spec_sides,
                gid_orig, k, spec_input,
            )
        if host_res is not None:
            self.stats["join_kernel"] = "host-native-merge-accumulate"
            out, spec_layout = host_res
        else:
            self.stats["join_kernel"] = "device-run-prefix"
            out, spec_layout = self._device_fused_channels(
                plan, data, codes, perms, primary, secondary, spec_sides,
                gid_orig, k, spec_input, fused=self._fused_kernels(),
            )
        star = out[0]

        keep = star > 0 if plan.group_by else np.ones(k, bool)
        out_schema = plan.schema
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        ptable = data[primary].table
        # first_idx may be empty when the primary side has no rows but a
        # global (no group_by) aggregate still emits its one k=1 row.
        kept_first = first_idx[keep[: len(first_idx)]]
        for c in plan.group_by:
            f = ptable.schema.field(c)
            out_f = out_schema.field(c)
            cols[out_f.name] = ptable.columns[f.name][kept_first]
            if f.name in ptable.dictionaries:
                dicts[out_f.name] = ptable.dictionaries[f.name]
            gv = ptable.valid_mask(c)
            if gv is not None:
                validity[out_f.name] = gv[kept_first]
        for spec, (vi, ci) in zip(plan.aggs, spec_layout):
            out_f = out_schema.field(spec.alias)
            cnt = out[ci][keep]
            if spec.fn == "count":
                cols[out_f.name] = cnt.astype(np.int64)
                continue
            val = out[vi][keep]
            if spec.fn == "mean":
                with np.errstate(invalid="ignore", divide="ignore"):
                    val = val / cnt
            empty = cnt == 0
            cols[out_f.name] = finalize_agg_values(val, empty, out_f.device_dtype)
            if empty.any():
                validity[out_f.name] = ~empty
        return ColumnTable(out_schema, cols, dicts, validity)

    def _device_fused_channels(
        self, plan, data, codes, perms, primary, secondary, spec_sides, gid_orig, k,
        spec_input, fused: str = "off",
    ):
        """Device venue: the run-prefix kernel over bucket-major padded
        channels (ops/join_agg.py). Pads, the channel stacks, and the
        uploads all route through the identity caches, so repeat queries
        over a stable index version serve from HBM. With `fused` = auto
        the pad widths round up to the 128-lane tile so the Pallas
        run-bounds kernel can engage (extra pads are sentinels/dead
        rows — results are unchanged by construction)."""
        from hyperspace_tpu.execution import device_cache as dcache
        from hyperspace_tpu.ops.join_agg import fused_join_aggregate

        def width_of(offsets) -> int | None:
            if fused != "auto":
                return None  # natural Lmax width
            counts = np.diff(offsets)
            lm = max(int(counts.max()) if counts.size else 1, 1)
            return ((lm + 127) // 128) * 128

        pk = _pad_bucket_major_cached(
            codes[primary], data[primary].offsets, width=width_of(data[primary].offsets)
        )
        sk = _pad_bucket_major_cached(
            codes[secondary], data[secondary].offsets, width=width_of(data[secondary].offsets)
        )
        b, lp = pk.shape
        ls = sk.shape[1]

        def pad_rows(side: str, vals: np.ndarray, fill=0.0) -> np.ndarray:
            """Per-orig-row values of `side` → bucket-sorted padded [B, L]."""
            v = np.asarray(vals, np.float64)
            if perms[side] is not None:
                v = v[perms[side]]
            width = lp if side == primary else ls
            return _pad_bucket_major_cached(v, data[side].offsets, fill=fill, width=width)

        # pad_rows reorders by perm internally — pass the ORIGINAL-order gid;
        # pads carry group id k (the dead segment).
        def build_gid():
            return pad_rows(primary, gid_orig, fill=float(k)).astype(np.int32)

        if dcache.is_stable(gid_orig) and perms[primary] is None:
            # Cacheable only when NO per-join permutation applies: the
            # perm depends on the join keys, which this key does not
            # carry — a different-keyed join sharing gid_orig must not
            # reuse the other layout's pad.
            gid_pad = dcache.derived(
                ("gidpad", id(gid_orig), data[primary].offsets.tobytes(), k, lp),
                (gid_orig,),
                build_gid,
            )
        else:
            gid_pad = build_gid()

        channels: list[tuple] = [("star",)]
        p_arrays: list[np.ndarray] = []
        s_arrays: list[np.ndarray] = []

        def add_channel(side: str, padded: np.ndarray, fn: str | None = None) -> int:
            base = "p" if side == primary else "s"
            kind = base + fn if fn in ("min", "max") else base
            if side == primary:
                p_arrays.append(padded)
                channels.append((kind, len(p_arrays) - 1))
            else:
                s_arrays.append(padded)
                channels.append((kind, len(s_arrays) - 1))
            return len(channels) - 1

        def mm_values(vals: np.ndarray, ind: np.ndarray, fn: str) -> np.ndarray:
            """Extremum channel input: nulls (and later pads) carry the
            ±inf identity instead of the sum channels' zero. Identity-
            cached so the derived pad/upload caches stay warm for stable
            sides."""
            ident = np.inf if fn == "min" else -np.inf

            def build():
                out = np.where(ind > 0, vals, ident)
                dcache.freeze(out)
                return out

            if dcache.is_stable(vals) and dcache.is_stable(ind):
                return dcache.derived(
                    ("mmvals", id(vals), id(ind), fn), (vals, ind), build
                )
            return np.where(ind > 0, vals, ident)

        spec_layout: list[tuple[int | None, int]] = []  # (value ch, count ch; 0=star)
        for spec, s in zip(plan.aggs, spec_sides):
            if s is None:  # count(*)
                spec_layout.append((None, 0))
                continue
            vals, ind = spec_input(s, spec)
            vi = None
            if spec.fn in ("sum", "mean"):
                vi = add_channel(s, pad_rows(s, vals))
            elif spec.fn in ("min", "max"):
                ident = np.inf if spec.fn == "min" else -np.inf
                vi = add_channel(
                    s, pad_rows(s, mm_values(vals, ind, spec.fn), fill=ident), spec.fn
                )
            ci = add_channel(s, pad_rows(s, ind))
            spec_layout.append((vi, ci))

        pvals = _stack_cached(p_arrays, (0, b, lp))
        svals = _stack_cached(s_arrays, (0, b, ls))
        out = fused_join_aggregate(
            pk, sk, pvals, svals, gid_pad, k, tuple(channels), fused=fused
        )
        return out, spec_layout

    def _host_fused_channels(
        self, plan, data, codes, perms, primary, secondary, spec_sides, gid_orig, k, spec_input
    ):
        """Host venue: one C++ merge+accumulate pass computes per-primary-
        row channel sums and match counts (no pair materialization), then
        per-group bincounts produce the same [K] channel layout the device
        kernel emits. Returns None when the native library is missing."""
        from hyperspace_tpu import native

        if not native.available():
            return None
        tbl_s = data[secondary].table
        sec_arrays: list[np.ndarray] = []  # SORTED secondary order
        parts: list[tuple] = []

        def sec_sorted(a: np.ndarray) -> np.ndarray:
            return a[perms[secondary]] if perms[secondary] is not None else a

        for spec, s in zip(plan.aggs, spec_sides):
            if s is None:
                parts.append(("star",))
                continue
            vals, ind = spec_input(s, spec)
            if spec.fn in ("min", "max"):
                # Extremum channels bypass the sum accumulator: per-KEY
                # run extrema (secondary) / matched-row extrema (primary).
                parts.append(("mm", spec.fn, s, vals, ind))
            elif s == secondary:
                vi = None
                if spec.fn in ("sum", "mean"):
                    sec_arrays.append(sec_sorted(vals))
                    vi = len(sec_arrays) - 1
                sec_arrays.append(sec_sorted(ind))
                parts.append(("sec", vi, len(sec_arrays) - 1))
            else:
                parts.append(("pri", vals if spec.fn in ("sum", "mean") else None, ind))

        rvals = _stack_cached(sec_arrays, (0, tbl_s.num_rows))
        res = native.merge_join_accumulate(
            codes[primary], data[primary].offsets,
            codes[secondary], data[secondary].offsets, rvals,
        )
        if res is None:
            return None
        acc_sorted, match_sorted = res
        n_l = data[primary].table.num_rows
        pperm = perms[primary]
        if pperm is not None:
            matches = np.empty(n_l)
            matches[pperm] = match_sorted
            acc = np.empty_like(acc_sorted)
            acc[:, pperm] = acc_sorted
        else:
            matches, acc = match_sorted, acc_sorted

        def greduce(w: np.ndarray) -> np.ndarray:
            if n_l == 0:
                return np.zeros(k)
            return np.bincount(gid_orig, weights=w, minlength=k)

        mm_rows = None
        if any(p[0] == "mm" for p in parts):
            mm_rows = _RunExtremum(
                codes[primary], data[primary].offsets, pperm,
                codes[secondary], data[secondary].offsets, perms[secondary],
                matches, n_l,
            )

        out: list[np.ndarray] = [greduce(matches)]  # star = pairs per group
        spec_layout: list[tuple[int | None, int]] = []
        for part in parts:
            if part[0] == "star":
                spec_layout.append((None, 0))
            elif part[0] == "sec":
                _, vi, ci = part
                v_idx = None
                if vi is not None:
                    out.append(greduce(acc[vi]))
                    v_idx = len(out) - 1
                out.append(greduce(acc[ci]))
                spec_layout.append((v_idx, len(out) - 1))
            elif part[0] == "mm":
                from hyperspace_tpu.ops.aggregate import aggregate_arrays_host

                _, fn, s, vals, ind = part
                row_ext, row_valid = mm_rows.per_primary_row(fn, s, secondary, vals, ind)
                res, cnt = aggregate_arrays_host([(row_ext, row_valid, fn)], gid_orig, k)
                out.append(res[0])
                out.append(cnt[0])
                spec_layout.append((len(out) - 2, len(out) - 1))
            else:
                _, vals, ind = part
                v_idx = None
                if vals is not None:
                    out.append(greduce(vals * matches))
                    v_idx = len(out) - 1
                out.append(greduce(ind * matches))
                spec_layout.append((v_idx, len(out) - 1))
        return out, spec_layout

