"""Join side preparation: aligned-side detection, bucket data, the
re-bucketing exchange, and dynamic partition pruning (Executor mixin)."""

from __future__ import annotations

from pathlib import Path

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.build_exchange import compute_row_hashes
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.plan.expr import And
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan, Union

from hyperspace_tpu.execution.exec_common import (
    AlignedSide,
    SideData,
    _concat_side_cached,
    _filter_side,
    _hash_fields_compatible,
    _stable_table_refs,
)


class JoinSidesMixin:
    @staticmethod
    def _bucket_hash_dtypes(scan: Scan) -> tuple[str, ...]:
        """The hash domain of a scan's bucket columns. The canonical row
        hash is dtype-sensitive (an int64 mixes two words; an int32 one),
        so two bucketings agree on equal key VALUES only when the bucket
        column dtypes agree."""
        out = []
        for c in scan.bucket_spec[1]:
            f = scan.scan_schema.field(c)
            out.append("string" if f.is_string else str(np.dtype(f.device_dtype)))
        return tuple(out)

    def _keyed_on_buckets(self, side: AlignedSide | None, join_on: list[str]) -> bool:
        """True iff the side is an index scan bucketed exactly on its
        join keys (the precondition for any bucket-parallel pairing)."""
        return (
            side is not None
            and side.scan.bucket_spec is not None
            and [c.lower() for c in side.scan.bucket_spec[1]]
            == [c.lower() for c in join_on]
        )

    def _join_sides(
        self, plan: Join
    ) -> tuple["SideData", "SideData", AlignedSide | None, AlignedSide | None]:
        """Per-side bucket data for a join — the one place that decides
        between the zero-exchange aligned path (both sides bucketed with
        equal counts on the join keys), the re-bucketing exchange (one
        side bucketed, the other re-bucketized on the fly to match), a
        bucket-preserving reuse of an inner join's output grouping, and
        the single-partition fallback. Returns the AlignedSides
        (None, None) on every non-both-aligned path."""
        left_side = self._aligned_side(plan.left)
        right_side = self._aligned_side(plan.right)
        if (
            self._keyed_on_buckets(left_side, plan.left_on)
            and self._keyed_on_buckets(right_side, plan.right_on)
            and left_side.scan.bucket_spec[0] == right_side.scan.bucket_spec[0]
            # Equal VALUES hash identically only in equal dtype domains.
            and self._bucket_hash_dtypes(left_side.scan)
            == self._bucket_hash_dtypes(right_side.scan)
        ):
            self.stats["join_path"] = "zero-exchange-aligned"
            num_buckets = left_side.scan.bucket_spec[0]
            # Dynamic partition pruning (the analog of Spark 3's DPP,
            # which post-dates the reference's engine): build the
            # predicate-bearing side FIRST, bound its surviving join
            # keys, and skip the other side's bucket files whose
            # manifest key stats cannot overlap — a dimension filtered
            # to one month reads ~1/60th of a date-bucketed fact index.
            producer = None
            if plan.how == "inner":
                if left_side.predicate is not None and right_side.predicate is None:
                    producer = "left"
                elif right_side.predicate is not None and left_side.predicate is None:
                    producer = "right"
                elif left_side.predicate is not None and right_side.predicate is not None:
                    producer = (
                        "left"
                        if self._base_rows(left_side) <= self._base_rows(right_side)
                        else "right"
                    )
            if producer == "left":
                lside = self._side_data(left_side, num_buckets)
                bounds = self._side_key_bounds(lside, left_side)
                rside = self._side_data(right_side, num_buckets, dpp_bounds=bounds)
            elif producer == "right":
                rside = self._side_data(right_side, num_buckets)
                bounds = self._side_key_bounds(rside, right_side)
                lside = self._side_data(left_side, num_buckets, dpp_bounds=bounds)
            else:
                lside = self._side_data(left_side, num_buckets)
                rside = self._side_data(right_side, num_buckets)
            return lside, rside, left_side, right_side
        # One side bucketed on its join keys: the other side can ride a
        # query-time re-bucketing exchange (hash + counting sort on host,
        # device sort on the device venue) so the merge stays
        # bucket-parallel — SURVEY §2.3's "single re-bucketing all-to-all
        # when bucket counts don't match" and the ranker's
        # mismatched-pair case (JoinIndexRanker.scala:31-34).
        mode = self.conf.join_rebucketize if self.conf is not None else "auto"
        lt = rt = None
        l_keyed = self._keyed_on_buckets(left_side, plan.left_on)
        r_keyed = self._keyed_on_buckets(right_side, plan.right_on)
        if mode != "off" and (l_keyed != r_keyed):
            if l_keyed:
                idx_side, other_plan, other_on = left_side, plan.right, plan.right_on
            else:
                idx_side, other_plan, other_on = right_side, plan.left, plan.left_on
            num_buckets = idx_side.scan.bucket_spec[0]
            idx_fields = [
                idx_side.scan.scan_schema.field(c) for c in idx_side.scan.bucket_spec[1]
            ]
            t_other = self._execute(other_plan)
            preserved = self._preserved_sidedata(t_other, other_on)
            if preserved is not None and not (
                len(preserved.offsets) - 1 == num_buckets
                and _hash_fields_compatible(preserved.hash_fields, idx_fields)
            ):
                preserved = None
            engage = (
                preserved is not None  # reuse is free — always take it
                or mode == "force"
                or not self._should_broadcast(t_other.num_rows, self._base_rows(idx_side))
            )
            if engage:
                sd_other = preserved or self._rebucketize_side(
                    t_other, other_on, idx_fields, num_buckets
                )
                if sd_other is not None:
                    # The materialized side doubles as the DPP producer
                    # when dropping unmatched INDEXED-side rows early is
                    # sound for this join type (the indexed side must not
                    # be a preserved outer side).
                    idx_is_right = not l_keyed
                    prune_ok = (
                        plan.how == "inner"
                        or (idx_is_right and plan.how in ("left", "semi", "anti"))
                        or (not idx_is_right and plan.how == "right")
                    )
                    dpp = None
                    if prune_ok:
                        dpp = self._table_key_bounds(t_other, other_on[0])
                    sd_idx = self._side_data(idx_side, num_buckets, dpp_bounds=dpp)
                    self.stats["join_path"] = (
                        "bucket-preserved-aligned" if preserved is not None else "rebucketized-aligned"
                    )
                    self._phys(
                        exchange="preserved" if preserved is not None else "rebucketize",
                        buckets=num_buckets,
                    )
                    if l_keyed:
                        return sd_idx, sd_other, None, None
                    return sd_other, sd_idx, None, None
            if l_keyed:
                rt = t_other
            else:
                lt = t_other
        if mode != "off" and not l_keyed and not r_keyed:
            # Neither side indexed: a child inner join's preserved bucket
            # grouping can still pair — directly against another
            # preserved side, or by re-bucketizing the other side into
            # its domain.
            lt = lt if lt is not None else self._execute(plan.left)
            rt = rt if rt is not None else self._execute(plan.right)
            pl = self._preserved_sidedata(lt, plan.left_on)
            pr = self._preserved_sidedata(rt, plan.right_on)
            if (
                pl is not None
                and pr is not None
                and len(pl.offsets) == len(pr.offsets)
                and _hash_fields_compatible(pl.hash_fields, pr.hash_fields)
            ):
                self.stats["join_path"] = "bucket-preserved-aligned"
                self._phys(exchange="preserved-both", buckets=len(pl.offsets) - 1)
                return pl, pr, None, None
            keyed = pl or pr
            if keyed is not None and (
                mode == "force" or not self._should_broadcast(lt.num_rows, rt.num_rows)
            ):
                if pl is not None:
                    other = self._rebucketize_side(
                        rt, plan.right_on, list(pl.hash_fields), len(pl.offsets) - 1
                    )
                    pair = (pl, other)
                else:
                    other = self._rebucketize_side(
                        lt, plan.left_on, list(pr.hash_fields), len(pr.offsets) - 1
                    )
                    pair = (other, pr)
                if pair[0] is not None and pair[1] is not None:
                    self.stats["join_path"] = "rebucketized-aligned"
                    self._phys(
                        exchange="preserved+rebucketize", buckets=len(keyed.offsets) - 1
                    )
                    return pair[0], pair[1], None, None
        # General path: single partition (bucket count 1). The path stat
        # is set AFTER the children run — a nested join inside them sets
        # its own path and must not leak into this frame's label.
        if lt is None:
            lt = self._execute(plan.left)
        if rt is None:
            rt = self._execute(plan.right)
        self.stats["join_path"] = "single-partition"
        one = lambda t: SideData(t, np.array([0, t.num_rows], dtype=np.int64), False)  # noqa: E731
        return one(lt), one(rt), None, None

    def _aligned_side(self, plan: LogicalPlan) -> AlignedSide | None:
        node, project, predicate = plan, None, None
        # Linear chain the join rule preserves: Project / Filter over the
        # (possibly hybrid) index scan, in any order.
        while isinstance(node, (Project, Filter)):
            if isinstance(node, Project):
                if not node.is_simple:
                    # Computed entries can't be absorbed into the scan
                    # column list; fall back to the general path (which
                    # executes the Project node itself).
                    return None
                if project is None:  # outermost projection defines output
                    project = node.columns
                node = node.child
            else:
                predicate = node.predicate if predicate is None else And(predicate, node.predicate)
                node = node.child
        if isinstance(node, Union):
            # Hybrid scan of ANY width: exactly one bucketed index scan
            # plus unbucketed delta scans (appended files). The rewrite
            # rule emits the two-input shape; refresh chains or manual
            # unions may widen it.
            base = None
            deltas: list[Scan] = []
            for inp in node.inputs:
                if isinstance(inp, Project) and inp.is_simple and isinstance(inp.child, Scan):
                    inp = inp.child
                if not isinstance(inp, Scan):
                    return None
                if inp.bucket_spec is not None:
                    if base is not None:
                        return None  # two index scans: not a hybrid side
                    base = inp
                else:
                    deltas.append(inp)
            if base is None:
                return None
            return AlignedSide(base, project, deltas=tuple(deltas), predicate=predicate)
        if isinstance(node, Scan):
            return AlignedSide(node, project, predicate=predicate)
        return None

    def _base_rows(self, side: AlignedSide) -> int:
        """Total indexed rows from the side's manifest (for picking the
        smaller DPP producer); large sentinel when unknown."""
        from pathlib import Path as _P

        files = self._scan_files(side.scan)
        if files:
            m = hio.read_manifest_cached(_P(files[0]).parent)
            if m and "bucketRows" in m:
                return int(sum(m["bucketRows"]))
        return 1 << 60

    # Set-based DPP only materializes the producer's distinct keys below
    # these sizes (the semi-join/bloom reduction; beyond them the range
    # alone applies).
    _DPP_SET_MAX_ROWS = 4_000_000
    _DPP_SET_MAX_KEYS = 262_144

    def _side_key_bounds(self, sdata: "SideData", side: AlignedSide):
        """DPP producer info of an aligned side (see _table_key_bounds)."""
        return self._table_key_bounds(sdata.table, side.scan.bucket_spec[1][0])

    def _table_key_bounds(self, t: ColumnTable, key: str):
        """(lo, hi, key_set | None) of the surviving join-key values
        (nulls excluded — they never match). lo/hi are value-domain
        (strings decoded via the dictionary); key_set is the SORTED
        distinct int keys when small enough to enumerate — the consumer
        filters its rows by membership (the semi-join reduction half of
        DPP: a 1/70-selective demographics filter cuts the fact side 70x
        BEFORE any pairing). (None, None, None) = empty."""
        f = t.schema.field(key)
        vals = t.columns[f.name]
        valid = t.valid_mask(key)
        if valid is not None:
            vals = vals[valid]
        if len(vals) == 0:
            return (None, None, None)  # empty producer: skip everything
        if f.device_dtype.kind == "f" and bool(np.isnan(vals).any()):
            # NaN keys are real joinable values in the float domain but
            # poison min/max (NaN bounds would slice every finite row
            # away) — disable DPP for this producer entirely.
            return None
        if f.name in t.dictionaries:
            # Decoded-string bounds have no consumer: string keys disable
            # the bucket set, row slicing, and kset reduction alike — a
            # non-None result here would only churn the derived cache
            # with dead no-op cut entries (pinning base refs per distinct
            # producer filter). Report "no DPP" instead.
            return None
        lo, hi = vals.min(), vals.max()
        kset = None
        if (
            f.device_dtype.kind in "iu"
            and len(vals) <= self._DPP_SET_MAX_ROWS
        ):
            u = np.unique(vals)
            if len(u) <= self._DPP_SET_MAX_KEYS:
                kset = u
        return (lo, hi, kset)

    def _rebucketize_side(
        self, table: ColumnTable, key_cols: list[str], idx_fields, num_buckets: int
    ) -> "SideData | None":
        """Query-time re-bucketing exchange: group an arbitrary
        materialized table into the SAME bucket layout an index side
        uses, by recomputing the canonical row hash with each key column
        cast into the index side's dtype domain (equal values then hash
        identically; values unrepresentable on the index side have no
        partner there, so their placement cannot matter). Host venue:
        native counting sort; device venue: one device sort of the
        bucket ids. None when the key shapes cannot share a hash domain
        (string vs non-string)."""
        from hyperspace_tpu.execution.build_exchange import NULL_HASH
        from hyperspace_tpu.ops.hashing import (
            combine_hashes,
            hash_int_column,
            string_dict_hashes,
        )

        hs = []
        for c, fi in zip(key_cols, idx_fields):
            f = table.schema.field(c)
            if f.is_string != fi.is_string:
                return None
            arr = table.columns[f.name]
            if f.is_string:
                dh = string_dict_hashes(table.dictionaries[f.name])
                h = dh[arr] if len(dh) else np.zeros(len(arr), np.uint32)
            else:
                if arr.dtype != fi.device_dtype:
                    arr = arr.astype(fi.device_dtype)
                h = hash_int_column(arr, np)
            valid = table.valid_mask(c)
            if valid is not None:
                h = np.where(valid, h, NULL_HASH)
            hs.append(h)
        bucket = np.asarray(bucket_ids(combine_hashes(hs, np), num_buckets, np), dtype=np.int32)
        venue = self._join_venue()
        kernel = None
        if venue == "device":
            import jax
            import jax.numpy as jnp

            order = np.asarray(jax.device_get(jnp.argsort(jnp.asarray(bucket))))
            counts = np.bincount(bucket, minlength=num_buckets).astype(np.int64)
            kernel = "device-sort-exchange"
        else:
            from hyperspace_tpu import native

            res = native.bucket_perm(bucket, num_buckets)
            if res is not None:
                order, counts = res
                kernel = "host-counting-sort-exchange"
            else:
                order = np.argsort(bucket, kind="stable")
                counts = np.bincount(bucket, minlength=num_buckets).astype(np.int64)
                kernel = "host-argsort-exchange"
        self.stats["exchange_kernel"] = kernel
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        return SideData(table.take(order), offsets, False, hash_fields=tuple(idx_fields))

    def _side_data(
        self, side: AlignedSide, num_buckets: int, dpp_bounds=None
    ) -> "SideData":
        """One concatenated bucket-grouped table per join side (bucket
        files read in parallel through the decoded-table cache), plus
        (hybrid scan) delta rows bucketized on the fly with the same
        canonical row hash the build used. `dpp_bounds` (lo, hi) is the
        other side's surviving key range (dynamic partition pruning): an
        enumerable span skips whole bucket FILES by hashing the span to
        its bucket set, and every surviving sorted bucket slices to the
        one contiguous ROW run inside the bounds."""
        from concurrent.futures import ThreadPoolExecutor

        schema = side.scan.scan_schema
        hf = tuple(schema.field(c) for c in side.scan.bucket_spec[1])
        groups = self._bucket_files_in_order(side.scan, num_buckets)
        if dpp_bounds is not None:
            keep = self._dpp_bucket_set(side, dpp_bounds, num_buckets)
            if keep is not None:
                pruned = sum(len(g) for b, g in enumerate(groups) if b not in keep)
                if pruned:
                    groups = [g if b in keep else [] for b, g in enumerate(groups)]
                    self.stats["files_pruned"] += pruned
                    self._phys(dpp_files_pruned=pruned)
        before = hio.table_cache_stats()
        empty = ColumnTable.empty(schema)
        with ThreadPoolExecutor(max_workers=8) as pool:
            tables = list(
                pool.map(
                    lambda g: hio.read_parquet_cached(g, columns=schema.names, schema=schema)
                    if g
                    else empty,
                    groups,
                )
            )
        if dpp_bounds is not None and dpp_bounds[0] is not None:
            import hashlib

            key_field = schema.field(side.scan.bucket_spec[1][0])
            kset_digest = (
                hashlib.md5(dpp_bounds[2].tobytes()).hexdigest()
                if dpp_bounds[2] is not None
                else None  # one digest per SIDE, not per bucket
            )
            rows_before = sum(t.num_rows for t in tables)
            tables = [
                self._dpp_cut_cached(
                    t, key_field, dpp_bounds, sliceable=len(g) <= 1, kset_digest=kset_digest
                )
                for g, t in zip(groups, tables)
            ]
            cut = rows_before - sum(t.num_rows for t in tables)
            if cut:
                self.stats["rows_pruned"] += cut
                self._phys(dpp_rows_pruned=cut)
        after = hio.table_cache_stats()
        self.stats["files_read"] += after["miss_files"] - before["miss_files"]
        self.stats["bytes_scanned"] += after["miss_bytes"] - before["miss_bytes"]
        counts = np.array([t.num_rows for t in tables], dtype=np.int64)
        base = _concat_side_cached(tables)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        # Empty (fully pruned) groups are trivially sorted.
        sorted_within = all(len(g) <= 1 for g in groups)
        if side.deltas:
            dts = [self._scan(d, columns=list(schema.names)) for d in side.deltas]
            # Hash on the bucket columns in BUILD order (not join-key
            # order) so delta rows land in the same buckets the index used.
            dbs = [
                bucket_ids(compute_row_hashes(dt, side.scan.bucket_spec[1]), num_buckets, np)
                for dt in dts
            ]
            all_bucket = np.concatenate(
                [np.repeat(np.arange(num_buckets, dtype=np.int32), counts), *dbs]
            )
            combined = ColumnTable.concat([base, *dts])
            order = np.argsort(all_bucket, kind="stable")
            counts2 = np.bincount(all_bucket, minlength=num_buckets)
            offsets = np.concatenate([[0], np.cumsum(counts2)]).astype(np.int64)
            out = SideData(combined.take(order), offsets, False, hash_fields=hf)
        else:
            out = SideData(base, offsets, sorted_within, hash_fields=hf)
        if side.predicate is not None:
            out = _filter_side(out, side.predicate, self.mesh, self._filter_venue())
        return out

    def _aligned_join(
        self,
        plan: Join,
        left: AlignedSide,
        right: AlignedSide,
        lside: "SideData",
        rside: "SideData",
    ) -> ColumnTable:
        """Bucket-aligned zero-exchange SMJ: both sides arrive grouped by
        the same bucket function, so per-bucket merge joins concatenated
        equal the global join."""
        out = self._partition_join(plan, lside, rside)
        cols = None
        if plan.how in ("semi", "anti"):
            # Left-only output; the right side contributes no columns.
            if left.project is not None:
                cols = list(left.project)
        elif left.project is not None or right.project is not None:
            keep = list(left.project if left.project is not None else left.scan.scan_schema.names)
            rkeys = {k.lower() for k in plan.right_on}
            for c in right.project if right.project is not None else right.scan.scan_schema.names:
                if c.lower() not in rkeys and c.lower() not in {k.lower() for k in keep}:
                    keep.append(c)
            cols = keep
        if cols is None:
            return out
        return self._propagate_stash(out, out.select(cols))

    # DPP only enumerates the producer's key span when it is this small
    # (a year of dates is 366 hashes; demographic keys spanning millions
    # stay un-enumerated and fall back to row slicing only).
    _DPP_SPAN_LIMIT = 8192

    def _dpp_bucket_set(self, side: AlignedSide, bounds, num_buckets: int):
        """The set of bucket ids the producer's surviving keys can hash
        into, or None when not enumerable (wide span / non-int / multi-
        column bucket key). Keys are hash-distributed across buckets, so
        file [min, max] stats cannot prune — but a small ENUMERABLE key
        span (or exact key set) hashes to a concrete bucket subset (31
        dates touch at most 31 of 64 buckets; a point key exactly one)."""
        lo, hi, kset = bounds
        if lo is None:  # empty producer: nothing joins
            return set()
        if len(side.scan.bucket_spec[1]) != 1:
            return None
        key = side.scan.bucket_spec[1][0]
        f = side.scan.scan_schema.field(key)
        if f.is_string or f.device_dtype.kind not in "iu":
            return None
        if kset is not None and len(kset) <= self._DPP_SPAN_LIMIT:
            vals = kset.astype(f.device_dtype, copy=False)
        else:
            span = int(hi) - int(lo) + 1
            if span > self._DPP_SPAN_LIMIT:
                return None
            vals = np.arange(int(lo), int(hi) + 1, dtype=f.device_dtype)
        probe = ColumnTable(
            side.scan.scan_schema.select([key]), {f.name: vals}, {}, {}
        )
        h = compute_row_hashes(probe, [key])
        return set(np.unique(bucket_ids(h, num_buckets, np)).tolist())

    def _dpp_cut_cached(
        self, t: ColumnTable, key_field, dpp_bounds, sliceable: bool, kset_digest=None
    ) -> ColumnTable:
        """Range-slice + set-membership cut of one bucket table, memoized
        on (stable table identity, bounds) so a REPEATED query serves the
        same frozen sliced tables — keeping the whole downstream identity
        chain (concat, factorize, channels, pads, HBM uploads) warm. A
        per-query (unstable) table just computes the cut directly."""
        from hyperspace_tpu.execution import device_cache as dc

        lo, hi, kset = dpp_bounds

        def cut() -> ColumnTable:
            s = (
                self._dpp_slice_table(t, key_field, lo, hi)
                if sliceable and t.num_rows
                else None
            )
            if s is None:
                s = t
            if (
                kset is not None
                and s.num_rows
                and not key_field.is_string
                and key_field.device_dtype.kind in "iu"
            ):
                # Semi-join reduction: keep only rows whose key is in the
                # producer's distinct set (sorted-membership probe; nulls
                # can't match). A sorted subsequence stays sorted.
                colv = s.columns[key_field.name]
                pos = np.minimum(np.searchsorted(kset, colv), len(kset) - 1)
                hit = kset[pos] == colv
                kvalid = s.valid_mask(key_field.name)
                if kvalid is not None:
                    hit = hit & kvalid
                if not hit.all():
                    s = s.filter_mask(hit)
            return s

        if t.num_rows == 0:
            return t
        if kset is not None and kset_digest is None:
            return cut()  # no digest supplied: never key a cache on part of the cut
        refs, parts = _stable_table_refs(t, {n.lower() for n in t.schema.names})
        if not refs:
            return cut()

        def scalar(v):
            return v.item() if hasattr(v, "item") else v

        key = ("dppcut", parts, scalar(lo), scalar(hi), kset_digest)

        def build():
            s = cut()
            if s is t:
                return s, 0  # uncut: pass the (already stable) base through
            for arr in (*s.columns.values(), *s.validity.values()):
                dc.freeze(arr)
            # Canonical footprint (codes + dictionary payload for
            # dict-coded columns) — the budget must see what the entry
            # retains, not an inflated or partial estimate.
            return s, dc.table_footprint_bytes(s)

        return dc.HOST_DERIVED.get_or_build(key, refs, build)

    @staticmethod
    def _dpp_slice_table(table: ColumnTable, field, lo, hi) -> ColumnTable | None:
        """Rows of one KEY-SORTED bucket table inside [lo, hi] — one
        contiguous searchsorted run (the within-file analog of range
        pruning; hash bucketing scatters the key domain across files,
        but WITHIN a file the build's sort makes any value range one
        slice). None when the table isn't safely sliceable."""
        if field.is_string or table.valid_mask(field.name) is not None:
            return None
        colv = table.columns[field.name]
        lo_i = int(np.searchsorted(colv, lo, side="left"))
        hi_i = int(np.searchsorted(colv, hi, side="right"))
        if lo_i == 0 and hi_i == table.num_rows:
            return table
        return table.take(np.arange(lo_i, hi_i))

    def _bucket_files_in_order(self, scan: Scan, num_buckets: int) -> list[list[str]]:
        """Per-bucket file groups. A bucket can have several files (base
        version + incremental-refresh deltas); order within a group is the
        sorted file-path order."""
        files = self._scan_files(scan)
        by_name: dict[str, list[str]] = {}
        for f in sorted(files):
            by_name.setdefault(Path(f).name, []).append(f)
        out = []
        for b in range(num_buckets):
            name = hio.bucket_file_name(b)
            if name not in by_name:
                raise HyperspaceError(f"missing bucket file {name} in {scan.root}")
            out.append(by_name[name])
        return out

    # -- fused join + aggregation ----------------------------------------
