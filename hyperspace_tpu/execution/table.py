"""ColumnTable: the host-side columnar container feeding the device plane.

Struct-of-arrays over numpy, with TPU-compatible physical types only:

- numerics/bools/dates map directly;
- strings are dictionary-encoded with a SORTED dictionary, so int32 codes
  preserve the string sort order — equality AND range predicates evaluate
  correctly on codes once literals are translated (schema.py describes the
  logical types);
- nulls are carried as per-column validity masks (True = valid), the analog
  of Arrow validity bitmaps / Spark nullable columns
  (reference stores nullable schemas, index/IndexLogEntry.scala:39-47).
  Null slots hold a deterministic zero in the physical array; every
  consumer that cares (predicates, key codes, hashing, output encode)
  reads the mask, so device kernels stay branch-free and dense.

This is the analog of the reference's reliance on Spark's columnar batches
(FileSourceScanExec / vectorized Parquet read, SURVEY.md §2.2) — but as an
explicit host staging structure that uploads to `jax.Array`s.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.schema import Schema


def _take(arr: np.ndarray, idx) -> np.ndarray:
    """Row gather: the threaded native kernel for large gathers (it
    releases the GIL, so concurrent carve/write threads overlap), numpy
    fancy indexing otherwise."""
    if isinstance(idx, np.ndarray) and idx.dtype.kind in "iu" and len(idx) > 4096:
        from hyperspace_tpu import native

        out = native.take_rows(arr, idx)
        if out is not None:
            return out
    return arr[idx]


@dataclasses.dataclass
class ColumnTable:
    schema: Schema
    columns: dict[str, np.ndarray]  # physical arrays (codes for strings)
    dictionaries: dict[str, np.ndarray]  # string name -> sorted object array
    # column name -> bool array, True = valid. Absent key = no nulls.
    validity: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        lens = {len(v) for v in self.columns.values()}  # len = rows for 2D too
        if len(lens) > 1:
            raise HyperspaceError(f"ragged columns: {lens}")

    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    def column(self, name: str) -> np.ndarray:
        f = self.schema.field(name)
        return self.columns[f.name]

    def dictionary(self, name: str) -> np.ndarray | None:
        f = self.schema.field(name)
        return self.dictionaries.get(f.name)

    def valid_mask(self, name: str) -> np.ndarray | None:
        """Validity of a column (True = valid), or None when null-free."""
        f = self.schema.field(name)
        return self.validity.get(f.name)

    # -- construction ----------------------------------------------------
    @staticmethod
    def from_arrow(table, schema: Schema | None = None, zero_copy_ok: bool = False) -> "ColumnTable":
        """Build from a pyarrow Table, dictionary-encoding string columns
        and extracting validity masks for nullable data.

        ``zero_copy_ok`` opts into the device-staging path
        (execution/staging.py): fixed-width null-free single-chunk
        columns are kept as READ-ONLY numpy views over the Arrow buffers
        (no host materialization) instead of owned copies. Only the
        cache-destined read path may pass it — read-only must keep
        meaning identity-stable, so `io.read_parquet_cached` freezes the
        table into the cache or downgrades it with :meth:`own_arrays`.
        """
        import pyarrow as pa
        import pyarrow.compute as pc

        from hyperspace_tpu.execution import staging

        if schema is None:
            schema = Schema.from_arrow(table.schema)
        columns: dict[str, np.ndarray] = {}
        dictionaries: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}

        def _owned(arr: np.ndarray) -> np.ndarray:
            """Arrow zero-copy buffers surface as READ-ONLY numpy arrays;
            copy those so that writeable=False means exactly one thing in
            this engine: frozen by the cache layer (identity-stable).
            Without this, per-query scan arrays would masquerade as
            cacheable and pile dead entries into the device cache. The
            staging path (zero_copy_ok=True) is the one sanctioned
            exception: its read-only views are frozen into the io cache
            or downgraded back to owned copies before anyone else sees
            them — a deliberate trade for an airtight stability
            invariant."""
            return arr if arr.flags.writeable else arr.copy()
        for f in schema.fields:
            arr = table.column(f.name)
            valid = None
            if arr.null_count:
                if f.is_vector:
                    raise HyperspaceError(
                        f"vector column {f.name!r} contains {arr.null_count} null "
                        "rows; null embeddings are not supported"
                    )
                # Packed-bitmap expansion (one vectorized unpackbits per
                # chunk) instead of a pyarrow compute round-trip that
                # materializes an intermediate byte-per-row Arrow array.
                valid = staging.validity_mask(arr)
                validity[f.name] = valid
            if f.is_string:
                # Arrow's C++ dictionary encode, then a SMALL sort of the
                # dictionary + an O(n) int remap — the order-preserving
                # sorted-codes invariant without np.unique's O(n log n)
                # string comparisons (10-30x on multi-million-row string
                # columns).
                # Encode BEFORE combining: dictionary_encode accepts the
                # chunked column, so only int32 indices ever combine —
                # a >2 GiB string payload never has to fit int32 offsets.
                enc = arr if pa.types.is_dictionary(arr.type) else pc.dictionary_encode(arr)
                if isinstance(enc, pa.ChunkedArray):
                    enc = enc.combine_chunks() if enc.num_chunks != 1 else enc.chunk(0)
                dict_arr = enc.dictionary
                dict_null = None
                if dict_arr.null_count:
                    # Arrow permits nulls IN the dictionary (entry-level
                    # nulls): rows referencing such an entry are logically
                    # NULL but invisible to the top-level null_count above.
                    # Fill the entry before the str cast (np.asarray would
                    # bake the literal string 'None') and fold the
                    # referencing rows into the validity mask below.
                    dict_null = ~np.asarray(pc.is_valid(dict_arr))
                    dict_arr = pc.fill_null(dict_arr, "")
                dvals = dict_arr.to_numpy(zero_copy_only=False)
                svals = np.asarray(dvals, dtype=str)
                idx = enc.indices
                if idx.null_count:
                    idx = pc.fill_null(idx, 0)
                codes0 = np.asarray(idx).astype(np.int64, copy=False)
                if dict_null is not None and dict_null.any():
                    row_null = dict_null[codes0]
                    if row_null.any():
                        valid = ~row_null if valid is None else (valid & ~row_null)
                        validity[f.name] = valid
                if valid is not None and not (svals == "").any():
                    # Null slots take the deterministic "" value (added to
                    # the dictionary when absent), as the decode always has.
                    svals = np.append(svals, "")
                # np.unique over the SMALL dictionary: sorts AND dedups
                # (arrow permits duplicate dictionary values — two codes
                # meaning the same string must collapse to one, or code-
                # domain equality silently drops rows).
                sorted_dict, inv = np.unique(svals, return_inverse=True)
                codes = inv.astype(np.int32, copy=False)[codes0]
                if valid is not None:
                    empty_code = np.int32(np.searchsorted(sorted_dict, ""))
                    codes = np.where(valid, codes, empty_code).astype(np.int32, copy=False)
                columns[f.name] = codes
                dictionaries[f.name] = sorted_dict
            elif f.is_vector:
                combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
                # .values, NOT .flatten(): flatten silently drops null list
                # slots and misaligns rows (top-level nulls are rejected
                # above, but .values is the physical buffer either way).
                child = combined.values
                if child.null_count:
                    raise HyperspaceError(
                        f"vector column {f.name!r} contains null elements"
                    )
                flat = child.to_numpy(zero_copy_only=False)
                columns[f.name] = _owned(
                    np.ascontiguousarray(flat).astype(np.float32, copy=False).reshape(-1, f.dim)
                )
            else:
                if zero_copy_ok and valid is None:
                    staged = staging.stage_column(arr, f)
                    if staged is not None:
                        columns[f.name] = staged
                        continue
                if f.dtype == "date":
                    arr = arr.cast(pa.int32())
                elif f.dtype == "timestamp":
                    arr = arr.cast(pa.int64())
                if valid is not None:
                    # Zero the null slots with a TYPED scalar (a bare int
                    # fill crashes on bool columns).
                    arr = pc.fill_null(arr, pa.scalar(False if f.dtype == "bool" else 0, arr.type))
                np_arr = arr.to_numpy(zero_copy_only=False)
                out = _owned(
                    np.ascontiguousarray(np_arr).astype(f.device_dtype, copy=False)
                )
                staging.count_copied(out.nbytes)
                columns[f.name] = out
        return ColumnTable(schema, columns, dictionaries, validity)

    def own_arrays(self) -> "ColumnTable":
        """Downgrade any read-only staged buffer views to owned WRITABLE
        copies (in place; returns self). The un-cached exit of the
        zero-copy read path: a table that will not be frozen into the io
        cache must not carry read-only arrays, or every downstream
        identity cache would mistake its per-query arrays for stable
        ones. Copied bytes are accounted to the staging counters."""
        from hyperspace_tpu.execution import staging

        for name, arr in self.columns.items():
            if not arr.flags.writeable:
                self.columns[name] = arr.copy()
                staging.count_copied(arr.nbytes)
        for name, arr in self.validity.items():
            if not arr.flags.writeable:
                self.validity[name] = arr.copy()
        return self

    @staticmethod
    def from_numpy(schema: Schema, columns: dict[str, np.ndarray], dictionaries=None, validity=None) -> "ColumnTable":
        return ColumnTable(schema, dict(columns), dict(dictionaries or {}), dict(validity or {}))

    @staticmethod
    def empty(schema: Schema) -> "ColumnTable":
        """Zero-row table for a schema (empty sorted dictionaries for
        string fields)."""
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        for f in schema.fields:
            if f.is_string:
                cols[f.name] = np.zeros(0, dtype=np.int32)
                dicts[f.name] = np.zeros(0, dtype=object)
            elif f.is_vector:
                cols[f.name] = np.zeros((0, f.dim), dtype=np.float32)
            else:
                cols[f.name] = np.zeros(0, dtype=f.device_dtype)
        return ColumnTable(schema, cols, dicts, {})

    # -- transforms ------------------------------------------------------
    def select(self, names: Iterable[str]) -> "ColumnTable":
        names = list(names)
        sub = self.schema.select(names)
        cols = {f.name: self.columns[f.name] for f in sub.fields}
        dicts = {f.name: self.dictionaries[f.name] for f in sub.fields if f.name in self.dictionaries}
        val = {f.name: self.validity[f.name] for f in sub.fields if f.name in self.validity}
        return ColumnTable(sub, cols, dicts, val)

    def take(self, indices: np.ndarray) -> "ColumnTable":
        cols = {k: _take(v, indices) for k, v in self.columns.items()}
        val = {k: _take(v, indices) for k, v in self.validity.items()}
        return ColumnTable(self.schema, cols, dict(self.dictionaries), val)

    def filter_mask(self, mask: np.ndarray) -> "ColumnTable":
        cols = {k: v[mask] for k, v in self.columns.items()}
        val = {k: v[mask] for k, v in self.validity.items()}
        return ColumnTable(self.schema, cols, dict(self.dictionaries), val)

    def translate_literal(self, column: str, value: Any, op: str) -> Any:
        """Map a literal to the physical domain of `column`.

        For string columns, translate a string literal to the dictionary
        code domain such that comparisons on codes are equivalent:
        - present in dict: its code works for all comparison ops;
        - absent: use the insertion point; eq ⇒ impossible (-1 with ne
          semantics handled by caller via code space), lt/ge boundaries
          still correct because the dictionary is sorted.
        """
        f = self.schema.field(column)
        if not f.is_string:
            return value
        d = self.dictionaries.get(f.name)
        if d is None:
            raise HyperspaceError(f"no dictionary for string column {column!r}")
        pos = int(np.searchsorted(d, value))
        present = pos < len(d) and d[pos] == value
        if present:
            return pos
        # Absent literal: map so code-domain comparison stays correct.
        if op in ("eq",):
            return -1  # no code is -1 ⇒ always false
        if op in ("ne",):
            return -1  # all codes != -1 ⇒ always true
        if op in ("lt", "ge"):
            return pos  # codes < pos are strictly smaller strings
        if op in ("le",):
            return pos - 1 if pos > 0 else -1
        if op in ("gt",):
            return pos - 1 if pos > 0 else -1
        return pos

    def decode(self) -> dict[str, np.ndarray]:
        """Materialize logical values (strings decoded, null slots as None
        in object arrays) for result checks."""
        out = {}
        for f in self.schema.fields:
            arr = self.columns[f.name]
            if f.is_string:
                vals = self.dictionaries[f.name][arr]
            else:
                vals = arr
            valid = self.validity.get(f.name)
            if valid is not None and not valid.all():
                # An all-true mask (e.g. after filtering the null rows
                # away) keeps the natural dtype — object arrays force
                # exact comparison on floats downstream.
                vals = vals.astype(object)
                vals[~valid] = None
            out[f.name] = vals
        return out

    def to_arrow(self):
        import pyarrow as pa

        arrays = {}
        for f in self.schema.fields:
            valid = self.validity.get(f.name)
            mask = ~valid if valid is not None else None  # pa: True = null
            if f.is_string:
                # Emit the (codes, dictionary) pair AS a DictionaryArray:
                # the column never inflates to a full per-row string
                # array on host — parquet/IPC writers consume the codes
                # and the small dictionary directly (write_bucket was an
                # O(n)-string materialization per bucket before this).
                d = self.dictionaries[f.name]
                idx = pa.array(
                    np.ascontiguousarray(self.columns[f.name], dtype=np.int32),
                    mask=mask,
                )
                arrays[f.name] = pa.DictionaryArray.from_arrays(
                    idx, pa.array(d.astype(object), type=pa.string())
                )
                continue
            v = self.columns[f.name]
            if f.is_vector:
                arrays[f.name] = pa.FixedSizeListArray.from_arrays(
                    pa.array(v.reshape(-1), type=pa.float32()), f.dim
                )
            elif f.dtype == "date":
                arrays[f.name] = pa.array(v, type=pa.date32(), mask=mask)
            elif f.dtype == "timestamp":
                arrays[f.name] = pa.array(v, type=pa.timestamp("us"), mask=mask)
            else:
                arrays[f.name] = pa.array(v, mask=mask)
        return pa.table(arrays)

    @staticmethod
    def concat(tables: list["ColumnTable"]) -> "ColumnTable":
        """Concatenate tables with the same schema. String columns merge
        on the DICTIONARIES (small) and remap codes with one searchsorted
        per part — never decoding row values (the O(n log n) re-encode was
        round 1's hot-path weakness #5)."""
        if not tables:
            raise HyperspaceError("cannot concat zero tables")
        if len(tables) == 1:
            return tables[0]
        schema = tables[0].schema
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for f in schema.fields:
            if f.is_string:
                parts_dicts = [t.dictionaries[f.name] for t in tables]
                if all(
                    len(d) == len(parts_dicts[0]) and np.array_equal(d, parts_dicts[0])
                    for d in parts_dicts[1:]
                ):
                    # Identical dictionaries (common: buckets of one index
                    # version) — codes concatenate directly.
                    dicts[f.name] = parts_dicts[0]
                    cols[f.name] = np.concatenate([t.columns[f.name] for t in tables])
                else:
                    merged = np.unique(np.concatenate(parts_dicts).astype(str))
                    remapped = []
                    for t, d in zip(tables, parts_dicts):
                        # Old code -> position of its string in the merged
                        # sorted dictionary (exact: every entry is present).
                        old_to_new = np.searchsorted(merged, d.astype(str)).astype(np.int32)
                        remapped.append(old_to_new[t.columns[f.name]] if len(d) else t.columns[f.name])
                    dicts[f.name] = merged.astype(object)
                    cols[f.name] = np.concatenate(remapped)
            else:
                cols[f.name] = np.concatenate([t.columns[f.name] for t in tables])
            if any(f.name in t.validity for t in tables):
                validity[f.name] = np.concatenate(
                    [t.validity.get(f.name, np.ones(t.num_rows, dtype=bool)) for t in tables]
                )
        return ColumnTable(schema, cols, dicts, validity)
