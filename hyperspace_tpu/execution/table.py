"""ColumnTable: the host-side columnar container feeding the device plane.

Struct-of-arrays over numpy, with TPU-compatible physical types only:

- numerics/bools/dates map directly;
- strings are dictionary-encoded with a SORTED dictionary, so int32 codes
  preserve the string sort order — equality AND range predicates evaluate
  correctly on codes once literals are translated (schema.py describes the
  logical types).

This is the analog of the reference's reliance on Spark's columnar batches
(FileSourceScanExec / vectorized Parquet read, SURVEY.md §2.2) — but as an
explicit host staging structure that uploads to `jax.Array`s.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.schema import Schema


@dataclasses.dataclass
class ColumnTable:
    schema: Schema
    columns: dict[str, np.ndarray]  # physical arrays (codes for strings)
    dictionaries: dict[str, np.ndarray]  # string name -> sorted object array

    def __post_init__(self):
        lens = {len(v) for v in self.columns.values()}  # len = rows for 2D too
        if len(lens) > 1:
            raise HyperspaceError(f"ragged columns: {lens}")

    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    def column(self, name: str) -> np.ndarray:
        f = self.schema.field(name)
        return self.columns[f.name]

    def dictionary(self, name: str) -> np.ndarray | None:
        f = self.schema.field(name)
        return self.dictionaries.get(f.name)

    # -- construction ----------------------------------------------------
    @staticmethod
    def from_arrow(table, schema: Schema | None = None) -> "ColumnTable":
        """Build from a pyarrow Table, dictionary-encoding string columns."""
        if schema is None:
            schema = Schema.from_arrow(table.schema)
        columns: dict[str, np.ndarray] = {}
        dictionaries: dict[str, np.ndarray] = {}
        for f in schema.fields:
            arr = table.column(f.name)
            if arr.null_count:
                # Nulls would silently corrupt: arrow→numpy turns int nulls
                # into NaN→INT_MIN and string nulls into the value "nan".
                raise HyperspaceError(
                    f"column {f.name!r} contains {arr.null_count} null values; "
                    "null handling is not supported — drop or fill nulls first"
                )
            if f.is_string:
                values = arr.to_pandas().to_numpy(dtype=object)
                # np.unique gives a sorted dictionary + inverse codes, so
                # codes are order-preserving.
                dictionary, codes = np.unique(values.astype(str), return_inverse=True)
                columns[f.name] = codes.astype(np.int32)
                dictionaries[f.name] = dictionary
            elif f.is_vector:
                import pyarrow as pa

                combined = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
                # .values, NOT .flatten(): flatten silently drops null list
                # slots and misaligns rows (top-level nulls are rejected
                # above, but .values is the physical buffer either way).
                child = combined.values
                if child.null_count:
                    raise HyperspaceError(
                        f"vector column {f.name!r} contains null elements"
                    )
                flat = child.to_numpy(zero_copy_only=False)
                columns[f.name] = (
                    np.ascontiguousarray(flat).astype(np.float32, copy=False).reshape(-1, f.dim)
                )
            else:
                import pyarrow as pa

                if f.dtype == "date":
                    arr = arr.cast(pa.int32())
                elif f.dtype == "timestamp":
                    arr = arr.cast(pa.int64())
                np_arr = arr.to_numpy(zero_copy_only=False)
                columns[f.name] = np.ascontiguousarray(np_arr).astype(f.device_dtype, copy=False)
        return ColumnTable(schema, columns, dictionaries)

    @staticmethod
    def from_numpy(schema: Schema, columns: dict[str, np.ndarray], dictionaries=None) -> "ColumnTable":
        return ColumnTable(schema, dict(columns), dict(dictionaries or {}))

    # -- transforms ------------------------------------------------------
    def select(self, names: Iterable[str]) -> "ColumnTable":
        names = list(names)
        sub = self.schema.select(names)
        cols = {f.name: self.columns[f.name] for f in sub.fields}
        dicts = {f.name: self.dictionaries[f.name] for f in sub.fields if f.name in self.dictionaries}
        return ColumnTable(sub, cols, dicts)

    def take(self, indices: np.ndarray) -> "ColumnTable":
        cols = {k: v[indices] for k, v in self.columns.items()}
        return ColumnTable(self.schema, cols, dict(self.dictionaries))

    def filter_mask(self, mask: np.ndarray) -> "ColumnTable":
        cols = {k: v[mask] for k, v in self.columns.items()}
        return ColumnTable(self.schema, cols, dict(self.dictionaries))

    def translate_literal(self, column: str, value: Any, op: str) -> Any:
        """Map a literal to the physical domain of `column`.

        For string columns, translate a string literal to the dictionary
        code domain such that comparisons on codes are equivalent:
        - present in dict: its code works for all comparison ops;
        - absent: use the insertion point; eq ⇒ impossible (-1 with ne
          semantics handled by caller via code space), lt/ge boundaries
          still correct because the dictionary is sorted.
        """
        f = self.schema.field(column)
        if not f.is_string:
            return value
        d = self.dictionaries.get(f.name)
        if d is None:
            raise HyperspaceError(f"no dictionary for string column {column!r}")
        pos = int(np.searchsorted(d, value))
        present = pos < len(d) and d[pos] == value
        if present:
            return pos
        # Absent literal: map so code-domain comparison stays correct.
        if op in ("eq",):
            return -1  # no code is -1 ⇒ always false
        if op in ("ne",):
            return -1  # all codes != -1 ⇒ always true
        if op in ("lt", "ge"):
            return pos  # codes < pos are strictly smaller strings
        if op in ("le",):
            return pos - 1 if pos > 0 else -1
        if op in ("gt",):
            return pos - 1 if pos > 0 else -1
        return pos

    def decode(self) -> dict[str, np.ndarray]:
        """Materialize logical values (strings decoded) for result checks."""
        out = {}
        for f in self.schema.fields:
            arr = self.columns[f.name]
            if f.is_string:
                out[f.name] = self.dictionaries[f.name][arr]
            else:
                out[f.name] = arr
        return out

    def to_arrow(self):
        import pyarrow as pa

        arrays = {}
        decoded = None
        for f in self.schema.fields:
            if f.is_string:
                decoded = decoded if decoded is not None else self.decode()
                v = decoded[f.name]
            else:
                v = self.columns[f.name]
            if f.is_vector:
                arrays[f.name] = pa.FixedSizeListArray.from_arrays(
                    pa.array(v.reshape(-1), type=pa.float32()), f.dim
                )
            else:
                arrays[f.name] = pa.array(v)
        return pa.table(arrays)

    @staticmethod
    def concat(tables: list["ColumnTable"]) -> "ColumnTable":
        """Concatenate tables with the same schema, re-encoding string
        columns onto a merged dictionary."""
        if not tables:
            raise HyperspaceError("cannot concat zero tables")
        if len(tables) == 1:
            return tables[0]
        schema = tables[0].schema
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        for f in schema.fields:
            if f.is_string:
                decoded = np.concatenate([t.dictionaries[f.name][t.columns[f.name]] for t in tables])
                dictionary, codes = np.unique(decoded.astype(str), return_inverse=True)
                cols[f.name] = codes.astype(np.int32)
                dicts[f.name] = dictionary
            else:
                cols[f.name] = np.concatenate([t.columns[f.name] for t in tables])
        return ColumnTable(schema, cols, dicts)
