"""Join execution: the per-bucket merge join over bucket-grouped
layouts, match-pair derivation, broadcast-hash fallback, outer/semi/
anti composition, and ON-residual matching (Executor mixin)."""

from __future__ import annotations

import numpy as np

from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.ops.filter import eval_predicate_mask
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.plan.nodes import Join

from hyperspace_tpu.execution.exec_common import (
    SideData,
    _broadcast_probe,
    _bucket_sorted_codes,
    _composite_keys,
    _copy_field,
    _factorize_keys_cached,
    _null_field,
    _pad_bucket_major_cached,
)


class JoinMixin:
    def _join(self, plan: Join) -> ColumnTable:
        lside, rside, left_side, right_side = self._join_sides(plan)
        # Path from THIS frame's decision (the _join_sides call above
        # sets it LAST, after any nested joins it executed ran). buckets/
        # devices are read after _partition_join, which sets them for the
        # kernel that just ran (this join's own).
        path = self.stats["join_path"]
        if left_side is not None:
            out = self._aligned_join(plan, left_side, right_side, lside, rside)
        else:
            out = self._partition_join(plan, lside, rside)
        if self.stats["join_kernel"] == "host-broadcast-hash":
            path = "broadcast-hash"
            self.stats["join_path"] = path
        if plan.condition is not None and plan.how == "inner":
            # Inner-join ON residual: a plain 3-valued filter over the
            # matched rows, venue- and mesh-aware like every other
            # predicate site. (Outer/semi/anti residuals alter MATCHING
            # and are applied inside _partition_join.) The filtered
            # table deliberately does NOT inherit any preserved bucket
            # grouping (per-bucket counts changed).
            before = out.num_rows
            mask = eval_predicate_mask(
                out, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            out = out.filter_mask(mask)
            self._phys(residual_condition=True, residual_rows_dropped=before - out.num_rows)
        self._phys(
            "BroadcastHashJoin" if path == "broadcast-hash" else "SortMergeJoin",
            path=path,
            kernel=self.stats["join_kernel"],
            buckets=self.stats["num_buckets"],
            devices=self.stats["join_devices"],
        )
        return out

    def _partition_join(self, plan: Join, lside: "SideData", rside: "SideData") -> ColumnTable:
        """Per-bucket merge join over the concatenated bucket-grouped
        layout: everything host-side is vectorized (pad-gather in, one
        repeat+add to globalize match indices, ONE native gather per
        column out) — no per-bucket Python loop (round 1 weakness #4).
        Non-inner join types derive from the same match pairs: outer
        variants append the unmatched side's rows null-extended, semi/anti
        keep left rows by match flag (the join-type surface Spark's
        SortMergeJoinExec serves over the reference's rewritten bucketed
        relations, JoinIndexRule.scala:124-153)."""
        lt, rt = lside.table, rside.table
        how = plan.how

        if how in ("semi", "anti") and plan.condition is None:
            # Existence is a membership probe, not a join: never expand the
            # match pairs (a hot key repeated k×k ways would materialize k²
            # pairs only to collapse into |L| bits).
            matched = self._semi_match_mask(plan, lside, rside)
            out = lt.filter_mask(matched if how == "semi" else ~matched)
            return ColumnTable(plan.schema, out.columns, out.dictionaries, out.validity)

        lidx, ridx, totals = self._match_pairs(plan, lside, rside)

        if how in ("semi", "anti"):
            # Residual existence (EXISTS with extra conditions): a left
            # row matches iff SOME equi-pair also passes the residual —
            # gather ONLY the columns the condition reads (the pairs are
            # k x k expanded; none of the payload survives the |L|-bit
            # reduction), evaluate, and reduce surviving lidx to bits.
            from hyperspace_tpu.schema import Schema as _Schema

            refs = {r.lower() for r in plan.condition.references()}
            rkeys_low = {rt.schema.field(c).name.lower() for c in plan.right_on}
            lkeep = [f.name for f in lt.schema.fields if f.name.lower() in refs]
            if not lkeep:  # keep one cheap key lane so row count survives
                lkeep = [lt.schema.field(plan.left_on[0]).name]
            rkeep = [rt.schema.field(c).name for c in plan.right_on] + [
                f.name
                for f in rt.schema.fields
                if f.name.lower() in refs and f.name.lower() not in rkeys_low
            ]
            sub_schema = _Schema(
                tuple(lt.schema.select(lkeep).fields)
                + tuple(
                    f for f in rt.schema.select(rkeep).fields
                    if f.name.lower() not in rkeys_low
                )
            )
            pairs = self._gather_pairs(
                plan, lt.select(lkeep), rt.select(rkeep), lidx, ridx, schema=sub_schema
            )
            pmask = eval_predicate_mask(
                pairs, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            matched = np.zeros(lt.num_rows, dtype=bool)
            matched[lidx[pmask]] = True
            self._phys(residual_condition=True, residual_pairs_dropped=int((~pmask).sum()))
            out = lt.filter_mask(matched if how == "semi" else ~matched)
            return ColumnTable(plan.schema, out.columns, out.dictionaries, out.validity)

        inner = self._gather_pairs(plan, lt, rt, lidx, ridx)
        if plan.condition is not None and how != "inner":
            # Outer-join ON residual alters MATCHING: a pair failing it
            # is no match, so its rows fall through to the null-extended
            # unmatched parts below (computed from the SURVIVING pairs).
            pmask = eval_predicate_mask(
                inner, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            inner = inner.filter_mask(pmask)
            lidx, ridx = lidx[pmask], ridx[pmask]
            self._phys(residual_condition=True, residual_pairs_dropped=int((~pmask).sum()))
        if how == "inner":
            # Bucket-preserving output: an inner join over B>1 buckets
            # emits pairs bucket-major, so the result STAYS bucket-
            # grouped on the (merged, left-named) join keys — a later
            # join on the same keys reuses the grouping with no exchange
            # (SURVEY §2.3: chained star joins stay bucket-parallel).
            if (
                totals is not None
                and len(totals) > 1
                and lside.hash_fields is not None
            ):
                self._stash_bucketed(
                    inner,
                    np.concatenate([[0], np.cumsum(totals)]).astype(np.int64),
                    plan.left_on,
                    lside.hash_fields,
                )
            return inner
        parts = [inner]
        if how in ("left", "full"):
            lmask = np.zeros(lt.num_rows, dtype=bool)
            lmask[lidx] = True
            parts.append(self._left_unmatched(plan, lt, rt, ~lmask))
        if how in ("right", "full"):
            rmask = np.zeros(rt.num_rows, dtype=bool)
            rmask[ridx] = True
            parts.append(self._right_unmatched(plan, lt, rt, ~rmask))
        parts = [p for p in parts if p.num_rows > 0]
        if not parts:
            return inner
        # Concat builds from plan.schema, so any extra physical columns a
        # wide index scan carried along are dropped here; the outer-join
        # output is exactly the declared join schema.
        return ColumnTable.concat(parts) if len(parts) > 1 else parts[0]

    def _semi_match_mask(self, plan: Join, lside: "SideData", rside: "SideData") -> np.ndarray:
        """Per-left-row existence of an equi-match in the right side:
        one sorted membership probe over (bucket, key-code) composites —
        O((n+m) log m) on host, no pair expansion, no device round-trip
        (the result is |L| bits the mask filter consumes on host anyway).
        Null-keyed rows carry side-distinct negative codes and never
        match (SQL: NULL = NULL is not true), so anti keeps them —
        unless the join is null-safe (set-op desugar), where NULL is a
        real per-column domain value and matches its twin."""
        lt, rt = lside.table, rside.table
        lkeys = [lt.schema.field(c).name for c in plan.left_on]
        rkeys = [rt.schema.field(c).name for c in plan.right_on]
        lc0, rc0 = _factorize_keys_cached(lt, rt, lkeys, rkeys, null_safe=plan.null_safe)
        lcodes = lc0.astype(np.int64)
        rcodes = rc0.astype(np.int64)
        b = len(lside.offsets) - 1
        self.stats["num_buckets"] = b
        self.stats["join_kernel"] = "host-membership-probe"
        comp_l = _composite_keys(lcodes, lside.offsets)
        comp_r = np.sort(_composite_keys(rcodes, rside.offsets))
        pos = np.searchsorted(comp_r, comp_l)
        matched = np.zeros(lt.num_rows, dtype=bool)
        in_range = pos < len(comp_r)
        matched[in_range] = comp_r[pos[in_range]] == comp_l[in_range]
        return matched

    def _match_pairs(self, plan: Join, lside: "SideData", rside: "SideData"):
        """(lidx, ridx) global match row indices of the equi-join, from the
        venue-selected merge kernel over bucket-sorted key codes. A
        heavily asymmetric single-partition join takes the broadcast hash
        path instead: only the small side is sorted, the large side
        probes it — the analog of Spark's BroadcastExchange fallback the
        reference environment supplies for small sides
        (PhysicalOperatorAnalyzer.scala:46-50)."""
        lt, rt = lside.table, rside.table
        lkeys = [lt.schema.field(c).name for c in plan.left_on]
        rkeys = [rt.schema.field(c).name for c in plan.right_on]

        # Shared order-preserving factorization of the key tuples.
        lcodes, rcodes = _factorize_keys_cached(
            lt, rt, lkeys, rkeys, null_safe=plan.null_safe
        )

        b0 = len(lside.offsets) - 1
        if b0 == 1 and self._should_broadcast(lt.num_rows, rt.num_rows):
            res = _broadcast_probe(lcodes, rcodes)
            if res is not None:
                self.stats["num_buckets"] = 1
                self.stats["join_kernel"] = "host-broadcast-hash"
                return res[0], res[1], None

        # Non-aligned sides re-group through the fused bucket+key device
        # sort when the sort venue allows (host np.lexsort otherwise —
        # identical stable permutation either way).
        regroup_venue = self._venue(
            "sort_venue", "hyperspace.sort.venue", False, needs_native=False
        )
        lcodes, lperm = _bucket_sorted_codes(lcodes, lside, venue=regroup_venue)
        rcodes, rperm = _bucket_sorted_codes(rcodes, rside, venue=regroup_venue)
        b = len(lside.offsets) - 1
        self.stats["num_buckets"] = b

        host_res = None
        if (
            lcodes.dtype == np.int32
            and rcodes.dtype == np.int32
            and self._join_venue() == "host"
        ):
            from hyperspace_tpu import native

            host_res = native.merge_join_sorted(
                lcodes, lside.offsets, rcodes, rside.offsets
            )
        if host_res is not None:
            # Host venue: exact bucket-parallel C++ merge over the already
            # host-resident sorted runs — no device round-trip (the match
            # pairs land on host either way; see parallel/bandwidth.py).
            lidx, ridx, totals = host_res
            self.stats["join_kernel"] = "host-native-merge"
        else:
            lk = _pad_bucket_major_cached(lcodes, lside.offsets)
            rk = _pad_bucket_major_cached(rcodes, rside.offsets)
            if self.mesh is not None:
                from hyperspace_tpu.parallel.mesh import mesh_for_parallelism, mesh_size

                jmesh = mesh_for_parallelism(self.mesh, b)
                li_flat, ri_flat, totals = join_ops.merge_join_sharded(lk, rk, jmesh)
                self.stats["join_devices"] = mesh_size(jmesh)
            else:
                li_flat, ri_flat, totals = join_ops.merge_join(lk, rk)
            self.stats["join_kernel"] = "device-searchsorted"
            # Local (within-bucket) match indices → global row indices.
            lidx = np.repeat(lside.offsets[:-1], totals) + li_flat
            ridx = np.repeat(rside.offsets[:-1], totals) + ri_flat
        if lperm is not None:
            lidx = lperm[lidx]
        if rperm is not None:
            ridx = rperm[ridx]
        # Pair order stays bucket-major through the perm mapping, so
        # `totals` doubles as the OUTPUT's bucket grouping.
        return lidx, ridx, np.asarray(totals, dtype=np.int64)

    def _should_broadcast(self, n_l: int, n_r: int) -> bool:
        """Small-enough and asymmetric-enough for the broadcast probe."""
        from hyperspace_tpu.config import DEFAULT_JOIN_BROADCAST_MAX_ROWS

        cap = (
            self.conf.join_broadcast_max_rows
            if self.conf is not None
            else DEFAULT_JOIN_BROADCAST_MAX_ROWS
        )
        if cap <= 0:
            return False
        small, large = min(n_l, n_r), max(n_l, n_r)
        return 0 < small <= cap and large >= 4 * small

    def _gather_pairs(
        self, plan: Join, lt: ColumnTable, rt: ColumnTable, lidx, ridx, schema=None
    ) -> ColumnTable:
        """Materialize matched rows: left columns + right non-key columns.
        `schema` overrides the output schema (semi/anti residual
        evaluation gathers in the inner-join shape)."""
        schema = schema if schema is not None else plan.schema
        rkeys_low = {rt.schema.field(c).name.lower() for c in plan.right_on}
        lgather = lt.take(lidx)
        cols = dict(lgather.columns)
        dicts = dict(lgather.dictionaries)
        val = dict(lgather.validity)
        rnames = [f.name for f in rt.schema.fields if f.name.lower() not in rkeys_low]
        rgather = rt.select(rnames).take(ridx)
        cols.update(rgather.columns)
        dicts.update(rgather.dictionaries)
        val.update(rgather.validity)
        return ColumnTable(schema, cols, dicts, val)

    def _left_unmatched(self, plan: Join, lt: ColumnTable, rt: ColumnTable, mask) -> ColumnTable:
        """Unmatched left rows, right-side fields null-extended."""
        sub = lt.filter_mask(mask)
        lnames = {x.lower() for x in plan.left.schema.names}
        cols: dict = {}
        dicts: dict = {}
        val: dict = {}
        for f in plan.schema.fields:
            if f.name.lower() in lnames:
                _copy_field(f, sub, f.name, cols, dicts, val)
            else:
                _null_field(f, sub.num_rows, rt, cols, dicts, val)
        return ColumnTable(plan.schema, cols, dicts, val)

    def _right_unmatched(self, plan: Join, lt: ColumnTable, rt: ColumnTable, mask) -> ColumnTable:
        """Unmatched right rows: key columns coalesce to the RIGHT key's
        values (under the left-named output column), right non-key fields
        carry their values, left-only fields are null-extended."""
        sub = rt.filter_mask(mask)
        key_src = {l.lower(): r for l, r in zip(plan.left_on, plan.right_on)}
        rnames = {x.lower() for x in plan.right.schema.names}
        cols: dict = {}
        dicts: dict = {}
        val: dict = {}
        for f in plan.schema.fields:
            low = f.name.lower()
            if low in key_src:
                _copy_field(f, sub, key_src[low], cols, dicts, val)
            elif low in rnames:
                _copy_field(f, sub, f.name, cols, dicts, val)
            else:
                _null_field(f, sub.num_rows, lt, cols, dicts, val)
        return ColumnTable(plan.schema, cols, dicts, val)


