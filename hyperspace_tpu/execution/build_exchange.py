"""The scale-out build's cross-process exchange: host-side hash/partition
helpers, the spill-file exchange format, and the worker-process bodies.

This module is the pooled build's analogue of Spark's hash shuffle
(PAPER.md §2.3): N **p1 shard** workers each decode a disjoint,
*contiguous* slice of the input files, hash/partition rows by the
canonical bucket hash, and append per-bucket spill parquet into the
directory of the bucket's **owner** (``owner = bucket % num_owners`` —
bucket id → owner is the shard key); N **p2 owner** workers then read
back their buckets' spill (concatenating the shard files in shard-id
order, which reproduces the global source row order exactly), key-sort,
and write the final bucket files + per-bucket manifest stats. Workers
exchange only *paths plus the decoded-byte ledger* — no ColumnTable is
ever pickled across the process boundary.

Byte-identity with the serial streaming reference
(`DeviceIndexBuilder._write_streaming`, pipeline off) follows from three
invariants, each pinned by tests/test_build_scaleout.py:

- file slices are contiguous and in order, and each shard streams its
  files in order, so shard-ordered spill concatenation == the serial
  path's single-writer chunk order (chunk *boundaries* differ across
  worker counts, but boundaries never reorder rows);
- the key sort is the stable host permutation (`native.sort_range`, or
  `np.lexsort` without the native kernel) — the same order every sort
  venue produces;
- the final encode is the same deterministic `io.write_bucket`.

Deliberately **jax-free**: a spawned worker importing this module (and
its io/table/hashing/sortkeys dependencies) never pays the jax import,
and never touches a device — all device work stays in the coordinator's
process (`execution/builder.py`). Keep it that way: the per-worker
interpreter start is on every pooled build's critical path.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.faults import fault_point
from hyperspace_tpu.obs import trace as obs_trace
from hyperspace_tpu.ops.hashing import bucket_ids, combine_hashes, hash_int_column, string_dict_hashes

# The fixed hash contribution of a NULL key slot: nulls bucket
# deterministically (they can never match an equality literal, so bucket
# pruning by literal hash stays correct regardless).
NULL_HASH = np.uint32(0x9E3779B9)


def compute_row_hashes(table: ColumnTable, key_columns: list[str]) -> np.ndarray:
    """Host-side uint32 row hash over the key columns. Deterministic and
    dictionary-independent (ops/hashing.py), so the query plane can prune
    buckets by recomputing the same hash on a literal."""
    hashes = []
    for name in key_columns:
        f = table.schema.field(name)
        arr = table.columns[f.name]
        if f.is_string:
            dh = string_dict_hashes(table.dictionaries[f.name])
            h = dh[arr]
        else:
            h = hash_int_column(arr, np)
        valid = table.valid_mask(name)
        if valid is not None:
            h = np.where(valid, h, NULL_HASH)
        hashes.append(h)
    return combine_hashes(hashes, np)


def hash_scalar_key(values: list, fields) -> np.ndarray:
    """Hash one key tuple (for bucket pruning at query time)."""
    hs = []
    for v, f in zip(values, fields):
        if f.is_string:
            hs.append(string_dict_hashes(np.array([v], dtype=object)))
        else:
            hs.append(hash_int_column(np.array([v], dtype=f.device_dtype), np))
    return combine_hashes(hs, np)


def host_sort_perm(table: ColumnTable, key_columns: list[str]) -> np.ndarray:
    """Stable key-sort permutation on host: the native C++ kernel when
    available, else np.lexsort — both produce the identical stable order
    device_sort_perms reproduces, so the sort venue never changes
    bytes."""
    from hyperspace_tpu import native
    from hyperspace_tpu.ops.sortkeys import key_lanes, lanes_as_unsigned, lexsort_lanes

    lanes = key_lanes(table, key_columns)
    if native.available():
        perm = np.arange(table.num_rows, dtype=np.int64)
        native.sort_range(perm, lanes_as_unsigned(lanes))
        return perm
    return lexsort_lanes(lanes)


# -- chunked source decode ----------------------------------------------------


def decoded_chunks(
    files: list[str],
    fmt: str,
    columns,
    schema,
    chunk_bytes: int,
    memory_budget_bytes: int,
    footers=None,
):
    """Yield pyarrow Tables of ≤ ~chunk_bytes decoded source data,
    format-aware: parquet by footer-planned row groups, CSV by streamed
    record batches, ORC by stripes, JSON per file (pyarrow has no
    incremental JSON reader, so the memory bound holds per file there).
    Shared by the single-process streaming build (which drives it from
    the coordinator) and the pooled build's p1 shard workers (each over
    its own file slice)."""
    import pyarrow as pa

    if fmt == "parquet":
        chunks = hio.plan_row_group_chunks(files, chunk_bytes, columns, footers=footers)
        for c in chunks:
            yield hio.read_chunk(c, columns)
        return
    if fmt == "csv":
        from pyarrow import csv as pcsv

        types = hio._arrow_types_for(schema)
        for f in files:
            opts = pcsv.ConvertOptions(
                include_columns=list(columns) if columns is not None else None,
                column_types=types,
            )
            ropts = pcsv.ReadOptions(
                block_size=int(max(16 << 10, min(chunk_bytes // 4, (1 << 31) - 1)))
            )
            with pcsv.open_csv(f, read_options=ropts, convert_options=opts) as reader:
                buf, size = [], 0
                for batch in reader:
                    buf.append(batch)
                    size += batch.nbytes
                    if size >= chunk_bytes:
                        yield pa.Table.from_batches(buf)
                        buf, size = [], 0
                if buf:
                    yield pa.Table.from_batches(buf)
        return
    if fmt == "orc":
        from pyarrow import orc

        for f in files:
            o = orc.ORCFile(f)
            buf, size = [], 0
            for s in range(o.nstripes):
                rb = o.read_stripe(s, columns=list(columns) if columns is not None else None)
                buf.append(rb)
                size += rb.nbytes
                if size >= chunk_bytes:
                    yield pa.Table.from_batches(buf)
                    buf, size = [], 0
            if buf:
                yield pa.Table.from_batches(buf)
        return
    if fmt == "json":
        import os

        for f in files:
            # No incremental JSON reader exists in pyarrow: the bound
            # holds per FILE. A single file above the budget would
            # silently break it — fail with the actionable message
            # instead of OOMing.
            if os.stat(f).st_size * 4 > memory_budget_bytes:
                raise HyperspaceError(
                    f"json file {f} (~{os.stat(f).st_size * 4 >> 20} MiB decoded "
                    "estimate) exceeds the build memory budget and JSON has no "
                    "incremental reader; raise "
                    "hyperspace.index.build.memoryBudgetBytes, split the file, "
                    "or convert the source to parquet"
                )
            yield hio._read_one_file(f, "json", list(columns) if columns is not None else None, schema)
        return
    raise HyperspaceError(f"unsupported streaming source format {fmt!r}")


# -- exchange layout ----------------------------------------------------------


def slice_files(files: list[str], sizes: list[int], workers: int) -> list[list[str]]:
    """Partition the file list into ≤ workers *contiguous* slices,
    greedily balanced by byte size. Contiguity is a correctness
    invariant, not a convenience: shard-ordered spill concatenation must
    reproduce the global file order, so shard w may only hold files that
    come after every file of shard w-1. Never returns an empty slice
    (fewer files than workers ⇒ fewer slices)."""
    n = min(max(1, workers), len(files))
    if n <= 1:
        return [list(files)] if files else []
    total = sum(max(1, s) for s in sizes)
    target = total / n
    slices: list[list[str]] = []
    cur: list[str] = []
    cur_bytes = 0
    remaining = len(files)
    for f, s in zip(files, sizes):
        # Leave at least one file for each unstarted slice.
        must_break = len(slices) + 1 < n and remaining <= n - len(slices) - 1 + (0 if cur else 1)
        if cur and (cur_bytes >= target or must_break) and len(slices) + 1 < n:
            slices.append(cur)
            cur, cur_bytes = [], 0
        cur.append(f)
        cur_bytes += max(1, s)
        remaining -= 1
    if cur:
        slices.append(cur)
    return slices


def owner_of(bucket: int, num_owners: int) -> int:
    """bucket id → owner: the shard key of the exchange (the exact
    analogue of Spark's hash-shuffle partition → reducer mapping)."""
    return bucket % num_owners


def spill_path(exchange_dir: str | Path, owner: int, shard: int, bucket: int) -> Path:
    """Where shard `shard` spills bucket `bucket` for its owner: one
    parquet file per (shard, bucket), grouped per owner directory so a
    p2 worker reads exactly one directory."""
    return (
        Path(exchange_dir)
        / f"owner-{owner:05d}"
        / f"shard-{shard:05d}.bucket-{bucket:05d}.parquet"
    )


def _ordered_names(schema, columns: list[str], indexed_columns: list[str]):
    """(sub_schema, ordered column names): indexed columns first, then
    payload — the on-disk column order of every spill and bucket file
    (mirrors _write_streaming exactly)."""
    sub_schema = schema.select(columns)
    key_names = [sub_schema.field(c).name for c in indexed_columns]
    payload_names = [f.name for f in sub_schema.fields if f.name not in key_names]
    return sub_schema, key_names + payload_names


# -- worker bodies ------------------------------------------------------------


@dataclasses.dataclass
class P1Task:
    """One p1 shard worker's assignment (pickled into the spawned
    process): decode `files`, partition by bucket hash, spill per
    destination owner under `exchange_dir`."""

    worker: int
    files: list[str]
    fmt: str
    columns: list[str]
    schema: object  # Schema (picklable dataclasses)
    indexed_columns: list[str]
    num_buckets: int
    num_owners: int
    chunk_bytes: int
    memory_budget_bytes: int
    exchange_dir: str


@dataclasses.dataclass
class P2Task:
    """One p2 owner worker's assignment: read its buckets' spill files
    (shard order), key-sort, write final bucket files + stats. Carries
    the p1 decoded-byte ledger for its buckets so the one-ahead spill
    read stays under `window_bytes` without ever opening a spill
    footer."""

    owner: int
    num_owners: int
    n_shards: int
    num_buckets: int
    exchange_dir: str
    dest_dir: str
    columns: list[str]
    schema: object
    indexed_columns: list[str]
    spill_bytes: dict
    window_bytes: int


def p1_shard(task: P1Task) -> dict:
    """Phase-1 worker body: stream this shard's file slice through the
    chunked decode, hash/partition each chunk, and append per-bucket
    spill parquet into the destination owners' exchange directories.
    Returns {rows, chunks, spill_bytes} — the byte ledger p2 budgets
    from (no spill footer is ever re-opened)."""
    import pyarrow.parquet as pq

    sub_schema, ordered = _ordered_names(task.schema, task.columns, task.indexed_columns)
    writers: dict[int, pq.ParquetWriter] = {}
    paths: dict[int, Path] = {}
    spill_bytes: dict[int, int] = {}
    total_rows = 0
    n_chunks = 0
    with obs_trace.trace("build.p1.worker", worker=task.worker, files=len(task.files)):
        gen = decoded_chunks(
            task.files, task.fmt, task.columns, task.schema,
            task.chunk_bytes, task.memory_budget_bytes,
        )
        while True:
            with obs_trace.span("build.p1.decode"):
                at = next(gen, None)
            if at is None:
                break
            n_chunks += 1
            ct = ColumnTable.from_arrow(at, sub_schema).select(ordered)
            total_rows += ct.num_rows
            bucket = bucket_ids(
                compute_row_hashes(ct, task.indexed_columns), task.num_buckets, np
            )
            order = np.argsort(bucket, kind="stable")
            sb = bucket[order]
            starts = np.searchsorted(sb, np.arange(task.num_buckets + 1))
            arrow_sorted = ct.take(order).to_arrow()
            with obs_trace.span("build.p1.spill"):
                for b in range(task.num_buckets):
                    lo, hi = int(starts[b]), int(starts[b + 1])
                    if hi <= lo:
                        continue
                    w = writers.get(b)
                    if w is None:
                        path = spill_path(
                            task.exchange_dir, owner_of(b, task.num_owners),
                            task.worker, b,
                        )
                        path.parent.mkdir(parents=True, exist_ok=True)
                        # Same spill codec/dictionary policy as the
                        # single-process streaming build: engine-private
                        # scratch, cheap codec, strings-only dictionary.
                        w = pq.ParquetWriter(
                            path,
                            arrow_sorted.schema,
                            compression=hio.INDEX_WRITE_COMPRESSION,
                            write_statistics=False,
                            use_dictionary=[
                                f.name for f in sub_schema.select(ordered).fields if f.is_string
                            ],
                        )
                        writers[b] = w
                        paths[b] = path
                    part = arrow_sorted.slice(lo, hi - lo)
                    spill_bytes[b] = spill_bytes.get(b, 0) + part.nbytes
                    w.write_table(part)
        for b in sorted(writers):
            fault_point("build.exchange.write", paths[b])
            writers[b].close()
    return {
        "worker": task.worker,
        "rows": total_rows,
        "chunks": n_chunks,
        "spill_bytes": spill_bytes,
        "spill_files": {b: str(p) for b, p in paths.items()},
    }


def p2_owner(task: P2Task) -> dict:
    """Phase-2 worker body: for every owned bucket (ascending), read its
    spill files in shard order (reproducing the global row order), apply
    the stable host key sort, and write the final bucket file + manifest
    stats. A one-ahead spill read overlaps the sort/encode of the
    current bucket whenever both buckets' ledger bytes fit the per-worker
    window. Returns {bucket_rows, key_stats, col_stats} for the
    coordinator's manifest merge."""
    from concurrent.futures import ThreadPoolExecutor

    sub_schema, ordered = _ordered_names(task.schema, task.columns, task.indexed_columns)
    sel = sub_schema.select(ordered)
    first_key = sub_schema.field(task.indexed_columns[0]).name
    stat_cols = [f.name for f in sel.fields if not f.is_vector and f.name != first_key]
    dest = Path(task.dest_dir)
    owned = [b for b in range(task.num_buckets) if owner_of(b, task.num_owners) == task.owner]
    out_rows: dict[int, int] = {}
    out_key: dict[int, object] = {}
    out_col: dict[int, dict] = {}

    def read_bucket(b: int):
        paths = [
            spill_path(task.exchange_dir, task.owner, w, b) for w in range(task.n_shards)
        ]
        paths = [p for p in paths if p.exists()]
        if not paths:
            return None
        fault_point("build.exchange.read", paths[0])
        with obs_trace.span("build.p2.read", bucket=b, files=len(paths)):
            return hio.read_parquet([str(p) for p in paths])

    with obs_trace.trace("build.p2.worker", owner=task.owner, buckets=len(owned)):
        empty = ColumnTable.empty(sel)
        with ThreadPoolExecutor(max_workers=1) as ex:
            fut, fut_b = None, None
            for i, b in enumerate(owned):
                if fut is not None and fut_b == b:
                    t = fut.result()
                    fut = None
                else:
                    t = read_bucket(b)
                # One-ahead spill read, admitted only while BOTH buckets'
                # decoded ledger bytes fit the per-worker window — the
                # memory bound derived from maxInflightBytes.
                if i + 1 < len(owned):
                    nb = owned[i + 1]
                    if (
                        task.spill_bytes.get(nb, 0) + task.spill_bytes.get(b, 0)
                        <= task.window_bytes
                    ):
                        fut, fut_b = ex.submit(obs_trace.wrap(read_bucket), nb), nb
                if t is None:
                    hio.write_bucket(dest, b, empty)
                    out_rows[b] = 0
                    continue
                with obs_trace.span("build.p2.sort", bucket=b, rows=t.num_rows):
                    perm = host_sort_perm(t, task.indexed_columns)
                # Manifest stats pre-gather: min/max is permutation-
                # invariant, so this matches the serial path exactly.
                out_rows[b] = t.num_rows
                out_key[b] = hio.bucket_key_stats(t, first_key)
                if stat_cols:
                    out_col[b] = hio.bucket_column_stats(t, stat_cols)
                with obs_trace.span("build.p2.write", bucket=b):
                    hio.write_bucket(dest, b, t.take(perm))
    return {
        "owner": task.owner,
        "bucket_rows": out_rows,
        "key_stats": out_key,
        "col_stats": out_col,
    }
