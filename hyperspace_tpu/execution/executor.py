"""Plan executor: runs logical plans against the device plane.

The analog of Spark's physical planning + execution for the four node types
our IR has (SURVEY.md §7 design stance). What matters for TPU performance:

- **bucket pruning** (Filter over an index scan with equality literals on
  every bucket column): recompute the canonical row hash on the literal
  tuple and read ONLY that bucket's file — the reference cannot do this
  (its FilterIndexRule keeps a full scan, FilterIndexRule.scala:114-120);
  for a point lookup this divides IO by numBuckets;
- **zero-exchange join** (Join over two index scans bucketed on the join
  keys with equal bucket counts): per-bucket sort-merge join, all buckets
  in one vmapped device kernel (ops/join.py) — the analog of the
  reference's shuffle-free SortMergeJoin;
- predicates evaluate as one fused XLA computation (ops/filter.py).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.builder import compute_row_hashes, hash_scalar_key
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.dataset import list_data_files
from hyperspace_tpu.ops.filter import apply_filter
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.plan.expr import BinOp, Col, Expr, Lit, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan, Project, Scan, Union


@dataclasses.dataclass
class AlignedSide:
    scan: Scan
    project: list[str] | None  # columns to keep after the join gather
    # Hybrid scan: an unbucketed delta scan whose rows are bucketized
    # on the fly and merged into the index buckets before the SMJ.
    delta: Scan | None = None


@dataclasses.dataclass
class SideData:
    """One join side in concatenated bucket-grouped layout: rows of bucket
    b occupy [offsets[b], offsets[b+1])."""

    table: ColumnTable
    offsets: np.ndarray  # [B+1] int64
    sorted_within: bool  # buckets key-sorted (index files are)?


def _bucket_sorted_codes(codes: np.ndarray, side: SideData):
    """Ensure codes are non-decreasing within each bucket. Returns
    (sorted codes, perm) where perm maps sorted positions back to the
    side's row order (None when already sorted — the index-file case,
    verified with one vectorized pass)."""
    n = len(codes)
    if n == 0:
        return codes, None
    counts = np.diff(side.offsets)
    bucket_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    if side.sorted_within:
        d = np.diff(codes)
        same = bucket_of[:-1] == bucket_of[1:]
        if not np.any(d[same] < 0):
            return codes, None
    perm = np.lexsort((codes, bucket_of))  # stable; regroups identically
    return codes[perm], perm


def _pad_bucket_major(codes: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """[n] bucket-grouped codes → [B, Lmax] padded array (pads carry the
    dtype's max so they sort last), built with one vectorized gather."""
    counts = np.diff(offsets)
    b = len(counts)
    lmax = max(int(counts.max()) if counts.size else 1, 1)
    idx = offsets[:-1, None] + np.arange(lmax, dtype=np.int64)[None, :]
    mask = np.arange(lmax)[None, :] < counts[:, None]
    sentinel = join_ops.sentinel_for(codes.dtype)
    if len(codes) == 0:
        return np.full((b, lmax), sentinel, dtype=codes.dtype)
    return np.where(mask, codes[np.minimum(idx, len(codes) - 1)], sentinel)


class Executor:
    """Runs plans on the device plane. With a mesh, the query plane is
    distributed: the bucket-aligned SMJ shards its bucket dimension over
    the mesh (zero collectives — the analog of the reference's
    cluster-parallel zero-exchange SortMergeJoin across executors,
    JoinIndexRule.scala:124-153) and filter predicates shard their row
    dimension (FilterIndexRule.scala:114-120 keeps full scan parallelism).
    `stats` records what physically ran (files read, kernels, devices) —
    the executed-plan evidence explain consumes."""

    def __init__(self, mesh=None):
        self.mesh = mesh
        self.stats: dict = {
            "files_read": 0,
            "files_pruned": 0,
            "join_path": None,
            "join_devices": 1,
            "num_buckets": None,
        }

    def execute(self, plan: LogicalPlan) -> ColumnTable:
        from hyperspace_tpu.plan.prune import prune_columns

        return self._execute(prune_columns(plan))

    def _execute(self, plan: LogicalPlan) -> ColumnTable:
        if isinstance(plan, Scan):
            return self._scan(plan)
        if isinstance(plan, Filter):
            return self._filter(plan)
        if isinstance(plan, Project):
            return self._execute(plan.child).select(plan.columns)
        if isinstance(plan, Join):
            return self._join(plan)
        if isinstance(plan, Union):
            return self._union(plan)
        raise HyperspaceError(f"cannot execute plan node {type(plan).__name__}")

    # -- union (hybrid scan) ----------------------------------------------
    def _union(self, plan: Union) -> ColumnTable:
        schema = plan.schema
        parts = []
        for child in plan.inputs:
            t = self._execute(child)
            # Remap onto the union schema's exact field names/order (child
            # names are validated case-insensitively compatible).
            cols, dicts, val = {}, {}, {}
            for f in schema.fields:
                cf = t.schema.field(f.name)
                cols[f.name] = t.columns[cf.name]
                if cf.name in t.dictionaries:
                    dicts[f.name] = t.dictionaries[cf.name]
                if cf.name in t.validity:
                    val[f.name] = t.validity[cf.name]
            parts.append(ColumnTable(schema, cols, dicts, val))
        return ColumnTable.concat(parts)

    # -- scan ------------------------------------------------------------
    def _scan_files(self, scan: Scan) -> list[str]:
        if scan.files is not None:
            return list(scan.files)
        return [fi.path for fi in list_data_files(scan.root)]

    def _cached_read(self, files: list[str], columns, schema) -> ColumnTable:
        """Index-file read through the decoded-table cache; files_read
        counts only physical (miss) reads."""
        before = hio.table_cache_stats()["miss_files"]
        table = hio.read_parquet_cached(files, columns=columns, schema=schema)
        self.stats["files_read"] += hio.table_cache_stats()["miss_files"] - before
        return table

    def _scan(self, scan: Scan, columns: list[str] | None = None) -> ColumnTable:
        files = self._scan_files(scan)
        cols = columns if columns is not None else scan.scan_schema.names
        if scan.bucket_spec is not None:
            # Index files are immutable per version — cache their decode.
            return self._cached_read(files, cols, scan.scan_schema)
        self.stats["files_read"] += len(files)
        return hio.read_parquet(files, columns=cols, schema=scan.scan_schema)

    # -- filter (with index bucket pruning) ------------------------------
    def _filter(self, plan: Filter) -> ColumnTable:
        child = plan.child
        if isinstance(child, Scan) and child.bucket_spec is not None:
            pruned = self._prune_bucket_files(child, plan.predicate)
            if pruned is not None:
                table = self._cached_read(pruned, child.scan_schema.names, child.scan_schema)
                return apply_filter(table, plan.predicate, mesh=self.mesh)
        if isinstance(child, Union):
            # Hybrid scan: prune the bucketed input(s), keep deltas whole.
            new_inputs: list[LogicalPlan] = []
            for inp in child.inputs:
                if isinstance(inp, Scan) and inp.bucket_spec is not None:
                    pruned = self._prune_bucket_files(inp, plan.predicate)
                    if pruned is not None:
                        inp = dataclasses.replace(inp, files=pruned)
                new_inputs.append(inp)
            return apply_filter(self._union(Union(new_inputs)), plan.predicate, mesh=self.mesh)
        return apply_filter(self._execute(child), plan.predicate, mesh=self.mesh)

    def _prune_bucket_files(self, scan: Scan, predicate: Expr) -> list[str] | None:
        """If the predicate pins every bucket column with an equality
        literal, return only the owning bucket's file."""
        num_buckets, bucket_cols = scan.bucket_spec
        eq_lits: dict[str, object] = {}
        for conj in split_conjuncts(predicate):
            if isinstance(conj, BinOp) and conj.op == "eq":
                if isinstance(conj.left, Col) and isinstance(conj.right, Lit):
                    eq_lits[conj.left.name.lower()] = conj.right.value
                elif isinstance(conj.right, Col) and isinstance(conj.left, Lit):
                    eq_lits[conj.right.name.lower()] = conj.left.value
        try:
            values = [eq_lits[c.lower()] for c in bucket_cols]
        except KeyError:
            return None
        fields = [scan.scan_schema.field(c) for c in bucket_cols]
        h = hash_scalar_key(values, fields)
        b = int(bucket_ids(h, num_buckets, np)[0])
        files = self._scan_files(scan)
        name = hio.bucket_file_name(b)
        matches = [f for f in files if Path(f).name == name]
        if matches:
            self.stats["files_pruned"] += len(files) - len(matches)
            return matches
        return None

    # -- join ------------------------------------------------------------
    def _join(self, plan: Join) -> ColumnTable:
        left_side = self._aligned_side(plan.left)
        right_side = self._aligned_side(plan.right)
        if (
            left_side is not None
            and right_side is not None
            and left_side.scan.bucket_spec is not None
            and right_side.scan.bucket_spec is not None
            and left_side.scan.bucket_spec[0] == right_side.scan.bucket_spec[0]
            and [c.lower() for c in left_side.scan.bucket_spec[1]] == [c.lower() for c in plan.left_on]
            and [c.lower() for c in right_side.scan.bucket_spec[1]] == [c.lower() for c in plan.right_on]
        ):
            self.stats["join_path"] = "zero-exchange-aligned"
            return self._aligned_join(plan, left_side, right_side)
        # General path: single partition (bucket count 1).
        self.stats["join_path"] = "single-partition"
        lt = self._execute(plan.left)
        rt = self._execute(plan.right)
        one = lambda t: SideData(t, np.array([0, t.num_rows], dtype=np.int64), False)  # noqa: E731
        return self._partition_join(plan, one(lt), one(rt))

    def _aligned_side(self, plan: LogicalPlan) -> AlignedSide | None:
        node, project = plan, None
        if isinstance(node, Project):
            project = node.columns
            node = node.child
        if isinstance(node, Union) and len(node.inputs) == 2:
            base, delta = node.inputs
            if isinstance(delta, Project) and isinstance(delta.child, Scan):
                delta = delta.child
            if (
                isinstance(base, Scan)
                and base.bucket_spec is not None
                and isinstance(delta, Scan)
                and delta.bucket_spec is None
            ):
                return AlignedSide(base, project, delta=delta)
            return None
        if isinstance(node, Scan):
            return AlignedSide(node, project)
        return None

    def _side_data(self, side: AlignedSide, num_buckets: int) -> "SideData":
        """One concatenated bucket-grouped table per join side (bucket
        files read in parallel through the decoded-table cache), plus
        (hybrid scan) delta rows bucketized on the fly with the same
        canonical row hash the build used."""
        from concurrent.futures import ThreadPoolExecutor

        schema = side.scan.scan_schema
        groups = self._bucket_files_in_order(side.scan, num_buckets)
        before = hio.table_cache_stats()["miss_files"]
        with ThreadPoolExecutor(max_workers=8) as pool:
            tables = list(
                pool.map(lambda g: hio.read_parquet_cached(g, columns=schema.names, schema=schema), groups)
            )
        self.stats["files_read"] += hio.table_cache_stats()["miss_files"] - before
        counts = np.array([t.num_rows for t in tables], dtype=np.int64)
        base = ColumnTable.concat(tables)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        sorted_within = all(len(g) == 1 for g in groups)
        if side.delta is not None:
            dt = self._scan(side.delta, columns=list(schema.names))
            # Hash on the bucket columns in BUILD order (not join-key
            # order) so delta rows land in the same buckets the index used.
            row_hash = compute_row_hashes(dt, side.scan.bucket_spec[1])
            db = bucket_ids(row_hash, num_buckets, np)
            all_bucket = np.concatenate(
                [np.repeat(np.arange(num_buckets, dtype=np.int32), counts), db]
            )
            combined = ColumnTable.concat([base, dt])
            order = np.argsort(all_bucket, kind="stable")
            counts2 = np.bincount(all_bucket, minlength=num_buckets)
            offsets = np.concatenate([[0], np.cumsum(counts2)]).astype(np.int64)
            return SideData(combined.take(order), offsets, False)
        return SideData(base, offsets, sorted_within)

    def _aligned_join(self, plan: Join, left: AlignedSide, right: AlignedSide) -> ColumnTable:
        """Bucket-aligned zero-exchange SMJ: both sides arrive grouped by
        the same bucket function, so per-bucket merge joins concatenated
        equal the global join."""
        num_buckets = left.scan.bucket_spec[0]
        lside = self._side_data(left, num_buckets)
        rside = self._side_data(right, num_buckets)
        out = self._partition_join(plan, lside, rside)
        cols = None
        if left.project is not None or right.project is not None:
            keep = list(left.project if left.project is not None else left.scan.scan_schema.names)
            rkeys = {k.lower() for k in plan.right_on}
            for c in right.project if right.project is not None else right.scan.scan_schema.names:
                if c.lower() not in rkeys and c.lower() not in {k.lower() for k in keep}:
                    keep.append(c)
            cols = keep
        return out.select(cols) if cols is not None else out

    def _bucket_files_in_order(self, scan: Scan, num_buckets: int) -> list[list[str]]:
        """Per-bucket file groups. A bucket can have several files (base
        version + incremental-refresh deltas); order within a group is the
        sorted file-path order."""
        files = self._scan_files(scan)
        by_name: dict[str, list[str]] = {}
        for f in sorted(files):
            by_name.setdefault(Path(f).name, []).append(f)
        out = []
        for b in range(num_buckets):
            name = hio.bucket_file_name(b)
            if name not in by_name:
                raise HyperspaceError(f"missing bucket file {name} in {scan.root}")
            out.append(by_name[name])
        return out

    def _partition_join(self, plan: Join, lside: "SideData", rside: "SideData") -> ColumnTable:
        """Per-bucket merge join over the concatenated bucket-grouped
        layout: everything host-side is vectorized (pad-gather in, one
        repeat+add to globalize match indices, ONE native gather per
        column out) — no per-bucket Python loop (round 1 weakness #4)."""
        lt, rt = lside.table, rside.table
        lkeys = [lt.schema.field(c).name for c in plan.left_on]
        rkeys = [rt.schema.field(c).name for c in plan.right_on]

        # Shared order-preserving factorization of the key tuples.
        lc, rc = _factorize_keys([lt], [rt], lkeys, rkeys)
        lcodes, rcodes = lc[0], rc[0]

        lcodes, lperm = _bucket_sorted_codes(lcodes, lside)
        rcodes, rperm = _bucket_sorted_codes(rcodes, rside)
        lk = _pad_bucket_major(lcodes, lside.offsets)
        rk = _pad_bucket_major(rcodes, rside.offsets)
        b = lk.shape[0]

        if self.mesh is not None:
            from hyperspace_tpu.parallel.mesh import mesh_for_parallelism, mesh_size

            jmesh = mesh_for_parallelism(self.mesh, b)
            li_flat, ri_flat, totals = join_ops.merge_join_sharded(lk, rk, jmesh)
            self.stats["join_devices"] = mesh_size(jmesh)
        else:
            li_flat, ri_flat, totals = join_ops.merge_join(lk, rk)
        self.stats["num_buckets"] = b

        # Local (within-bucket) match indices → global row indices.
        lidx = np.repeat(lside.offsets[:-1], totals) + li_flat
        ridx = np.repeat(rside.offsets[:-1], totals) + ri_flat
        if lperm is not None:
            lidx = lperm[lidx]
        if rperm is not None:
            ridx = rperm[ridx]

        rkeys_low = {k.lower() for k in rkeys}
        lgather = lt.take(lidx)
        cols = dict(lgather.columns)
        dicts = dict(lgather.dictionaries)
        val = dict(lgather.validity)
        rnames = [f.name for f in rt.schema.fields if f.name.lower() not in rkeys_low]
        rgather = rt.select(rnames).take(ridx)
        cols.update(rgather.columns)
        dicts.update(rgather.dictionaries)
        val.update(rgather.validity)
        return ColumnTable(plan.schema, cols, dicts, val)


def _key_null_mask(table: ColumnTable, keys: list[str]) -> np.ndarray | None:
    """True where ANY key column is null (such rows never join — SQL:
    NULL = NULL is not true). None when every key column is null-free."""
    m = None
    for k in keys:
        valid = table.valid_mask(k)
        if valid is not None:
            m = ~valid if m is None else (m | ~valid)
    return m


def _apply_null_codes(lcodes, rcodes, lnulls, rnulls):
    """Null-keyed rows get side-distinct negative codes (-2 left, -1
    right): they sort first and can never equal across sides, so the merge
    kernel drops them with zero extra work."""
    for c, m in zip(lcodes, lnulls):
        if m is not None:
            c[m] = -2
    for c, m in zip(rcodes, rnulls):
        if m is not None:
            c[m] = -1
    return lcodes, rcodes


def _factorize_keys(ltables, rtables, lkeys, rkeys):
    """Map each partition's key tuples to a shared int32 rank-code space
    whose order matches the lexicographic order of the raw key tuples.
    int32 keeps the device merge-join kernels on native 32-bit lanes (TPU
    emulates 64-bit); ranks always fit (bounded by total row count)."""
    lnulls = [_key_null_mask(t, lkeys) for t in ltables]
    rnulls = [_key_null_mask(t, rkeys) for t in rtables]
    has_nulls = any(m is not None for m in lnulls + rnulls)
    # Fast path: a single integer key whose values already fit int32 needs
    # no ranking at all — the raw values ARE order-preserving codes.
    # (Skipped with nulls: raw values could collide with the null codes.)
    if len(lkeys) == 1 and not has_nulls:
        lvals = [_logical_key(t, lkeys[0]) for t in ltables]
        rvals = [_logical_key(t, rkeys[0]) for t in rtables]
        if all(np.issubdtype(v.dtype, np.integer) for v in lvals + rvals):
            lo = min((int(v.min()) for v in lvals + rvals if len(v)), default=0)
            hi = max((int(v.max()) for v in lvals + rvals if len(v)), default=0)
            # Strictly below int32 max: the sentinel pad must sort last.
            if lo >= np.iinfo(np.int32).min and hi < np.iinfo(np.int32).max:
                return (
                    [v.astype(np.int32) for v in lvals],
                    [v.astype(np.int32) for v in rvals],
                )

    per_col_codes_l: list[list[np.ndarray]] = [[] for _ in ltables]
    per_col_codes_r: list[list[np.ndarray]] = [[] for _ in rtables]
    cards: list[int] = []
    for lname, rname in zip(lkeys, rkeys):
        lvals = [_logical_key(t, lname) for t in ltables]
        rvals = [_logical_key(t, rname) for t in rtables]
        allv = np.concatenate(lvals + rvals) if (lvals or rvals) else np.array([])
        uniq, inv = np.unique(allv, return_inverse=True)
        cards.append(max(len(uniq), 1))
        pos = 0
        for i, v in enumerate(lvals):
            per_col_codes_l[i].append(inv[pos : pos + len(v)])
            pos += len(v)
        for i, v in enumerate(rvals):
            per_col_codes_r[i].append(inv[pos : pos + len(v)])
            pos += len(v)

    def combine(per_part):
        out = []
        for codes in per_part:
            acc = np.zeros(len(codes[0]) if codes else 0, dtype=np.int64)
            for c, k in zip(codes, cards):
                acc = acc * np.int64(k) + c.astype(np.int64)
            out.append(acc)
        return out

    import math

    if math.prod(cards) >= np.iinfo(np.int64).max:
        # The int64 mixed-radix combination itself would wrap — the codes
        # in `combine` below would collide before any re-rank could help.
        raise HyperspaceError(
            f"join key cardinalities {cards} overflow the int64 code space"
        )
    lcomb, rcomb = combine(per_col_codes_l), combine(per_col_codes_r)
    int32_max = np.iinfo(np.int32).max
    # Mixed-radix codes that provably fit int32 cast directly — no
    # re-rank pass needed (math.prod is exact, arbitrary precision).
    if math.prod(cards) < int32_max:
        return _apply_null_codes(
            [c.astype(np.int32) for c in lcomb],
            [c.astype(np.int32) for c in rcomb],
            lnulls,
            rnulls,
        )
    # Otherwise re-rank the combined codes down to int32 (order preserved
    # by np.unique).
    allc = np.concatenate(lcomb + rcomb) if (lcomb or rcomb) else np.zeros(0, np.int64)
    uniq, inv = np.unique(allc, return_inverse=True)
    if len(uniq) >= int32_max:
        raise HyperspaceError(
            f"join key space has {len(uniq)} distinct tuples — exceeds the "
            "int32 code space"
        )
    inv = inv.astype(np.int32)
    pos, out_l, out_r = 0, [], []
    for c in lcomb:
        out_l.append(inv[pos : pos + len(c)])
        pos += len(c)
    for c in rcomb:
        out_r.append(inv[pos : pos + len(c)])
        pos += len(c)
    return _apply_null_codes(out_l, out_r, lnulls, rnulls)


def _logical_key(table: ColumnTable, name: str) -> np.ndarray:
    f = table.schema.field(name)
    arr = table.columns[f.name]
    if f.is_string:
        return table.dictionaries[f.name][arr]
    return arr
