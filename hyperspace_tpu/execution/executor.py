"""Plan executor: runs logical plans against the device plane.

The analog of Spark's physical planning + execution for the four node types
our IR has (SURVEY.md §7 design stance). What matters for TPU performance:

- **bucket pruning** (Filter over an index scan with equality literals on
  every bucket column): recompute the canonical row hash on the literal
  tuple and read ONLY that bucket's file — the reference cannot do this
  (its FilterIndexRule keeps a full scan, FilterIndexRule.scala:114-120);
  for a point lookup this divides IO by numBuckets;
- **zero-exchange join** (Join over two index scans bucketed on the join
  keys with equal bucket counts): per-bucket sort-merge join, all buckets
  in one vmapped device kernel (ops/join.py) — the analog of the
  reference's shuffle-free SortMergeJoin;
- predicates evaluate as one fused XLA computation (ops/filter.py).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path

import numpy as np

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.builder import compute_row_hashes, hash_scalar_key
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.dataset import format_suffix, list_data_files
from hyperspace_tpu.ops.filter import apply_filter, eval_predicate_mask
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.plan.expr import And, BinOp, Col, Expr, Lit, evaluate, split_conjuncts
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
    Window,
)


@dataclasses.dataclass
class _TableLeaf(LogicalPlan):
    """Executor-internal leaf wrapping an already-materialized table
    (partial-aggregation pushdown splices one under a Join). Never
    serialized; never seen by the rules."""

    table: ColumnTable

    @property
    def schema(self):
        return self.table.schema

    def children(self) -> list[LogicalPlan]:
        return []

    def to_json(self):
        raise HyperspaceError("_TableLeaf is executor-internal")


@dataclasses.dataclass
class AlignedSide:
    scan: Scan
    project: list[str] | None  # columns to keep after the join gather
    # Hybrid scan: unbucketed delta scans whose rows are bucketized
    # on the fly and merged into the index buckets before the SMJ.
    # Any number of deltas is accepted (a Union of the index scan with
    # several appended-file scans, not just the canonical two-input
    # shape the rewrite rule emits today).
    deltas: tuple[Scan, ...] = ()
    # Side-local filter (JoinIndexRule keeps linear sides with filters):
    # applied per bucket BEFORE the merge, preserving bucket grouping and
    # within-bucket sort order (a filtered subsequence stays sorted).
    predicate: Expr | None = None


@dataclasses.dataclass
class SideData:
    """One join side in concatenated bucket-grouped layout: rows of bucket
    b occupy [offsets[b], offsets[b+1])."""

    table: ColumnTable
    offsets: np.ndarray  # [B+1] int64
    sorted_within: bool  # buckets key-sorted (index files are)?
    # Fields defining the bucket hash domain (the dtypes the row hash was
    # computed in) — two bucketings pair only when these are compatible.
    hash_fields: tuple | None = None


def _hash_fields_compatible(a, b) -> bool:
    """Equal key values bucket identically under both domains."""
    if a is None or b is None or len(a) != len(b):
        return False
    for fa, fb in zip(a, b):
        if fa.is_string != fb.is_string:
            return False
        if not fa.is_string and np.dtype(fa.device_dtype) != np.dtype(fb.device_dtype):
            return False
    return True


def _filter_side(side: SideData, predicate, mesh, venue: str = "auto") -> SideData:
    """Apply a side-local filter to bucket-grouped data, recomputing the
    bucket offsets over the surviving rows (grouping and within-bucket
    order are preserved — a filtered subsequence stays sorted)."""
    t = side.table
    if t.num_rows == 0:
        return side
    mask = eval_predicate_mask(t, predicate, mesh=mesh, venue=venue)
    counts = np.diff(side.offsets)
    bucket_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    new_counts = np.bincount(bucket_of[mask], minlength=len(counts))
    offsets = np.concatenate([[0], np.cumsum(new_counts)]).astype(np.int64)
    return SideData(t.filter_mask(mask), offsets, side.sorted_within)


def _bucket_sorted_codes(codes: np.ndarray, side: SideData):
    """Ensure codes are non-decreasing within each bucket. Returns
    (sorted codes, perm) where perm maps sorted positions back to the
    side's row order (None when already sorted — the index-file case,
    verified with one vectorized pass, memoized for stable codes)."""
    from hyperspace_tpu.execution import device_cache as dc

    n = len(codes)
    if n == 0:
        return codes, None
    if side.sorted_within:

        def check() -> bool:
            counts0 = np.diff(side.offsets)
            b_of = np.repeat(np.arange(len(counts0), dtype=np.int64), counts0)
            d = np.diff(codes)
            return not np.any(d[b_of[:-1] == b_of[1:]] < 0)

        if dc.is_stable(codes):
            ok = dc.HOST_DERIVED.get_or_build(
                ("sortck", id(codes), side.offsets.tobytes()),
                (codes,),
                lambda: (check(), 1),
            )
        else:
            ok = check()
        if ok:
            return codes, None
    counts = np.diff(side.offsets)
    bucket_of = np.repeat(np.arange(len(counts), dtype=np.int64), counts)
    perm = np.lexsort((codes, bucket_of))  # stable; regroups identically
    return codes[perm], perm


@dataclasses.dataclass
class KeyBounds:
    """Conjunct bounds on one column: lo/hi literal (None = unbounded) and
    whether each bound is strict (< / >) rather than inclusive."""

    lo: object = None
    lo_strict: bool = False
    hi: object = None
    hi_strict: bool = False


_FLIP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}


def _conjunct_col_lit(conj) -> tuple[str, str, object] | None:
    """Destructure one conjunct as (column, op, literal), normalizing
    `lit op col` by flipping the comparison. NaN literals are rejected
    (they defeat ordered-bound reasoning: every comparison is False, but
    searchsorted treats NaN as largest). Returns None otherwise."""
    if not isinstance(conj, BinOp):
        return None
    op = conj.op
    if isinstance(conj.left, Col) and isinstance(conj.right, Lit):
        name, v = conj.left.name, conj.right.value
    elif isinstance(conj.right, Col) and isinstance(conj.left, Lit):
        name, v = conj.right.name, conj.left.value
        op = _FLIP.get(op, op)
    else:
        return None
    if v is None:
        return None
    if isinstance(v, (float, np.floating)) and np.isnan(v):
        return None
    return name, op, v


def _like_prefix(pattern: str) -> str | None:
    """The literal prefix of a prefix-shaped LIKE pattern ('PROMO%'), or
    None when the pattern isn't prefix-shaped."""
    if pattern.endswith("%") and len(pattern) > 1:
        body = pattern[:-1]
        if "%" not in body and "_" not in body:
            return body
    return None


def _prefix_upper(prefix: str) -> str | None:
    """Smallest string ABOVE every string with `prefix` (exclusive upper
    bound for prefix matching); None when the last char can't increment."""
    last = ord(prefix[-1])
    if last >= 0x10FFFF:
        return None
    return prefix[:-1] + chr(last + 1)


def _conjunct_bound_ops(conj, key: str) -> list[tuple[str, object]] | None:
    """One conjunct → literal (op, value) bounds it implies on `key`:
    plain comparisons pass through; IN gives its min/max envelope; a
    prefix LIKE gives [prefix, next-prefix). The residual filter mask
    still applies the exact predicate — bounds only need to be a valid
    superset."""
    from hyperspace_tpu.plan.expr import InList, Like

    if isinstance(conj, InList) and isinstance(conj.child, Col):
        if conj.child.name.lower() != key:
            return None
        vals = conj.values
        if any(isinstance(v, (float, np.floating)) and np.isnan(v) for v in vals):
            return None
        try:
            return [("ge", min(vals)), ("le", max(vals))]
        except TypeError:
            return None
    if isinstance(conj, Like) and isinstance(conj.child, Col):
        if conj.child.name.lower() != key:
            return None
        prefix = _like_prefix(conj.pattern)
        if prefix is None:
            if "%" not in conj.pattern and "_" not in conj.pattern:
                return [("eq", conj.pattern)]  # wildcard-free LIKE = equality
            return None
        out: list[tuple[str, object]] = [("ge", prefix)]
        upper = _prefix_upper(prefix)
        if upper is not None:
            out.append(("lt", upper))
        return out
    if isinstance(conj, BinOp) and conj.is_comparison:
        from hyperspace_tpu.ops.filter import _translate_date_part_cmp
        from hyperspace_tpu.plan.expr import DatePart

        l, r, op = conj.left, conj.right, conj.op
        if isinstance(r, DatePart) and isinstance(l, Lit):
            l, r, op = r, l, _FLIP.get(op, op)
        if isinstance(l, DatePart) and isinstance(r, Lit):
            # year(d) OP lit → the same day-range tree the filter layer
            # lowers to; recurse so the range feeds pruning too.
            t = _translate_date_part_cmp(op, l, r.value)
            if t is None:
                return None
            out: list[tuple[str, object]] = []
            for sub in split_conjuncts(t):
                pairs = _conjunct_bound_ops(sub, key)
                if pairs is None:
                    return None  # ne-shaped (an OR): not a conjunct bound
                out.extend(pairs)
            return out
    dec = _conjunct_col_lit(conj)
    if dec is None:
        return None
    name, op, v = dec
    if name.lower() != key or op not in ("eq", "lt", "le", "gt", "ge"):
        return None
    return [(op, v)]


def key_bounds(predicate: Expr, key: str) -> KeyBounds | None:
    """Extract literal comparison bounds on `key` from the predicate's
    conjuncts (key op lit / lit op key; eq pins both ends; IN gives its
    envelope; prefix LIKE gives a string range). Returns None when no
    conjunct bounds the column. Incomparable literal types are ignored
    (the residual filter mask still applies them exactly)."""
    key = key.lower()
    b = KeyBounds()
    found = False
    for conj in split_conjuncts(predicate):
        pairs = _conjunct_bound_ops(conj, key)
        if pairs is None:
            continue
        for op, v in pairs:
            try:
                if op in ("gt", "ge", "eq") and (
                    b.lo is None or v > b.lo or (v == b.lo and op == "gt")
                ):
                    b.lo, b.lo_strict = v, op == "gt"
                    found = True
                if op in ("lt", "le", "eq") and (
                    b.hi is None or v < b.hi or (v == b.hi and op == "lt")
                ):
                    b.hi, b.hi_strict = v, op == "lt"
                    found = True
            except TypeError:
                continue
    return b if found else None


def predicate_all_key_bounds(predicate: Expr, key: str) -> bool:
    """True iff EVERY conjunct is a comparable literal bound on `key`
    (eq/lt/le/gt/ge) — i.e. an exact searchsorted slice on the sorted key
    fully implements the predicate and the residual mask is redundant."""
    key = key.lower()
    for conj in split_conjuncts(predicate):
        dec = _conjunct_col_lit(conj)
        if dec is None:
            return False
        name, op, v = dec
        if name.lower() != key or op not in ("eq", "lt", "le", "gt", "ge"):
            return False
        if not isinstance(v, (int, float, bool, np.number)):
            return False
    return True


def _stats_overlap(bounds: KeyBounds, mn, mx) -> bool:
    """Can any value in [mn, mx] satisfy the bounds?"""
    try:
        if bounds.hi is not None and (mn > bounds.hi or (bounds.hi_strict and mn == bounds.hi)):
            return False
        if bounds.lo is not None and (mx < bounds.lo or (bounds.lo_strict and mx == bounds.lo)):
            return False
    except TypeError:
        return True  # incomparable stats: keep the file
    return True


def _bounds_domain(field, bounds: KeyBounds):
    """Conversion putting pruning comparisons in the SAME numeric domain
    the filter mask uses (ops/filter.py _lower_col_lit's numpy promotion):
    float32 columns compare weak scalars in float32 (the literal ROUNDS),
    and int columns compare float literals in float64. Without this,
    pruning could drop rows the mask would keep. Returns None when raw
    comparison already matches (ints vs ints, strings)."""
    dt = field.device_dtype
    vals = [v for v in (bounds.lo, bounds.hi) if v is not None]
    if dt.kind == "f":
        weak = all(
            type(v) in (int, float, bool) or isinstance(v, (np.bool_, np.float32))
            for v in vals
        )
        return np.float32 if (dt.itemsize <= 4 and weak) else np.float64
    if dt.kind in "iu" and any(isinstance(v, (float, np.floating)) for v in vals):
        return np.float64
    return None


def _convert_bounds(field, bounds: KeyBounds) -> tuple[KeyBounds, object]:
    """(bounds cast into the comparison domain, stat-value converter)."""
    conv = _bounds_domain(field, bounds)
    if conv is None:
        return bounds, lambda v: v
    try:
        cast = KeyBounds(
            conv(bounds.lo) if bounds.lo is not None else None,
            bounds.lo_strict,
            conv(bounds.hi) if bounds.hi is not None else None,
            bounds.hi_strict,
        )
    except (TypeError, ValueError, OverflowError):
        return bounds, lambda v: v
    def stat_conv(v):
        try:
            return conv(v)
        except (TypeError, ValueError, OverflowError):
            return v
    return cast, stat_conv


def _pad_bucket_major(
    codes: np.ndarray,
    offsets: np.ndarray,
    fill=None,
    width: int | None = None,
) -> np.ndarray:
    """[n] bucket-grouped values → [B, L] padded array, built with one
    vectorized gather. Default fill is the dtype's sort-last sentinel
    (key codes); value channels pass an explicit fill and width."""
    counts = np.diff(offsets)
    b = len(counts)
    lmax = width if width is not None else max(int(counts.max()) if counts.size else 1, 1)
    sentinel = join_ops.sentinel_for(codes.dtype) if fill is None else fill
    if len(codes) == 0:
        return np.full((b, lmax), sentinel, dtype=codes.dtype)
    idx = offsets[:-1, None] + np.arange(lmax, dtype=np.int64)[None, :]
    mask = np.arange(lmax)[None, :] < counts[:, None]
    return np.where(mask, codes[np.minimum(idx, len(codes) - 1)], sentinel)


class Executor:
    """Runs plans on the device plane. With a mesh, the query plane is
    distributed: the bucket-aligned SMJ shards its bucket dimension over
    the mesh (zero collectives — the analog of the reference's
    cluster-parallel zero-exchange SortMergeJoin across executors,
    JoinIndexRule.scala:124-153) and filter predicates shard their row
    dimension (FilterIndexRule.scala:114-120 keeps full scan parallelism).
    `stats` records what physically ran (files read, kernels, devices) —
    the executed-plan evidence explain consumes."""

    def __init__(self, mesh=None, conf=None):
        self.mesh = mesh
        self.conf = conf
        self.stats: dict = {
            "files_read": 0,
            "files_pruned": 0,
            "rows_pruned": 0,
            "join_path": None,
            "join_kernel": None,
            "join_devices": 1,
            "num_buckets": None,
            "agg_path": None,
        }
        # Executed physical plan, built as the query runs (the analog of
        # the reference diffing executedPlans, PlanAnalyzer.scala:163-178).
        self.physical_plan = None
        self._cur_phys = None
        # Bucket-preserving join outputs: id(table) -> (weakref, offsets,
        # lowered key names, hash-domain fields). Bounded; weakrefs keep
        # id-reuse from matching a dead table.
        self._bucketed_outputs: dict[int, tuple] = {}

    def _stash_bucketed(self, table: ColumnTable, offsets, keys, hash_fields) -> None:
        import weakref

        if len(self._bucketed_outputs) >= 16:
            self._bucketed_outputs.clear()
        self._bucketed_outputs[id(table)] = (
            weakref.ref(table),
            offsets,
            tuple(k.lower() for k in keys),
            hash_fields,
        )

    def _preserved_sidedata(self, table: ColumnTable, join_on: list[str]) -> "SideData | None":
        e = self._bucketed_outputs.get(id(table))
        if e is None or e[0]() is not table:
            return None
        if e[2] != tuple(k.lower() for k in join_on):
            return None
        return SideData(table, e[1], False, hash_fields=e[3])

    def _propagate_stash(self, src: ColumnTable, dst: ColumnTable) -> ColumnTable:
        """Row-preserving transforms (column selection) keep a stashed
        bucket grouping valid — carry it to the derived table so chained
        star joins still find it (select() builds a NEW ColumnTable, so
        identity lookups would otherwise go dead)."""
        e = self._bucketed_outputs.get(id(src))
        if e is not None and e[0]() is src and dst is not src:
            names = {n.lower() for n in dst.schema.names}
            if all(k in names for k in e[2]):  # bucket keys survived
                self._stash_bucketed(dst, e[1], list(e[2]), e[3])
        return dst

    def execute(self, plan: LogicalPlan) -> ColumnTable:
        from hyperspace_tpu.plan.prune import prune_columns
        from hyperspace_tpu.plan.pushdown import push_down_filters

        return self._execute(prune_columns(push_down_filters(plan)))

    def _execute(self, plan: LogicalPlan) -> ColumnTable:
        from hyperspace_tpu.execution.physical import PhysicalNode

        node = PhysicalNode(op=type(plan).__name__)
        parent, self._cur_phys = self._cur_phys, node
        if parent is not None:
            parent.children.append(node)
        else:
            self.physical_plan = node
        files_before = self.stats["files_read"]
        try:
            result = self._dispatch(plan)
        finally:
            self._cur_phys = parent
        # Physical file IO attributed to THIS operator = its frame's delta
        # minus what child frames already claimed.
        subtree = self.stats["files_read"] - files_before
        node._subtree_files = subtree
        own = subtree - sum(getattr(c, "_subtree_files", 0) for c in node.children)
        if own > 0:
            node.detail.setdefault("files", own)
        node.rows_out = result.num_rows
        return result

    def _dispatch(self, plan: LogicalPlan) -> ColumnTable:
        if isinstance(plan, Scan):
            # Labeled here, not in _scan: _scan also runs as a subroutine
            # of other operators (hybrid delta reads) whose node must not
            # be renamed.
            if plan.bucket_spec is not None:
                self._phys("IndexScan", buckets=plan.bucket_spec[0])
            else:
                self._phys("TableScan")
            return self._scan(plan)
        if isinstance(plan, Filter):
            return self._filter(plan)
        if isinstance(plan, Project):
            self._cur_phys.detail["columns"] = list(plan.output_names)
            child = self._execute(plan.child)
            if plan.is_simple:
                return self._propagate_stash(child, child.select(plan.columns))
            from hyperspace_tpu.ops.project import project_table

            self._phys(
                "ProjectCompute",
                computed=[c[0] for c in plan.columns if not isinstance(c, str)],
            )
            return project_table(child, plan.columns, plan.schema)
        if isinstance(plan, Join):
            return self._join(plan)
        if isinstance(plan, Union):
            self._cur_phys.op = "HybridScanUnion"
            return self._union(plan)
        if isinstance(plan, Aggregate):
            return self._aggregate(plan)
        if isinstance(plan, Window):
            from hyperspace_tpu.ops.window import window_table

            t = self._execute(plan.child)
            self._phys(
                "WindowSortedSegments",
                partitions=list(plan.partition_by),
                frame=plan.frame,
                funcs=[f.fn for f in plan.funcs],
            )
            return window_table(
                t, plan.partition_by, plan.order_by, plan.funcs, plan.frame, plan.schema
            )
        if isinstance(plan, Sort):
            return self._sort(plan)
        if isinstance(plan, _TableLeaf):
            return plan.table
        if isinstance(plan, Limit):
            self._cur_phys.detail["n"] = plan.n
            if isinstance(plan.child, Sort):
                return self._top_n(plan.child, plan.n)
            early = self._limit_early_out(plan.child, plan.n)
            if early is not None:
                return early
            t = self._execute(plan.child)
            return t.take(np.arange(min(plan.n, t.num_rows)))
        raise HyperspaceError(f"cannot execute plan node {type(plan).__name__}")

    def _limit_early_out(self, child: LogicalPlan, n: int) -> ColumnTable | None:
        """LIMIT over an unordered linear scan chain: pull rows file by
        file and STOP once n rows survive, instead of materializing the
        whole child (any n rows are a correct answer without ORDER BY —
        the analog of Spark's CollectLimit incremental take). Returns
        None when the shape doesn't apply (non-linear child, single
        file, pinned hybrid scans)."""
        import functools

        chain: list[LogicalPlan] = []
        node = child
        while isinstance(node, (Project, Filter)):
            chain.append(node)
            node = node.child
        if not isinstance(node, Scan):
            return None
        files = self._scan_files(node)
        preds = [w.predicate for w in chain if isinstance(w, Filter)]
        if node.bucket_spec is not None and preds:
            # Index scans prune FIRST — a point lookup must stay a
            # single-file IndexPointLookup, not a file-by-file walk
            # through non-owning buckets.
            pred = functools.reduce(And, preds)
            pruned = self._prune_bucket_files(node, pred)
            if pruned is None:
                ranged = self._range_prune_list(node, pred)
                pruned = ranged[0] if ranged is not None else None
            if pruned is not None:
                files = pruned
        if len(files) <= 1:
            return None
        parts: list[ColumnTable] = []
        total = 0
        scanned = 0
        for f in files:
            sub: LogicalPlan = dataclasses.replace(node, files=[f])
            for wrapper in reversed(chain):
                sub = dataclasses.replace(wrapper, child=sub)
            # Sequential by design: stopping early is the point; the
            # non-limited path keeps its thread-pooled parallel reads.
            t = self._execute(sub)
            scanned += 1
            if t.num_rows:
                parts.append(t)
                total += t.num_rows
            if total >= n:
                break
        self._phys(
            "LimitEarlyOut", files_scanned=scanned, files_total=len(files)
        )
        if not parts:
            return ColumnTable.empty(child.schema)
        out = ColumnTable.concat(parts) if len(parts) > 1 else parts[0]
        return out.take(np.arange(min(n, out.num_rows)))

    def _join_venue(self) -> str:
        """auto: host when the measured device→host link is slower than
        the configured floor (tunneled deployments) AND the native library
        built; the pairs land on host either way."""
        # Auto with a mesh keeps the distributed device kernel (the
        # query-plane sharding is the point); a forced "host" wins — the
        # host kernel is bucket-parallel too.
        return self._venue(
            "join_venue", "hyperspace.join.venue", self.mesh is not None, needs_native=True
        )

    def _phys(self, op: str | None = None, **detail) -> None:
        """Annotate the operator currently executing."""
        if self._cur_phys is None:
            return
        if op is not None:
            self._cur_phys.op = op
        self._cur_phys.detail.update(detail)

    # -- aggregate / sort -------------------------------------------------
    def _aggregate(self, plan: "Aggregate") -> ColumnTable:
        from hyperspace_tpu.ops.aggregate import aggregate_table

        if plan.grouping_sets is not None:
            return self._grouping_sets_aggregate(plan)
        if any(a.fn == "count_distinct" for a in plan.aggs):
            for a in plan.aggs:
                if a.fn == "count_distinct" and not isinstance(a.expr, Col):
                    raise HyperspaceError("count_distinct requires a plain column")
            dcols = {a.expr.name.lower() for a in plan.aggs if a.fn == "count_distinct"}
            if len(dcols) == 1 and not any(a.fn == "mean" for a in plan.aggs):
                # Single distinct column, no mean: the plan-level two-phase
                # desugar keeps the inner aggregate eligible for the fused
                # Aggregate(Join) path.
                self._phys("CountDistinctReaggregate")
                plan2, count_aliases = _desugar_count_distinct(plan)
                out = self._execute(plan2)
                # SQL count is never NULL: the outer SUM of count partials
                # yields NULL over zero inner rows — restore the 0.
                for alias in count_aliases:
                    f = out.schema.field(alias)
                    v = out.validity.pop(f.name, None)
                    if v is not None:
                        out.columns[f.name] = np.where(v, out.columns[f.name], 0)
                return out
            return self._distinct_aggregate(plan, sorted(dcols))
        venue = self._agg_venue()
        pushed = self._try_partial_agg_pushdown(plan)
        if pushed is not None:
            return pushed
        # Fuse Aggregate(Join) on both venues: the device run-prefix
        # kernel avoids the match-pair readback; the host C++
        # merge+accumulate avoids materializing the pairs at all.
        fused = self._try_fused_join_aggregate(plan)
        if fused is not None:
            self._phys(
                "FusedJoinAggregate",
                join_path=self.stats["join_path"],
                kernel=self.stats["join_kernel"],
                buckets=self.stats["num_buckets"],
            )
            return fused
        table = self._execute(plan.child)
        self.stats["agg_path"] = f"segment-reduce-{venue}"
        mesh = self.mesh if venue == "device" else None
        if mesh is not None:
            from hyperspace_tpu.parallel.mesh import mesh_size

            self.stats["agg_devices"] = mesh_size(mesh)
        self._phys(
            "SegmentReduceAggregate",
            venue=venue,
            groups=len(plan.group_by),
            aggs=len(plan.aggs),
            devices=self.stats.get("agg_devices", 1),
        )
        return aggregate_table(
            table, plan.group_by, plan.aggs, plan.schema, venue=venue, mesh=mesh,
            # Identity-cached factorization: repeat aggregations over a
            # stable index version skip re-factorizing the keys.
            groups=_group_ids_cached(table, plan.group_by),
        )

    def _try_partial_agg_pushdown(self, plan: "Aggregate") -> ColumnTable | None:
        """Partial aggregation pushdown (Spark's PartialAggregate /
        aggregate-through-join analog): for Aggregate(Join(L, R)) where
        every aggregate reads only the L side — optionally inside a
        CASE whose CONDITION reads only the R side (the q43/q59 weekly
        pivot shape; R attributes are constant per join-key run, so the
        case splits into the outer re-aggregation) — pre-aggregate L by
        (join keys + L group columns), join the FEW partial rows, and
        re-fold. Adaptive: bails when the partial grouping would not
        actually shrink L (measured, not guessed), in which case the
        normal fused path re-executes the (cheap, cached) L side."""
        from hyperspace_tpu.ops.aggregate import aggregate_table
        from hyperspace_tpu.plan.expr import Case, Lit
        from hyperspace_tpu.plan.nodes import AggSpec

        child = plan.child
        if not isinstance(child, Join) or child.how != "inner" or child.condition is not None:
            return None
        if isinstance(child.left, _TableLeaf) or isinstance(child.right, _TableLeaf):
            return None  # already pushed (recursion guard)
        lnames = {n.lower() for n in child.left.schema.names}
        rnames = {n.lower() for n in child.right.schema.names}
        g_l = [c for c in plan.group_by if c.lower() in lnames]
        g_r = [c for c in plan.group_by if c.lower() not in lnames]
        if any(c.lower() not in rnames for c in g_r):
            return None

        partial_specs: list[AggSpec] = []
        outer_specs: list[AggSpec] = []
        mean_parts: dict[str, tuple[str, str]] = {}  # alias -> (sum, cnt) temp names
        count_aliases: list[str] = []
        uses_r = bool(g_r)
        for i, a in enumerate(plan.aggs):
            refs = {r.lower() for r in a.references()}
            if a.fn == "count" and a.expr is None:
                partial_specs.append(AggSpec("count", None, f"__pp{i}"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}"), a.alias))
                count_aliases.append(a.alias)
                continue
            if a.fn in ("sum", "count", "min", "max") and refs and refs <= lnames:
                partial_specs.append(AggSpec(a.fn, a.expr, f"__pp{i}"))
                fn2 = "sum" if a.fn in ("sum", "count") else a.fn
                outer_specs.append(AggSpec(fn2, Col(f"__pp{i}"), a.alias))
                if a.fn == "count":
                    count_aliases.append(a.alias)
                continue
            if a.fn == "mean" and refs and refs <= lnames:
                partial_specs.append(AggSpec("sum", a.expr, f"__pp{i}s"))
                partial_specs.append(AggSpec("count", a.expr, f"__pp{i}c"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}s"), f"__po{i}s"))
                outer_specs.append(AggSpec("sum", Col(f"__pp{i}c"), f"__po{i}c"))
                mean_parts[a.alias] = (f"__po{i}s", f"__po{i}c")
                continue
            if (
                a.fn == "sum"
                and isinstance(a.expr, Case)
                and len(a.expr.branches) == 1
                and isinstance(a.expr.default, Lit)
                and a.expr.default.value in (0, 0.0)
            ):
                cond, val = a.expr.branches[0]
                crefs = {r.lower() for r in cond.references()}
                vrefs = {r.lower() for r in val.references()}
                if crefs and crefs <= rnames and vrefs <= lnames:
                    uses_r = True
                    partial_specs.append(AggSpec("sum", val, f"__pp{i}"))
                    from hyperspace_tpu.plan.expr import when as _when

                    outer_specs.append(
                        AggSpec("sum", _when(cond, Col(f"__pp{i}")).otherwise(0.0), a.alias)
                    )
                    continue
            return None
        if not uses_r:
            # The aggregate never needs R beyond the join's filtering
            # effect — the fused path already handles that shape better.
            return None

        pkeys: list[str] = list(child.left_on)
        pk_low = {c.lower() for c in pkeys}
        for c in g_l:
            if c.lower() not in pk_low:
                pkeys.append(c)
                pk_low.add(c.lower())

        lt = self._execute(child.left)
        gid, k, rep = _group_ids_cached(lt, pkeys)
        if k > max(64, lt.num_rows // 8):
            # Less than ~8x shrink: the extra factorize + re-fold beats
            # nothing the fused path doesn't already do better.
            return None

        from hyperspace_tpu.plan.nodes import Aggregate as _Agg

        pschema = _Agg(_TableLeaf(lt), pkeys, partial_specs).schema
        venue = self._agg_venue()
        partial = aggregate_table(
            lt, pkeys, partial_specs, pschema, venue=venue, groups=(gid, k, rep)
        )
        self._phys(
            "PartialAggPushdown",
            partial_rows=partial.num_rows,
            input_rows=lt.num_rows,
            keys=pkeys,
        )
        outer_plan: LogicalPlan = _Agg(
            Join(_TableLeaf(partial), child.right, child.left_on, child.right_on, "inner"),
            list(plan.group_by),
            outer_specs,
        )
        out = self._execute(outer_plan)
        # Re-shape to the original output: means recompose from their
        # sum/count partials (NULL when no valid input), counts restore
        # SQL's never-NULL zero, columns return in declared order.
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for f in plan.schema.fields:
            low = f.name.lower()
            if low in {c.lower() for c in plan.group_by}:
                _copy_field(f, out, f.name, cols, dicts, validity)
                continue
            if f.name in mean_parts or low in {a.lower() for a in mean_parts}:
                s_name, c_name = mean_parts[f.name]
                s = out.column(s_name).astype(np.float64)
                c = out.column(c_name).astype(np.float64)
                with np.errstate(invalid="ignore", divide="ignore"):
                    cols[f.name] = np.where(c > 0, s / np.maximum(c, 1), 0.0)
                if (c == 0).any():
                    validity[f.name] = c > 0
                continue
            _copy_field(f, out, f.name, cols, dicts, validity)
            if f.name in count_aliases:
                v = validity.pop(f.name, None)
                if v is not None:
                    cols[f.name] = np.where(v, cols[f.name], 0)
        return ColumnTable(plan.schema, cols, dicts, validity)

    def _distinct_aggregate(self, plan: "Aggregate", dcols: list[str]) -> ColumnTable:
        """General distinct expansion (the Spark planner's Expand analog
        for multi-distinct aggregates, q38/q87 shapes): execute the child
        ONCE, factorize the group keys ONCE, run the non-distinct specs
        as a normal segment reduce sharing that factorization, and count
        each distinct column by factorizing (group keys, column) pairs —
        the representative row of each pair maps back to its outer group,
        so a bincount over pair representatives IS the distinct count.
        No join, no per-spec re-execution; mean shares freely."""
        from hyperspace_tpu.ops.aggregate import aggregate_table, group_ids
        from hyperspace_tpu.schema import Schema

        ct = self._execute(plan.child)
        venue = self._agg_venue()
        gid, k, rep = _group_ids_cached(ct, plan.group_by)
        self._phys(
            "DistinctExpandAggregate",
            distinct_cols=dcols,
            groups=len(plan.group_by),
            venue=venue,
        )
        out_schema = plan.schema
        if k == 0 or (ct.num_rows == 0 and plan.group_by):
            return ColumnTable.empty(out_schema)
        regular = [a for a in plan.aggs if a.fn != "count_distinct"]
        reg_fields = [out_schema.field(c) for c in plan.group_by]
        reg_fields += [out_schema.field(a.alias) for a in regular]
        base = aggregate_table(
            ct, plan.group_by, regular, Schema(tuple(reg_fields)),
            venue=venue, groups=(gid, k, rep),
        )
        cols = dict(base.columns)
        dicts = dict(base.dictionaries)
        validity = dict(base.validity)
        pair_counts: dict[str, np.ndarray] = {}
        for d in dcols:
            pgid, pk, prep = group_ids(ct, [*plan.group_by, d])
            del pgid, pk
            outer = gid[prep]
            vd = ct.valid_mask(d)
            if vd is not None:
                outer = outer[vd[prep]]  # SQL: distinct counts exclude NULL
            pair_counts[d] = np.bincount(outer, minlength=k).astype(np.int64)
        for a in plan.aggs:
            if a.fn == "count_distinct":
                cols[out_schema.field(a.alias).name] = pair_counts[a.expr.name.lower()]
        return ColumnTable(out_schema, cols, dicts, validity)

    def _grouping_sets_aggregate(self, plan: "Aggregate") -> ColumnTable:
        """ROLLUP / CUBE / GROUPING SETS as ONE finest-grain aggregate
        (which gets the fused Aggregate(Join) path when it applies) plus
        cheap re-aggregations of its partials per set — the two-phase
        machinery the count_distinct desugar introduced, generalized.
        The union null-extends group columns a set aggregates away;
        grouping() flags tell data NULLs from subtotal NULLs."""
        from hyperspace_tpu.ops.aggregate import aggregate_table
        from hyperspace_tpu.plan.expr import Col
        from hyperspace_tpu.plan.nodes import AggSpec
        from hyperspace_tpu.schema import Field, Schema

        if any(a.fn == "count_distinct" for a in plan.aggs):
            # Distinct counts do not compose from partials (the same value
            # in two finest groups of one coarser group would double
            # count), so the re-fold below cannot serve them: materialize
            # the child ONCE and aggregate each set directly over it —
            # the plain-aggregate path owns the distinct machinery.
            return self._grouping_sets_distinct(plan)

        # Phase 1: finest grain over the full group_by, means split into
        # sum+count partials so coarser sets can recompose them exactly.
        base_specs: list[AggSpec] = []
        for a in plan.aggs:
            if a.fn == "grouping":
                continue
            if a.fn == "mean":
                base_specs.append(AggSpec("sum", a.expr, f"__gs_sum_{a.alias}"))
                base_specs.append(AggSpec("count", a.expr, f"__gs_cnt_{a.alias}"))
            else:
                base_specs.append(AggSpec(a.fn, a.expr, a.alias))
        base = Aggregate(plan.child, plan.group_by, base_specs)
        bt = self._execute(base)

        out_schema = plan.schema
        venue = self._agg_venue()
        self._phys(
            "GroupingSetsReaggregate",
            sets=[list(s) for s in plan.grouping_sets],
            venue=venue,
        )

        def refold(a: AggSpec) -> list[AggSpec]:
            """Phase-2 spec(s) re-aggregating a phase-1 partial column."""
            if a.fn == "mean":
                return [
                    AggSpec("sum", Col(f"__gs_sum_{a.alias}"), f"__gs_sum_{a.alias}"),
                    AggSpec("sum", Col(f"__gs_cnt_{a.alias}"), f"__gs_cnt_{a.alias}"),
                ]
            fn2 = "sum" if a.fn in ("sum", "count") else a.fn
            return [AggSpec(fn2, Col(a.alias), a.alias)]

        parts: list[ColumnTable] = []
        for s in plan.grouping_sets:
            specs2 = [sp for a in plan.aggs if a.fn != "grouping" for sp in refold(a)]
            fields = [bt.schema.field(c) for c in s]
            for sp in specs2:
                src = bt.schema.field(sp.expr.name)
                dtype = src.dtype if sp.fn in ("min", "max") else (
                    "int64" if src.dtype in ("int32", "int64", "bool", "date") else "float64"
                )
                fields.append(Field(sp.alias, dtype))
            sub = aggregate_table(bt, list(s), specs2, Schema(tuple(fields)), venue=venue)

            def agg_col(f, spec, cols, dicts, validity, sub=sub):
                if spec.fn == "mean":
                    ssum = sub.column(f"__gs_sum_{spec.alias}").astype(np.float64)
                    scnt = sub.column(f"__gs_cnt_{spec.alias}").astype(np.float64)
                    sv = sub.valid_mask(f"__gs_sum_{spec.alias}")
                    with np.errstate(invalid="ignore", divide="ignore"):
                        cols[f.name] = np.where(scnt > 0, ssum / np.maximum(scnt, 1), 0.0)
                    if sv is not None or (scnt == 0).any():
                        ok = scnt > 0
                        validity[f.name] = ok if sv is None else (ok & sv)
                elif spec.fn == "count":
                    # COUNT is never NULL: zero-row re-folds yield a NULL
                    # sum partial — restore 0 (same rule as the
                    # count_distinct desugar's outer sum).
                    v = sub.valid_mask(spec.alias)
                    c = sub.column(spec.alias)
                    cols[f.name] = np.where(v, c, 0) if v is not None else c
                else:
                    _copy_field(f, sub, spec.alias, cols, dicts, validity)

            parts.append(self._gs_assemble(plan, out_schema, sub, s, bt, agg_col))
        return ColumnTable.concat(parts)

    def _gs_assemble(
        self, plan: "Aggregate", out_schema, sub: ColumnTable, s, dict_src, agg_col
    ) -> ColumnTable:
        """One grouping set's output part, shared by the re-fold and
        distinct grouping-set paths: group columns in `s` copy through,
        group columns aggregated away null-extend, grouping() flags
        derive from set membership, and `agg_col(field, spec, cols,
        dicts, validity)` fills the aggregate columns."""
        in_set = {c.lower() for c in s}
        gb_low = {c.lower() for c in plan.group_by}
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        nrows = sub.num_rows
        for f in out_schema.fields:
            low = f.name.lower()
            if low in gb_low:
                if low in in_set:
                    _copy_field(f, sub, f.name, cols, dicts, validity)
                else:
                    _null_field(
                        f, nrows, dict_src if f.is_string else None, cols, dicts, validity
                    )
                continue
            spec = next(a for a in plan.aggs if a.alias.lower() == low)
            if spec.fn == "grouping":
                cols[f.name] = np.full(
                    nrows, 0 if spec.expr.name.lower() in in_set else 1, np.int64
                )
            else:
                agg_col(f, spec, cols, dicts, validity)
        return ColumnTable(out_schema, cols, dicts, validity)

    def _grouping_sets_distinct(self, plan: "Aggregate") -> ColumnTable:
        """GROUPING SETS with count_distinct aggregates (q14/q18 shapes):
        the child materializes once, then every set aggregates it
        directly — per-set work instead of the partial re-fold, because
        distinct counts cannot be composed from finer partials."""

        ct = self._execute(plan.child)
        leaf = _TableLeaf(ct)
        out_schema = plan.schema
        self._phys(
            "GroupingSetsDistinct",
            sets=[list(s) for s in plan.grouping_sets],
            distinct_cols=sorted(
                a.expr.name.lower() for a in plan.aggs if a.fn == "count_distinct"
            ),
        )
        parts: list[ColumnTable] = []
        for s in plan.grouping_sets:
            specs = [a for a in plan.aggs if a.fn != "grouping"]
            sub = self._execute(Aggregate(leaf, list(s), specs))

            def agg_col(f, spec, cols, dicts, validity, sub=sub):
                _copy_field(f, sub, spec.alias, cols, dicts, validity)

            parts.append(self._gs_assemble(plan, out_schema, sub, s, ct, agg_col))
        return ColumnTable.concat(parts)

    def _venue(self, conf_attr: str, what: str, prefer_device: bool, needs_native: bool) -> str:
        """One pick_venue wrapper: conf defaults and the shared link floor
        live here instead of at every venue-choosing call site."""
        from hyperspace_tpu.parallel.bandwidth import pick_venue

        return pick_venue(
            getattr(self.conf, conf_attr) if self.conf is not None else "auto",
            self.conf.join_venue_min_mbps if self.conf is not None else 200.0,
            prefer_device=prefer_device,
            what=what,
            needs_native=needs_native,
        )

    def _filter_venue(self) -> str:
        """Mask venue: host numpy below the link floor (the mask and the
        columns are host-resident); device (mesh-sharded) otherwise."""
        return self._venue("filter_venue", "hyperspace.filter.venue",
                           self.mesh is not None, needs_native=False)

    def _agg_venue(self) -> str:
        """Where the segment reduce runs. The inputs are host-resident and
        the [A, K] result is tiny, so below the link floor the numpy
        bincount/reduceat path beats uploading every channel (and avoids
        emulated f64 on chips without native double support)."""
        return self._venue("agg_venue", "hyperspace.agg.venue", False, needs_native=False)

    def _top_n(self, sort_plan: "Sort", n: int) -> ColumnTable:
        """ORDER BY ... LIMIT n as an O(rows) selection: np.partition on
        the first sort column finds the n-th threshold, only the (ties-
        inclusive) candidate set gets the full lexicographic sort. The
        TopK analog of Spark's TakeOrderedAndProject."""
        from hyperspace_tpu.ops.sortkeys import column_lanes, lanes_as_unsigned

        table = self._execute(sort_plan.child)
        rows = table.num_rows
        if n <= 0:
            return table.take(np.arange(0))
        if rows <= max(2 * n, 1024):
            # Full sort (venue-aware via _sort's own machinery).
            self._phys("TopN", n=n, kernel="full-sort")
            full = self._sorted_table(table, sort_plan)
            return full.take(np.arange(min(n, full.num_rows)))
        # Pack the FIRST sort column's lanes into one u64 selection key
        # (DESC via the same lane inversion the full sort uses). A
        # constant validity lane is dropped so both 32-bit words carry
        # real key entropy (else a low-entropy hi word degenerates the
        # selection to ~all rows).
        c0, asc0 = sort_plan.by[0]
        has_nulls = table.valid_mask(c0) is not None
        lanes = column_lanes(table, c0, force_validity=has_nulls)
        if not asc0:
            lanes = [~l for l in lanes]
        lu = lanes_as_unsigned(lanes[:2])
        from hyperspace_tpu.parallel.mesh import mesh_size

        if (
            self.mesh is not None
            and mesh_size(self.mesh) > 1
            # Venue-gated like every other operator: auto prefers the
            # distributed kernel on a real mesh (the query-plane sharding
            # is the point), HYPERSPACE_VENUE=host / sort_venue=host
            # still force the host partition path.
            and self._venue("sort_venue", "hyperspace.sort.venue", True, needs_native=False)
            == "device"
        ):
            # Mesh-sharded selection: per-device first-n + one threshold
            # broadcast; the ORDER BY participates in the mesh.
            from hyperspace_tpu.ops.sortkeys import distributed_top_n_candidates

            cand = distributed_top_n_candidates(lu, n, self.mesh)
            if cand is not None:
                sub = table.take(cand)
                self._phys(
                    "TopN",
                    n=n,
                    kernel="mesh-sharded-select + sort",
                    candidates=len(cand),
                    devices=mesh_size(self.mesh),
                )
                full = self._sorted_table(sub, sort_plan)
                return full.take(np.arange(min(n, full.num_rows)))
        kpack = (lu[0].astype(np.uint64) << np.uint64(32)) | (
            lu[1].astype(np.uint64) if lu.shape[0] > 1 else np.uint64(0)
        )
        thr = np.partition(kpack, n - 1)[n - 1]
        # The selection key may be a PREFIX of the first column's order
        # (extra lanes unseen) — prefix-ties stay in, and every true
        # top-n row provably has prefix <= thr; the exact sort of the
        # candidate set settles the rest.
        cand = np.flatnonzero(kpack <= thr)
        sub = table.take(cand)
        self._phys("TopN", n=n, kernel="partition-select + sort", candidates=len(cand))
        full = self._sorted_table(sub, sort_plan)
        return full.take(np.arange(min(n, full.num_rows)))

    def _sort(self, plan: "Sort") -> ColumnTable:
        table = self._execute(plan.child)
        venue = self._venue("sort_venue", "hyperspace.sort.venue", False, needs_native=False)
        self._phys(f"{venue.capitalize()}Sort", keys=[c for c, _ in plan.by])
        return self._sorted_table(table, plan, venue)

    def _sorted_table(self, table: ColumnTable, plan: "Sort", venue: str | None = None) -> ColumnTable:
        """Venue-aware total order of an already-materialized table."""
        from hyperspace_tpu.ops.sortkeys import (
            device_order_perm,
            lexsort_lanes,
            order_lanes,
        )

        if table.num_rows <= 1:
            return table
        if venue is None:
            venue = self._venue("sort_venue", "hyperspace.sort.venue", False, needs_native=False)
        if venue == "host":
            # ORDER BY output must land on host; below the link floor a
            # numpy lexsort beats the device round-trip (latency-bound
            # for the typical small post-aggregation result).
            return table.take(lexsort_lanes(order_lanes(table, plan.by)))
        return table.take(device_order_perm(table, plan.by))

    # -- union (hybrid scan) ----------------------------------------------
    def _union(self, plan: Union) -> ColumnTable:
        schema = plan.schema
        parts = []
        for child in plan.inputs:
            t = self._execute(child)
            # Remap onto the union schema's exact field names/order (child
            # names are validated case-insensitively compatible).
            cols, dicts, val = {}, {}, {}
            for f in schema.fields:
                cf = t.schema.field(f.name)
                cols[f.name] = t.columns[cf.name]
                if cf.name in t.dictionaries:
                    dicts[f.name] = t.dictionaries[cf.name]
                if cf.name in t.validity:
                    val[f.name] = t.validity[cf.name]
            parts.append(ColumnTable(schema, cols, dicts, val))
        return ColumnTable.concat(parts)

    # -- scan ------------------------------------------------------------
    def _scan_files(self, scan: Scan) -> list[str]:
        if scan.files is not None:
            return list(scan.files)
        return [fi.path for fi in list_data_files(scan.root, suffix=format_suffix(scan.format))]

    def _cached_read(self, files: list[str], columns, schema) -> ColumnTable:
        """Index-file read through the decoded-table cache; files_read
        counts only physical (miss) reads."""
        before = hio.table_cache_stats()["miss_files"]
        table = hio.read_parquet_cached(files, columns=columns, schema=schema)
        self.stats["files_read"] += hio.table_cache_stats()["miss_files"] - before
        return table

    def _scan(self, scan: Scan, columns: list[str] | None = None) -> ColumnTable:
        files = self._scan_files(scan)
        cols = columns if columns is not None else scan.scan_schema.names
        if not files:  # everything pruned away
            return ColumnTable.empty(scan.scan_schema.select(cols))
        if scan.bucket_spec is not None:
            # Index files are immutable per version — cache their decode.
            return self._cached_read(files, cols, scan.scan_schema)
        self.stats["files_read"] += len(files)
        return hio.read_table_files(files, scan.format, columns=cols, schema=scan.scan_schema)

    # -- filter (with index bucket pruning) ------------------------------
    def _filter(self, plan: Filter) -> ColumnTable:
        child = plan.child
        # Per-OPERATOR pruning evidence: deltas of the query-cumulative
        # counters from this frame's start.
        fp0, rp0 = self.stats["files_pruned"], self.stats["rows_pruned"]
        mask_venue = self._filter_venue()
        mask_kernel = "host-mask" if mask_venue == "host" else "fused-xla-mask"
        if isinstance(child, Scan) and child.bucket_spec is not None:
            pruned = self._prune_bucket_files(child, plan.predicate)
            if pruned is not None:
                self._phys(
                    "IndexPointLookup",
                    files_pruned=self.stats["files_pruned"] - fp0,
                    kernel=f"bucket-hash-prune + {mask_kernel}",
                )
                table = self._cached_read(pruned, child.scan_schema.names, child.scan_schema)
                return apply_filter(table, plan.predicate, mesh=self.mesh, venue=mask_venue)
            ranged = self._range_read(child, plan.predicate)
            if ranged is not None:
                table, exact = ranged
                if exact and predicate_all_key_bounds(plan.predicate, child.bucket_spec[1][0]):
                    # The slice IS the predicate: every conjunct bounds the
                    # sorted key, so the residual mask would be all-true —
                    # skip its evaluation (and the device round-trip).
                    self._phys(
                        "IndexRangeScan",
                        files_pruned=self.stats["files_pruned"] - fp0,
                        rows_pruned=self.stats["rows_pruned"] - rp0,
                        kernel="minmax-prune + searchsorted-slice (exact, mask skipped)",
                    )
                    return table
                self._phys(
                    "IndexRangeScan",
                    files_pruned=self.stats["files_pruned"] - fp0,
                    rows_pruned=self.stats["rows_pruned"] - rp0,
                    kernel=f"minmax-prune + searchsorted-slice + {mask_kernel}",
                )
                return apply_filter(table, plan.predicate, mesh=self.mesh, venue=mask_venue)
        if isinstance(child, Union):
            # Hybrid scan: prune the bucketed input(s), keep deltas whole.
            new_inputs: list[LogicalPlan] = []
            for inp in child.inputs:
                if isinstance(inp, Scan) and inp.bucket_spec is not None:
                    pruned = self._prune_bucket_files(inp, plan.predicate)
                    if pruned is None:
                        ranged = self._range_prune_list(inp, plan.predicate)
                        pruned = ranged[0] if ranged is not None else None  # (kept, bounds, stats)
                    if pruned is not None:
                        inp = dataclasses.replace(inp, files=pruned)
                new_inputs.append(inp)
            self._phys(
                "HybridScanFilter",
                files_pruned=self.stats["files_pruned"] - fp0,
                kernel=f"bucket/minmax-prune + {mask_kernel}",
            )
            return apply_filter(
                self._union(Union(new_inputs)), plan.predicate,
                mesh=self.mesh, venue=mask_venue,
            )
        self._phys(kernel=mask_kernel)
        return apply_filter(self._execute(child), plan.predicate, mesh=self.mesh, venue=mask_venue)

    # Bucket pruning reads at most this many point combinations; above it
    # the (still-correct) range/mask machinery takes over.
    _MAX_POINT_COMBOS = 64

    def _prune_bucket_files(self, scan: Scan, predicate: Expr) -> list[str] | None:
        """If the predicate pins every bucket column with equality
        literals — single (eq) or multi-point (IN) — return only the
        owning buckets' files. The analog of partition pruning the
        reference cannot do (FilterIndexRule keeps a full scan,
        FilterIndexRule.scala:114-120); IN on the bucket column divides
        IO by numBuckets/|IN| instead of 1."""
        import itertools
        import math

        from hyperspace_tpu.plan.expr import InList

        num_buckets, bucket_cols = scan.bucket_spec
        cand: dict[str, list] = {}
        for conj in split_conjuncts(predicate):
            got: tuple[str, list] | None = None
            if isinstance(conj, BinOp) and conj.op == "eq":
                if isinstance(conj.left, Col) and isinstance(conj.right, Lit):
                    got = (conj.left.name.lower(), [conj.right.value])
                elif isinstance(conj.right, Col) and isinstance(conj.left, Lit):
                    got = (conj.right.name.lower(), [conj.left.value])
            elif isinstance(conj, InList) and isinstance(conj.child, Col):
                got = (conj.child.name.lower(), list(conj.values))
            if got is not None:
                name, vals = got
                # Conjunctive constraints: any one conjunct's list is a
                # valid superset of the reachable values — keep the
                # smallest.
                if name not in cand or len(vals) < len(cand[name]):
                    cand[name] = vals
        try:
            lists = [cand[c.lower()] for c in bucket_cols]
        except KeyError:
            return None
        if math.prod(len(l) for l in lists) > self._MAX_POINT_COMBOS:
            return None
        fields = [scan.scan_schema.field(c) for c in bucket_cols]
        names = set()
        for combo in itertools.product(*lists):
            h = hash_scalar_key(list(combo), fields)
            names.add(hio.bucket_file_name(int(bucket_ids(h, num_buckets, np)[0])))
        files = self._scan_files(scan)
        matches = [f for f in files if Path(f).name in names]
        if matches:
            self.stats["files_pruned"] += len(files) - len(matches)
            return matches
        return None

    def _range_prune_list(
        self, scan: Scan, predicate: Expr
    ) -> tuple[list[str], KeyBounds, dict] | None:
        """File-level range (min/max) pruning: drop bucket files whose
        manifest key stats cannot overlap the predicate's bounds on the
        leading indexed column. The analog of FileSourceScanExec's parquet
        min/max pruning (SURVEY.md §2.2), which the reference inherits
        from Spark. Comparisons run in the filter mask's own numeric
        domain so pruning never disagrees with it. Returns None when no
        literal bounds or no stats exist."""
        key = scan.bucket_spec[1][0]
        bounds = key_bounds(predicate, key)
        files = self._scan_files(scan)
        stats = hio.file_key_stats(files) if bounds is not None else {}
        if bounds is not None and stats:
            bounds, stat_conv = _convert_bounds(scan.scan_schema.field(key), bounds)
        else:
            stat_conv = None
        # Included-column pruning: any OTHER referenced column with
        # manifest columnStats and literal bounds prunes too (the
        # reference gets this from parquet per-column min/max via
        # FileSourceScanExec, SURVEY.md §2.2).
        refs = {r.lower() for r in predicate.references()}
        extra: list[tuple[KeyBounds, object, dict]] = []
        for c in scan.scan_schema.names:
            if c.lower() == key.lower() or c.lower() not in refs:
                continue
            b = key_bounds(predicate, c)
            if b is None:
                continue
            cstats = hio.file_column_stats(files, c)
            if not cstats:
                continue
            cb, cconv = _convert_bounds(scan.scan_schema.field(c), b)
            extra.append((cb, cconv, cstats))
        if stat_conv is None and not extra:
            return None
        kept: list[str] = []
        for f in files:
            keep = True
            if stat_conv is not None and f in stats:
                s = stats[f]
                # s is None ⇔ bucket empty or all-null key: no row can
                # satisfy a literal comparison (3VL), safe to skip.
                keep = s is not None and _stats_overlap(bounds, stat_conv(s[0]), stat_conv(s[1]))
            for cb, cconv, cstats in extra:
                if not keep:
                    break
                if f in cstats:
                    s = cstats[f]
                    keep = s is not None and _stats_overlap(cb, cconv(s[0]), cconv(s[1]))
            if keep:
                kept.append(f)
        if stat_conv is None and len(kept) == len(files):
            # Included-column stats pruned nothing and the key gives no
            # slicing bounds: stay on the plain scan path (whole cached
            # bucket files — the device upload cache keys on them).
            return None
        self.stats["files_pruned"] += len(files) - len(kept)
        return kept, (bounds if stat_conv is not None else None), stats

    def _range_read(self, scan: Scan, predicate: Expr) -> tuple[ColumnTable, bool] | None:
        """File-level range pruning + within-file searchsorted slicing
        (each surviving file is key-sorted by construction, so qualifying
        rows form one contiguous run). Dictionary codes are not
        value-ordered across files and null prefixes break sortedness —
        both fall back to reading the file whole (mask handles the rest).
        Returns (table, exact): exact ⇔ every row returned provably
        satisfies the key bounds (all parts sliced on a sorted, null-free,
        stats-backed key)."""
        from concurrent.futures import ThreadPoolExecutor

        pruned = self._range_prune_list(scan, predicate)
        if pruned is None:
            return None
        kept, bounds, stats_files = pruned
        schema = scan.scan_schema
        field = schema.field(scan.bucket_spec[1][0])
        if not kept:
            return ColumnTable.empty(schema), True
        before = hio.table_cache_stats()["miss_files"]
        with ThreadPoolExecutor(max_workers=min(8, len(kept))) as pool:
            tables = list(
                pool.map(
                    lambda fp: hio.read_parquet_cached([fp], columns=schema.names, schema=schema),
                    kept,
                )
            )
        self.stats["files_read"] += hio.table_cache_stats()["miss_files"] - before
        parts: list[ColumnTable] = []
        # Float keys can hold NaN VALUES (sorted last by the build); a
        # lower-bound-only slice would include them while the mask drops
        # them — never claim exactness for float key columns. bounds is
        # None when only included-column stats pruned: no key slicing.
        exact = bounds is not None and field.device_dtype.kind != "f"
        for fp, t in zip(kept, tables):
            if t.num_rows == 0:
                continue
            sliceable = (
                bounds is not None
                and not field.is_string
                and t.valid_mask(field.name) is None
                and fp in stats_files  # stats-backed ⇒ written key-sorted
            )
            if sliceable:
                colv = t.columns[field.name]
                lo_i, hi_i = 0, t.num_rows
                if bounds.lo is not None:
                    lo_i = int(np.searchsorted(colv, bounds.lo, side="right" if bounds.lo_strict else "left"))
                if bounds.hi is not None:
                    hi_i = int(np.searchsorted(colv, bounds.hi, side="left" if bounds.hi_strict else "right"))
                if hi_i <= lo_i:
                    self.stats["rows_pruned"] += t.num_rows
                    continue
                if lo_i > 0 or hi_i < t.num_rows:
                    self.stats["rows_pruned"] += t.num_rows - (hi_i - lo_i)
                    t = t.take(np.arange(lo_i, hi_i))
            else:
                exact = False
            parts.append(t)
        if not parts:
            return ColumnTable.empty(schema), True
        out = ColumnTable.concat(parts) if len(parts) > 1 else parts[0]
        return out, exact

    # -- join ------------------------------------------------------------
    def _join(self, plan: Join) -> ColumnTable:
        lside, rside, left_side, right_side = self._join_sides(plan)
        # Path from THIS frame's decision (the _join_sides call above
        # sets it LAST, after any nested joins it executed ran). buckets/
        # devices are read after _partition_join, which sets them for the
        # kernel that just ran (this join's own).
        path = self.stats["join_path"]
        if left_side is not None:
            out = self._aligned_join(plan, left_side, right_side, lside, rside)
        else:
            out = self._partition_join(plan, lside, rside)
        if self.stats["join_kernel"] == "host-broadcast-hash":
            path = "broadcast-hash"
            self.stats["join_path"] = path
        if plan.condition is not None and plan.how == "inner":
            # Inner-join ON residual: a plain 3-valued filter over the
            # matched rows, venue- and mesh-aware like every other
            # predicate site. (Outer/semi/anti residuals alter MATCHING
            # and are applied inside _partition_join.) The filtered
            # table deliberately does NOT inherit any preserved bucket
            # grouping (per-bucket counts changed).
            before = out.num_rows
            mask = eval_predicate_mask(
                out, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            out = out.filter_mask(mask)
            self._phys(residual_condition=True, residual_rows_dropped=before - out.num_rows)
        self._phys(
            "BroadcastHashJoin" if path == "broadcast-hash" else "SortMergeJoin",
            path=path,
            kernel=self.stats["join_kernel"],
            buckets=self.stats["num_buckets"],
            devices=self.stats["join_devices"],
        )
        return out

    @staticmethod
    def _bucket_hash_dtypes(scan: Scan) -> tuple[str, ...]:
        """The hash domain of a scan's bucket columns. The canonical row
        hash is dtype-sensitive (an int64 mixes two words; an int32 one),
        so two bucketings agree on equal key VALUES only when the bucket
        column dtypes agree."""
        out = []
        for c in scan.bucket_spec[1]:
            f = scan.scan_schema.field(c)
            out.append("string" if f.is_string else str(np.dtype(f.device_dtype)))
        return tuple(out)

    def _keyed_on_buckets(self, side: AlignedSide | None, join_on: list[str]) -> bool:
        """True iff the side is an index scan bucketed exactly on its
        join keys (the precondition for any bucket-parallel pairing)."""
        return (
            side is not None
            and side.scan.bucket_spec is not None
            and [c.lower() for c in side.scan.bucket_spec[1]]
            == [c.lower() for c in join_on]
        )

    def _join_sides(
        self, plan: Join
    ) -> tuple["SideData", "SideData", AlignedSide | None, AlignedSide | None]:
        """Per-side bucket data for a join — the one place that decides
        between the zero-exchange aligned path (both sides bucketed with
        equal counts on the join keys), the re-bucketing exchange (one
        side bucketed, the other re-bucketized on the fly to match), a
        bucket-preserving reuse of an inner join's output grouping, and
        the single-partition fallback. Returns the AlignedSides
        (None, None) on every non-both-aligned path."""
        left_side = self._aligned_side(plan.left)
        right_side = self._aligned_side(plan.right)
        if (
            self._keyed_on_buckets(left_side, plan.left_on)
            and self._keyed_on_buckets(right_side, plan.right_on)
            and left_side.scan.bucket_spec[0] == right_side.scan.bucket_spec[0]
            # Equal VALUES hash identically only in equal dtype domains.
            and self._bucket_hash_dtypes(left_side.scan)
            == self._bucket_hash_dtypes(right_side.scan)
        ):
            self.stats["join_path"] = "zero-exchange-aligned"
            num_buckets = left_side.scan.bucket_spec[0]
            # Dynamic partition pruning (the analog of Spark 3's DPP,
            # which post-dates the reference's engine): build the
            # predicate-bearing side FIRST, bound its surviving join
            # keys, and skip the other side's bucket files whose
            # manifest key stats cannot overlap — a dimension filtered
            # to one month reads ~1/60th of a date-bucketed fact index.
            producer = None
            if plan.how == "inner":
                if left_side.predicate is not None and right_side.predicate is None:
                    producer = "left"
                elif right_side.predicate is not None and left_side.predicate is None:
                    producer = "right"
                elif left_side.predicate is not None and right_side.predicate is not None:
                    producer = (
                        "left"
                        if self._base_rows(left_side) <= self._base_rows(right_side)
                        else "right"
                    )
            if producer == "left":
                lside = self._side_data(left_side, num_buckets)
                bounds = self._side_key_bounds(lside, left_side)
                rside = self._side_data(right_side, num_buckets, dpp_bounds=bounds)
            elif producer == "right":
                rside = self._side_data(right_side, num_buckets)
                bounds = self._side_key_bounds(rside, right_side)
                lside = self._side_data(left_side, num_buckets, dpp_bounds=bounds)
            else:
                lside = self._side_data(left_side, num_buckets)
                rside = self._side_data(right_side, num_buckets)
            return lside, rside, left_side, right_side
        # One side bucketed on its join keys: the other side can ride a
        # query-time re-bucketing exchange (hash + counting sort on host,
        # device sort on the device venue) so the merge stays
        # bucket-parallel — SURVEY §2.3's "single re-bucketing all-to-all
        # when bucket counts don't match" and the ranker's
        # mismatched-pair case (JoinIndexRanker.scala:31-34).
        mode = self.conf.join_rebucketize if self.conf is not None else "auto"
        lt = rt = None
        l_keyed = self._keyed_on_buckets(left_side, plan.left_on)
        r_keyed = self._keyed_on_buckets(right_side, plan.right_on)
        if mode != "off" and (l_keyed != r_keyed):
            if l_keyed:
                idx_side, other_plan, other_on = left_side, plan.right, plan.right_on
            else:
                idx_side, other_plan, other_on = right_side, plan.left, plan.left_on
            num_buckets = idx_side.scan.bucket_spec[0]
            idx_fields = [
                idx_side.scan.scan_schema.field(c) for c in idx_side.scan.bucket_spec[1]
            ]
            t_other = self._execute(other_plan)
            preserved = self._preserved_sidedata(t_other, other_on)
            if preserved is not None and not (
                len(preserved.offsets) - 1 == num_buckets
                and _hash_fields_compatible(preserved.hash_fields, idx_fields)
            ):
                preserved = None
            engage = (
                preserved is not None  # reuse is free — always take it
                or mode == "force"
                or not self._should_broadcast(t_other.num_rows, self._base_rows(idx_side))
            )
            if engage:
                sd_other = preserved or self._rebucketize_side(
                    t_other, other_on, idx_fields, num_buckets
                )
                if sd_other is not None:
                    # The materialized side doubles as the DPP producer
                    # when dropping unmatched INDEXED-side rows early is
                    # sound for this join type (the indexed side must not
                    # be a preserved outer side).
                    idx_is_right = not l_keyed
                    prune_ok = (
                        plan.how == "inner"
                        or (idx_is_right and plan.how in ("left", "semi", "anti"))
                        or (not idx_is_right and plan.how == "right")
                    )
                    dpp = None
                    if prune_ok:
                        dpp = self._table_key_bounds(t_other, other_on[0])
                    sd_idx = self._side_data(idx_side, num_buckets, dpp_bounds=dpp)
                    self.stats["join_path"] = (
                        "bucket-preserved-aligned" if preserved is not None else "rebucketized-aligned"
                    )
                    self._phys(
                        exchange="preserved" if preserved is not None else "rebucketize",
                        buckets=num_buckets,
                    )
                    if l_keyed:
                        return sd_idx, sd_other, None, None
                    return sd_other, sd_idx, None, None
            if l_keyed:
                rt = t_other
            else:
                lt = t_other
        if mode != "off" and not l_keyed and not r_keyed:
            # Neither side indexed: a child inner join's preserved bucket
            # grouping can still pair — directly against another
            # preserved side, or by re-bucketizing the other side into
            # its domain.
            lt = lt if lt is not None else self._execute(plan.left)
            rt = rt if rt is not None else self._execute(plan.right)
            pl = self._preserved_sidedata(lt, plan.left_on)
            pr = self._preserved_sidedata(rt, plan.right_on)
            if (
                pl is not None
                and pr is not None
                and len(pl.offsets) == len(pr.offsets)
                and _hash_fields_compatible(pl.hash_fields, pr.hash_fields)
            ):
                self.stats["join_path"] = "bucket-preserved-aligned"
                self._phys(exchange="preserved-both", buckets=len(pl.offsets) - 1)
                return pl, pr, None, None
            keyed = pl or pr
            if keyed is not None and (
                mode == "force" or not self._should_broadcast(lt.num_rows, rt.num_rows)
            ):
                if pl is not None:
                    other = self._rebucketize_side(
                        rt, plan.right_on, list(pl.hash_fields), len(pl.offsets) - 1
                    )
                    pair = (pl, other)
                else:
                    other = self._rebucketize_side(
                        lt, plan.left_on, list(pr.hash_fields), len(pr.offsets) - 1
                    )
                    pair = (other, pr)
                if pair[0] is not None and pair[1] is not None:
                    self.stats["join_path"] = "rebucketized-aligned"
                    self._phys(
                        exchange="preserved+rebucketize", buckets=len(keyed.offsets) - 1
                    )
                    return pair[0], pair[1], None, None
        # General path: single partition (bucket count 1). The path stat
        # is set AFTER the children run — a nested join inside them sets
        # its own path and must not leak into this frame's label.
        if lt is None:
            lt = self._execute(plan.left)
        if rt is None:
            rt = self._execute(plan.right)
        self.stats["join_path"] = "single-partition"
        one = lambda t: SideData(t, np.array([0, t.num_rows], dtype=np.int64), False)  # noqa: E731
        return one(lt), one(rt), None, None

    def _aligned_side(self, plan: LogicalPlan) -> AlignedSide | None:
        node, project, predicate = plan, None, None
        # Linear chain the join rule preserves: Project / Filter over the
        # (possibly hybrid) index scan, in any order.
        while isinstance(node, (Project, Filter)):
            if isinstance(node, Project):
                if not node.is_simple:
                    # Computed entries can't be absorbed into the scan
                    # column list; fall back to the general path (which
                    # executes the Project node itself).
                    return None
                if project is None:  # outermost projection defines output
                    project = node.columns
                node = node.child
            else:
                predicate = node.predicate if predicate is None else And(predicate, node.predicate)
                node = node.child
        if isinstance(node, Union):
            # Hybrid scan of ANY width: exactly one bucketed index scan
            # plus unbucketed delta scans (appended files). The rewrite
            # rule emits the two-input shape; refresh chains or manual
            # unions may widen it.
            base = None
            deltas: list[Scan] = []
            for inp in node.inputs:
                if isinstance(inp, Project) and inp.is_simple and isinstance(inp.child, Scan):
                    inp = inp.child
                if not isinstance(inp, Scan):
                    return None
                if inp.bucket_spec is not None:
                    if base is not None:
                        return None  # two index scans: not a hybrid side
                    base = inp
                else:
                    deltas.append(inp)
            if base is None:
                return None
            return AlignedSide(base, project, deltas=tuple(deltas), predicate=predicate)
        if isinstance(node, Scan):
            return AlignedSide(node, project, predicate=predicate)
        return None

    def _base_rows(self, side: AlignedSide) -> int:
        """Total indexed rows from the side's manifest (for picking the
        smaller DPP producer); large sentinel when unknown."""
        from pathlib import Path as _P

        files = self._scan_files(side.scan)
        if files:
            m = hio.read_manifest_cached(_P(files[0]).parent)
            if m and "bucketRows" in m:
                return int(sum(m["bucketRows"]))
        return 1 << 60

    # Set-based DPP only materializes the producer's distinct keys below
    # these sizes (the semi-join/bloom reduction; beyond them the range
    # alone applies).
    _DPP_SET_MAX_ROWS = 4_000_000
    _DPP_SET_MAX_KEYS = 262_144

    def _side_key_bounds(self, sdata: "SideData", side: AlignedSide):
        """DPP producer info of an aligned side (see _table_key_bounds)."""
        return self._table_key_bounds(sdata.table, side.scan.bucket_spec[1][0])

    def _table_key_bounds(self, t: ColumnTable, key: str):
        """(lo, hi, key_set | None) of the surviving join-key values
        (nulls excluded — they never match). lo/hi are value-domain
        (strings decoded via the dictionary); key_set is the SORTED
        distinct int keys when small enough to enumerate — the consumer
        filters its rows by membership (the semi-join reduction half of
        DPP: a 1/70-selective demographics filter cuts the fact side 70x
        BEFORE any pairing). (None, None, None) = empty."""
        f = t.schema.field(key)
        vals = t.columns[f.name]
        valid = t.valid_mask(key)
        if valid is not None:
            vals = vals[valid]
        if len(vals) == 0:
            return (None, None, None)  # empty producer: skip everything
        if f.device_dtype.kind == "f" and bool(np.isnan(vals).any()):
            # NaN keys are real joinable values in the float domain but
            # poison min/max (NaN bounds would slice every finite row
            # away) — disable DPP for this producer entirely.
            return None
        if f.name in t.dictionaries:
            # Decoded-string bounds have no consumer: string keys disable
            # the bucket set, row slicing, and kset reduction alike — a
            # non-None result here would only churn the derived cache
            # with dead no-op cut entries (pinning base refs per distinct
            # producer filter). Report "no DPP" instead.
            return None
        lo, hi = vals.min(), vals.max()
        kset = None
        if (
            f.device_dtype.kind in "iu"
            and len(vals) <= self._DPP_SET_MAX_ROWS
        ):
            u = np.unique(vals)
            if len(u) <= self._DPP_SET_MAX_KEYS:
                kset = u
        return (lo, hi, kset)

    def _rebucketize_side(
        self, table: ColumnTable, key_cols: list[str], idx_fields, num_buckets: int
    ) -> "SideData | None":
        """Query-time re-bucketing exchange: group an arbitrary
        materialized table into the SAME bucket layout an index side
        uses, by recomputing the canonical row hash with each key column
        cast into the index side's dtype domain (equal values then hash
        identically; values unrepresentable on the index side have no
        partner there, so their placement cannot matter). Host venue:
        native counting sort; device venue: one device sort of the
        bucket ids. None when the key shapes cannot share a hash domain
        (string vs non-string)."""
        from hyperspace_tpu.execution.builder import NULL_HASH
        from hyperspace_tpu.ops.hashing import (
            combine_hashes,
            hash_int_column,
            string_dict_hashes,
        )

        hs = []
        for c, fi in zip(key_cols, idx_fields):
            f = table.schema.field(c)
            if f.is_string != fi.is_string:
                return None
            arr = table.columns[f.name]
            if f.is_string:
                dh = string_dict_hashes(table.dictionaries[f.name])
                h = dh[arr] if len(dh) else np.zeros(len(arr), np.uint32)
            else:
                if arr.dtype != fi.device_dtype:
                    arr = arr.astype(fi.device_dtype)
                h = hash_int_column(arr, np)
            valid = table.valid_mask(c)
            if valid is not None:
                h = np.where(valid, h, NULL_HASH)
            hs.append(h)
        bucket = np.asarray(bucket_ids(combine_hashes(hs, np), num_buckets, np), dtype=np.int32)
        venue = self._join_venue()
        kernel = None
        if venue == "device":
            import jax
            import jax.numpy as jnp

            order = np.asarray(jax.device_get(jnp.argsort(jnp.asarray(bucket))))
            counts = np.bincount(bucket, minlength=num_buckets).astype(np.int64)
            kernel = "device-sort-exchange"
        else:
            from hyperspace_tpu import native

            res = native.bucket_perm(bucket, num_buckets)
            if res is not None:
                order, counts = res
                kernel = "host-counting-sort-exchange"
            else:
                order = np.argsort(bucket, kind="stable")
                counts = np.bincount(bucket, minlength=num_buckets).astype(np.int64)
                kernel = "host-argsort-exchange"
        self.stats["exchange_kernel"] = kernel
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        return SideData(table.take(order), offsets, False, hash_fields=tuple(idx_fields))

    def _side_data(
        self, side: AlignedSide, num_buckets: int, dpp_bounds=None
    ) -> "SideData":
        """One concatenated bucket-grouped table per join side (bucket
        files read in parallel through the decoded-table cache), plus
        (hybrid scan) delta rows bucketized on the fly with the same
        canonical row hash the build used. `dpp_bounds` (lo, hi) is the
        other side's surviving key range (dynamic partition pruning): an
        enumerable span skips whole bucket FILES by hashing the span to
        its bucket set, and every surviving sorted bucket slices to the
        one contiguous ROW run inside the bounds."""
        from concurrent.futures import ThreadPoolExecutor

        schema = side.scan.scan_schema
        hf = tuple(schema.field(c) for c in side.scan.bucket_spec[1])
        groups = self._bucket_files_in_order(side.scan, num_buckets)
        if dpp_bounds is not None:
            keep = self._dpp_bucket_set(side, dpp_bounds, num_buckets)
            if keep is not None:
                pruned = sum(len(g) for b, g in enumerate(groups) if b not in keep)
                if pruned:
                    groups = [g if b in keep else [] for b, g in enumerate(groups)]
                    self.stats["files_pruned"] += pruned
                    self._phys(dpp_files_pruned=pruned)
        before = hio.table_cache_stats()["miss_files"]
        empty = ColumnTable.empty(schema)
        with ThreadPoolExecutor(max_workers=8) as pool:
            tables = list(
                pool.map(
                    lambda g: hio.read_parquet_cached(g, columns=schema.names, schema=schema)
                    if g
                    else empty,
                    groups,
                )
            )
        if dpp_bounds is not None and dpp_bounds[0] is not None:
            import hashlib

            key_field = schema.field(side.scan.bucket_spec[1][0])
            kset_digest = (
                hashlib.md5(dpp_bounds[2].tobytes()).hexdigest()
                if dpp_bounds[2] is not None
                else None  # one digest per SIDE, not per bucket
            )
            rows_before = sum(t.num_rows for t in tables)
            tables = [
                self._dpp_cut_cached(
                    t, key_field, dpp_bounds, sliceable=len(g) <= 1, kset_digest=kset_digest
                )
                for g, t in zip(groups, tables)
            ]
            cut = rows_before - sum(t.num_rows for t in tables)
            if cut:
                self.stats["rows_pruned"] += cut
                self._phys(dpp_rows_pruned=cut)
        self.stats["files_read"] += hio.table_cache_stats()["miss_files"] - before
        counts = np.array([t.num_rows for t in tables], dtype=np.int64)
        base = _concat_side_cached(tables)
        offsets = np.concatenate([[0], np.cumsum(counts)]).astype(np.int64)
        # Empty (fully pruned) groups are trivially sorted.
        sorted_within = all(len(g) <= 1 for g in groups)
        if side.deltas:
            dts = [self._scan(d, columns=list(schema.names)) for d in side.deltas]
            # Hash on the bucket columns in BUILD order (not join-key
            # order) so delta rows land in the same buckets the index used.
            dbs = [
                bucket_ids(compute_row_hashes(dt, side.scan.bucket_spec[1]), num_buckets, np)
                for dt in dts
            ]
            all_bucket = np.concatenate(
                [np.repeat(np.arange(num_buckets, dtype=np.int32), counts), *dbs]
            )
            combined = ColumnTable.concat([base, *dts])
            order = np.argsort(all_bucket, kind="stable")
            counts2 = np.bincount(all_bucket, minlength=num_buckets)
            offsets = np.concatenate([[0], np.cumsum(counts2)]).astype(np.int64)
            out = SideData(combined.take(order), offsets, False, hash_fields=hf)
        else:
            out = SideData(base, offsets, sorted_within, hash_fields=hf)
        if side.predicate is not None:
            out = _filter_side(out, side.predicate, self.mesh, self._filter_venue())
        return out

    def _aligned_join(
        self,
        plan: Join,
        left: AlignedSide,
        right: AlignedSide,
        lside: "SideData",
        rside: "SideData",
    ) -> ColumnTable:
        """Bucket-aligned zero-exchange SMJ: both sides arrive grouped by
        the same bucket function, so per-bucket merge joins concatenated
        equal the global join."""
        out = self._partition_join(plan, lside, rside)
        cols = None
        if plan.how in ("semi", "anti"):
            # Left-only output; the right side contributes no columns.
            if left.project is not None:
                cols = list(left.project)
        elif left.project is not None or right.project is not None:
            keep = list(left.project if left.project is not None else left.scan.scan_schema.names)
            rkeys = {k.lower() for k in plan.right_on}
            for c in right.project if right.project is not None else right.scan.scan_schema.names:
                if c.lower() not in rkeys and c.lower() not in {k.lower() for k in keep}:
                    keep.append(c)
            cols = keep
        if cols is None:
            return out
        return self._propagate_stash(out, out.select(cols))

    # DPP only enumerates the producer's key span when it is this small
    # (a year of dates is 366 hashes; demographic keys spanning millions
    # stay un-enumerated and fall back to row slicing only).
    _DPP_SPAN_LIMIT = 8192

    def _dpp_bucket_set(self, side: AlignedSide, bounds, num_buckets: int):
        """The set of bucket ids the producer's surviving keys can hash
        into, or None when not enumerable (wide span / non-int / multi-
        column bucket key). Keys are hash-distributed across buckets, so
        file [min, max] stats cannot prune — but a small ENUMERABLE key
        span (or exact key set) hashes to a concrete bucket subset (31
        dates touch at most 31 of 64 buckets; a point key exactly one)."""
        lo, hi, kset = bounds
        if lo is None:  # empty producer: nothing joins
            return set()
        if len(side.scan.bucket_spec[1]) != 1:
            return None
        key = side.scan.bucket_spec[1][0]
        f = side.scan.scan_schema.field(key)
        if f.is_string or f.device_dtype.kind not in "iu":
            return None
        if kset is not None and len(kset) <= self._DPP_SPAN_LIMIT:
            vals = kset.astype(f.device_dtype, copy=False)
        else:
            span = int(hi) - int(lo) + 1
            if span > self._DPP_SPAN_LIMIT:
                return None
            vals = np.arange(int(lo), int(hi) + 1, dtype=f.device_dtype)
        probe = ColumnTable(
            side.scan.scan_schema.select([key]), {f.name: vals}, {}, {}
        )
        h = compute_row_hashes(probe, [key])
        return set(np.unique(bucket_ids(h, num_buckets, np)).tolist())

    def _dpp_cut_cached(
        self, t: ColumnTable, key_field, dpp_bounds, sliceable: bool, kset_digest=None
    ) -> ColumnTable:
        """Range-slice + set-membership cut of one bucket table, memoized
        on (stable table identity, bounds) so a REPEATED query serves the
        same frozen sliced tables — keeping the whole downstream identity
        chain (concat, factorize, channels, pads, HBM uploads) warm. A
        per-query (unstable) table just computes the cut directly."""
        from hyperspace_tpu.execution import device_cache as dc

        lo, hi, kset = dpp_bounds

        def cut() -> ColumnTable:
            s = (
                self._dpp_slice_table(t, key_field, lo, hi)
                if sliceable and t.num_rows
                else None
            )
            if s is None:
                s = t
            if (
                kset is not None
                and s.num_rows
                and not key_field.is_string
                and key_field.device_dtype.kind in "iu"
            ):
                # Semi-join reduction: keep only rows whose key is in the
                # producer's distinct set (sorted-membership probe; nulls
                # can't match). A sorted subsequence stays sorted.
                colv = s.columns[key_field.name]
                pos = np.minimum(np.searchsorted(kset, colv), len(kset) - 1)
                hit = kset[pos] == colv
                kvalid = s.valid_mask(key_field.name)
                if kvalid is not None:
                    hit = hit & kvalid
                if not hit.all():
                    s = s.filter_mask(hit)
            return s

        if t.num_rows == 0:
            return t
        if kset is not None and kset_digest is None:
            return cut()  # no digest supplied: never key a cache on part of the cut
        refs, parts = _stable_table_refs(t, {n.lower() for n in t.schema.names})
        if not refs:
            return cut()

        def scalar(v):
            return v.item() if hasattr(v, "item") else v

        key = ("dppcut", parts, scalar(lo), scalar(hi), kset_digest)

        def build():
            s = cut()
            if s is t:
                return s, 0  # uncut: pass the (already stable) base through
            for arr in (*s.columns.values(), *s.validity.values()):
                dc.freeze(arr)
            size = int(sum(a.nbytes for a in s.columns.values()))
            return s, size

        return dc.HOST_DERIVED.get_or_build(key, refs, build)

    @staticmethod
    def _dpp_slice_table(table: ColumnTable, field, lo, hi) -> ColumnTable | None:
        """Rows of one KEY-SORTED bucket table inside [lo, hi] — one
        contiguous searchsorted run (the within-file analog of range
        pruning; hash bucketing scatters the key domain across files,
        but WITHIN a file the build's sort makes any value range one
        slice). None when the table isn't safely sliceable."""
        if field.is_string or table.valid_mask(field.name) is not None:
            return None
        colv = table.columns[field.name]
        lo_i = int(np.searchsorted(colv, lo, side="left"))
        hi_i = int(np.searchsorted(colv, hi, side="right"))
        if lo_i == 0 and hi_i == table.num_rows:
            return table
        return table.take(np.arange(lo_i, hi_i))

    def _bucket_files_in_order(self, scan: Scan, num_buckets: int) -> list[list[str]]:
        """Per-bucket file groups. A bucket can have several files (base
        version + incremental-refresh deltas); order within a group is the
        sorted file-path order."""
        files = self._scan_files(scan)
        by_name: dict[str, list[str]] = {}
        for f in sorted(files):
            by_name.setdefault(Path(f).name, []).append(f)
        out = []
        for b in range(num_buckets):
            name = hio.bucket_file_name(b)
            if name not in by_name:
                raise HyperspaceError(f"missing bucket file {name} in {scan.root}")
            out.append(by_name[name])
        return out

    # -- fused join + aggregation ----------------------------------------
    def _try_fused_join_aggregate(self, plan: Aggregate) -> ColumnTable | None:
        """Aggregate(Join) without materializing the joined pairs
        (ops/join_agg.py). Applies when every aggregate is
        sum/count/mean/min/max over a single side's numeric expression
        and the grouping columns (if any) come from one side; cross-side
        expressions fall back to the materialized join. min/max run as
        run-extremum channels on BOTH venues (all equal-key secondary
        rows are one contiguous run of the sorted side, and extrema are
        multiplicity-independent): the host C++ pass walks runs directly;
        the device kernel takes the segmented-prefix-scan value at each
        run end and folds groups with segment_min/max."""
        from hyperspace_tpu.ops.aggregate import agg_input, finalize_agg_values, group_ids

        child = plan.child
        if isinstance(child, Project):
            child = child.child
        if not isinstance(child, Join) or child.how != "inner" or child.condition is not None:
            return None
        join = child
        lnames = {n.lower() for n in join.left.schema.names}
        rnames = {n.lower() for n in join.right.schema.names}

        def side_of(cols) -> str | None:
            cl = {c.lower() for c in cols}
            if cl and cl <= lnames:
                return "left"
            if cl and cl <= rnames:
                return "right"
            return None

        gside = None
        if plan.group_by:
            gside = side_of(plan.group_by)
            if gside is None:
                return None
        from hyperspace_tpu.plan.expr import Case

        spec_sides: list[str | None] = []
        for a in plan.aggs:
            if a.fn not in ("sum", "count", "mean", "min", "max"):
                return None
            if a.expr is None:
                spec_sides.append(None)  # count(*)
                continue
            refs = a.references()
            # Constant expressions (sum(lit(2))) and cross-side expressions
            # have no single owning side — use the materialized join.
            s = side_of(refs)
            if s is None:
                return None
            sch = join.left.schema if s == "left" else join.right.schema
            if any(sch.field(r).is_vector for r in refs):
                return None
            # Case conditions handle strings via the predicate machinery;
            # any other string reference cannot feed a numeric channel.
            if not isinstance(a.expr, Case) and any(sch.field(r).is_string for r in refs):
                return None
            spec_sides.append(s)
        primary = gside or "left"

        lside, rside, _, _ = self._join_sides(join)
        data = {"left": lside, "right": rside}
        self.stats["agg_path"] = "fused-join-agg"
        self.stats["num_buckets"] = len(data["left"].offsets) - 1

        lkeys = [data["left"].table.schema.field(c).name for c in join.left_on]
        rkeys = [data["right"].table.schema.field(c).name for c in join.right_on]
        lc0, rc0 = _factorize_keys_cached(data["left"].table, data["right"].table, lkeys, rkeys)
        codes = {}
        perms = {}
        codes["left"], perms["left"] = _bucket_sorted_codes(lc0, data["left"])
        codes["right"], perms["right"] = _bucket_sorted_codes(rc0, data["right"])
        secondary = "right" if primary == "left" else "left"

        # Group ids on the primary table (original row order; memoized
        # for stable index-backed sides).
        gid_orig, k, first_idx = _group_ids_cached(data[primary].table, plan.group_by)
        if k == 0:  # empty primary side
            if plan.group_by:
                return ColumnTable.empty(plan.schema)
            k, gid_orig, first_idx = 1, np.zeros(0, np.int64), np.zeros(0, np.int64)

        def spec_input(side: str, spec):
            """(masked values, indicator) per original row of `side` with
            the plain aggregate path's null semantics (ops/aggregate);
            memoized per (expression, input identity) for stable sides."""
            return _agg_channels_cached(data[side].table, spec)

        host_res = None
        if (
            self._join_venue() == "host"
            and codes[primary].dtype == np.int32
            and codes[secondary].dtype == np.int32
        ):
            host_res = self._host_fused_channels(
                plan, data, codes, perms, primary, secondary, spec_sides,
                gid_orig, k, spec_input,
            )
        if host_res is not None:
            self.stats["join_kernel"] = "host-native-merge-accumulate"
            out, spec_layout = host_res
        else:
            self.stats["join_kernel"] = "device-run-prefix"
            out, spec_layout = self._device_fused_channels(
                plan, data, codes, perms, primary, secondary, spec_sides,
                gid_orig, k, spec_input,
            )
        star = out[0]

        keep = star > 0 if plan.group_by else np.ones(k, bool)
        out_schema = plan.schema
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        ptable = data[primary].table
        # first_idx may be empty when the primary side has no rows but a
        # global (no group_by) aggregate still emits its one k=1 row.
        kept_first = first_idx[keep[: len(first_idx)]]
        for c in plan.group_by:
            f = ptable.schema.field(c)
            out_f = out_schema.field(c)
            cols[out_f.name] = ptable.columns[f.name][kept_first]
            if f.name in ptable.dictionaries:
                dicts[out_f.name] = ptable.dictionaries[f.name]
            gv = ptable.valid_mask(c)
            if gv is not None:
                validity[out_f.name] = gv[kept_first]
        for spec, (vi, ci) in zip(plan.aggs, spec_layout):
            out_f = out_schema.field(spec.alias)
            cnt = out[ci][keep]
            if spec.fn == "count":
                cols[out_f.name] = cnt.astype(np.int64)
                continue
            val = out[vi][keep]
            if spec.fn == "mean":
                with np.errstate(invalid="ignore", divide="ignore"):
                    val = val / cnt
            empty = cnt == 0
            cols[out_f.name] = finalize_agg_values(val, empty, out_f.device_dtype)
            if empty.any():
                validity[out_f.name] = ~empty
        return ColumnTable(out_schema, cols, dicts, validity)

    def _device_fused_channels(
        self, plan, data, codes, perms, primary, secondary, spec_sides, gid_orig, k, spec_input
    ):
        """Device venue: the run-prefix kernel over bucket-major padded
        channels (ops/join_agg.py). Pads, the channel stacks, and the
        uploads all route through the identity caches, so repeat queries
        over a stable index version serve from HBM."""
        from hyperspace_tpu.execution import device_cache as dcache
        from hyperspace_tpu.ops.join_agg import fused_join_aggregate

        pk = _pad_bucket_major_cached(codes[primary], data[primary].offsets)
        sk = _pad_bucket_major_cached(codes[secondary], data[secondary].offsets)
        b, lp = pk.shape
        ls = sk.shape[1]

        def pad_rows(side: str, vals: np.ndarray, fill=0.0) -> np.ndarray:
            """Per-orig-row values of `side` → bucket-sorted padded [B, L]."""
            v = np.asarray(vals, np.float64)
            if perms[side] is not None:
                v = v[perms[side]]
            width = lp if side == primary else ls
            return _pad_bucket_major_cached(v, data[side].offsets, fill=fill, width=width)

        # pad_rows reorders by perm internally — pass the ORIGINAL-order gid;
        # pads carry group id k (the dead segment).
        def build_gid():
            return pad_rows(primary, gid_orig, fill=float(k)).astype(np.int32)

        if dcache.is_stable(gid_orig) and perms[primary] is None:
            # Cacheable only when NO per-join permutation applies: the
            # perm depends on the join keys, which this key does not
            # carry — a different-keyed join sharing gid_orig must not
            # reuse the other layout's pad.
            gid_pad = dcache.derived(
                ("gidpad", id(gid_orig), data[primary].offsets.tobytes(), k, lp),
                (gid_orig,),
                build_gid,
            )
        else:
            gid_pad = build_gid()

        channels: list[tuple] = [("star",)]
        p_arrays: list[np.ndarray] = []
        s_arrays: list[np.ndarray] = []

        def add_channel(side: str, padded: np.ndarray, fn: str | None = None) -> int:
            base = "p" if side == primary else "s"
            kind = base + fn if fn in ("min", "max") else base
            if side == primary:
                p_arrays.append(padded)
                channels.append((kind, len(p_arrays) - 1))
            else:
                s_arrays.append(padded)
                channels.append((kind, len(s_arrays) - 1))
            return len(channels) - 1

        def mm_values(vals: np.ndarray, ind: np.ndarray, fn: str) -> np.ndarray:
            """Extremum channel input: nulls (and later pads) carry the
            ±inf identity instead of the sum channels' zero. Identity-
            cached so the derived pad/upload caches stay warm for stable
            sides."""
            ident = np.inf if fn == "min" else -np.inf

            def build():
                out = np.where(ind > 0, vals, ident)
                dcache.freeze(out)
                return out

            if dcache.is_stable(vals) and dcache.is_stable(ind):
                return dcache.derived(
                    ("mmvals", id(vals), id(ind), fn), (vals, ind), build
                )
            return np.where(ind > 0, vals, ident)

        spec_layout: list[tuple[int | None, int]] = []  # (value ch, count ch; 0=star)
        for spec, s in zip(plan.aggs, spec_sides):
            if s is None:  # count(*)
                spec_layout.append((None, 0))
                continue
            vals, ind = spec_input(s, spec)
            vi = None
            if spec.fn in ("sum", "mean"):
                vi = add_channel(s, pad_rows(s, vals))
            elif spec.fn in ("min", "max"):
                ident = np.inf if spec.fn == "min" else -np.inf
                vi = add_channel(
                    s, pad_rows(s, mm_values(vals, ind, spec.fn), fill=ident), spec.fn
                )
            ci = add_channel(s, pad_rows(s, ind))
            spec_layout.append((vi, ci))

        pvals = _stack_cached(p_arrays, (0, b, lp))
        svals = _stack_cached(s_arrays, (0, b, ls))
        out = fused_join_aggregate(pk, sk, pvals, svals, gid_pad, k, tuple(channels))
        return out, spec_layout

    def _host_fused_channels(
        self, plan, data, codes, perms, primary, secondary, spec_sides, gid_orig, k, spec_input
    ):
        """Host venue: one C++ merge+accumulate pass computes per-primary-
        row channel sums and match counts (no pair materialization), then
        per-group bincounts produce the same [K] channel layout the device
        kernel emits. Returns None when the native library is missing."""
        from hyperspace_tpu import native

        if not native.available():
            return None
        tbl_s = data[secondary].table
        sec_arrays: list[np.ndarray] = []  # SORTED secondary order
        parts: list[tuple] = []

        def sec_sorted(a: np.ndarray) -> np.ndarray:
            return a[perms[secondary]] if perms[secondary] is not None else a

        for spec, s in zip(plan.aggs, spec_sides):
            if s is None:
                parts.append(("star",))
                continue
            vals, ind = spec_input(s, spec)
            if spec.fn in ("min", "max"):
                # Extremum channels bypass the sum accumulator: per-KEY
                # run extrema (secondary) / matched-row extrema (primary).
                parts.append(("mm", spec.fn, s, vals, ind))
            elif s == secondary:
                vi = None
                if spec.fn in ("sum", "mean"):
                    sec_arrays.append(sec_sorted(vals))
                    vi = len(sec_arrays) - 1
                sec_arrays.append(sec_sorted(ind))
                parts.append(("sec", vi, len(sec_arrays) - 1))
            else:
                parts.append(("pri", vals if spec.fn in ("sum", "mean") else None, ind))

        rvals = _stack_cached(sec_arrays, (0, tbl_s.num_rows))
        res = native.merge_join_accumulate(
            codes[primary], data[primary].offsets,
            codes[secondary], data[secondary].offsets, rvals,
        )
        if res is None:
            return None
        acc_sorted, match_sorted = res
        n_l = data[primary].table.num_rows
        pperm = perms[primary]
        if pperm is not None:
            matches = np.empty(n_l)
            matches[pperm] = match_sorted
            acc = np.empty_like(acc_sorted)
            acc[:, pperm] = acc_sorted
        else:
            matches, acc = match_sorted, acc_sorted

        def greduce(w: np.ndarray) -> np.ndarray:
            if n_l == 0:
                return np.zeros(k)
            return np.bincount(gid_orig, weights=w, minlength=k)

        mm_rows = None
        if any(p[0] == "mm" for p in parts):
            mm_rows = _RunExtremum(
                codes[primary], data[primary].offsets, pperm,
                codes[secondary], data[secondary].offsets, perms[secondary],
                matches, n_l,
            )

        out: list[np.ndarray] = [greduce(matches)]  # star = pairs per group
        spec_layout: list[tuple[int | None, int]] = []
        for part in parts:
            if part[0] == "star":
                spec_layout.append((None, 0))
            elif part[0] == "sec":
                _, vi, ci = part
                v_idx = None
                if vi is not None:
                    out.append(greduce(acc[vi]))
                    v_idx = len(out) - 1
                out.append(greduce(acc[ci]))
                spec_layout.append((v_idx, len(out) - 1))
            elif part[0] == "mm":
                from hyperspace_tpu.ops.aggregate import aggregate_arrays_host

                _, fn, s, vals, ind = part
                row_ext, row_valid = mm_rows.per_primary_row(fn, s, secondary, vals, ind)
                res, cnt = aggregate_arrays_host([(row_ext, row_valid, fn)], gid_orig, k)
                out.append(res[0])
                out.append(cnt[0])
                spec_layout.append((len(out) - 2, len(out) - 1))
            else:
                _, vals, ind = part
                v_idx = None
                if vals is not None:
                    out.append(greduce(vals * matches))
                    v_idx = len(out) - 1
                out.append(greduce(ind * matches))
                spec_layout.append((v_idx, len(out) - 1))
        return out, spec_layout

    def _partition_join(self, plan: Join, lside: "SideData", rside: "SideData") -> ColumnTable:
        """Per-bucket merge join over the concatenated bucket-grouped
        layout: everything host-side is vectorized (pad-gather in, one
        repeat+add to globalize match indices, ONE native gather per
        column out) — no per-bucket Python loop (round 1 weakness #4).
        Non-inner join types derive from the same match pairs: outer
        variants append the unmatched side's rows null-extended, semi/anti
        keep left rows by match flag (the join-type surface Spark's
        SortMergeJoinExec serves over the reference's rewritten bucketed
        relations, JoinIndexRule.scala:124-153)."""
        lt, rt = lside.table, rside.table
        how = plan.how

        if how in ("semi", "anti") and plan.condition is None:
            # Existence is a membership probe, not a join: never expand the
            # match pairs (a hot key repeated k×k ways would materialize k²
            # pairs only to collapse into |L| bits).
            matched = self._semi_match_mask(plan, lside, rside)
            out = lt.filter_mask(matched if how == "semi" else ~matched)
            return ColumnTable(plan.schema, out.columns, out.dictionaries, out.validity)

        lidx, ridx, totals = self._match_pairs(plan, lside, rside)

        if how in ("semi", "anti"):
            # Residual existence (EXISTS with extra conditions): a left
            # row matches iff SOME equi-pair also passes the residual —
            # gather ONLY the columns the condition reads (the pairs are
            # k x k expanded; none of the payload survives the |L|-bit
            # reduction), evaluate, and reduce surviving lidx to bits.
            from hyperspace_tpu.schema import Schema as _Schema

            refs = {r.lower() for r in plan.condition.references()}
            rkeys_low = {rt.schema.field(c).name.lower() for c in plan.right_on}
            lkeep = [f.name for f in lt.schema.fields if f.name.lower() in refs]
            if not lkeep:  # keep one cheap key lane so row count survives
                lkeep = [lt.schema.field(plan.left_on[0]).name]
            rkeep = [rt.schema.field(c).name for c in plan.right_on] + [
                f.name
                for f in rt.schema.fields
                if f.name.lower() in refs and f.name.lower() not in rkeys_low
            ]
            sub_schema = _Schema(
                tuple(lt.schema.select(lkeep).fields)
                + tuple(
                    f for f in rt.schema.select(rkeep).fields
                    if f.name.lower() not in rkeys_low
                )
            )
            pairs = self._gather_pairs(
                plan, lt.select(lkeep), rt.select(rkeep), lidx, ridx, schema=sub_schema
            )
            pmask = eval_predicate_mask(
                pairs, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            matched = np.zeros(lt.num_rows, dtype=bool)
            matched[lidx[pmask]] = True
            self._phys(residual_condition=True, residual_pairs_dropped=int((~pmask).sum()))
            out = lt.filter_mask(matched if how == "semi" else ~matched)
            return ColumnTable(plan.schema, out.columns, out.dictionaries, out.validity)

        inner = self._gather_pairs(plan, lt, rt, lidx, ridx)
        if plan.condition is not None and how != "inner":
            # Outer-join ON residual alters MATCHING: a pair failing it
            # is no match, so its rows fall through to the null-extended
            # unmatched parts below (computed from the SURVIVING pairs).
            pmask = eval_predicate_mask(
                inner, plan.condition, mesh=self.mesh, venue=self._filter_venue()
            )
            inner = inner.filter_mask(pmask)
            lidx, ridx = lidx[pmask], ridx[pmask]
            self._phys(residual_condition=True, residual_pairs_dropped=int((~pmask).sum()))
        if how == "inner":
            # Bucket-preserving output: an inner join over B>1 buckets
            # emits pairs bucket-major, so the result STAYS bucket-
            # grouped on the (merged, left-named) join keys — a later
            # join on the same keys reuses the grouping with no exchange
            # (SURVEY §2.3: chained star joins stay bucket-parallel).
            if (
                totals is not None
                and len(totals) > 1
                and lside.hash_fields is not None
            ):
                self._stash_bucketed(
                    inner,
                    np.concatenate([[0], np.cumsum(totals)]).astype(np.int64),
                    plan.left_on,
                    lside.hash_fields,
                )
            return inner
        parts = [inner]
        if how in ("left", "full"):
            lmask = np.zeros(lt.num_rows, dtype=bool)
            lmask[lidx] = True
            parts.append(self._left_unmatched(plan, lt, rt, ~lmask))
        if how in ("right", "full"):
            rmask = np.zeros(rt.num_rows, dtype=bool)
            rmask[ridx] = True
            parts.append(self._right_unmatched(plan, lt, rt, ~rmask))
        parts = [p for p in parts if p.num_rows > 0]
        if not parts:
            return inner
        # Concat builds from plan.schema, so any extra physical columns a
        # wide index scan carried along are dropped here; the outer-join
        # output is exactly the declared join schema.
        return ColumnTable.concat(parts) if len(parts) > 1 else parts[0]

    def _semi_match_mask(self, plan: Join, lside: "SideData", rside: "SideData") -> np.ndarray:
        """Per-left-row existence of an equi-match in the right side:
        one sorted membership probe over (bucket, key-code) composites —
        O((n+m) log m) on host, no pair expansion, no device round-trip
        (the result is |L| bits the mask filter consumes on host anyway).
        Null-keyed rows carry side-distinct negative codes and never
        match (SQL: NULL = NULL is not true), so anti keeps them."""
        lt, rt = lside.table, rside.table
        lkeys = [lt.schema.field(c).name for c in plan.left_on]
        rkeys = [rt.schema.field(c).name for c in plan.right_on]
        lc0, rc0 = _factorize_keys_cached(lt, rt, lkeys, rkeys)
        lcodes = lc0.astype(np.int64)
        rcodes = rc0.astype(np.int64)
        b = len(lside.offsets) - 1
        self.stats["num_buckets"] = b
        self.stats["join_kernel"] = "host-membership-probe"
        comp_l = _composite_keys(lcodes, lside.offsets)
        comp_r = np.sort(_composite_keys(rcodes, rside.offsets))
        pos = np.searchsorted(comp_r, comp_l)
        matched = np.zeros(lt.num_rows, dtype=bool)
        in_range = pos < len(comp_r)
        matched[in_range] = comp_r[pos[in_range]] == comp_l[in_range]
        return matched

    def _match_pairs(self, plan: Join, lside: "SideData", rside: "SideData"):
        """(lidx, ridx) global match row indices of the equi-join, from the
        venue-selected merge kernel over bucket-sorted key codes. A
        heavily asymmetric single-partition join takes the broadcast hash
        path instead: only the small side is sorted, the large side
        probes it — the analog of Spark's BroadcastExchange fallback the
        reference environment supplies for small sides
        (PhysicalOperatorAnalyzer.scala:46-50)."""
        lt, rt = lside.table, rside.table
        lkeys = [lt.schema.field(c).name for c in plan.left_on]
        rkeys = [rt.schema.field(c).name for c in plan.right_on]

        # Shared order-preserving factorization of the key tuples.
        lcodes, rcodes = _factorize_keys_cached(lt, rt, lkeys, rkeys)

        b0 = len(lside.offsets) - 1
        if b0 == 1 and self._should_broadcast(lt.num_rows, rt.num_rows):
            res = _broadcast_probe(lcodes, rcodes)
            if res is not None:
                self.stats["num_buckets"] = 1
                self.stats["join_kernel"] = "host-broadcast-hash"
                return res[0], res[1], None

        lcodes, lperm = _bucket_sorted_codes(lcodes, lside)
        rcodes, rperm = _bucket_sorted_codes(rcodes, rside)
        b = len(lside.offsets) - 1
        self.stats["num_buckets"] = b

        host_res = None
        if (
            lcodes.dtype == np.int32
            and rcodes.dtype == np.int32
            and self._join_venue() == "host"
        ):
            from hyperspace_tpu import native

            host_res = native.merge_join_sorted(
                lcodes, lside.offsets, rcodes, rside.offsets
            )
        if host_res is not None:
            # Host venue: exact bucket-parallel C++ merge over the already
            # host-resident sorted runs — no device round-trip (the match
            # pairs land on host either way; see parallel/bandwidth.py).
            lidx, ridx, totals = host_res
            self.stats["join_kernel"] = "host-native-merge"
        else:
            lk = _pad_bucket_major_cached(lcodes, lside.offsets)
            rk = _pad_bucket_major_cached(rcodes, rside.offsets)
            if self.mesh is not None:
                from hyperspace_tpu.parallel.mesh import mesh_for_parallelism, mesh_size

                jmesh = mesh_for_parallelism(self.mesh, b)
                li_flat, ri_flat, totals = join_ops.merge_join_sharded(lk, rk, jmesh)
                self.stats["join_devices"] = mesh_size(jmesh)
            else:
                li_flat, ri_flat, totals = join_ops.merge_join(lk, rk)
            self.stats["join_kernel"] = "device-searchsorted"
            # Local (within-bucket) match indices → global row indices.
            lidx = np.repeat(lside.offsets[:-1], totals) + li_flat
            ridx = np.repeat(rside.offsets[:-1], totals) + ri_flat
        if lperm is not None:
            lidx = lperm[lidx]
        if rperm is not None:
            ridx = rperm[ridx]
        # Pair order stays bucket-major through the perm mapping, so
        # `totals` doubles as the OUTPUT's bucket grouping.
        return lidx, ridx, np.asarray(totals, dtype=np.int64)

    def _should_broadcast(self, n_l: int, n_r: int) -> bool:
        """Small-enough and asymmetric-enough for the broadcast probe."""
        from hyperspace_tpu.config import DEFAULT_JOIN_BROADCAST_MAX_ROWS

        cap = (
            self.conf.join_broadcast_max_rows
            if self.conf is not None
            else DEFAULT_JOIN_BROADCAST_MAX_ROWS
        )
        if cap <= 0:
            return False
        small, large = min(n_l, n_r), max(n_l, n_r)
        return 0 < small <= cap and large >= 4 * small

    def _gather_pairs(
        self, plan: Join, lt: ColumnTable, rt: ColumnTable, lidx, ridx, schema=None
    ) -> ColumnTable:
        """Materialize matched rows: left columns + right non-key columns.
        `schema` overrides the output schema (semi/anti residual
        evaluation gathers in the inner-join shape)."""
        schema = schema if schema is not None else plan.schema
        rkeys_low = {rt.schema.field(c).name.lower() for c in plan.right_on}
        lgather = lt.take(lidx)
        cols = dict(lgather.columns)
        dicts = dict(lgather.dictionaries)
        val = dict(lgather.validity)
        rnames = [f.name for f in rt.schema.fields if f.name.lower() not in rkeys_low]
        rgather = rt.select(rnames).take(ridx)
        cols.update(rgather.columns)
        dicts.update(rgather.dictionaries)
        val.update(rgather.validity)
        return ColumnTable(schema, cols, dicts, val)

    def _left_unmatched(self, plan: Join, lt: ColumnTable, rt: ColumnTable, mask) -> ColumnTable:
        """Unmatched left rows, right-side fields null-extended."""
        sub = lt.filter_mask(mask)
        lnames = {x.lower() for x in plan.left.schema.names}
        cols: dict = {}
        dicts: dict = {}
        val: dict = {}
        for f in plan.schema.fields:
            if f.name.lower() in lnames:
                _copy_field(f, sub, f.name, cols, dicts, val)
            else:
                _null_field(f, sub.num_rows, rt, cols, dicts, val)
        return ColumnTable(plan.schema, cols, dicts, val)

    def _right_unmatched(self, plan: Join, lt: ColumnTable, rt: ColumnTable, mask) -> ColumnTable:
        """Unmatched right rows: key columns coalesce to the RIGHT key's
        values (under the left-named output column), right non-key fields
        carry their values, left-only fields are null-extended."""
        sub = rt.filter_mask(mask)
        key_src = {l.lower(): r for l, r in zip(plan.left_on, plan.right_on)}
        rnames = {x.lower() for x in plan.right.schema.names}
        cols: dict = {}
        dicts: dict = {}
        val: dict = {}
        for f in plan.schema.fields:
            low = f.name.lower()
            if low in key_src:
                _copy_field(f, sub, key_src[low], cols, dicts, val)
            elif low in rnames:
                _copy_field(f, sub, f.name, cols, dicts, val)
            else:
                _null_field(f, sub.num_rows, lt, cols, dicts, val)
        return ColumnTable(plan.schema, cols, dicts, val)


def _broadcast_probe(lcodes: np.ndarray, rcodes: np.ndarray):
    """Match pairs via a broadcast hash table: the smaller side builds a
    dense code -> (start, count) table, every large-side row probes it
    with ONE vectorized gather (no binary search — random-access
    searchsorted over millions of probes is ~10x slower than a
    cache-resident table), and duplicate runs expand vectorized. The
    large side is never sorted. Null codes are side-distinct negatives
    and never match. Returns None when the shared code space is too
    sparse for a table (caller falls back to the merge kernel); else
    (lidx, ridx) in the merge path's contract."""
    swap = len(lcodes) < len(rcodes)
    build, probe = (lcodes, rcodes) if swap else (rcodes, lcodes)
    top = 0
    if len(build):
        top = max(top, int(build.max()) + 1)
    if len(probe):
        top = max(top, int(probe.max()) + 1)
    if top == 0:
        # Every key on both sides is null-coded: no row can match.
        empty = np.zeros(0, dtype=np.int64)
        return empty, empty
    if top > 8 * len(build) + 65_536:
        return None  # sparse code space: the table would dwarf the side
    bvalid = build >= 0
    counts = np.bincount(build[bvalid], minlength=top)
    starts = np.concatenate([[0], np.cumsum(counts[:-1])]) if top else np.zeros(0, np.int64)
    order = np.argsort(build, kind="stable")  # null codes sort first
    nneg = int((~bvalid).sum())
    pvalid = probe >= 0
    pc = np.where(pvalid, probe, 0)
    cnt = np.where(pvalid, counts[pc], 0)
    lo = nneg + starts[pc]
    if not counts.size or counts.max() <= 1:
        # Unique build keys (the normal dimension-table case): each probe
        # row matches 0 or 1 build rows — no run expansion at all.
        matched = cnt > 0
        probe_idx = np.flatnonzero(matched)
        build_idx = order[lo[matched]]
        if swap:
            return build_idx, probe_idx
        return probe_idx, build_idx
    total = int(cnt.sum())
    probe_idx = np.repeat(np.arange(len(probe), dtype=np.int64), cnt)
    run_starts = np.cumsum(cnt) - cnt
    within = np.arange(total, dtype=np.int64) - np.repeat(run_starts, cnt)
    build_idx = order[np.repeat(lo, cnt) + within]
    if swap:
        return build_idx, probe_idx  # build side is the LEFT input
    return probe_idx, build_idx


def _copy_field(out_f, src: ColumnTable, src_name: str, cols, dicts, val) -> None:
    """Copy src column `src_name` into output field `out_f` (dtype-cast
    for numeric mismatches — outer-join key coalescing may source the
    left-named key column from the right side)."""
    sf = src.schema.field(src_name)
    arr = src.columns[sf.name]
    if sf.name in src.dictionaries:
        dicts[out_f.name] = src.dictionaries[sf.name]
        cols[out_f.name] = arr
    else:
        want = np.dtype(out_f.device_dtype)
        cols[out_f.name] = arr if arr.ndim > 1 or arr.dtype == want else arr.astype(want)
    v = src.validity.get(sf.name)
    if v is not None:
        val[out_f.name] = v


def _null_field(out_f, n: int, dict_src: ColumnTable | None, cols, dicts, val) -> None:
    """All-null column for output field `out_f` (outer-join null
    extension). String fields reuse `dict_src`'s dictionary for that
    field when available, so concat with the matched part needs no
    dictionary merge."""
    if out_f.is_vector:
        raise HyperspaceError(
            f"outer join cannot null-extend vector column {out_f.name!r}"
        )
    if out_f.is_string:
        d = None
        if dict_src is not None:
            try:
                sf = dict_src.schema.field(out_f.name)
                d = dict_src.dictionaries.get(sf.name)
            except Exception:
                d = None
        if d is None or len(d) == 0:
            d = np.array([""], dtype=object)
        cols[out_f.name] = np.zeros(n, dtype=np.int32)
        dicts[out_f.name] = d
    else:
        cols[out_f.name] = np.zeros(n, dtype=out_f.device_dtype)
    val[out_f.name] = np.zeros(n, dtype=bool)


def _concat_side_cached(tables: list[ColumnTable]) -> ColumnTable:
    """Concatenated bucket-grouped side table, memoized on the identity
    of the per-bucket cached tables (the device plane's HBM-resident
    container rests on this stability: frozen concat => stable codes =>
    cached pads => cached uploads). Falls through for single groups (the
    cached table passes through already frozen)."""
    from hyperspace_tpu.execution import device_cache as dc

    if len(tables) == 1:
        return tables[0]
    # Only identity-stable inputs may be memoized (and only then may the
    # output be frozen): per-query tables too large for the io cache get
    # fresh ids every time — caching against those would pile dead pinned
    # entries, and freezing their concat would let every downstream cache
    # mistake per-query arrays for stable ones.
    stable = all(
        all(
            dc.is_stable(a)
            for a in (*t.columns.values(), *t.validity.values(), *t.dictionaries.values())
        )
        for t in tables
    )
    if not stable:
        return ColumnTable.concat(tables)

    def build():
        out = ColumnTable.concat(tables)
        for arr in (*out.columns.values(), *out.validity.values(), *out.dictionaries.values()):
            dc.freeze(arr)
        # _table_nbytes counts string payloads, not just object pointers —
        # the budget must see what the entry actually retains.
        return out, int(hio._table_nbytes(out))

    return dc.HOST_DERIVED.get_or_build(
        ("sidecat", tuple(id(t) for t in tables)), tuple(tables), build
    )


def _composite_keys(codes: np.ndarray, offsets: np.ndarray) -> np.ndarray:
    """(bucket << 33) + code composites: codes span int32 (±2^31) and
    buckets are small, so the shifted sum is collision-free in int64 and
    globally SORTED for bucket-major key-sorted inputs. Shared by the
    semi/anti membership probe and the fused run-extremum channels."""
    b = np.repeat(np.arange(len(offsets) - 1, dtype=np.int64), np.diff(offsets))
    return (b << np.int64(33)) + codes.astype(np.int64)


class _RunExtremum:
    """Per-primary-row extrema over the secondary match runs, shared by
    every min/max channel of one fused join-aggregation. The secondary
    side is bucket-major key-sorted, so all rows with one key form a
    contiguous run; the composite key is globally sorted and each
    primary row's run bounds come from two searchsorteds (built LAZILY —
    primary-side-only channels never pay for them). Extrema are
    multiplicity-independent, so the per-KEY extremum stands in for
    every duplicate primary row with that key."""

    def __init__(self, pri_codes, pri_offsets, pperm, sec_codes, sec_offsets, sperm, matches, n_l):
        self.sperm = sperm
        self.pperm = pperm
        self.matches = matches
        self.n_l = n_l
        self._pri = (pri_codes, pri_offsets)
        self._sec = (sec_codes, sec_offsets)
        self._runs = None

    def _run_index(self):
        if self._runs is None:
            cp = _composite_keys(*self._pri)
            cs = _composite_keys(*self._sec)
            st = np.searchsorted(cs, cp, side="left")
            en = np.searchsorted(cs, cp, side="right")
            if len(cs):
                starts = np.concatenate([[0], np.flatnonzero(np.diff(cs) != 0) + 1])
                ridx = np.clip(
                    np.searchsorted(starts, st, side="right") - 1, 0, len(starts) - 1
                )
            else:
                starts = np.zeros(0, np.int64)
                ridx = np.zeros(len(cp), np.int64)
            self._runs = (st, en, en > st, starts, ridx)
        return self._runs

    def per_primary_row(self, fn: str, side: str, secondary: str, vals, ind):
        """(row extremum, row validity) in ORIGINAL primary order for one
        channel; `vals`/`ind` are the channel's per-orig-row arrays of
        `side` (invalid slots already zeroed, `ind` marking them)."""
        identity = np.inf if fn == "min" else -np.inf
        if side == secondary:
            _st, _en, has, starts, ridx = self._run_index()
            sv = vals if self.sperm is None else vals[self.sperm]
            si = ind if self.sperm is None else ind[self.sperm]
            if not len(starts):
                return np.full(self.n_l, identity), np.zeros(self.n_l, bool)
            op = np.minimum if fn == "min" else np.maximum
            sv = np.where(si > 0, np.asarray(sv, np.float64), identity)
            key_ext = op.reduceat(sv, starts)
            key_validcnt = np.add.reduceat(np.asarray(si, np.float64), starts)
            ext_sorted = np.where(has, key_ext[ridx], identity)
            valid_sorted = has & (key_validcnt[ridx] > 0)
            if self.pperm is not None:
                ext = np.empty(self.n_l)
                ext[self.pperm] = ext_sorted
                valid = np.empty(self.n_l, bool)
                valid[self.pperm] = valid_sorted
                return ext, valid
            return ext_sorted, valid_sorted
        # Primary-side channel: extremum over the group's MATCHED rows.
        v = np.where(np.asarray(ind) > 0, np.asarray(vals, np.float64), identity)
        valid = (self.matches > 0) & (np.asarray(ind) > 0)
        return v, valid


def _desugar_count_distinct(plan: "Aggregate"):
    """count(distinct col) as a TWO-PHASE re-aggregation: the inner
    aggregate groups by (group keys, distinct column) — its rows are the
    distinct (group, value) pairs — and computes partials for every
    sibling aggregate; the outer counts the distinct column (nulls
    excluded, SQL semantics) and recombines the partials (sum of sums /
    counts, min of mins, max of maxes). The Spark analog is the planner's
    distinct-aggregate Expand rewrite. Returns (desugared plan, aliases
    of the original count specs — the caller zero-fills their NULLs)."""
    from hyperspace_tpu.plan.nodes import AggSpec, Aggregate

    # The caller routes multi-distinct / mean-sharing aggregates to
    # _distinct_aggregate; this fast path sees exactly one distinct
    # column and no mean.
    dcol = next(a.expr.name for a in plan.aggs if a.fn == "count_distinct")
    group_low = {c.lower() for c in plan.group_by}
    inner_groups = list(plan.group_by) + ([dcol] if dcol.lower() not in group_low else [])
    inner_aggs: list = []
    outer_aggs: list = []
    count_aliases: list[str] = []
    for i, a in enumerate(plan.aggs):
        if a.fn == "count_distinct":
            outer_aggs.append(AggSpec("count", Col(dcol), a.alias))
            continue
        part = f"__partial_{i}"
        if a.fn == "count":
            inner_aggs.append(AggSpec("count", a.expr, part))
            outer_aggs.append(AggSpec("sum", Col(part), a.alias))
            count_aliases.append(a.alias)
        else:  # sum / min / max recombine with themselves
            inner_aggs.append(AggSpec(a.fn, a.expr, part))
            outer_aggs.append(AggSpec(a.fn, Col(part), a.alias))
    inner = Aggregate(plan.child, inner_groups, inner_aggs)
    return Aggregate(inner, list(plan.group_by), outer_aggs), count_aliases


def _stable_table_refs(table: ColumnTable, names: set[str]):
    """(refs, id-parts) over every array the named columns touch (data,
    dictionary, validity), or (None, None) when any is unstable."""
    from hyperspace_tpu.execution import device_cache as dc

    refs: list = []
    parts: list = []
    for nm in sorted(names):
        f = table.schema.field(nm)
        for a in (table.columns[f.name], table.dictionaries.get(f.name), table.validity.get(f.name)):
            if a is None:
                parts.append(None)
                continue
            if not dc.is_stable(a):
                return None, None
            refs.append(a)
            parts.append(id(a))
    return tuple(refs), tuple(parts)


def _group_ids_cached(table: ColumnTable, group_by: list[str]):
    """group_ids memoized on the identity of the (stable) group-key
    arrays — repeat aggregations over the same index version skip the
    factorization of millions of keys."""
    from hyperspace_tpu.execution import device_cache as dc
    from hyperspace_tpu.ops.aggregate import group_ids

    if not group_by:
        return group_ids(table, group_by)
    refs, parts = _stable_table_refs(table, {c.lower() for c in group_by})
    if refs is None:
        return group_ids(table, group_by)

    def build():
        gid, k, first = group_ids(table, group_by)
        dc.freeze(gid)
        dc.freeze(first)
        return (gid, k, first), int(gid.nbytes + first.nbytes)

    return dc.HOST_DERIVED.get_or_build(
        ("gid", tuple(c.lower() for c in group_by), parts), refs, build
    )


def _agg_channels_cached(tbl: ColumnTable, spec):
    """(masked values, indicator) channels for one AggSpec, memoized per
    (expression, input identity) for stable tables."""
    import json

    from hyperspace_tpu.execution import device_cache as dc
    from hyperspace_tpu.ops.aggregate import agg_input

    def raw():
        vals, valid, _ = agg_input(tbl, spec)
        vals = np.asarray(vals, dtype=np.float64)
        if valid is not None:
            vals = np.where(valid, vals, 0.0)
        ind = np.ones(tbl.num_rows, np.float64) if valid is None else valid.astype(np.float64)
        return vals, ind

    refs, parts = _stable_table_refs(tbl, {r.lower() for r in spec.references()})
    if not refs:  # unstable or constant expression: no identity to key on
        return raw()
    key = ("aggin", json.dumps(spec.expr.to_json(), sort_keys=True), parts)

    def build():
        vals, ind = raw()
        dc.freeze(vals)
        dc.freeze(ind)
        return (vals, ind), int(vals.nbytes + ind.nbytes)

    return dc.HOST_DERIVED.get_or_build(key, refs, build)


def _factorize_keys_cached(lt: ColumnTable, rt: ColumnTable, lkeys, rkeys):
    """Pairwise key factorization memoized on the IDENTITY of every input
    it reads (key columns, dictionaries, validity) — valid only when all
    are stable (frozen index-cache arrays). Repeat joins over the same
    index version skip ranking entirely; codes are frozen so downstream
    pad/upload caches can key on them. Returns (lcodes, rcodes)."""
    from hyperspace_tpu.execution import device_cache as dc

    lrefs, lparts = _stable_table_refs(lt, {k.lower() for k in lkeys})
    rrefs, rparts = _stable_table_refs(rt, {k.lower() for k in rkeys})
    if lrefs is None or rrefs is None:
        lc, rc = _factorize_keys([lt], [rt], lkeys, rkeys)
        return lc[0], rc[0]
    refs = lrefs + rrefs
    parts = (lparts, rparts)

    def build():
        lc, rc = _factorize_keys([lt], [rt], lkeys, rkeys)
        out = (dc.freeze(lc[0]), dc.freeze(rc[0]))
        return out, int(lc[0].nbytes + rc[0].nbytes)

    return dc.HOST_DERIVED.get_or_build(("fact", parts), refs, build)


def _pad_bucket_major_cached(
    codes: np.ndarray, offsets: np.ndarray, fill=None, width: int | None = None
) -> np.ndarray:
    """Bucket-major pad through the derived cache when the input is
    stable (index-sorted, frozen) — the [B, L] device upload then hits
    the HBM cache too."""
    from hyperspace_tpu.execution import device_cache as dc

    if dc.is_stable(codes):
        return dc.derived(
            ("padbm", id(codes), offsets.tobytes(), repr(fill), width),
            (codes,),
            lambda: _pad_bucket_major(codes, offsets, fill=fill, width=width),
        )
    return _pad_bucket_major(codes, offsets, fill=fill, width=width)


def _stack_cached(arrs: list, empty_shape: tuple) -> np.ndarray:
    """np.stack through the derived cache when every channel is stable
    (the [A, n] float64 stack is a 100MB-scale memcpy per query)."""
    from hyperspace_tpu.execution import device_cache as dc

    if not arrs:
        return np.zeros(empty_shape)
    if all(dc.is_stable(a) for a in arrs):
        return dc.derived(
            ("stack", tuple(id(a) for a in arrs)), tuple(arrs), lambda: np.stack(arrs)
        )
    return np.stack(arrs)


def _key_null_mask(table: ColumnTable, keys: list[str]) -> np.ndarray | None:
    """True where ANY key column is null (such rows never join — SQL:
    NULL = NULL is not true). None when every key column is null-free."""
    m = None
    for k in keys:
        valid = table.valid_mask(k)
        if valid is not None:
            m = ~valid if m is None else (m | ~valid)
    return m


def _apply_null_codes(lcodes, rcodes, lnulls, rnulls):
    """Null-keyed rows get side-distinct negative codes (-2 left, -1
    right): they sort first and can never equal across sides, so the merge
    kernel drops them with zero extra work."""
    for c, m in zip(lcodes, lnulls):
        if m is not None:
            c[m] = -2
    for c, m in zip(rcodes, rnulls):
        if m is not None:
            c[m] = -1
    return lcodes, rcodes


def _factorize_keys(ltables, rtables, lkeys, rkeys):
    """Map each partition's key tuples to a shared int32 rank-code space
    whose order matches the lexicographic order of the raw key tuples.
    int32 keeps the device merge-join kernels on native 32-bit lanes (TPU
    emulates 64-bit); ranks always fit (bounded by total row count)."""
    lnulls = [_key_null_mask(t, lkeys) for t in ltables]
    rnulls = [_key_null_mask(t, rkeys) for t in rtables]
    has_nulls = any(m is not None for m in lnulls + rnulls)
    # Fast path: a single integer key whose value SPAN fits int32 needs no
    # ranking — values shifted by the minimum are order-preserving codes.
    # Codes are NON-NEGATIVE by construction, so a negative code always
    # means a null-keyed row (the invariant _broadcast_probe and the
    # null-code scheme below rely on). (Skipped with nulls: raw values
    # could collide with the null codes.)
    if len(lkeys) == 1 and not has_nulls:
        lvals = [_logical_key(t, lkeys[0]) for t in ltables]
        rvals = [_logical_key(t, rkeys[0]) for t in rtables]
        if all(np.issubdtype(v.dtype, np.integer) for v in lvals + rvals):
            lo = min((int(v.min()) for v in lvals + rvals if len(v)), default=0)
            hi = max((int(v.max()) for v in lvals + rvals if len(v)), default=0)
            # Span strictly below int32 max: the sentinel pad must still
            # sort last after the shift.
            if hi - lo < np.iinfo(np.int32).max - 1:
                shift = np.int64(lo)
                return (
                    [(v.astype(np.int64) - shift).astype(np.int32) for v in lvals],
                    [(v.astype(np.int64) - shift).astype(np.int32) for v in rvals],
                )

    per_col_codes_l: list[list[np.ndarray]] = [[] for _ in ltables]
    per_col_codes_r: list[list[np.ndarray]] = [[] for _ in rtables]
    cards: list[int] = []
    for lname, rname in zip(lkeys, rkeys):
        lvals = [_logical_key(t, lname) for t in ltables]
        rvals = [_logical_key(t, rname) for t in rtables]
        allv = np.concatenate(lvals + rvals) if (lvals or rvals) else np.array([])
        uniq, inv = np.unique(allv, return_inverse=True)
        cards.append(max(len(uniq), 1))
        pos = 0
        for i, v in enumerate(lvals):
            per_col_codes_l[i].append(inv[pos : pos + len(v)])
            pos += len(v)
        for i, v in enumerate(rvals):
            per_col_codes_r[i].append(inv[pos : pos + len(v)])
            pos += len(v)

    def combine(per_part):
        out = []
        for codes in per_part:
            acc = np.zeros(len(codes[0]) if codes else 0, dtype=np.int64)
            for c, k in zip(codes, cards):
                acc = acc * np.int64(k) + c.astype(np.int64)
            out.append(acc)
        return out

    import math

    if math.prod(cards) >= np.iinfo(np.int64).max:
        # The int64 mixed-radix combination itself would wrap — the codes
        # in `combine` below would collide before any re-rank could help.
        raise HyperspaceError(
            f"join key cardinalities {cards} overflow the int64 code space"
        )
    lcomb, rcomb = combine(per_col_codes_l), combine(per_col_codes_r)
    int32_max = np.iinfo(np.int32).max
    # Mixed-radix codes that provably fit int32 cast directly — no
    # re-rank pass needed (math.prod is exact, arbitrary precision).
    if math.prod(cards) < int32_max:
        return _apply_null_codes(
            [c.astype(np.int32) for c in lcomb],
            [c.astype(np.int32) for c in rcomb],
            lnulls,
            rnulls,
        )
    # Otherwise re-rank the combined codes down to int32 (order preserved
    # by np.unique).
    allc = np.concatenate(lcomb + rcomb) if (lcomb or rcomb) else np.zeros(0, np.int64)
    uniq, inv = np.unique(allc, return_inverse=True)
    if len(uniq) >= int32_max:
        raise HyperspaceError(
            f"join key space has {len(uniq)} distinct tuples — exceeds the "
            "int32 code space"
        )
    inv = inv.astype(np.int32)
    pos, out_l, out_r = 0, [], []
    for c in lcomb:
        out_l.append(inv[pos : pos + len(c)])
        pos += len(c)
    for c in rcomb:
        out_r.append(inv[pos : pos + len(c)])
        pos += len(c)
    return _apply_null_codes(out_l, out_r, lnulls, rnulls)


def _logical_key(table: ColumnTable, name: str) -> np.ndarray:
    f = table.schema.field(name)
    arr = table.columns[f.name]
    if f.is_string:
        return table.dictionaries[f.name][arr]
    return arr
