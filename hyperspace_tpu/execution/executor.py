"""Plan executor: runs logical plans against the device plane.

The analog of Spark's physical planning + execution for the IR's node
types (SURVEY.md §7 design stance). What matters for TPU performance:

- **bucket pruning** (Filter over an index scan with equality literals on
  every bucket column): recompute the canonical row hash on the literal
  tuple and read ONLY that bucket's file — the reference cannot do this
  (its FilterIndexRule keeps a full scan, FilterIndexRule.scala:114-120);
  for a point lookup this divides IO by numBuckets;
- **zero-exchange join** (Join over two index scans bucketed on the join
  keys with equal bucket counts): per-bucket sort-merge join, all buckets
  in one vmapped device kernel (ops/join.py) — the analog of the
  reference's shuffle-free SortMergeJoin;
- predicates evaluate as one fused XLA computation (ops/filter.py).

Round-5 layout: this module owns dispatch, venue selection, and the
order/limit/union operators; the heavy operator families live in
per-operator mixins (exec_scan / exec_side / exec_join / exec_join_agg /
exec_agg) over the shared support layer (exec_common).
"""

from __future__ import annotations

import dataclasses
import time
from pathlib import Path

import numpy as np

from hyperspace_tpu.obs import trace as obs_trace

from hyperspace_tpu.exceptions import HyperspaceError
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.execution.build_exchange import compute_row_hashes, hash_scalar_key
from hyperspace_tpu.execution.table import ColumnTable
from hyperspace_tpu.dataset import format_suffix, list_data_files
from hyperspace_tpu.ops.filter import apply_filter, eval_predicate_mask
from hyperspace_tpu.ops.hashing import bucket_ids
from hyperspace_tpu.ops import join as join_ops
from hyperspace_tpu.plan.expr import And, BinOp, Col, Expr, Lit, evaluate, split_conjuncts
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
    Window,
)


from hyperspace_tpu.execution.exec_agg import AggregateMixin
from hyperspace_tpu.execution.exec_common import (  # noqa: F401  (re-exports)
    AlignedSide,
    KeyBounds,
    SideData,
    _TableLeaf,
    _broadcast_probe,
    _bucket_sorted_codes,
    _composite_keys,
    _concat_side_cached,
    _copy_field,
    _desugar_count_distinct,
    _factorize_keys,
    _factorize_keys_cached,
    _filter_side,
    _group_ids_cached,
    _hash_fields_compatible,
    _logical_key,
    _null_field,
    _pad_bucket_major,
    _stable_table_refs,
    key_bounds,
    predicate_all_key_bounds,
)
from hyperspace_tpu.execution.exec_join import JoinMixin
from hyperspace_tpu.execution.exec_join_agg import FusedJoinAggMixin
from hyperspace_tpu.execution.exec_scan import ScanFilterMixin
from hyperspace_tpu.execution.exec_side import JoinSidesMixin


class Executor(
    ScanFilterMixin,
    JoinSidesMixin,
    JoinMixin,
    FusedJoinAggMixin,
    AggregateMixin,
):
    """Runs plans on the device plane. With a mesh, the query plane is
    distributed: the bucket-aligned SMJ shards its bucket dimension over
    the mesh (zero collectives — the analog of the reference's
    cluster-parallel zero-exchange SortMergeJoin across executors,
    JoinIndexRule.scala:124-153) and filter predicates shard their row
    dimension (FilterIndexRule.scala:114-120 keeps full scan parallelism).
    `stats` records what physically ran (files read, kernels, devices) —
    the executed-plan evidence explain consumes."""

    def __init__(self, mesh=None, conf=None):
        self.mesh = mesh
        self.conf = conf
        self.stats: dict = {
            "files_read": 0,
            "files_pruned": 0,
            "rows_pruned": 0,
            "bytes_scanned": 0,
            "join_path": None,
            "join_kernel": None,
            "join_devices": 1,
            "num_buckets": None,
            "agg_path": None,
        }
        # Executed physical plan, built as the query runs (the analog of
        # the reference diffing executedPlans, PlanAnalyzer.scala:163-178).
        self.physical_plan = None
        self._cur_phys = None
        # Bucket-preserving join outputs: id(table) -> (weakref, offsets,
        # lowered key names, hash-domain fields). Bounded; weakrefs keep
        # id-reuse from matching a dead table.
        self._bucketed_outputs: dict[int, tuple] = {}

    def _stash_bucketed(self, table: ColumnTable, offsets, keys, hash_fields) -> None:
        import weakref

        if len(self._bucketed_outputs) >= 16:
            self._bucketed_outputs.clear()
        self._bucketed_outputs[id(table)] = (
            weakref.ref(table),
            offsets,
            tuple(k.lower() for k in keys),
            hash_fields,
        )

    def _preserved_sidedata(self, table: ColumnTable, join_on: list[str]) -> "SideData | None":
        e = self._bucketed_outputs.get(id(table))
        if e is None or e[0]() is not table:
            return None
        if e[2] != tuple(k.lower() for k in join_on):
            return None
        return SideData(table, e[1], False, hash_fields=e[3])

    def _propagate_stash(self, src: ColumnTable, dst: ColumnTable) -> ColumnTable:
        """Row-preserving transforms (column selection) keep a stashed
        bucket grouping valid — carry it to the derived table so chained
        star joins still find it (select() builds a NEW ColumnTable, so
        identity lookups would otherwise go dead)."""
        e = self._bucketed_outputs.get(id(src))
        if e is not None and e[0]() is src and dst is not src:
            names = {n.lower() for n in dst.schema.names}
            if all(k in names for k in e[2]):  # bucket keys survived
                self._stash_bucketed(dst, e[1], list(e[2]), e[3])
        return dst

    def execute(self, plan: LogicalPlan) -> ColumnTable:
        from hyperspace_tpu.plan.prune import prune_columns
        from hyperspace_tpu.plan.pushdown import push_down_filters

        from hyperspace_tpu.utils.jit_memory import maybe_relieve_jit_pressure

        # Long-lived processes compiling many distinct programs can hit
        # the kernel's vm.max_map_count and SIGSEGV inside LLVM on the
        # next compile; drop jax caches before that point (sampled).
        maybe_relieve_jit_pressure()
        validate = self.conf is None or getattr(self.conf, "validate_plans", True)
        if validate:
            # Pre-execution analysis (analysis/validator.py): reject a
            # malformed plan with node-provenance diagnostics up front
            # instead of an opaque mid-execution KeyError / XLA error.
            from hyperspace_tpu.analysis.validator import check_plan, validate_rewrite

            check_plan(plan)
        optimized = prune_columns(push_down_filters(plan))
        if validate:
            # Guard our own rewrites: pushdown/prune must preserve the
            # output schema and never push a filter beneath the
            # null-extended side of an outer join.
            validate_rewrite(plan, optimized)
        return self._execute(optimized)

    def _execute(self, plan: LogicalPlan) -> ColumnTable:
        from hyperspace_tpu.execution.physical import PhysicalNode

        node = PhysicalNode(op=type(plan).__name__)
        parent, self._cur_phys = self._cur_phys, node
        if parent is not None:
            parent.children.append(node)
        else:
            self.physical_plan = node
        files_before = self.stats["files_read"]
        bytes_before = self.stats["bytes_scanned"]
        sp = obs_trace.span(f"execute.{type(plan).__name__}")
        t0 = time.perf_counter()
        with sp:
            try:
                result = self._dispatch(plan)
            finally:
                self._cur_phys = parent
                # Wall time of this operator's frame (children included);
                # recorded even on failure so partial profiles stay honest.
                node.wall_s = time.perf_counter() - t0
                sp.rename(f"execute.{node.op}")
            # Physical file IO attributed to THIS operator = its frame's delta
            # minus what child frames already claimed.
            subtree = self.stats["files_read"] - files_before
            node._subtree_files = subtree
            own = subtree - sum(getattr(c, "_subtree_files", 0) for c in node.children)
            if own > 0:
                node.detail.setdefault("files", own)
            sub_bytes = self.stats["bytes_scanned"] - bytes_before
            node._subtree_bytes = sub_bytes
            own_bytes = sub_bytes - sum(getattr(c, "_subtree_bytes", 0) for c in node.children)
            if own_bytes > 0:
                node.detail.setdefault("bytes", own_bytes)
            node.rows_out = result.num_rows
            sp.set(rows_out=result.num_rows)
            if own > 0:
                sp.set(files=own, bytes=own_bytes)
        return result

    def _dispatch(self, plan: LogicalPlan) -> ColumnTable:
        if isinstance(plan, Scan):
            # Labeled here, not in _scan: _scan also runs as a subroutine
            # of other operators (hybrid delta reads) whose node must not
            # be renamed.
            if plan.bucket_spec is not None:
                self._phys("IndexScan", buckets=plan.bucket_spec[0])
            else:
                self._phys("TableScan")
            return self._scan(plan)
        if isinstance(plan, Filter):
            return self._filter(plan)
        if isinstance(plan, Project):
            self._cur_phys.detail["columns"] = list(plan.output_names)
            child = self._execute(plan.child)
            if plan.is_simple:
                return self._propagate_stash(child, child.select(plan.columns))
            from hyperspace_tpu.ops.project import project_table

            self._phys(
                "ProjectCompute",
                computed=[c[0] for c in plan.columns if not isinstance(c, str)],
            )
            return project_table(child, plan.columns, plan.schema)
        if isinstance(plan, Join):
            return self._join(plan)
        if isinstance(plan, Union):
            self._cur_phys.op = "HybridScanUnion"
            return self._union(plan)
        if isinstance(plan, Aggregate):
            return self._aggregate(plan)
        if isinstance(plan, Window):
            from hyperspace_tpu.ops.window import window_table

            t = self._execute(plan.child)
            self._phys(
                "WindowSortedSegments",
                partitions=list(plan.partition_by),
                frame=plan.frame,
                funcs=[f.fn for f in plan.funcs],
            )
            return window_table(
                t, plan.partition_by, plan.order_by, plan.funcs, plan.frame, plan.schema
            )
        if isinstance(plan, Sort):
            return self._sort(plan)
        if isinstance(plan, _TableLeaf):
            return plan.table
        if isinstance(plan, Limit):
            self._cur_phys.detail["n"] = plan.n
            if isinstance(plan.child, Sort):
                return self._top_n(plan.child, plan.n)
            early = self._limit_early_out(plan.child, plan.n)
            if early is not None:
                return early
            t = self._execute(plan.child)
            return t.take(np.arange(min(plan.n, t.num_rows)))
        raise HyperspaceError(f"cannot execute plan node {type(plan).__name__}")

    def _limit_early_out(self, child: LogicalPlan, n: int) -> ColumnTable | None:
        """LIMIT over an unordered linear scan chain: pull rows file by
        file and STOP once n rows survive, instead of materializing the
        whole child (any n rows are a correct answer without ORDER BY —
        the analog of Spark's CollectLimit incremental take). Returns
        None when the shape doesn't apply (non-linear child, single
        file, pinned hybrid scans)."""
        import functools

        chain: list[LogicalPlan] = []
        node = child
        while isinstance(node, (Project, Filter)):
            chain.append(node)
            node = node.child
        if not isinstance(node, Scan):
            return None
        files = self._scan_files(node)
        preds = [w.predicate for w in chain if isinstance(w, Filter)]
        if node.bucket_spec is not None and preds:
            # Index scans prune FIRST — a point lookup must stay a
            # single-file IndexPointLookup, not a file-by-file walk
            # through non-owning buckets.
            pred = functools.reduce(And, preds)
            pruned = self._prune_bucket_files(node, pred)
            if pruned is None:
                ranged = self._range_prune_list(node, pred)
                pruned = ranged[0] if ranged is not None else None
            if pruned is not None:
                files = pruned
        if len(files) <= 1:
            return None
        parts: list[ColumnTable] = []
        total = 0
        scanned = 0
        for f in files:
            sub: LogicalPlan = dataclasses.replace(node, files=[f])
            for wrapper in reversed(chain):
                sub = dataclasses.replace(wrapper, child=sub)
            # Sequential by design: stopping early is the point; the
            # non-limited path keeps its thread-pooled parallel reads.
            t = self._execute(sub)
            scanned += 1
            if t.num_rows:
                parts.append(t)
                total += t.num_rows
            if total >= n:
                break
        self._phys(
            "LimitEarlyOut", files_scanned=scanned, files_total=len(files)
        )
        if not parts:
            return ColumnTable.empty(child.schema)
        out = ColumnTable.concat(parts) if len(parts) > 1 else parts[0]
        return out.take(np.arange(min(n, out.num_rows)))

    def _join_venue(self) -> str:
        """auto: host when the measured device→host link is slower than
        the configured floor (tunneled deployments) AND the native library
        built; the pairs land on host either way."""
        # Auto with a mesh keeps the distributed device kernel (the
        # query-plane sharding is the point); a forced "host" wins — the
        # host kernel is bucket-parallel too.
        return self._venue(
            "join_venue", "hyperspace.join.venue", self.mesh is not None, needs_native=True
        )

    def _phys(self, op: str | None = None, **detail) -> None:
        """Annotate the operator currently executing."""
        if self._cur_phys is None:
            return
        if op is not None:
            self._cur_phys.op = op
        self._cur_phys.detail.update(detail)

    # -- aggregate / sort -------------------------------------------------

    def _venue(self, conf_attr: str, what: str, prefer_device: bool, needs_native: bool) -> str:
        """One pick_venue wrapper: conf defaults and the shared link floor
        live here instead of at every venue-choosing call site."""
        from hyperspace_tpu.parallel.bandwidth import pick_venue

        return pick_venue(
            getattr(self.conf, conf_attr) if self.conf is not None else "auto",
            self.conf.join_venue_min_mbps if self.conf is not None else 200.0,
            prefer_device=prefer_device,
            what=what,
            needs_native=needs_native,
        )

    def _filter_venue(self) -> str:
        """Mask venue: host numpy below the link floor (the mask and the
        columns are host-resident); device (mesh-sharded) otherwise."""
        return self._venue("filter_venue", "hyperspace.filter.venue",
                           self.mesh is not None, needs_native=False)

    def _agg_venue(self) -> str:
        """Where the segment reduce runs. The inputs are host-resident and
        the [A, K] result is tiny, so below the link floor the numpy
        bincount/reduceat path beats uploading every channel (and avoids
        emulated f64 on chips without native double support)."""
        return self._venue("agg_venue", "hyperspace.agg.venue", False, needs_native=False)

    def _fused_kernels(self) -> str:
        """Fused Pallas kernel gate for the device venue ("auto"/"off",
        `hyperspace.device.fusedKernels`): auto engages the fused
        segment-reduce / run-bounds kernels when the shape is eligible
        and byte-identity is provable; the jitted lax path is the
        always-available fallback (docs/architecture.md "device data
        path")."""
        return self.conf.device_fused_kernels if self.conf is not None else "auto"

    def _top_n(self, sort_plan: "Sort", n: int) -> ColumnTable:
        """ORDER BY ... LIMIT n as an O(rows) selection: np.partition on
        the first sort column finds the n-th threshold, only the (ties-
        inclusive) candidate set gets the full lexicographic sort. The
        TopK analog of Spark's TakeOrderedAndProject."""
        from hyperspace_tpu.ops.sortkeys import column_lanes, lanes_as_unsigned

        table = self._execute(sort_plan.child)
        rows = table.num_rows
        if n <= 0:
            return table.take(np.arange(0))
        if rows <= max(2 * n, 1024):
            # Full sort (venue-aware via _sort's own machinery).
            self._phys("TopN", n=n, kernel="full-sort")
            full = self._sorted_table(table, sort_plan)
            return full.take(np.arange(min(n, full.num_rows)))
        # Pack the FIRST sort column's lanes into one u64 selection key
        # (DESC via the same lane inversion the full sort uses). A
        # constant validity lane is dropped so both 32-bit words carry
        # real key entropy (else a low-entropy hi word degenerates the
        # selection to ~all rows).
        c0, asc0 = sort_plan.by[0]
        has_nulls = table.valid_mask(c0) is not None
        lanes = column_lanes(table, c0, force_validity=has_nulls)
        if not asc0:
            lanes = [~l for l in lanes]
        lu = lanes_as_unsigned(lanes[:2])
        from hyperspace_tpu.parallel.mesh import mesh_size

        if (
            self.mesh is not None
            and mesh_size(self.mesh) > 1
            # Venue-gated like every other operator: auto prefers the
            # distributed kernel on a real mesh (the query-plane sharding
            # is the point), HYPERSPACE_VENUE=host / sort_venue=host
            # still force the host partition path.
            and self._venue("sort_venue", "hyperspace.sort.venue", True, needs_native=False)
            == "device"
        ):
            # Mesh-sharded selection: per-device first-n + one threshold
            # broadcast; the ORDER BY participates in the mesh.
            from hyperspace_tpu.ops.sortkeys import distributed_top_n_candidates

            cand = distributed_top_n_candidates(lu, n, self.mesh)
            if cand is not None:
                sub = table.take(cand)
                self._phys(
                    "TopN",
                    n=n,
                    kernel="mesh-sharded-select + sort",
                    candidates=len(cand),
                    devices=mesh_size(self.mesh),
                )
                full = self._sorted_table(sub, sort_plan)
                return full.take(np.arange(min(n, full.num_rows)))
        kpack = (lu[0].astype(np.uint64) << np.uint64(32)) | (
            lu[1].astype(np.uint64) if lu.shape[0] > 1 else np.uint64(0)
        )
        thr = np.partition(kpack, n - 1)[n - 1]
        # The selection key may be a PREFIX of the first column's order
        # (extra lanes unseen) — prefix-ties stay in, and every true
        # top-n row provably has prefix <= thr; the exact sort of the
        # candidate set settles the rest.
        cand = np.flatnonzero(kpack <= thr)
        sub = table.take(cand)
        self._phys("TopN", n=n, kernel="partition-select + sort", candidates=len(cand))
        full = self._sorted_table(sub, sort_plan)
        return full.take(np.arange(min(n, full.num_rows)))

    def _sort(self, plan: "Sort") -> ColumnTable:
        table = self._execute(plan.child)
        venue = self._venue("sort_venue", "hyperspace.sort.venue", False, needs_native=False)
        self._phys(f"{venue.capitalize()}Sort", keys=[c for c, _ in plan.by])
        return self._sorted_table(table, plan, venue)

    def _sorted_table(self, table: ColumnTable, plan: "Sort", venue: str | None = None) -> ColumnTable:
        """Venue-aware total order of an already-materialized table."""
        from hyperspace_tpu.ops.sortkeys import (
            device_order_perm,
            lexsort_lanes,
            order_lanes,
        )

        if table.num_rows <= 1:
            return table
        if venue is None:
            venue = self._venue("sort_venue", "hyperspace.sort.venue", False, needs_native=False)
        if venue == "host":
            # ORDER BY output must land on host; below the link floor a
            # numpy lexsort beats the device round-trip (latency-bound
            # for the typical small post-aggregation result).
            return table.take(lexsort_lanes(order_lanes(table, plan.by)))
        return table.take(device_order_perm(table, plan.by))

    # -- union (hybrid scan) ----------------------------------------------
    def _union(self, plan: Union) -> ColumnTable:
        schema = plan.schema
        parts = []
        for child in plan.inputs:
            t = self._execute(child)
            # Remap onto the union schema's exact field names/order (child
            # names are validated case-insensitively compatible).
            cols, dicts, val = {}, {}, {}
            for f in schema.fields:
                cf = t.schema.field(f.name)
                cols[f.name] = t.columns[cf.name]
                if cf.name in t.dictionaries:
                    dicts[f.name] = t.dictionaries[cf.name]
                if cf.name in t.validity:
                    val[f.name] = t.validity[cf.name]
            parts.append(ColumnTable(schema, cols, dicts, val))
        return ColumnTable.concat(parts)

    # -- scan ------------------------------------------------------------
