"""Ranking of candidate index pairs for the join rewrite.

Reference parity: index/rankers/JoinIndexRanker.scala:24-56 — prefer pairs
with EQUAL bucket counts (zero-exchange join), then larger bucket counts
(more parallelism).
"""

from __future__ import annotations

from hyperspace_tpu.metadata.log_entry import IndexLogEntry


class JoinIndexRanker:
    @staticmethod
    def rank(pairs: list[tuple[IndexLogEntry, IndexLogEntry]]) -> list[tuple[IndexLogEntry, IndexLogEntry]]:
        def score(pair):
            l, r = pair
            equal = l.num_buckets == r.num_buckets
            return (0 if equal else 1, -(l.num_buckets + r.num_buckets))

        return sorted(pairs, key=score)
