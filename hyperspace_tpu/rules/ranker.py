"""Ranking of candidate index pairs for the join rewrite.

Reference parity: index/rankers/JoinIndexRanker.scala:24-56 — prefer pairs
with EQUAL bucket counts (zero-exchange join), then larger bucket counts
(more parallelism).

The advisor's what-if analyzer (advisor/whatif.py) replays hypothetical
index pairs through the same :meth:`JoinIndexRanker.score`, so a
re-bucket recommendation is justified by exactly the criterion the real
rewrite will rank by — not a parallel reimplementation that could drift.
"""

from __future__ import annotations

from hyperspace_tpu.metadata.log_entry import IndexLogEntry


class JoinIndexRanker:
    @staticmethod
    def score(pair: tuple[IndexLogEntry, IndexLogEntry]) -> tuple[int, int]:
        """Sort key of a candidate pair — smaller ranks first: equal
        bucket counts beat unequal (the merge needs no re-bucketing
        exchange), then more total buckets beat fewer (parallelism)."""
        l, r = pair
        equal = l.num_buckets == r.num_buckets
        return (0 if equal else 1, -(l.num_buckets + r.num_buckets))

    @staticmethod
    def rank(pairs: list[tuple[IndexLogEntry, IndexLogEntry]]) -> list[tuple[IndexLogEntry, IndexLogEntry]]:
        return sorted(pairs, key=JoinIndexRanker.score)
