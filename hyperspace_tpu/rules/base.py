"""Rule infrastructure for transparent plan rewriting.

The analog of the reference's Catalyst rule batch
(`JoinIndexRule :: FilterIndexRule` registered at package.scala:34). The
ordering is load-bearing and preserved: join first, then filter, because a
source already rewritten to an index scan cannot be rewritten again
(package.scala:23-33). Rules never throw: any failure downgrades to a no-op
(reference behavior at FilterIndexRule.scala:76-80).
"""

from __future__ import annotations

import dataclasses
import logging
from pathlib import Path

from hyperspace_tpu.dataset import list_data_files
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.schema import Schema
from hyperspace_tpu.signature import create_signature_provider

logger = logging.getLogger("hyperspace_tpu")


class Rule:
    name: str = "rule"

    def __init__(self, conf=None):
        # Session conf (hybrid-scan knobs); None ⇒ defaults (hybrid off).
        self.conf = conf

    def apply(self, plan: LogicalPlan, indexes: list[IndexLogEntry]) -> LogicalPlan:
        raise NotImplementedError


def apply_rules(plan: LogicalPlan, indexes: list[IndexLogEntry], rules=None, conf=None) -> LogicalPlan:
    from hyperspace_tpu.obs import trace as obs_trace

    if rules is None:
        from hyperspace_tpu.rules.filter_index_rule import FilterIndexRule
        from hyperspace_tpu.rules.join_index_rule import JoinIndexRule

        rules = [JoinIndexRule(conf), FilterIndexRule(conf)]
    for rule in rules:
        with obs_trace.span(f"rule.{rule.name}", candidates=len(indexes)):
            try:
                plan = rule.apply(plan, indexes)
            except Exception as e:  # noqa: BLE001 — rules must never break a query
                # The span records the failure (a no-op rewrite is a
                # per-query fact worth profiling), the query proceeds.
                obs_trace.annotate(error=f"{type(e).__name__}: {e}")
                logger.warning("rule %s failed, skipping: %s", rule.name, e)
    return plan


def index_scan_for(entry: IndexLogEntry) -> Scan:
    """Build the bucketed index Scan replacing a source relation — the
    analog of constructing the index-backed HadoopFsRelation with a
    BucketSpec (JoinIndexRule.scala:124-153). All version dirs listed in
    `content.directories` participate: bucket b's data is the union of the
    bucket-b files across dirs (base + incremental-refresh deltas)."""
    root = Path(entry.content.root)
    schema = Schema.from_json(entry.derived_dataset.schema)
    files: list[str] = []
    for d in entry.content.directories:
        files.extend(fi.path for fi in list_data_files(root / d))
    first_dir = root / entry.content.directories[0]
    manifest = hio.read_manifest(first_dir)
    num_buckets = manifest["numBuckets"] if manifest else entry.derived_dataset.num_buckets
    return Scan(
        str(root),
        "parquet",
        schema,
        files=sorted(files),
        bucket_spec=(num_buckets, list(entry.derived_dataset.indexed_columns)),
    )


def hybrid_scan_for(match: "IndexMatch", source_scan: Scan):
    """Plan fragment for a hybrid match: the bucketed index scan unioned
    with a raw scan pinned to the appended source files, projected to the
    index's column set so both union inputs line up."""
    from hyperspace_tpu.plan.nodes import Project, Union

    import dataclasses

    entry = match.entry
    idx_scan = index_scan_for(entry)
    delta_scan = Scan(
        source_scan.root,
        source_scan.format,
        source_scan.scan_schema,
        files=sorted(f.path for f in match.appended),
    )
    # The source scan may be column-pruned (pruning runs before rules);
    # narrow the index side to the same columns so the union aligns.
    src_cols = {c.lower() for c in source_scan.scan_schema.names}
    idx_cols = [
        c for c in entry.derived_dataset.all_columns
        if source_scan.scan_schema.names and c.lower() in src_cols
    ]
    idx_schema = idx_scan.scan_schema.select(
        [idx_scan.scan_schema.field(c).name for c in idx_cols]
    )
    idx_scan = dataclasses.replace(idx_scan, scan_schema=idx_schema)
    cols = [source_scan.scan_schema.field(c).name for c in idx_cols]
    return Union([idx_scan, Project(delta_scan, cols)])


@dataclasses.dataclass
class IndexMatch:
    """How an index applies to a source relation: exactly (signature equal)
    or via hybrid scan (index data + `appended` source files scanned raw)."""

    entry: IndexLogEntry
    appended: list  # FileInfo; empty ⇒ exact match

    @property
    def is_exact(self) -> bool:
        return not self.appended


class SignatureMatcher:
    """Memoized plan-fingerprint matching (the reference memoizes per
    provider within one optimizer invocation, JoinIndexRule.scala:328-353).
    With hybrid scan enabled, a signature mismatch can still match when the
    only divergence is appended source files within the configured ratio."""

    def __init__(self, conf=None):
        self._provider = create_signature_provider()
        self._cache: dict[int, str | None] = {}
        self._files_cache: dict[int, list] = {}
        self._hybrid = bool(conf.hybrid_scan_enabled) if conf is not None else False
        self._max_ratio = (
            float(conf.hybrid_scan_max_appended_ratio) if conf is not None else 0.0
        )

    def match(self, entry: IndexLogEntry, source: LogicalPlan) -> IndexMatch | None:
        key = id(source)
        if key not in self._cache:
            fp = self._provider.signature(source)
            self._cache[key] = None if fp is None else fp.value
        value = self._cache[key]
        if value is not None and value == entry.signature.value:
            return IndexMatch(entry, [])
        if not self._hybrid:
            return None
        from hyperspace_tpu.signature import collect_leaf_files, diff_source_files

        # One live listing per source plan, reused across candidate entries.
        if key not in self._files_cache:
            current = []
            for leaf in source.leaves():
                current.extend(collect_leaf_files(leaf))
            self._files_cache[key] = current
        appended, deleted = diff_source_files(entry, source, current=self._files_cache[key])
        if deleted or not appended:
            return None
        logged_bytes = sum(f.size for f in entry.source.files) or 1
        if sum(f.size for f in appended) > self._max_ratio * logged_bytes:
            return None
        return IndexMatch(entry, appended)
