"""Rule infrastructure for transparent plan rewriting.

The analog of the reference's Catalyst rule batch
(`JoinIndexRule :: FilterIndexRule` registered at package.scala:34). The
ordering is load-bearing and preserved: join first, then filter, because a
source already rewritten to an index scan cannot be rewritten again
(package.scala:23-33). Rules never throw: any failure downgrades to a no-op
(reference behavior at FilterIndexRule.scala:76-80).
"""

from __future__ import annotations

import logging
from pathlib import Path

from hyperspace_tpu.dataset import list_data_files
from hyperspace_tpu.execution import io as hio
from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import LogicalPlan, Scan
from hyperspace_tpu.schema import Schema
from hyperspace_tpu.signature import create_signature_provider

logger = logging.getLogger("hyperspace_tpu")


class Rule:
    name: str = "rule"

    def apply(self, plan: LogicalPlan, indexes: list[IndexLogEntry]) -> LogicalPlan:
        raise NotImplementedError


def apply_rules(plan: LogicalPlan, indexes: list[IndexLogEntry], rules=None) -> LogicalPlan:
    if rules is None:
        from hyperspace_tpu.rules.filter_index_rule import FilterIndexRule
        from hyperspace_tpu.rules.join_index_rule import JoinIndexRule

        rules = [JoinIndexRule(), FilterIndexRule()]
    for rule in rules:
        try:
            plan = rule.apply(plan, indexes)
        except Exception as e:  # noqa: BLE001 — rules must never break a query
            logger.warning("rule %s failed, skipping: %s", rule.name, e)
    return plan


def index_scan_for(entry: IndexLogEntry) -> Scan:
    """Build the bucketed index Scan replacing a source relation — the
    analog of constructing the index-backed HadoopFsRelation with a
    BucketSpec (JoinIndexRule.scala:124-153)."""
    version_dir = Path(entry.content.root) / entry.content.directories[-1]
    schema = Schema.from_json(entry.derived_dataset.schema)
    files = [fi.path for fi in list_data_files(version_dir)]
    manifest = hio.read_manifest(version_dir)
    num_buckets = manifest["numBuckets"] if manifest else entry.derived_dataset.num_buckets
    return Scan(
        str(version_dir),
        "parquet",
        schema,
        files=sorted(files),
        bucket_spec=(num_buckets, list(entry.derived_dataset.indexed_columns)),
    )


class SignatureMatcher:
    """Memoized plan-fingerprint matching (the reference memoizes per
    provider within one optimizer invocation, JoinIndexRule.scala:328-353)."""

    def __init__(self):
        self._provider = create_signature_provider()
        self._cache: dict[int, str | None] = {}

    def matches(self, entry: IndexLogEntry, source: LogicalPlan) -> bool:
        key = id(source)
        if key not in self._cache:
            fp = self._provider.signature(source)
            self._cache[key] = None if fp is None else fp.value
        value = self._cache[key]
        return value is not None and value == entry.signature.value
