from hyperspace_tpu.rules.base import apply_rules, index_scan_for
from hyperspace_tpu.rules.filter_index_rule import FilterIndexRule
from hyperspace_tpu.rules.join_index_rule import JoinIndexRule
from hyperspace_tpu.rules.ranker import JoinIndexRanker

__all__ = ["apply_rules", "index_scan_for", "FilterIndexRule", "JoinIndexRule", "JoinIndexRanker"]
