"""JoinIndexRule: rewrite equi-joins to bucket-aligned index scans.

Reference parity: index/rules/JoinIndexRule.scala:54-595 (the reference's
largest component). Our plan IR makes several of its checks structural:
the equi-join CNF and base-table attribute requirements
(JoinIndexRule.scala:179-185, 278-317) are guaranteed by the `Join` node
shape. What remains:

- sides must be linear sub-plans over a single source relation
  (JoinIndexRule.scala:210-211): here Scan / Project(Scan) / Filter(Scan);
- the key mapping must be 1:1 (no column repeated on either side);
- a side's candidate indexes are those whose signature matches the side's
  relation (JoinIndexRule.scala:328-353); usable iff indexed columns are
  set-equal to the side's join columns AND the index covers the side's
  required output columns (JoinIndexRule.scala:515-524);
- a compatible pair lists indexed columns in the same mapped order
  (JoinIndexRule.scala:547-594);
- the best pair is chosen by JoinIndexRanker (equal bucket counts first —
  zero-exchange, then more buckets);
- the rewrite swaps both sides' relations for bucketed index scans so the
  executor's per-bucket SMJ needs no exchange (JoinIndexRule.scala:124-153).
"""

from __future__ import annotations

from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Aggregate, Filter, Join, Limit, LogicalPlan, Project, Scan, Sort, Window
from hyperspace_tpu.plan.nodes import Union
from hyperspace_tpu.rules.base import Rule, SignatureMatcher, hybrid_scan_for, index_scan_for
from hyperspace_tpu.rules.ranker import JoinIndexRanker


def _side_scan(plan: LogicalPlan) -> Scan | None:
    """The single source relation of a linear side, if any."""
    node = plan
    while True:
        if isinstance(node, Scan):
            return node if node.bucket_spec is None else None
        if isinstance(node, (Project, Filter)):
            node = node.child
            continue
        return None


def _side_required_columns(plan: LogicalPlan, join_cols: list[str]) -> set[str]:
    """Columns the side must produce: its output + its own predicates +
    the join keys (analog of JoinIndexRule.scala:399-457). The outermost
    Project defines the side's output; computed entries require their
    INPUT references (the alias itself is not a scan column)."""
    required = {c.lower() for c in join_cols}
    node = plan
    saw_project = False
    while not isinstance(node, Scan):
        if isinstance(node, Filter):
            required |= {c.lower() for c in node.predicate.references()}
        elif isinstance(node, Project) and not saw_project:
            required |= node.input_columns()
            saw_project = True
        node = node.child
    if not saw_project:
        required |= {c.lower() for c in plan.schema.names}
    return required


def _replace_scan(plan: LogicalPlan, new_scan: LogicalPlan) -> LogicalPlan:
    if isinstance(plan, Scan):
        return new_scan
    if isinstance(plan, Project):
        return Project(_replace_scan(plan.child, new_scan), plan.columns)
    if isinstance(plan, Filter):
        return Filter(_replace_scan(plan.child, new_scan), plan.predicate)
    raise AssertionError("non-linear side")


class JoinIndexRule(Rule):
    name = "JoinIndexRule"

    def apply(self, plan: LogicalPlan, indexes: list[IndexLogEntry]) -> LogicalPlan:
        matcher = SignatureMatcher(self.conf)
        return self._rewrite(plan, indexes, matcher)

    def _rewrite(self, plan: LogicalPlan, indexes, matcher) -> LogicalPlan:
        if isinstance(plan, Join):
            rewritten = self._try_rewrite_join(plan, indexes, matcher)
            if rewritten is not None:
                return rewritten
            new = Join(
                self._rewrite(plan.left, indexes, matcher),
                self._rewrite(plan.right, indexes, matcher),
                plan.left_on,
                plan.right_on,
                plan.how,
                condition=plan.condition,
                null_safe=plan.null_safe,
            )
            return new
        if isinstance(plan, Project):
            return Project(self._rewrite(plan.child, indexes, matcher), plan.columns)
        if isinstance(plan, Filter):
            return Filter(self._rewrite(plan.child, indexes, matcher), plan.predicate)
        if isinstance(plan, (Aggregate, Sort, Limit, Window)):
            import dataclasses

            return dataclasses.replace(plan, child=self._rewrite(plan.child, indexes, matcher))
        if isinstance(plan, Union):
            # A USER-written union (multi-channel UNION ALL queries) —
            # rewrite each branch. Hybrid-scan unions the rules emit are
            # harmless to revisit: their scans are index scans already.
            return Union([self._rewrite(c, indexes, matcher) for c in plan.inputs])
        return plan

    def _try_rewrite_join(self, plan: Join, indexes, matcher) -> LogicalPlan | None:
        # 1:1 mapping: no repeated columns on either side.
        if len({c.lower() for c in plan.left_on}) != len(plan.left_on):
            return None
        if len({c.lower() for c in plan.right_on}) != len(plan.right_on):
            return None

        lscan = _side_scan(plan.left)
        rscan = _side_scan(plan.right)
        if (lscan is None and rscan is None) or lscan is rscan:
            return None

        lcands = rcands = []
        if lscan is not None:
            lreq = _side_required_columns(plan.left, plan.left_on)
            lcands = self._usable(indexes, lscan, plan.left_on, lreq, matcher)
        if rscan is not None:
            rreq = _side_required_columns(plan.right, plan.right_on)
            rcands = self._usable(indexes, rscan, plan.right_on, rreq, matcher)
        if not lcands and not rcands:
            return None

        pairs = (
            self._compatible_pairs(lcands, rcands, plan.left_on, plan.right_on)
            if lcands and rcands
            else []
        )
        if not pairs:
            # One-sided rewrite: a lone usable index still serves the
            # join — the executor's re-bucketing exchange groups the
            # other side into the index's bucket layout on the fly
            # (the ranker's mismatched-pair fallback generalized,
            # JoinIndexRanker.scala:31-34). Prefer more buckets (more
            # parallelism), like the ranker's second criterion.
            # Compare across BOTH sides — a higher-bucket-count right
            # index beats the best left candidate.
            best_l = max(lcands, key=lambda c: c.entry.num_buckets) if lcands else None
            best_r = max(rcands, key=lambda c: c.entry.num_buckets) if rcands else None
            if best_l is not None and (
                best_r is None or best_l.entry.num_buckets >= best_r.entry.num_buckets
            ):
                new_left = _replace_scan(plan.left, self._side_plan(best_l, lscan))
                return Join(new_left, self._rewrite(plan.right, indexes, matcher),
                            plan.left_on, plan.right_on, plan.how,
                            condition=plan.condition, null_safe=plan.null_safe)
            m = best_r
            new_right = _replace_scan(plan.right, self._side_plan(m, rscan))
            return Join(self._rewrite(plan.left, indexes, matcher), new_right,
                        plan.left_on, plan.right_on, plan.how,
                        condition=plan.condition, null_safe=plan.null_safe)
        best_l, best_r = JoinIndexRanker.rank(
            [(lm.entry, rm.entry) for lm, rm in pairs],
        )[0]
        lmatch = next(lm for lm, _ in pairs if lm.entry is best_l)
        rmatch = next(rm for _, rm in pairs if rm.entry is best_r)

        new_left = _replace_scan(plan.left, self._side_plan(lmatch, lscan))
        new_right = _replace_scan(plan.right, self._side_plan(rmatch, rscan))
        return Join(new_left, new_right, plan.left_on, plan.right_on, plan.how,
                    condition=plan.condition, null_safe=plan.null_safe)

    @staticmethod
    def _side_plan(match, scan: Scan) -> LogicalPlan:
        """Exact match ⇒ the bucketed index scan; hybrid ⇒ index ∪ appended
        (the executor bucketizes the appended rows on the fly, the analog of
        later-Hyperspace's on-the-fly shuffle of appended data)."""
        if match.is_exact:
            return index_scan_for(match.entry)
        return hybrid_scan_for(match, scan)

    def _usable(self, indexes, scan: Scan, join_cols, required: set[str], matcher):
        out = []
        jset = {c.lower() for c in join_cols}
        for entry in indexes:
            if entry.derived_dataset.kind != "CoveringIndex":
                continue  # vector indexes serve ann_search, not joins
            iset = {c.lower() for c in entry.indexed_columns}
            cover = {c.lower() for c in entry.derived_dataset.all_columns}
            if iset == jset and required <= cover:
                m = matcher.match(entry, scan)
                if m is not None:
                    out.append(m)
        return out

    def _compatible_pairs(self, lcands, rcands, left_on, right_on):
        """Pairs whose indexed column order respects the key mapping
        (JoinIndexRule.scala:547-594)."""
        l2r = {l.lower(): r.lower() for l, r in zip(left_on, right_on)}
        pairs = []
        for lm in lcands:
            expected_r = [l2r[c.lower()] for c in lm.entry.indexed_columns]
            for rm in rcands:
                if [c.lower() for c in rm.entry.indexed_columns] == expected_r:
                    pairs.append((lm, rm))
        return pairs
