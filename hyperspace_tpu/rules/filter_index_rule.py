"""FilterIndexRule: rewrite filter queries to scan a covering index.

Reference parity: index/rules/FilterIndexRule.scala:41-229. Matches
`Project(Filter(Scan))` or `Filter(Scan)` where the scan is a source
relation (FilterIndexRule.scala:47-56); an index applies iff

  (a) its stored signature matches the scan's recomputed fingerprint,
  (b) it covers every column the filter + projection reference,
  (c) the filter references the FIRST indexed column
      (FilterIndexRule.scala:203-215);

the rewrite swaps only the relation for the bucketed index scan
(FilterIndexRule.scala:114-128). Unlike the reference (which drops the
BucketSpec to keep scan parallelism), our index Scan carries the bucket
spec — the executor uses it for bucket pruning on point predicates, which
a full-scan rewrite cannot do.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.metadata.log_entry import IndexLogEntry
from hyperspace_tpu.plan.nodes import Aggregate, Filter, Limit, LogicalPlan, Project, Scan, Sort, Window
from hyperspace_tpu.plan.nodes import Union
from hyperspace_tpu.rules.base import Rule, SignatureMatcher, hybrid_scan_for, index_scan_for


class FilterIndexRule(Rule):
    name = "FilterIndexRule"

    def apply(self, plan: LogicalPlan, indexes: list[IndexLogEntry]) -> LogicalPlan:
        matcher = SignatureMatcher(self.conf)
        return self._rewrite(plan, indexes, matcher)

    def _rewrite(self, plan: LogicalPlan, indexes, matcher) -> LogicalPlan:
        if isinstance(plan, Project) and isinstance(plan.child, Filter) and isinstance(plan.child.child, Scan):
            scan = plan.child.child
            new_scan = self._replacement(scan, plan.child.predicate, plan.input_columns(), indexes, matcher)
            if new_scan is not None:
                return Project(Filter(new_scan, plan.child.predicate), plan.columns)
            return plan
        if isinstance(plan, Filter) and isinstance(plan.child, Scan):
            scan = plan.child
            required = scan.scan_schema.names  # no projection: full output
            new_scan = self._replacement(scan, plan.predicate, required, indexes, matcher)
            if new_scan is not None:
                return Filter(new_scan, plan.predicate)
            return plan
        if (
            isinstance(plan, Filter)
            and isinstance(plan.child, Project)
            and plan.child.is_simple
            and isinstance(plan.child.child, Scan)
        ):
            # Filter(Project(Scan)) — the select-then-filter spelling of
            # the same shape (the filter can only reference projected
            # columns, so coverage over the projection's inputs suffices).
            proj = plan.child
            new_scan = self._replacement(
                proj.child, plan.predicate, proj.input_columns(), indexes, matcher
            )
            if new_scan is not None:
                return Filter(Project(new_scan, proj.columns), plan.predicate)
            return plan
        # Recurse into children.
        if isinstance(plan, Project):
            return Project(self._rewrite(plan.child, indexes, matcher), plan.columns)
        if isinstance(plan, Filter):
            return Filter(self._rewrite(plan.child, indexes, matcher), plan.predicate)
        if isinstance(plan, (Aggregate, Sort, Limit, Window)):
            return dataclasses.replace(plan, child=self._rewrite(plan.child, indexes, matcher))
        if isinstance(plan, Union):
            # User-written UNION ALL branches each get their own rewrite.
            return Union([self._rewrite(c, indexes, matcher) for c in plan.inputs])
        if hasattr(plan, "left") and hasattr(plan, "right"):
            new = dataclasses.replace(plan)
            new.left = self._rewrite(plan.left, indexes, matcher)
            new.right = self._rewrite(plan.right, indexes, matcher)
            return new
        return plan

    def _replacement(self, scan: Scan, predicate, output_columns, indexes, matcher) -> LogicalPlan | None:
        if scan.bucket_spec is not None:
            return None  # already an index scan — never rewrite twice
        filter_cols = {c.lower() for c in predicate.references()}
        required = filter_cols | {c.lower() for c in output_columns}
        for entry in indexes:
            if entry.derived_dataset.kind != "CoveringIndex":
                continue  # vector indexes serve ann_search, not filters
            idx_cols = {c.lower() for c in entry.derived_dataset.all_columns}
            first_indexed = entry.indexed_columns[0].lower()
            if required <= idx_cols and first_indexed in filter_cols:
                m = matcher.match(entry, scan)
                if m is None:
                    continue
                # First matching candidate wins (FilterIndexRule.scala:222-228).
                if m.is_exact:
                    return index_scan_for(entry)
                return hybrid_scan_for(m, scan)
        return None
