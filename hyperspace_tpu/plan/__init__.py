from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Col,
    Expr,
    Lit,
    Not,
    Or,
    col,
    expr_from_json,
    lit,
)
from hyperspace_tpu.plan.nodes import (
    Filter,
    Join,
    LogicalPlan,
    Project,
    Scan,
    plan_from_json,
)

__all__ = [
    "And",
    "BinOp",
    "Col",
    "Expr",
    "Lit",
    "Not",
    "Or",
    "col",
    "lit",
    "expr_from_json",
    "Filter",
    "Join",
    "LogicalPlan",
    "Project",
    "Scan",
    "plan_from_json",
]
