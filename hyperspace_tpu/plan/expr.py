"""Expression IR: a small, JSON-serializable predicate/projection language.

The reference has no expression IR of its own — it pattern-matches Catalyst
expressions (e.g. CNF of EqualTo at index/rules/JoinIndexRule.scala:179-185)
and pays for it with a 495-LoC Kryo serde layer (index/serde/). Here
expressions are plain dataclasses with trivial JSON round-trip, evaluable on
host (numpy) or device (jax.numpy) arrays.

String semantics: device columns hold dictionary codes whose dictionary is
sorted at encode time, so both equality and range comparisons on codes are
order-correct once a string literal is translated to its code (the executor
does the translation; see execution/table.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

_BIN_OPS = {"eq", "ne", "lt", "le", "gt", "ge", "add", "sub", "mul", "div", "mod"}
_CMP_OPS = {"eq", "ne", "lt", "le", "gt", "ge"}


class Expr:
    """Base expression node."""

    # Operator sugar so users can write col("a") == 5, (p1 & p2), etc.
    def __eq__(self, other):  # type: ignore[override]
        return BinOp("eq", self, _wrap(other))

    def __ne__(self, other):  # type: ignore[override]
        return BinOp("ne", self, _wrap(other))

    def __lt__(self, other):
        return BinOp("lt", self, _wrap(other))

    def __le__(self, other):
        return BinOp("le", self, _wrap(other))

    def __gt__(self, other):
        return BinOp("gt", self, _wrap(other))

    def __ge__(self, other):
        return BinOp("ge", self, _wrap(other))

    def __add__(self, other):
        return BinOp("add", self, _wrap(other))

    def __sub__(self, other):
        return BinOp("sub", self, _wrap(other))

    def __mul__(self, other):
        return BinOp("mul", self, _wrap(other))

    def __truediv__(self, other):
        return BinOp("div", self, _wrap(other))

    def __mod__(self, other):
        return BinOp("mod", self, _wrap(other))

    def __and__(self, other):
        return And(self, _wrap(other))

    def __or__(self, other):
        return Or(self, _wrap(other))

    def __invert__(self):
        return Not(self)

    # -- SQL predicate sugar ----------------------------------------------
    def is_null(self) -> "IsNull":
        return IsNull(self)

    def is_not_null(self) -> "Not":
        return Not(IsNull(self))

    def isin(self, values) -> "InList":
        return InList(self, list(values))

    def like(self, pattern: str) -> "Like":
        return Like(self, pattern)

    def between(self, lo, hi) -> "And":
        """SQL BETWEEN sugar: inclusive on both ends."""
        return And(BinOp("ge", self, _wrap(lo)), BinOp("le", self, _wrap(hi)))

    def substr(self, start: int, length: int) -> "Substr":
        """SQL SUBSTRING (1-based start), usable inside comparisons / IN."""
        return Substr(self, int(start), int(length))

    def __hash__(self):
        return hash(repr(self))

    def to_json(self) -> dict[str, Any]:
        raise NotImplementedError

    def references(self) -> set[str]:
        """Column names this expression reads (lowercased)."""
        raise NotImplementedError


@dataclasses.dataclass(eq=False, repr=True)
class Col(Expr):
    name: str

    def to_json(self):
        return {"type": "col", "name": self.name}

    def references(self):
        return {self.name.lower()}


@dataclasses.dataclass(eq=False, repr=True)
class Lit(Expr):
    value: Any

    def to_json(self):
        return {"type": "lit", "value": self.value}

    def references(self):
        return set()


@dataclasses.dataclass(eq=False, repr=True)
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr

    def __post_init__(self):
        if self.op not in _BIN_OPS:
            raise ValueError(f"unknown op {self.op!r}")

    @property
    def is_comparison(self) -> bool:
        return self.op in _CMP_OPS

    def to_json(self):
        return {"type": "binop", "op": self.op, "left": self.left.to_json(), "right": self.right.to_json()}

    def references(self):
        return self.left.references() | self.right.references()


@dataclasses.dataclass(eq=False, repr=True)
class And(Expr):
    left: Expr
    right: Expr

    def to_json(self):
        return {"type": "and", "left": self.left.to_json(), "right": self.right.to_json()}

    def references(self):
        return self.left.references() | self.right.references()


@dataclasses.dataclass(eq=False, repr=True)
class Or(Expr):
    left: Expr
    right: Expr

    def to_json(self):
        return {"type": "or", "left": self.left.to_json(), "right": self.right.to_json()}

    def references(self):
        return self.left.references() | self.right.references()


@dataclasses.dataclass(eq=False, repr=True)
class Not(Expr):
    child: Expr

    def to_json(self):
        return {"type": "not", "child": self.child.to_json()}

    def references(self):
        return self.child.references()


@dataclasses.dataclass(eq=False, repr=True)
class Case(Expr):
    """SQL CASE WHEN: ordered (condition, value) branches + default.
    Conditions use full predicate semantics (3-valued logic; a null
    condition does not take its branch); usable inside aggregate
    expressions (the common TPC-H conditional-aggregate shape)."""

    branches: list[tuple[Expr, Expr]]
    default: Expr

    def to_json(self):
        return {
            "type": "case",
            "branches": [[c.to_json(), v.to_json()] for c, v in self.branches],
            "default": self.default.to_json(),
        }

    def references(self):
        out: set[str] = self.default.references()
        for c, v in self.branches:
            out |= c.references() | v.references()
        return out


@dataclasses.dataclass(eq=False, repr=True)
class IsNull(Expr):
    """SQL IS NULL. Never UNKNOWN (the point of the operator); IS NOT
    NULL is Not(IsNull(...)). For a compound child, null iff any input
    column is null (matching the engine's expression null semantics)."""

    child: Expr

    def to_json(self):
        return {"type": "isnull", "child": self.child.to_json()}

    def references(self):
        return self.child.references()


@dataclasses.dataclass(eq=False, repr=True)
class InList(Expr):
    """SQL IN over a literal list. 3-valued: a null probe is UNKNOWN.
    Desugars (at translation time) to an OR of equalities in the physical
    code domain — which also feeds multi-point bucket pruning and
    min/max envelope pruning on indexed columns."""

    child: Expr
    values: list

    def __post_init__(self):
        if not self.values:
            raise ValueError("IN requires a non-empty value list")
        if any(v is None for v in self.values):
            raise ValueError("IN list literals must be non-null")

    def to_json(self):
        return {"type": "in", "child": self.child.to_json(), "values": list(self.values)}

    def references(self):
        return self.child.references()


@dataclasses.dataclass(eq=False, repr=True)
class Like(Expr):
    """SQL LIKE (% = any run, _ = any one char), case-sensitive, against
    a string column. Evaluates over the (small, sorted) dictionary and
    desugars to code-range / code-equality tests — a prefix pattern
    becomes ONE contiguous code range."""

    child: Expr
    pattern: str

    def to_json(self):
        return {"type": "like", "child": self.child.to_json(), "pattern": self.pattern}

    def references(self):
        return self.child.references()


@dataclasses.dataclass(eq=False, repr=True)
class Substr(Expr):
    """SQL SUBSTRING(col, start, length), 1-based, over a string column;
    valid inside comparisons against string literals and IN lists
    (TPC-H Q22's substring(c_phone, 1, 2) shape)."""

    child: Expr
    start: int
    length: int

    def __post_init__(self):
        if self.start < 1:
            raise ValueError("SUBSTRING start is 1-based and must be >= 1")
        if self.length < 0:
            raise ValueError("SUBSTRING length must be >= 0")

    def to_json(self):
        return {
            "type": "substr",
            "child": self.child.to_json(),
            "start": self.start,
            "length": self.length,
        }

    def references(self):
        return self.child.references()


@dataclasses.dataclass(eq=False, repr=True)
class MathFn(Expr):
    """Unary numeric function: sqrt / abs / floor (SQL STDDEV recompose,
    ABS deviations, FLOOR bucket arithmetic — q17/q39/q54 shapes)."""

    fn: str  # sqrt | abs | floor
    child: Expr

    def __post_init__(self):
        if self.fn not in ("sqrt", "abs", "floor"):
            raise ValueError(f"unknown math fn {self.fn!r}")

    def to_json(self):
        return {"type": "mathfn", "fn": self.fn, "child": self.child.to_json()}

    def references(self):
        return self.child.references()


def sqrt(e: Expr) -> MathFn:
    return MathFn("sqrt", e)


def abs_(e: Expr) -> MathFn:
    return MathFn("abs", e)


def floor(e: Expr) -> MathFn:
    return MathFn("floor", e)


@dataclasses.dataclass(eq=False, repr=True)
class DatePart(Expr):
    """Extract year/month/day from a date column (int32 days since
    epoch). Comparisons against literals translate to equivalent day
    ranges, so they lower to the device and drive range pruning."""

    part: str  # year | month | day
    child: Expr

    def __post_init__(self):
        if self.part not in ("year", "month", "day"):
            raise ValueError(f"unknown date part {self.part!r}")

    def to_json(self):
        return {"type": "datepart", "part": self.part, "child": self.child.to_json()}

    def references(self):
        return self.child.references()


def year(e) -> DatePart:
    return DatePart("year", _wrap(e))


def month(e) -> DatePart:
    return DatePart("month", _wrap(e))


def day(e) -> DatePart:
    return DatePart("day", _wrap(e))


def date_lit(iso: str) -> Lit:
    """A date literal from ISO text, as the engine's physical day count."""
    import datetime

    d = datetime.date.fromisoformat(iso)
    return Lit((d - datetime.date(1970, 1, 1)).days)


class CaseBuilder:
    """`when(cond, value).when(...).otherwise(default)` sugar."""

    def __init__(self, branches):
        self._branches = branches

    def when(self, cond: Expr, value) -> "CaseBuilder":
        return CaseBuilder(self._branches + [(cond, _wrap(value))])

    def otherwise(self, default) -> Case:
        return Case(self._branches, _wrap(default))


def when(cond: Expr, value) -> CaseBuilder:
    return CaseBuilder([(cond, _wrap(value))])


def col(name: str) -> Col:
    return Col(name)


def lit(value: Any) -> Lit:
    return Lit(value)


def _wrap(v: Any) -> Expr:
    return v if isinstance(v, Expr) else Lit(v)


def expr_from_json(d: dict[str, Any]) -> Expr:
    t = d["type"]
    if t == "col":
        return Col(d["name"])
    if t == "lit":
        return Lit(d["value"])
    if t == "binop":
        return BinOp(d["op"], expr_from_json(d["left"]), expr_from_json(d["right"]))
    if t == "and":
        return And(expr_from_json(d["left"]), expr_from_json(d["right"]))
    if t == "or":
        return Or(expr_from_json(d["left"]), expr_from_json(d["right"]))
    if t == "not":
        return Not(expr_from_json(d["child"]))
    if t == "case":
        return Case(
            [(expr_from_json(c), expr_from_json(v)) for c, v in d["branches"]],
            expr_from_json(d["default"]),
        )
    if t == "isnull":
        return IsNull(expr_from_json(d["child"]))
    if t == "in":
        return InList(expr_from_json(d["child"]), list(d["values"]))
    if t == "like":
        return Like(expr_from_json(d["child"]), d["pattern"])
    if t == "substr":
        return Substr(expr_from_json(d["child"]), int(d["start"]), int(d["length"]))
    if t == "datepart":
        return DatePart(d["part"], expr_from_json(d["child"]))
    if t == "mathfn":
        return MathFn(d["fn"], expr_from_json(d["child"]))
    raise ValueError(f"unknown expr type {t!r}")


def expr_dtype(e: Expr, schema) -> str:
    """Engine dtype an expression produces when evaluated over `schema`.
    The projection analog of Catalyst's expression type resolution (the
    reference leans on Spark for it; our Project carries named computed
    expressions, so the IR must type them itself)."""
    if isinstance(e, Col):
        return schema.field(e.name).dtype
    if isinstance(e, Lit):
        if isinstance(e.value, bool):
            return "bool"
        if isinstance(e.value, int):
            return "int64"
        if isinstance(e.value, float):
            return "float64"
        return "string"
    if isinstance(e, BinOp):
        if e.op in _CMP_OPS:
            return "bool"
        lt, rt = expr_dtype(e.left, schema), expr_dtype(e.right, schema)
        if "date" in (lt, rt):
            # Dates are day numbers on device: date ± days stays a date,
            # date - date is the day count; anything else is undefined
            # rather than silently an int.
            if e.op == "sub" and lt == "date" and rt == "date":
                return "int64"
            if e.op in ("add", "sub") and lt == "date" and rt in ("int32", "int64", "bool"):
                return "date"
            if e.op == "add" and rt == "date" and lt in ("int32", "int64", "bool"):
                return "date"
            raise ValueError(f"unsupported date arithmetic {lt} {e.op} {rt}")
        if e.op == "div" or "float64" in (lt, rt) or "float32" in (lt, rt):
            return "float64"
        return "int64"
    if isinstance(e, (And, Or, Not, IsNull, InList, Like)):
        return "bool"
    if isinstance(e, Case):
        vals = [v for _, v in e.branches] + [e.default]
        ts = [expr_dtype(v, schema) for v in vals]
        if all(t == ts[0] for t in ts):
            return ts[0]
        nonlit = [t for v, t in zip(vals, ts) if not isinstance(v, Lit)]
        if (
            nonlit
            and all(t == "date" for t in nonlit)
            and all(t in ("int32", "int64", "bool", "date") for t in ts)
        ):
            # CASE over date columns with integer literal defaults keeps
            # the date dtype (literals are day numbers).
            return "date"
        if any(t in ("float64", "float32") for t in ts):
            return "float64"
        if all(t in ("int32", "int64", "bool") for t in ts):
            return "int64"
        raise ValueError(f"CASE branches mix incompatible types {ts}")
    if isinstance(e, DatePart):
        return "int64"
    if isinstance(e, MathFn):
        if e.fn == "sqrt":
            return "float64"
        if e.fn == "floor":
            return "int64"
        return expr_dtype(e.child, schema)  # abs preserves
    if isinstance(e, Substr):
        return "string"
    raise ValueError(f"cannot type expression {type(e).__name__}")


def split_conjuncts(e: Expr) -> list[Expr]:
    """Flatten a conjunction into its factors (CNF top level).

    Reference analog: splitConjunctivePredicates usage at
    index/rules/JoinIndexRule.scala:179-185."""
    if isinstance(e, And):
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def evaluate(e: Expr, resolve: Callable[[str], Any], xp) -> Any:
    """Evaluate an expression given `resolve(name) -> array` and an array
    namespace `xp` (numpy or jax.numpy). Literal translation for string
    columns happens in the caller (see execution/table.py)."""
    if isinstance(e, Col):
        return resolve(e.name)
    if isinstance(e, Lit):
        return e.value
    if isinstance(e, BinOp):
        a = evaluate(e.left, resolve, xp)
        b = evaluate(e.right, resolve, xp)
        if e.op in ("div", "mod"):
            import numpy as _np

            if xp is _np:
                # SQL division by zero yields NULL via the validity
                # masks upstream; the raw IEEE result here is inf/nan by
                # design — don't leak the numpy warning to users.
                with _np.errstate(divide="ignore", invalid="ignore"):
                    return a / b if e.op == "div" else a % b
        return {
            "eq": lambda: a == b,
            "ne": lambda: a != b,
            "lt": lambda: a < b,
            "le": lambda: a <= b,
            "gt": lambda: a > b,
            "ge": lambda: a >= b,
            "add": lambda: a + b,
            "sub": lambda: a - b,
            "mul": lambda: a * b,
            "div": lambda: a / b,
            "mod": lambda: a % b,
        }[e.op]()
    if isinstance(e, And):
        return xp.logical_and(evaluate(e.left, resolve, xp), evaluate(e.right, resolve, xp))
    if isinstance(e, Or):
        return xp.logical_or(evaluate(e.left, resolve, xp), evaluate(e.right, resolve, xp))
    if isinstance(e, Not):
        return xp.logical_not(evaluate(e.child, resolve, xp))
    if isinstance(e, Case):
        out = evaluate(e.default, resolve, xp)
        for cond, val in reversed(e.branches):
            out = xp.where(
                evaluate(cond, resolve, xp), evaluate(val, resolve, xp), out
            )
        return out
    if isinstance(e, DatePart):
        return eval_date_part(e.part, evaluate(e.child, resolve, xp), xp)
    if isinstance(e, MathFn):
        v = evaluate(e.child, resolve, xp)
        if e.fn == "sqrt":
            import numpy as _np

            if xp is _np:
                with _np.errstate(invalid="ignore"):
                    return xp.sqrt(v)
            return xp.sqrt(v)
        if e.fn == "abs":
            return xp.abs(v)
        return xp.floor(v).astype(xp.int64)
    if isinstance(e, InList):
        v = evaluate(e.child, resolve, xp)
        out = None
        for lv in e.values:
            m = v == lv
            out = m if out is None else xp.logical_or(out, m)
        return out
    raise ValueError(f"cannot evaluate {e!r}")


def eval_date_part(part: str, days, xp) -> Any:
    """year/month/day from days-since-epoch. numpy calendar conversion on
    host; the device path never reaches here (comparisons are translated
    to day ranges first)."""
    import numpy as _np

    if xp is not _np:
        raise ValueError("date part extraction evaluates on host only")
    d64 = _np.asarray(days).astype("datetime64[D]")
    if part == "year":
        return d64.astype("datetime64[Y]").astype(_np.int64) + 1970
    if part == "month":
        m = d64.astype("datetime64[M]").astype(_np.int64)
        return m % 12 + 1
    return (d64 - d64.astype("datetime64[M]")).astype(_np.int64) + 1
