"""Predicate pushdown: move filters below inner joins.

The analog of Spark's PushDownPredicate, which runs before the
reference's rewrite rules (the Hyperspace rules see plans Catalyst has
already normalized). Side-local conjuncts of a filter above an inner
equi-join filter that side BEFORE the join — the executor's
bucket-aligned path then applies them per bucket and the merge works
over the (much smaller) surviving rows; conjuncts touching both sides
stay above as a residual filter. Semantics-preserving for inner joins.
"""

from __future__ import annotations

import dataclasses
import functools

from hyperspace_tpu.plan.expr import And, Expr, split_conjuncts
from hyperspace_tpu.plan.nodes import Filter, Join, LogicalPlan


def _conjoin(conjuncts: list[Expr]) -> Expr:
    return functools.reduce(And, conjuncts)


def push_down_filters(plan: LogicalPlan) -> LogicalPlan:
    """Rewrite Filter(Join) shapes so side-local conjuncts run on their
    side; applied recursively over the whole plan."""
    if isinstance(plan, Filter):
        child = push_down_filters(plan.child)
        if isinstance(child, Join):
            # Which sides accept a pushed filter without changing the join
            # semantics: the null-EXTENDED side of an outer join cannot (a
            # pushed filter would drop rows before null extension instead
            # of nulling their columns after); semi/anti output left rows
            # verbatim, so left pushes are safe there too.
            push_left = child.how in ("inner", "left", "semi", "anti")
            push_right = child.how in ("inner", "right")
            lnames = {n.lower() for n in child.left.schema.names}
            rnames = {n.lower() for n in child.right.schema.names}
            left_c: list[Expr] = []
            right_c: list[Expr] = []
            residual: list[Expr] = []
            for conj in split_conjuncts(plan.predicate):
                refs = {r.lower() for r in conj.references()}
                if push_left and refs and refs <= lnames:
                    left_c.append(conj)
                elif push_right and refs and refs <= rnames:
                    right_c.append(conj)
                else:
                    residual.append(conj)
            if left_c or right_c:
                new_left = (
                    push_down_filters(Filter(child.left, _conjoin(left_c)))
                    if left_c
                    else child.left
                )
                new_right = (
                    push_down_filters(Filter(child.right, _conjoin(right_c)))
                    if right_c
                    else child.right
                )
                out: LogicalPlan = Join(
                    new_left, new_right, child.left_on, child.right_on, child.how,
                    condition=child.condition, null_safe=child.null_safe,
                )
                return Filter(out, _conjoin(residual)) if residual else out
        return Filter(child, plan.predicate)
    kids = plan.children()
    if not kids:
        return plan
    from hyperspace_tpu.plan.nodes import Union

    if isinstance(plan, Union):
        return Union([push_down_filters(c) for c in plan.inputs])
    if isinstance(plan, Join):
        return dataclasses.replace(
            plan,
            left=push_down_filters(plan.left),
            right=push_down_filters(plan.right),
        )
    return dataclasses.replace(plan, child=push_down_filters(plan.child))
