"""Logical plan IR: declarative, JSON-native relational algebra.

Design stance (SURVEY.md §7): the reference's most fragile subsystem is its
Kryo plan serde (index/serde/LogicalPlanSerDeUtils.scala:37-246 + 12 wrapper
classes) which exists only because Catalyst plans aren't serializable. Our
plans are plain dataclasses that round-trip through JSON trivially, while
keeping the same capability: the log entry stores the plan as lineage and
`refresh` re-executes it (actions/RefreshAction.scala:45-50).

A `Scan` stores the dataset root + format + schema — NOT a pinned file list.
On (re-)execution the file list is derived from the live filesystem, which is
exactly how the reference's deserialize rebuilds `InMemoryFileIndex` against
the live session to pick up new source files
(index/serde/LogicalPlanSerDeUtils.scala:156-223).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from hyperspace_tpu.plan.expr import Expr, expr_from_json
from hyperspace_tpu.schema import Schema


class LogicalPlan:
    """Base plan node. Offers the fluent builder users treat as a DataFrame."""

    def filter(self, predicate: Expr) -> "Filter":
        return Filter(self, predicate)

    def select(self, *columns) -> "Project":
        """Project columns. Entries are names (passthrough) or
        ``(alias, Expr)`` pairs for computed output columns."""
        return Project(self, list(columns))

    def with_column(self, alias: str, expression) -> "Project":
        """Add one computed column, or replace an existing column of the
        same name (Spark withColumn semantics)."""
        entries = [
            (alias, expression) if c.lower() == alias.lower() else c
            for c in self.schema.names
        ]
        if not any(c.lower() == alias.lower() for c in self.schema.names):
            entries.append((alias, expression))
        return Project(self, entries)

    def join(
        self,
        other: "LogicalPlan",
        left_on: list[str],
        right_on: list[str] | None = None,
        how: str = "inner",
        condition: "Expr | None" = None,
    ) -> "Join":
        """Equi-join on key lists; `condition` adds a non-equi residual
        (`ON a.k = b.k AND a.lo <= b.hi` shapes). For inner joins it
        filters the matched rows; for outer/semi/anti joins it alters
        MATCHING — a pair failing the residual does not count as a
        match, so the left/right row null-extends (outer) or flips its
        existence verdict (semi/anti), per SQL ON-clause semantics."""
        return Join(
            self, other, list(left_on), list(right_on or left_on), how,
            condition=condition,
        )

    def aggregate(
        self, group_by: list[str], aggs: list, grouping_sets: list[list[str]] | None = None
    ) -> "Aggregate":
        """Grouped aggregation. `aggs` entries are AggSpec or
        (fn, expr|column|None, alias) tuples; fn ∈ sum/count/min/max/mean
        (+ count_distinct, and grouping with grouping_sets)."""
        specs = [a if isinstance(a, AggSpec) else AggSpec.of(*a) for a in aggs]
        return Aggregate(self, list(group_by), specs, grouping_sets=grouping_sets)

    def rollup(self, group_by: list[str], aggs: list) -> "Aggregate":
        """SQL GROUP BY ROLLUP(c1..cn): grouping sets are the prefixes
        (c1..cn), (c1..cn-1), ..., () — subtotals at every level plus the
        grand total."""
        sets = [list(group_by[:i]) for i in range(len(group_by), -1, -1)]
        return self.aggregate(group_by, aggs, grouping_sets=sets)

    def cube(self, group_by: list[str], aggs: list) -> "Aggregate":
        """SQL GROUP BY CUBE(c1..cn): all 2^n column subsets."""
        import itertools

        sets = [
            [c for c in group_by if c in chosen]
            for r in range(len(group_by), -1, -1)
            for chosen in map(set, itertools.combinations(group_by, r))
        ]
        return self.aggregate(group_by, aggs, grouping_sets=sets)

    def window(
        self,
        partition_by: list[str],
        order_by: list | None = None,
        funcs: list | None = None,
        frame: str | None = None,
    ) -> "Window":
        """Window functions. `funcs` entries are WindowSpec or
        (fn, expr|column|None, alias) tuples; `order_by` entries are
        names or (name, asc) pairs. Default frame: SQL's — "range"
        (peers share) when an ORDER BY is present, else the whole
        partition."""
        ob = []
        for b in order_by or []:
            ob.append((b[0], bool(b[1])) if isinstance(b, tuple) else (b, True))
        specs = [f if isinstance(f, WindowSpec) else WindowSpec.of(*f) for f in funcs or []]
        if frame is None:
            frame = "range" if ob else "partition"
        return Window(self, list(partition_by), ob, specs, frame)

    def sort(self, by: list, ascending: bool | list[bool] = True) -> "Sort":
        """Order by columns. `by` entries are names or (name, asc) pairs."""
        keys = []
        asc_list = ascending if isinstance(ascending, list) else [ascending] * len(by)
        for b, a in zip(by, asc_list):
            if isinstance(b, tuple):
                keys.append((b[0], bool(b[1])))
            else:
                keys.append((b, bool(a)))
        return Sort(self, keys)

    def limit(self, n: int) -> "Limit":
        return Limit(self, int(n))

    def intersect(self, other: "LogicalPlan") -> "Join":
        """SQL INTERSECT (set semantics, positional columns like the
        reference round-trips via Catalyst's Intersect node,
        LogicalPlanSerDeUtils.scala:82-145): distinct left rows that also
        appear in `other`. Desugars to DISTINCT + NULL-SAFE SEMI JOIN on
        every column: set comparison treats NULL as equal to NULL (SQL's
        IS NOT DISTINCT FROM), so a NULL-bearing row intersects with its
        NULL-bearing twin — unlike the engine's ordinary join semantics
        where NULL never equals anything."""
        return self._set_op(other, "semi")

    def except_(self, other: "LogicalPlan") -> "Join":
        """SQL EXCEPT: distinct left rows absent from `other`. Desugars
        to DISTINCT + NULL-SAFE ANTI JOIN on every column (same NULL
        semantics as intersect: a left NULL-bearing row is removed when
        `other` holds an identical NULL-bearing row)."""
        return self._set_op(other, "anti")

    def _set_op(self, other: "LogicalPlan", how: str) -> "Join":
        if len(self.schema.names) != len(other.schema.names):
            raise ValueError(
                f"set operation inputs must have equal width: "
                f"{self.schema.names} vs {other.schema.names}"
            )
        for lf, rf in zip(self.schema.fields, other.schema.fields):
            # Positional pairs must share a comparison domain — a silent
            # string/number coercion would "match" 1 with '1'.
            if lf.is_string != rf.is_string:
                raise ValueError(
                    f"set operation column types are incompatible: "
                    f"{lf.name} ({lf.dtype}) vs {rf.name} ({rf.dtype})"
                )
        return Join(
            self.distinct(), other, list(self.schema.names),
            list(other.schema.names), how, null_safe=True,
        )

    def distinct(self) -> "Aggregate":
        """Distinct rows = group by every column with no aggregates.
        Vector (embedding) columns have no grouping semantics — select
        the scalar columns first."""
        vec = [f.name for f in self.schema.fields if f.is_vector]
        if vec:
            raise ValueError(
                f"distinct() is not defined over vector columns {vec}; "
                "select the scalar columns first"
            )
        return Aggregate(self, list(self.schema.names), [])

    # -- interface --------------------------------------------------------
    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    def children(self) -> list["LogicalPlan"]:
        raise NotImplementedError

    def to_json(self) -> dict[str, Any]:
        raise NotImplementedError

    def leaves(self) -> list["Scan"]:
        if isinstance(self, Scan):
            return [self]
        out: list[Scan] = []
        for c in self.children():
            out.extend(c.leaves())
        return out

    def is_linear(self) -> bool:
        """True iff no node has more than one child (reference requires
        linear sub-plans for join sides, JoinIndexRule.scala:210-211)."""
        cs = self.children()
        return len(cs) <= 1 and all(c.is_linear() for c in cs)


@dataclasses.dataclass
class Scan(LogicalPlan):
    """Leaf: scan a registered columnar dataset (analog of
    LogicalRelation(HadoopFsRelation) in the reference)."""

    root: str
    format: str
    scan_schema: Schema
    # Optional pinned file subset (used for index scans / hybrid scan);
    # None ⇒ list the live filesystem at execution time.
    files: list[str] | None = None
    # Bucket spec when scanning bucketed index data (num_buckets, bucket_cols)
    bucket_spec: tuple[int, list[str]] | None = None

    @property
    def schema(self) -> Schema:
        return self.scan_schema

    def children(self) -> list[LogicalPlan]:
        return []

    def to_json(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "type": "scan",
            "root": self.root,
            "format": self.format,
            "schema": self.scan_schema.to_json(),
        }
        if self.files is not None:
            d["files"] = self.files
        if self.bucket_spec is not None:
            d["bucketSpec"] = {"numBuckets": self.bucket_spec[0], "bucketColumns": self.bucket_spec[1]}
        return d


@dataclasses.dataclass
class Filter(LogicalPlan):
    child: LogicalPlan
    predicate: Expr

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        return {"type": "filter", "child": self.child.to_json(), "predicate": self.predicate.to_json()}


@dataclasses.dataclass
class Project(LogicalPlan):
    """Projection with optional named computed expressions. Entries of
    `columns` are either a column name (passthrough) or an
    ``(alias, Expr)`` pair (`SELECT a*b AS x` — the reference gets
    computed select lists from Catalyst's Project for free; our IR
    carries them explicitly and types them via expr_dtype)."""

    child: LogicalPlan
    columns: list

    @property
    def is_simple(self) -> bool:
        """True iff every entry is a plain passthrough column name."""
        return all(isinstance(c, str) for c in self.columns)

    @property
    def output_names(self) -> list[str]:
        return [c if isinstance(c, str) else c[0] for c in self.columns]

    def input_columns(self) -> set[str]:
        """Lowercased child columns the projection reads (what index
        coverage checks and column pruning need)."""
        out: set[str] = set()
        for c in self.columns:
            if isinstance(c, str):
                out.add(c.lower())
            else:
                out |= c[1].references()
        return out

    @property
    def schema(self) -> Schema:
        from hyperspace_tpu.plan.expr import expr_dtype
        from hyperspace_tpu.schema import Field

        if self.is_simple:
            return self.child.schema.select(self.columns)
        child = self.child.schema
        fields = []
        for c in self.columns:
            if isinstance(c, str):
                fields.append(child.field(c))
            else:
                fields.append(Field(c[0], expr_dtype(c[1], child)))
        return Schema(tuple(fields))

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        if self.is_simple:
            return {"type": "project", "child": self.child.to_json(), "columns": self.columns}
        cols = [
            c if isinstance(c, str) else {"alias": c[0], "expr": c[1].to_json()}
            for c in self.columns
        ]
        return {"type": "project", "child": self.child.to_json(), "columns": cols}


@dataclasses.dataclass
class Union(LogicalPlan):
    """Concatenate rows of name-compatible children. Exists for Hybrid Scan:
    an index scan unioned with a scan pinned to source files appended since
    the index build (the analog of later-Hyperspace's hybrid scan plan,
    which unions index data with an on-the-fly scan of appended files)."""

    inputs: list[LogicalPlan]

    def __post_init__(self):
        if not self.inputs:
            raise ValueError("union needs at least one input")
        first = [n.lower() for n in self.inputs[0].schema.names]
        for child in self.inputs[1:]:
            if [n.lower() for n in child.schema.names] != first:
                raise ValueError(
                    f"union inputs must share column names: {first} vs {child.schema.names}"
                )

    @property
    def schema(self) -> Schema:
        return self.inputs[0].schema

    def children(self) -> list[LogicalPlan]:
        return list(self.inputs)

    def to_json(self) -> dict[str, Any]:
        return {"type": "union", "inputs": [c.to_json() for c in self.inputs]}


JOIN_TYPES = ("inner", "left", "right", "full", "semi", "anti")


@dataclasses.dataclass
class Join(LogicalPlan):
    """Equi-join on key column lists (reference matches CNF of EqualTo,
    JoinIndexRule.scala:179-185; we make the equi-join structural). `how`
    covers the join types Spark's SortMergeJoinExec serves over the
    reference's rewritten bucketed relations (JoinIndexRule.scala:124-153
    swaps only the relations inside whatever join node it matched):
    inner / left / right / full outer, plus (left) semi and anti."""

    left: LogicalPlan
    right: LogicalPlan
    left_on: list[str]
    right_on: list[str]
    how: str = "inner"
    # Non-equi residual of the ON clause (equality stays structural):
    # evaluated with full 3-valued semantics over the equi-matched
    # pairs. Inner joins filter; outer/semi/anti joins treat a failing
    # pair as NO MATCH (null-extension / existence semantics).
    condition: Expr | None = None
    # NULL-safe key equality (SQL IS NOT DISTINCT FROM): NULL matches
    # NULL per key column instead of never matching. The set operations
    # (intersect/except_) desugar with this on; the key factorization
    # gives NULL its own code-domain value per column, shared across
    # sides (execution/exec_common.py).
    null_safe: bool = False

    def __post_init__(self):
        if len(self.left_on) != len(self.right_on):
            raise ValueError("join key lists must have equal length")
        if self.how not in JOIN_TYPES:
            raise ValueError(f"unknown join type {self.how!r}; one of {JOIN_TYPES}")
        if self.condition is not None:
            # Validate references against the MATCH schema now (right
            # key names merge into the left-named column; semi/anti
            # conditions may read right non-key columns even though the
            # output is left-only), so a typo or a merged-away key fails
            # here, not mid-execution.
            out_names = {n.lower() for n in self.match_schema.names}
            missing = sorted(
                r for r in self.condition.references() if r not in out_names
            )
            if missing:
                raise ValueError(
                    f"join condition references {missing} not present in the "
                    f"join match schema (right-side key columns merge into "
                    f"the left-named key)"
                )

    @property
    def match_schema(self) -> Schema:
        """The schema an ON residual evaluates over: left columns plus
        right non-key columns — the inner-join shape, whatever `how` is.
        A non-key name collision is ambiguous and rejected."""
        lf = self.left.schema.fields
        left_names = {f.name.lower() for f in lf}
        keys = {k.lower() for k in self.right_on}
        rf = []
        for f in self.right.schema.fields:
            low = f.name.lower()
            if low in keys:
                continue  # merged into the left key column
            if low in left_names:
                raise ValueError(
                    f"ambiguous non-key column {f.name!r} appears on both join sides"
                )
            rf.append(f)
        return Schema(tuple(lf) + tuple(rf))

    @property
    def schema(self) -> Schema:
        """Join key columns appear once (equal for matches; outer joins
        coalesce the surviving side's key into the left-named column).
        Semi/anti produce the left side's schema only."""
        if self.how in ("semi", "anti"):
            return Schema(tuple(self.left.schema.fields))
        return self.match_schema

    def children(self) -> list[LogicalPlan]:
        return [self.left, self.right]

    def to_json(self) -> dict[str, Any]:
        d = {
            "type": "join",
            "left": self.left.to_json(),
            "right": self.right.to_json(),
            "leftOn": self.left_on,
            "rightOn": self.right_on,
            "how": self.how,
        }
        if self.condition is not None:
            d["condition"] = self.condition.to_json()
        if self.null_safe:
            # Emitted only when set, so pre-existing plan signatures and
            # logged lineage stay byte-identical for ordinary joins.
            d["nullSafe"] = True
        return d


@dataclasses.dataclass
class AggSpec:
    """One aggregation: fn over an expression (None = count(*)).
    count_distinct counts distinct non-null values of a column and
    executes as a two-phase re-aggregation (the executor desugars it)."""

    fn: str  # sum | count | min | max | mean | count_distinct | grouping
    expr: Expr | None
    alias: str

    _FNS = ("sum", "count", "min", "max", "mean", "count_distinct", "grouping")

    def __post_init__(self):
        from hyperspace_tpu.plan.expr import Col

        if self.fn not in self._FNS:
            raise ValueError(f"unknown aggregate fn {self.fn!r}")
        if self.expr is None and self.fn != "count":
            raise ValueError(f"{self.fn} requires an input expression")
        if self.fn == "grouping" and not isinstance(self.expr, Col):
            # SQL GROUPING(col): 1 when the output row aggregates the
            # column away (a coarser grouping set), else 0.
            raise ValueError("grouping() takes a single group-by column")

    @staticmethod
    def of(fn: str, expr=None, alias: str | None = None) -> "AggSpec":
        from hyperspace_tpu.plan.expr import Col

        if isinstance(expr, str):
            expr = Col(expr)
        if alias is None:
            base = expr.name if isinstance(expr, Col) else ("star" if expr is None else "expr")
            alias = f"{fn}_{base}" if expr is not None else "count"
        return AggSpec(fn, expr, alias)

    def references(self) -> set[str]:
        return self.expr.references() if self.expr is not None else set()

    def to_json(self) -> dict[str, Any]:
        return {
            "fn": self.fn,
            "expr": self.expr.to_json() if self.expr is not None else None,
            "alias": self.alias,
        }

    @staticmethod
    def from_json(d: dict[str, Any]) -> "AggSpec":
        e = expr_from_json(d["expr"]) if d.get("expr") is not None else None
        return AggSpec(d["fn"], e, d["alias"])


@dataclasses.dataclass
class Aggregate(LogicalPlan):
    """Grouped aggregation — one of the engine-side operators the TPU build
    owns (SURVEY.md §2.2 lists the WholeStageCodegen'd operators Spark
    'provided' to the reference). Sorted-key segments post-index make the
    device reduction cheap; Aggregate(Join) additionally fuses into a
    run-prefix aggregation that never materializes the joined pairs."""

    child: LogicalPlan
    group_by: list[str]
    aggs: list[AggSpec]
    # GROUPING SETS: each entry is a subset of group_by; the output is
    # the union of re-groupings (ROLLUP/CUBE desugar to this). None =
    # plain GROUP BY. Executes as ONE finest-grain aggregate + cheap
    # re-aggregations of its partials (the two-phase machinery that
    # count_distinct pioneered, generalized).
    grouping_sets: list[list[str]] | None = None

    def __post_init__(self):
        seen: set[str] = set()
        for name in [*(c.lower() for c in self.group_by), *(a.alias.lower() for a in self.aggs)]:
            if name in seen:
                raise ValueError(f"duplicate output column {name!r} in aggregate")
            seen.add(name)
        gset = {c.lower() for c in self.group_by}
        if self.grouping_sets is not None:
            for s in self.grouping_sets:
                if not {c.lower() for c in s} <= gset:
                    raise ValueError(f"grouping set {s} is not a subset of group_by")
        for a in self.aggs:
            if a.fn == "grouping":
                if self.grouping_sets is None:
                    raise ValueError("grouping() requires grouping sets / rollup")
                if a.expr.name.lower() not in gset:
                    raise ValueError(f"grouping({a.expr.name}) is not a group-by column")

    @property
    def schema(self) -> Schema:
        from hyperspace_tpu.plan.expr import Col
        from hyperspace_tpu.schema import Field

        child = self.child.schema
        fields = [child.field(c) for c in self.group_by]
        for a in self.aggs:
            if a.fn in ("count", "count_distinct", "grouping"):
                dtype = "int64"
            elif a.fn == "mean":
                dtype = "float64"
            elif isinstance(a.expr, Col):
                src = child.field(a.expr.name)
                if a.fn in ("min", "max"):
                    dtype = src.dtype
                else:  # sum widens integers
                    dtype = "int64" if src.dtype in ("int32", "int64", "bool", "date") else "float64"
            else:
                dtype = "float64"
            fields.append(Field(a.alias, dtype))
        return Schema(tuple(fields))

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        d = {
            "type": "aggregate",
            "child": self.child.to_json(),
            "groupBy": self.group_by,
            "aggs": [a.to_json() for a in self.aggs],
        }
        if self.grouping_sets is not None:
            d["groupingSets"] = [list(s) for s in self.grouping_sets]
        return d


@dataclasses.dataclass
class WindowSpec:
    """One window function: fn over an expression (None for the ranking
    family and count(*)). lag/lead shift the value within the partition
    by `offset` rows of the ORDER BY (SQL LAG/LEAD with a NULL default);
    they ignore the frame."""

    fn: str  # row_number | rank | dense_rank | sum | count | mean | min | max | lag | lead
    expr: Expr | None
    alias: str
    offset: int = 1  # lag/lead only

    _FNS = ("row_number", "rank", "dense_rank", "sum", "count", "mean", "min", "max", "lag", "lead")
    RANKING = ("row_number", "rank", "dense_rank")
    SHIFT = ("lag", "lead")

    def __post_init__(self):
        if self.fn not in self._FNS:
            raise ValueError(f"unknown window fn {self.fn!r}")
        if self.expr is None and self.fn not in (*self.RANKING, "count"):
            raise ValueError(f"{self.fn} requires an input expression")
        if self.expr is not None and self.fn in self.RANKING:
            raise ValueError(f"{self.fn} takes no input expression")
        if self.fn in self.SHIFT and self.offset < 1:
            raise ValueError(f"{self.fn} offset must be >= 1")

    @staticmethod
    def of(fn: str, expr=None, alias: str | None = None, offset: int = 1) -> "WindowSpec":
        from hyperspace_tpu.plan.expr import Col

        if isinstance(expr, str):
            expr = Col(expr)
        if alias is None:
            base = expr.name if isinstance(expr, Col) else ("star" if expr is None else "expr")
            alias = f"{fn}_{base}" if expr is not None else fn
        return WindowSpec(fn, expr, alias, offset)

    def references(self) -> set[str]:
        return self.expr.references() if self.expr is not None else set()

    def to_json(self) -> dict[str, Any]:
        d = {
            "fn": self.fn,
            "expr": self.expr.to_json() if self.expr is not None else None,
            "alias": self.alias,
        }
        if self.fn in self.SHIFT:
            d["offset"] = self.offset
        return d

    @staticmethod
    def from_json(d: dict[str, Any]) -> "WindowSpec":
        e = expr_from_json(d["expr"]) if d.get("expr") is not None else None
        return WindowSpec(d["fn"], e, d["alias"], d.get("offset", 1))


WINDOW_FRAMES = ("partition", "rows", "range")


@dataclasses.dataclass
class Window(LogicalPlan):
    """Window functions over partitions: every child row passes through
    with one extra column per WindowSpec. The reference's environment gets
    Spark's Window exec; the TPU build formulates it as sorted segments
    over the engine's order-preserving key lanes (ops/window.py).

    `frame` applies to the aggregate functions:
      - "partition": the whole partition (no ORDER BY needed);
      - "rows":  ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW;
      - "range": RANGE ... CURRENT ROW (peer rows by the order key share
        the frame result — SQL's default frame when ORDER BY is present).
    Ranking functions always need an ORDER BY and ignore the frame."""

    child: LogicalPlan
    partition_by: list[str]
    order_by: list[tuple[str, bool]]
    funcs: list["WindowSpec"]
    frame: str = "partition"

    def __post_init__(self):
        if not self.funcs:
            raise ValueError("window requires at least one function")
        if self.frame not in WINDOW_FRAMES:
            raise ValueError(f"unknown window frame {self.frame!r}; one of {WINDOW_FRAMES}")
        if self.frame != "partition" and not self.order_by:
            raise ValueError(f"window frame {self.frame!r} requires an ORDER BY")
        if not self.order_by and any(
            f.fn in (*WindowSpec.RANKING, *WindowSpec.SHIFT) for f in self.funcs
        ):
            raise ValueError("ranking and lag/lead window functions require an ORDER BY")
        child_names = {n.lower() for n in self.child.schema.names}
        seen = set(child_names)
        for f in self.funcs:
            low = f.alias.lower()
            if low in seen:
                raise ValueError(f"window output column {f.alias!r} collides")
            seen.add(low)

    @property
    def schema(self) -> Schema:
        from hyperspace_tpu.plan.expr import Col
        from hyperspace_tpu.schema import Field

        child = self.child.schema
        fields = list(child.fields)
        for f in self.funcs:
            if f.fn in (*WindowSpec.RANKING, "count"):
                dtype = "int64"
            elif f.fn == "mean":
                dtype = "float64"
            elif isinstance(f.expr, Col):
                src = child.field(f.expr.name)
                if f.fn in ("min", "max", "lag", "lead"):
                    dtype = src.dtype  # extremum / shift preserve the input type
                else:  # sum widens integers
                    dtype = "int64" if src.dtype in ("int32", "int64", "bool", "date") else "float64"
            else:
                dtype = "float64"
            fields.append(Field(f.alias, dtype))
        return Schema(tuple(fields))

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        return {
            "type": "window",
            "child": self.child.to_json(),
            "partitionBy": self.partition_by,
            "orderBy": [[c, bool(a)] for c, a in self.order_by],
            "funcs": [f.to_json() for f in self.funcs],
            "frame": self.frame,
        }


@dataclasses.dataclass
class Sort(LogicalPlan):
    """Total order by (column, ascending) keys — executes as one device
    lax.sort over order-preserving 32-bit lanes (ops/sortkeys.py)."""

    child: LogicalPlan
    by: list[tuple[str, bool]]

    def __post_init__(self):
        if not self.by:
            raise ValueError("sort requires at least one order-by key")

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        return {
            "type": "sort",
            "child": self.child.to_json(),
            "by": [[c, bool(a)] for c, a in self.by],
        }


@dataclasses.dataclass
class Limit(LogicalPlan):
    child: LogicalPlan
    n: int

    @property
    def schema(self) -> Schema:
        return self.child.schema

    def children(self) -> list[LogicalPlan]:
        return [self.child]

    def to_json(self) -> dict[str, Any]:
        return {"type": "limit", "child": self.child.to_json(), "n": self.n}


def plan_from_json(d: dict[str, Any]) -> LogicalPlan:
    t = d["type"]
    if t == "scan":
        bs = None
        if "bucketSpec" in d:
            bs = (int(d["bucketSpec"]["numBuckets"]), list(d["bucketSpec"]["bucketColumns"]))
        return Scan(
            d["root"],
            d["format"],
            Schema.from_json(d["schema"]),
            files=d.get("files"),
            bucket_spec=bs,
        )
    if t == "filter":
        return Filter(plan_from_json(d["child"]), expr_from_json(d["predicate"]))
    if t == "project":
        cols = [
            c if isinstance(c, str) else (c["alias"], expr_from_json(c["expr"]))
            for c in d["columns"]
        ]
        return Project(plan_from_json(d["child"]), cols)
    if t == "union":
        return Union([plan_from_json(c) for c in d["inputs"]])
    if t == "join":
        return Join(
            plan_from_json(d["left"]),
            plan_from_json(d["right"]),
            list(d["leftOn"]),
            list(d["rightOn"]),
            d.get("how", "inner"),
            condition=expr_from_json(d["condition"]) if "condition" in d else None,
            null_safe=bool(d.get("nullSafe", False)),
        )
    if t == "aggregate":
        gs = d.get("groupingSets")
        return Aggregate(
            plan_from_json(d["child"]),
            list(d["groupBy"]),
            [AggSpec.from_json(a) for a in d["aggs"]],
            grouping_sets=[list(s) for s in gs] if gs is not None else None,
        )
    if t == "window":
        return Window(
            plan_from_json(d["child"]),
            list(d["partitionBy"]),
            [(c, bool(a)) for c, a in d["orderBy"]],
            [WindowSpec.from_json(f) for f in d["funcs"]],
            d.get("frame", "partition"),
        )
    if t == "sort":
        return Sort(plan_from_json(d["child"]), [(c, bool(a)) for c, a in d["by"]])
    if t == "limit":
        return Limit(plan_from_json(d["child"]), int(d["n"]))
    raise ValueError(f"unknown plan node type {t!r}")
