"""Projection pushdown: read only the columns a query needs.

The analog of Spark's ColumnPruning + Parquet column projection, which
the reference inherits for free from its host engine (SURVEY.md §2.2,
FileSourceScanExec vectorized read). Without it every scan decodes the
full table width — on real TPC-H schemas that means dictionary-encoding
6M comment strings to answer a 3-column query. The pass rewrites each
Scan's `scan_schema` to the subset of columns required by its ancestors
(projections, predicate references, join keys); the executor then feeds
the pruned schema straight into the parquet column projection.
"""

from __future__ import annotations

import dataclasses

from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
    Window,
)


def prune_columns(plan: LogicalPlan, needed: set[str] | None = None) -> LogicalPlan:
    """Rewrite `plan` so every Scan reads only columns in `needed`
    (lowercase names; None = all columns are required)."""
    if isinstance(plan, Scan):
        if needed is None:
            return plan
        cols = [c for c in plan.scan_schema.names if c.lower() in needed]
        if not cols and plan.scan_schema.names:
            # A zero-column scan would report num_rows == 0; a pure
            # count(*) needs the row count, so keep one (cheap) column.
            names = plan.scan_schema.names
            cols = [next((c for c in names if not plan.scan_schema.field(c).is_string), names[0])]
        if len(cols) == len(plan.scan_schema.names):
            return plan
        return dataclasses.replace(plan, scan_schema=plan.scan_schema.select(cols))
    if isinstance(plan, Project):
        # Inner projections narrow to what ancestors need (the top-level
        # call has needed=None, so the user-visible schema never changes);
        # narrowing keeps Union branches consistently aligned. Entries
        # are names or (alias, Expr) — a kept computed entry needs every
        # column its expression references.
        if needed is None:
            keep = list(plan.columns)
        else:
            keep = [
                c
                for c in plan.columns
                if (c if isinstance(c, str) else c[0]).lower() in needed
            ]
        child_needed: set[str] = set()
        for c in keep:
            if isinstance(c, str):
                child_needed.add(c.lower())
            else:
                child_needed |= c[1].references()
        return Project(prune_columns(plan.child, child_needed), keep)
    if isinstance(plan, Filter):
        if needed is None:
            child_needed = None
        else:
            child_needed = set(needed) | {c.lower() for c in plan.predicate.references()}
        return Filter(prune_columns(plan.child, child_needed), plan.predicate)
    if isinstance(plan, Join):
        if needed is None:
            lneed = rneed = None
        else:
            cond_refs = (
                {c.lower() for c in plan.condition.references()}
                if plan.condition is not None
                else set()
            )
            lneed = {
                c.lower()
                for c in plan.left.schema.names
                if c.lower() in needed or c.lower() in cond_refs
            }
            lneed |= {c.lower() for c in plan.left_on}
            rneed = {
                c.lower()
                for c in plan.right.schema.names
                if c.lower() in needed or c.lower() in cond_refs
            }
            rneed |= {c.lower() for c in plan.right_on}
        return dataclasses.replace(
            plan, left=prune_columns(plan.left, lneed), right=prune_columns(plan.right, rneed)
        )
    if isinstance(plan, Union):
        return Union([prune_columns(c, needed) for c in plan.inputs])
    if isinstance(plan, Aggregate):
        child_needed = {c.lower() for c in plan.group_by}
        for a in plan.aggs:
            child_needed |= {c.lower() for c in a.references()}
        if not child_needed:
            # Pure count(*): an empty set would prune every width-defining
            # node (Scan, Project, Union branches) to zero columns and
            # collapse num_rows; keep one (cheap) child column instead.
            names = plan.child.schema.names
            if names:
                pick = next((c for c in names if not plan.child.schema.field(c).is_string), names[0])
                child_needed = {pick.lower()}
        return dataclasses.replace(plan, child=prune_columns(plan.child, child_needed))
    if isinstance(plan, Window):
        aliases = {f.alias.lower() for f in plan.funcs}
        if needed is None:
            child_needed = None
        else:
            child_needed = {c for c in needed if c not in aliases}
            child_needed |= {c.lower() for c in plan.partition_by}
            child_needed |= {c.lower() for c, _ in plan.order_by}
            for f in plan.funcs:
                child_needed |= f.references()
            if not child_needed:
                # count(*)-style window over no keys: keep one cheap
                # column so the child's row count survives pruning.
                names = plan.child.schema.names
                if names:
                    pick = next(
                        (c for c in names if not plan.child.schema.field(c).is_string),
                        names[0],
                    )
                    child_needed = {pick.lower()}
        return dataclasses.replace(plan, child=prune_columns(plan.child, child_needed))
    if isinstance(plan, Sort):
        if needed is None:
            child_needed = None
        else:
            child_needed = set(needed) | {c.lower() for c, _ in plan.by}
        return dataclasses.replace(plan, child=prune_columns(plan.child, child_needed))
    if isinstance(plan, Limit):
        return dataclasses.replace(plan, child=prune_columns(plan.child, needed))
    return plan
