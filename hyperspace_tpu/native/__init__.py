"""Loader for the native host-runtime kernels (hashing.cpp).

Compiles the C++ on first use with g++ (cached as a .so keyed by source
hash under ~/.cache/hyperspace_tpu/native) and binds it via ctypes — no
pybind11 dependency. Every caller falls back to the numpy implementation
when the toolchain or the build is unavailable, so this module is a pure
accelerator: `available()` reports which path is active.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from pathlib import Path

import numpy as np

_SRC = Path(__file__).with_name("hashing.cpp")

_lib: ctypes.CDLL | None = None
_tried = False


def _cache_dir() -> Path:
    root = os.environ.get(
        "HYPERSPACE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "hyperspace_tpu", "native"),
    )
    return Path(root)


def _build() -> Path | None:
    src = _SRC.read_bytes()
    tag = hashlib.sha256(src).hexdigest()[:16]
    out = _cache_dir() / f"libhs_native_{tag}.so"
    if out.exists():
        return out
    out.parent.mkdir(parents=True, exist_ok=True)
    # Per-process temp name: concurrent builders must not interleave writes
    # into one file, or os.replace could publish a corrupted .so.
    tmp = out.parent / f"{out.name}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        "-march=native", str(_SRC), "-o", str(tmp),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except Exception:
        try:  # retry without -march=native (portability)
            cmd.remove("-march=native")
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        except Exception:
            tmp.unlink(missing_ok=True)
            return None
    os.replace(tmp, out)  # atomic publish; concurrent builders converge
    return out


def _load() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("HYPERSPACE_TPU_DISABLE_NATIVE"):
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(str(path))
    except OSError:
        return None
    u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
    i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    lib.hs_hash_i64.argtypes = [i64p, u32p, ctypes.c_int64]
    lib.hs_hash_i32.argtypes = [i32p, u32p, ctypes.c_int64]
    lib.hs_md5_prefix.argtypes = [u8p, i64p, u32p, ctypes.c_int64]
    lib.hs_take_rows.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, i64p, ctypes.c_int64, ctypes.c_int64,
    ]
    lib.hs_combine.argtypes = [u32p, u32p, ctypes.c_int64]
    lib.hs_mj_count.argtypes = [i32p, i64p, i32p, i64p, ctypes.c_int64, i64p]
    lib.hs_mj_fill.argtypes = [i32p, i64p, i32p, i64p, i64p, ctypes.c_int64, i64p, i64p]
    f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
    lib.hs_mj_accum.argtypes = [
        i32p, i64p, i32p, i64p, ctypes.c_int64,
        f64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, f64p, f64p,
    ]
    lib.hs_bucket_perm.argtypes = [i32p, ctypes.c_int64, ctypes.c_int64, i64p, i64p]
    lib.hs_sort_range.argtypes = [i64p, ctypes.c_int64, u32p, ctypes.c_int64, ctypes.c_int64]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


# ---- typed wrappers (None ⇒ caller uses the numpy path) --------------------

def hash_i64(arr: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.int64)
    out = np.empty(len(arr), dtype=np.uint32)
    lib.hs_hash_i64(arr, out, len(arr))
    return out


def hash_i32(arr: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr, dtype=np.int32)
    out = np.empty(len(arr), dtype=np.uint32)
    lib.hs_hash_i32(arr, out, len(arr))
    return out


def md5_prefix(strings: np.ndarray) -> np.ndarray | None:
    """uint32 md5-prefix per entry of an object array of strings."""
    lib = _load()
    if lib is None:
        return None
    encoded = [str(s).encode("utf-8") for s in strings]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(b) for b in encoded], out=offsets[1:])
    blob = np.frombuffer(b"".join(encoded), dtype=np.uint8) if encoded else np.zeros(0, np.uint8)
    blob = np.ascontiguousarray(blob)
    out = np.empty(len(encoded), dtype=np.uint32)
    lib.hs_md5_prefix(blob if len(blob) else np.zeros(1, np.uint8), offsets, out, len(encoded))
    return out


def take_rows(arr: np.ndarray, idx: np.ndarray) -> np.ndarray | None:
    """arr[idx] for 1-D/2-D contiguous arrays, threaded."""
    lib = _load()
    if lib is None:
        return None
    arr = np.ascontiguousarray(arr)
    idx = np.ascontiguousarray(idx, dtype=np.int64)
    row_bytes = arr.dtype.itemsize * (arr.shape[1] if arr.ndim == 2 else 1)
    out = np.empty((len(idx),) + arr.shape[1:], dtype=arr.dtype)
    lib.hs_take_rows(
        arr.ctypes.data_as(ctypes.c_void_p),
        out.ctypes.data_as(ctypes.c_void_p),
        idx, len(idx), row_bytes,
    )
    return out


def bucket_perm(
    bucket: np.ndarray, num_buckets: int
) -> tuple[np.ndarray, np.ndarray] | None:
    """Stable counting sort of row ids by bucket. Returns (perm int64,
    per-bucket counts int64), or None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    bucket = np.ascontiguousarray(bucket, dtype=np.int32)
    perm = np.empty(len(bucket), dtype=np.int64)
    counts = np.zeros(num_buckets, dtype=np.int64)
    lib.hs_bucket_perm(bucket, len(bucket), num_buckets, perm, counts)
    return perm, counts


def sort_range(perm_slice: np.ndarray, lanes_u32: np.ndarray) -> bool:
    """In-place key sort of one bucket's contiguous permutation slice by
    the [L, n] unsigned lanes (GIL released — pipelines with encode)."""
    lib = _load()
    if lib is None:
        return False
    assert perm_slice.flags.c_contiguous and perm_slice.dtype == np.int64
    num_lanes = lanes_u32.shape[0] if lanes_u32.ndim == 2 else 0
    lib.hs_sort_range(
        perm_slice,
        len(perm_slice),
        lanes_u32 if num_lanes else np.zeros((1, 1), np.uint32),
        lanes_u32.shape[1] if num_lanes else 0,
        num_lanes,
    )
    return True


def merge_join_accumulate(
    lk: np.ndarray, lofs: np.ndarray, rk: np.ndarray, rofs: np.ndarray,
    rvals: np.ndarray,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Fused merge + accumulate over within-bucket-sorted int32 codes:
    per SORTED-primary-row channel sums of the matching secondary rows
    plus the per-row match count — Aggregate(Join) without materializing
    pairs. rvals is [A, n_r] float64; returns (out [A, n_l], counts
    [n_l]); None when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    lk = np.ascontiguousarray(lk, dtype=np.int32)
    rk = np.ascontiguousarray(rk, dtype=np.int32)
    lofs = np.ascontiguousarray(lofs, dtype=np.int64)
    rofs = np.ascontiguousarray(rofs, dtype=np.int64)
    rvals = np.ascontiguousarray(rvals, dtype=np.float64)
    a_r = rvals.shape[0]
    n_r, n_l = len(rk), len(lk)
    out = np.zeros((a_r, n_l), dtype=np.float64)
    counts = np.zeros(n_l, dtype=np.float64)
    lib.hs_mj_accum(
        lk, lofs, rk, rofs, len(lofs) - 1,
        rvals if a_r else np.zeros((1, 1)), a_r, n_r, n_l, out, counts,
    )
    return out, counts


def merge_join_sorted(
    lk: np.ndarray, lofs: np.ndarray, rk: np.ndarray, rofs: np.ndarray
) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
    """Exact bucket-parallel merge join over within-bucket-sorted int32
    codes. Returns (li, ri, totals): GLOBAL row indices (int64) in
    bucket-major match order, and per-bucket match counts. None when the
    library is unavailable (caller uses the device path)."""
    lib = _load()
    if lib is None:
        return None
    lk = np.ascontiguousarray(lk, dtype=np.int32)
    rk = np.ascontiguousarray(rk, dtype=np.int32)
    lofs = np.ascontiguousarray(lofs, dtype=np.int64)
    rofs = np.ascontiguousarray(rofs, dtype=np.int64)
    nb = len(lofs) - 1
    counts = np.zeros(nb, dtype=np.int64)
    lib.hs_mj_count(lk, lofs, rk, rofs, nb, counts)
    oofs = np.zeros(nb + 1, dtype=np.int64)
    np.cumsum(counts, out=oofs[1:])
    total = int(oofs[-1])
    li = np.empty(total, dtype=np.int64)
    ri = np.empty(total, dtype=np.int64)
    lib.hs_mj_fill(lk, lofs, rk, rofs, oofs, nb, li, ri)
    return li, ri, counts


def combine(acc: np.ndarray, h: np.ndarray) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    acc = np.ascontiguousarray(acc, dtype=np.uint32).copy()
    lib.hs_combine(acc, np.ascontiguousarray(h, dtype=np.uint32), len(acc))
    return acc
