// hyperspace_tpu native host-runtime kernels.
//
// The TPU analog of the engine-side native machinery the reference leans on
// (SURVEY.md §2.2: Spark's JVM codegen'd operators, Netty shuffle, Parquet
// codecs — all "provided" native code). The device plane is XLA/Pallas; this
// library covers the HOST hot loops of the build/query pipeline:
//
//   - murmur3-fmix32 row hashing for bucket assignment (bit-identical to
//     ops/hashing.py's numpy/jnp implementation — bucket pruning and
//     on-disk indexes depend on the match),
//   - MD5 prefix hashes for string dictionaries (RFC 1321, replacing a
//     per-entry Python hashlib loop),
//   - threaded row gather (the permutation apply after the device sort).
//
// Built on demand by hyperspace_tpu/native/__init__.py with g++ -O3; every
// entry point has a numpy fallback, so the library is an accelerator, never
// a dependency.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

inline uint32_t mix32(uint32_t x) {
  x ^= x >> 16;
  x *= 0x85EBCA6Bu;
  x ^= x >> 13;
  x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return x;
}

void parallel_for(int64_t n, int64_t grain, const std::function<void(int64_t, int64_t)>& fn) {
  unsigned hw = std::thread::hardware_concurrency();
  int64_t nthreads = hw ? static_cast<int64_t>(hw) : 4;
  if (nthreads > (n + grain - 1) / grain) nthreads = (n + grain - 1) / grain;
  if (nthreads <= 1) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + nthreads - 1) / nthreads;
  for (int64_t t = 0; t < nthreads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(fn, lo, hi);
  }
  for (auto& th : threads) th.join();
}

// ---- compact MD5 (RFC 1321) ------------------------------------------------

struct MD5 {
  uint32_t a0 = 0x67452301, b0 = 0xefcdab89, c0 = 0x98badcfe, d0 = 0x10325476;

  static constexpr uint32_t K[64] = {
      0xd76aa478, 0xe8c7b756, 0x242070db, 0xc1bdceee, 0xf57c0faf, 0x4787c62a,
      0xa8304613, 0xfd469501, 0x698098d8, 0x8b44f7af, 0xffff5bb1, 0x895cd7be,
      0x6b901122, 0xfd987193, 0xa679438e, 0x49b40821, 0xf61e2562, 0xc040b340,
      0x265e5a51, 0xe9b6c7aa, 0xd62f105d, 0x02441453, 0xd8a1e681, 0xe7d3fbc8,
      0x21e1cde6, 0xc33707d6, 0xf4d50d87, 0x455a14ed, 0xa9e3e905, 0xfcefa3f8,
      0x676f02d9, 0x8d2a4c8a, 0xfffa3942, 0x8771f681, 0x6d9d6122, 0xfde5380c,
      0xa4beea44, 0x4bdecfa9, 0xf6bb4b60, 0xbebfbc70, 0x289b7ec6, 0xeaa127fa,
      0xd4ef3085, 0x04881d05, 0xd9d4d039, 0xe6db99e5, 0x1fa27cf8, 0xc4ac5665,
      0xf4292244, 0x432aff97, 0xab9423a7, 0xfc93a039, 0x655b59c3, 0x8f0ccc92,
      0xffeff47d, 0x85845dd1, 0x6fa87e4f, 0xfe2ce6e0, 0xa3014314, 0x4e0811a1,
      0xf7537e82, 0xbd3af235, 0x2ad7d2bb, 0xeb86d391};
  static constexpr int S[64] = {7, 12, 17, 22, 7, 12, 17, 22, 7, 12, 17, 22,
                                7, 12, 17, 22, 5, 9,  14, 20, 5, 9,  14, 20,
                                5, 9,  14, 20, 5, 9,  14, 20, 4, 11, 16, 23,
                                4, 11, 16, 23, 4, 11, 16, 23, 4, 11, 16, 23,
                                6, 10, 15, 21, 6, 10, 15, 21, 6, 10, 15, 21,
                                6, 10, 15, 21};

  static uint32_t rotl(uint32_t x, int c) { return (x << c) | (x >> (32 - c)); }

  void block(const uint8_t* p) {
    uint32_t M[16];
    std::memcpy(M, p, 64);
    uint32_t A = a0, B = b0, C = c0, D = d0;
    for (int i = 0; i < 64; ++i) {
      uint32_t F;
      int g;
      if (i < 16) {
        F = (B & C) | (~B & D);
        g = i;
      } else if (i < 32) {
        F = (D & B) | (~D & C);
        g = (5 * i + 1) & 15;
      } else if (i < 48) {
        F = B ^ C ^ D;
        g = (3 * i + 5) & 15;
      } else {
        F = C ^ (B | ~D);
        g = (7 * i) & 15;
      }
      F += A + K[i] + M[g];
      A = D;
      D = C;
      C = B;
      B += rotl(F, S[i]);
    }
    a0 += A;
    b0 += B;
    c0 += C;
    d0 += D;
  }

  // Digest prefix (first 4 bytes, little-endian) of one message.
  static uint32_t prefix32(const uint8_t* msg, uint64_t len) {
    MD5 m;
    uint64_t full = len / 64;
    for (uint64_t i = 0; i < full; ++i) m.block(msg + i * 64);
    uint8_t tail[128] = {0};
    uint64_t rem = len - full * 64;
    std::memcpy(tail, msg + full * 64, rem);
    tail[rem] = 0x80;
    uint64_t tail_len = (rem + 9 <= 64) ? 64 : 128;
    uint64_t bitlen = len * 8;
    std::memcpy(tail + tail_len - 8, &bitlen, 8);
    m.block(tail);
    if (tail_len == 128) m.block(tail + 64);
    return m.a0;  // little-endian word 0 == first 4 digest bytes LE
  }
};

constexpr uint32_t MD5::K[64];
constexpr int MD5::S[64];

// Fixed-width gather with software prefetch: the permutation is random
// over a working set far beyond cache, so each element load is a DRAM
// miss — prefetching the index stream ~16 ahead overlaps those misses
// (2-3x on the build's carve gather, which is this function's hot use).
template <typename T>
void take_fixed(const T* src, T* dst, const int64_t* idx, int64_t lo,
                int64_t hi) {
  constexpr int64_t kPrefetch = 16;
  int64_t i = lo;
  for (; i + kPrefetch < hi; ++i) {
    __builtin_prefetch(src + idx[i + kPrefetch], 0, 0);
    dst[i] = src[idx[i]];
  }
  for (; i < hi; ++i) dst[i] = src[idx[i]];
}

}  // namespace

extern "C" {

// out[i] = mix32(lo ^ (mix32(hi) * 0x9E3779B1)) — int64 lanes.
void hs_hash_i64(const int64_t* in, uint32_t* out, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      uint64_t v = static_cast<uint64_t>(in[i]);
      uint32_t l = static_cast<uint32_t>(v & 0xFFFFFFFFu);
      uint32_t h = static_cast<uint32_t>(v >> 32);
      out[i] = mix32(l ^ (mix32(h) * 0x9E3779B1u));
    }
  });
}

// out[i] = mix32(in[i]) — 32-bit lanes.
void hs_hash_i32(const int32_t* in, uint32_t* out, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      out[i] = mix32(static_cast<uint32_t>(in[i]));
  });
}

// MD5-prefix hash per string: bytes in [offsets[i], offsets[i+1]).
void hs_md5_prefix(const uint8_t* bytes, const int64_t* offsets, uint32_t* out,
                   int64_t n) {
  parallel_for(n, 1 << 10, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i)
      out[i] = MD5::prefix32(bytes + offsets[i],
                             static_cast<uint64_t>(offsets[i + 1] - offsets[i]));
  });
}

// dst[i, :] = src[idx[i], :] for row_bytes-wide rows (any dtype/2D shape).
void hs_take_rows(const uint8_t* src, uint8_t* dst, const int64_t* idx,
                  int64_t n_idx, int64_t row_bytes) {
  // The fixed-width fast paths reinterpret src/dst as wider lanes, which
  // is UB (and a SIGBUS on strict-alignment targets) unless both base
  // pointers are aligned to the lane width. Callers normally pass
  // allocator-aligned numpy buffers, but sliced/offset views can start
  // anywhere — route those through the memcpy loop.
  const bool aligned =
      row_bytes <= 1 ||
      (reinterpret_cast<uintptr_t>(src) % static_cast<uintptr_t>(row_bytes) == 0 &&
       reinterpret_cast<uintptr_t>(dst) % static_cast<uintptr_t>(row_bytes) == 0);
  parallel_for(n_idx, 1 << 14, [&](int64_t lo, int64_t hi) {
    switch (aligned ? row_bytes : int64_t{0}) {
      case 1:
        take_fixed(src, dst, idx, lo, hi);
        break;
      case 2:
        take_fixed(reinterpret_cast<const uint16_t*>(src),
                   reinterpret_cast<uint16_t*>(dst), idx, lo, hi);
        break;
      case 4:
        take_fixed(reinterpret_cast<const uint32_t*>(src),
                   reinterpret_cast<uint32_t*>(dst), idx, lo, hi);
        break;
      case 8:
        take_fixed(reinterpret_cast<const uint64_t*>(src),
                   reinterpret_cast<uint64_t*>(dst), idx, lo, hi);
        break;
      default:
        for (int64_t i = lo; i < hi; ++i)
          std::memcpy(dst + i * row_bytes, src + idx[i] * row_bytes, row_bytes);
    }
  });
}

// acc = mix32(acc * 31 + h) column combine, in place on acc.
void hs_combine(uint32_t* acc, const uint32_t* h, int64_t n) {
  parallel_for(n, 1 << 16, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) acc[i] = mix32(acc[i] * 31u + h[i]);
  });
}

// ---- bucket-grouped key sort ----------------------------------------------
// The host venue of the build's bucketize+sort, split in two so the Python
// side can PIPELINE each bucket's key sort with its parquet encode:
//
//   1. hs_bucket_perm: stable counting sort of row ids by bucket;
//   2. hs_sort_range: sort one bucket's slice of the permutation by the
//      order-preserving uint32 key lanes (original index as the final
//      tiebreak — deterministic, equal to the device path's stable
//      lexicographic order). lanes is [num_lanes, n] row-major.

void hs_bucket_perm(const int32_t* bucket, int64_t n, int64_t num_buckets,
                    int64_t* perm, int64_t* counts) {
  for (int64_t b = 0; b < num_buckets; ++b) counts[b] = 0;
  for (int64_t i = 0; i < n; ++i) ++counts[bucket[i]];
  std::vector<int64_t> cur(num_buckets, 0);
  for (int64_t b = 1; b < num_buckets; ++b) cur[b] = cur[b - 1] + counts[b - 1];
  for (int64_t i = 0; i < n; ++i) perm[cur[bucket[i]]++] = i;
}

void hs_sort_range(int64_t* perm, int64_t count, const uint32_t* lanes,
                   int64_t n, int64_t num_lanes) {
  if (num_lanes <= 2) {
    // Fast path (int32/int64/float keys = 1-2 lanes): pack into one u64
    // so the slice sorts contiguous 16-byte (key, idx) pairs instead of
    // gather-loading lanes in the comparator.
    std::vector<std::pair<uint64_t, int64_t>> buf(count);
    for (int64_t p = 0; p < count; ++p) {
      int64_t i = perm[p];
      uint64_t k = num_lanes ? (static_cast<uint64_t>(lanes[i]) << 32) : 0;
      if (num_lanes == 2) k |= lanes[n + i];
      buf[p] = {k, i};
    }
    std::sort(buf.begin(), buf.end());
    for (int64_t p = 0; p < count; ++p) perm[p] = buf[p].second;
    return;
  }
  std::sort(perm, perm + count, [&](int64_t a, int64_t c) {
    for (int64_t l = 0; l < num_lanes; ++l) {
      uint32_t x = lanes[l * n + a], y = lanes[l * n + c];
      if (x != y) return x < y;
    }
    return a < c;
  });
}

// ---- bucket-parallel sorted merge join ------------------------------------
// The host venue of the zero-exchange SMJ: both sides arrive as int32 key
// codes sorted within each bucket (the index file layout). On tunneled-TPU
// deployments device->host readback of the match pairs dominates the whole
// join; the pairs land on host either way, and the sorted runs are already
// host-resident, so an exact two-pass merge here beats the device round-trip
// whenever the link is slow (executor._join_venue decides by measured
// bandwidth).

// Pass 1: counts[b] = number of matches in bucket b.
void hs_mj_count(const int32_t* lk, const int64_t* lofs, const int32_t* rk,
                 const int64_t* rofs, int64_t nb, int64_t* counts) {
  parallel_for(nb, 1, [&](int64_t blo, int64_t bhi) {
    for (int64_t b = blo; b < bhi; ++b) {
      int64_t i = lofs[b], il = lofs[b + 1];
      int64_t j = rofs[b], jl = rofs[b + 1];
      int64_t c = 0;
      while (i < il && j < jl) {
        int32_t a = lk[i], v = rk[j];
        if (a < v) {
          ++i;
        } else if (a > v) {
          ++j;
        } else {
          int64_t i2 = i + 1;
          while (i2 < il && lk[i2] == a) ++i2;
          int64_t j2 = j + 1;
          while (j2 < jl && rk[j2] == a) ++j2;
          c += (i2 - i) * (j2 - j);
          i = i2;
          j = j2;
        }
      }
      counts[b] = c;
    }
  });
}

// Fused merge + accumulate (the host venue of Aggregate(Join)): instead
// of materializing match pairs, each equal-key run accumulates the
// secondary side's channel sums onto every primary row of the run, plus
// the per-primary-row match count. out is [a_r, n_l] row-major (indexed
// by SORTED primary position); counts is [n_l].
void hs_mj_accum(const int32_t* lk, const int64_t* lofs, const int32_t* rk,
                 const int64_t* rofs, int64_t nb, const double* rvals,
                 int64_t a_r, int64_t n_r, int64_t n_l, double* out,
                 double* counts) {
  parallel_for(nb, 1, [&](int64_t blo, int64_t bhi) {
    for (int64_t b = blo; b < bhi; ++b) {
      int64_t i = lofs[b], il = lofs[b + 1];
      int64_t j = rofs[b], jl = rofs[b + 1];
      while (i < il && j < jl) {
        int32_t a = lk[i], v = rk[j];
        if (a < v) {
          ++i;
        } else if (a > v) {
          ++j;
        } else {
          int64_t i2 = i + 1;
          while (i2 < il && lk[i2] == a) ++i2;
          int64_t j2 = j + 1;
          while (j2 < jl && rk[j2] == a) ++j2;
          double m = static_cast<double>(j2 - j);
          for (int64_t x = i; x < i2; ++x) counts[x] = m;
          for (int64_t c = 0; c < a_r; ++c) {
            double s = 0.0;
            const double* rv = rvals + c * n_r;
            for (int64_t y = j; y < j2; ++y) s += rv[y];
            double* ov = out + c * n_l;
            for (int64_t x = i; x < i2; ++x) ov[x] = s;
          }
          i = i2;
          j = j2;
        }
      }
    }
  });
}

// Pass 2: fill GLOBAL row indices; bucket b's matches occupy
// [oofs[b], oofs[b+1]) (oofs = prefix sum of pass-1 counts).
void hs_mj_fill(const int32_t* lk, const int64_t* lofs, const int32_t* rk,
                const int64_t* rofs, const int64_t* oofs, int64_t nb,
                int64_t* li, int64_t* ri) {
  parallel_for(nb, 1, [&](int64_t blo, int64_t bhi) {
    for (int64_t b = blo; b < bhi; ++b) {
      int64_t i = lofs[b], il = lofs[b + 1];
      int64_t j = rofs[b], jl = rofs[b + 1];
      int64_t o = oofs[b];
      while (i < il && j < jl) {
        int32_t a = lk[i], v = rk[j];
        if (a < v) {
          ++i;
        } else if (a > v) {
          ++j;
        } else {
          int64_t i2 = i + 1;
          while (i2 < il && lk[i2] == a) ++i2;
          int64_t j2 = j + 1;
          while (j2 < jl && rk[j2] == a) ++j2;
          for (int64_t x = i; x < i2; ++x)
            for (int64_t y = j; y < j2; ++y) {
              li[o] = x;
              ri[o] = y;
              ++o;
            }
          i = i2;
          j = j2;
        }
      }
    }
  });
}
}
