"""Framework exception types.

Reference parity: com/microsoft/hyperspace/HyperspaceException.scala:17-19 —
a single exception class carrying a message. The static-analysis subsystem
(analysis/) extends this with STRUCTURED diagnostics: plan validation
failures carry one `PlanDiagnostic` per finding, each naming the offending
plan node and its path from the plan root, so a malformed plan fails
before execution with provenance instead of an opaque mid-execution XLA
shape error.
"""

from __future__ import annotations

import dataclasses
import errno as _errno


class HyperspaceError(Exception):
    """Raised for any user-facing framework error."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.msg = msg


class IndexCorruptionError(HyperspaceError):
    """Index data on disk is unreadable: a truncated/garbage bucket file,
    a torn `_index_manifest.json`, or a missing file the log still
    references. Carries enough provenance for the query plane to mark the
    index unhealthy and re-plan against the source data instead of
    failing the query (graceful degradation, docs/fault_tolerance.md)."""

    def __init__(self, msg: str, index_root: str | None = None, path: str | None = None):
        super().__init__(msg)
        self.index_root = index_root
        self.path = path


class AdmissionRejected(HyperspaceError):
    """The serving layer refused to enqueue a query (docs/serving.md):
    the admission queue is at its configured max depth, or the server is
    draining/shut down. Deliberately raised at submit time — load
    shedding happens at the door, not after a query has consumed queue
    slots and worker time. Carries the observed depth for backpressure
    decisions (retry-after, client-side throttling)."""

    def __init__(self, msg: str, depth: int | None = None, max_depth: int | None = None):
        super().__init__(msg)
        self.depth = depth
        self.max_depth = max_depth


class QuotaExceeded(AdmissionRejected):
    """A tenant's token-bucket admission quota is exhausted
    (serve/fleet/quota.py): the submit was refused before it cost a
    queue slot, exactly like a depth rejection — but scoped to one
    tenant id, so a single noisy tenant cannot starve the rest of the
    fleet. Carries `retry_after_s`, the earliest time a token will be
    available again, for client-side backoff. Subclasses
    :class:`AdmissionRejected` so `QueryServer.submit`'s declared error
    contract covers it structurally."""

    def __init__(self, msg: str, tenant: str | None = None, retry_after_s: float | None = None):
        super().__init__(msg)
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class UnknownConfigKeyError(HyperspaceError):
    """A `hyperspace.*` config key was get/set that is not declared in
    `config.KNOWN_KEYS` — almost always a typo (`hyperspace.srve.workers`),
    which under the old accept-anything behavior silently configured
    nothing. Carries a did-you-mean `suggestion` when a declared key is
    close (edit distance); the static rule HSL010 catches the same drift
    before runtime. Declare new keys in `config.KNOWN_KEYS`."""

    def __init__(self, key: str, suggestion: str | None = None):
        msg = f"unknown config key {key!r}"
        if suggestion:
            msg += f" — did you mean {suggestion!r}?"
        msg += " (declared keys live in hyperspace_tpu.config.KNOWN_KEYS)"
        super().__init__(msg)
        self.key = key
        self.suggestion = suggestion


class QueryTimeout(HyperspaceError):
    """A served query exceeded its per-query timeout (docs/serving.md):
    either it expired while still waiting in the admission queue (the
    worker discards it unexecuted), or the caller's `result()` wait ran
    out while the query was still executing. `elapsed_s` is how long the
    query had been in the system when the timeout fired."""

    def __init__(self, msg: str, elapsed_s: float | None = None):
        super().__init__(msg)
        self.elapsed_s = elapsed_s


class WorkerCrashed(HyperspaceError):
    """A pooled-build worker process died without posting its result —
    a real ``kill -9``, an OOM kill, or an injected
    :class:`~hyperspace_tpu.faults.CrashPoint` unwinding out of the
    worker. Raised by the coordinator's bounded join
    (`parallel/procpool.py`) so a crashed worker aborts the build with a
    typed error instead of hanging the coordinator on a result queue
    that will never fill; `Action.run` then rolls the build back like
    any other op() failure."""

    def __init__(self, msg: str, task_id=None, exitcode: int | None = None):
        super().__init__(msg)
        self.task_id = task_id
        self.exitcode = exitcode


class WorkerFailed(HyperspaceError):
    """A pooled-build worker's task body raised: the worker posted the
    error (type, message, full traceback text) through the result queue
    and the coordinator re-raises it as this typed abort, preserving the
    worker-side traceback in the message. Distinct from
    :class:`WorkerCrashed`: the worker process stayed alive and reported
    its own failure."""

    def __init__(self, msg: str, task_id=None, error_type: str | None = None):
        super().__init__(msg)
        self.task_id = task_id
        self.error_type = error_type


class TransientIOError(OSError):
    """Marker for IO failures worth retrying (lease contention, flaky
    remote filesystems). Carries errno EIO so `is_retryable` classifies
    it without special-casing the type."""

    def __init__(self, msg: str):
        super().__init__(_errno.EIO, msg)


# errnos that signal a transient condition: the same call can succeed on
# retry without anything else changing. ENOENT/EEXIST/EACCES are excluded
# on purpose — they describe durable state, and retrying masks real bugs.
TRANSIENT_ERRNOS = frozenset(
    {
        _errno.EIO,
        _errno.EAGAIN,
        _errno.EBUSY,
        _errno.EINTR,
        _errno.ETIMEDOUT,
        _errno.ECONNRESET,
        _errno.ECONNABORTED,
        _errno.ESTALE,
    }
)


# The declared typed-error surface of every public entry point: the
# exception types (by class name, hierarchy-aware — an entry covers its
# subclasses) that MAY escape each API. The static rule HSL016
# (analysis/raises.py) verifies both directions on every push: any
# statically observed escape not covered here is contract drift, and a
# declared program-local type covering no observed escape is dead.
# docs/errors.md renders this table (python -m
# hyperspace_tpu.analysis.check --write-error-docs regenerates it).
#
# Reading guide: HyperspaceError covers the typed framework surface
# (plan validation, admission, timeouts, corruption); OSError covers
# real disk failures AND injected FaultError; CrashPoint is the
# simulated hard death that must NEVER be absorbed below these APIs;
# ValueError/KeyError/NotImplementedError are the programming-error
# surface (bad plans, undeclared counters, abstract hooks).
_QUERY_SURFACE = (
    "HyperspaceError", "OSError", "CrashPoint",
    "ValueError", "KeyError", "NotImplementedError",
)
ERROR_CONTRACTS: dict[str, tuple[str, ...]] = {
    "hyperspace_tpu.hyperspace.HyperspaceSession.run": _QUERY_SURFACE,
    "hyperspace_tpu.hyperspace.HyperspaceSession.run_query": _QUERY_SURFACE,
    # submit emits admission telemetry; the journal's seal path arms the
    # journal.seal fault point (HSL028 torn window), so a simulated hard
    # death there escapes untouched — and stats.increment's KeyError is
    # the declared-counter-registry programming-error surface.
    "hyperspace_tpu.serve.scheduler.QueryServer.submit": (
        "AdmissionRejected", "CrashPoint", "KeyError",
    ),
    "hyperspace_tpu.serve.scheduler.QueryHandle.result": (
        "QueryTimeout", "HyperspaceError", "OSError", "CrashPoint",
    ),
    "hyperspace_tpu.hyperspace.Hyperspace.create_index": _QUERY_SURFACE,
    "hyperspace_tpu.hyperspace.Hyperspace.refresh_index": _QUERY_SURFACE,
    "hyperspace_tpu.hyperspace.Hyperspace.optimize_index": _QUERY_SURFACE,
    "hyperspace_tpu.hyperspace.Hyperspace.vacuum_index": _QUERY_SURFACE,
    "hyperspace_tpu.hyperspace.Hyperspace.recover": _QUERY_SURFACE,
    # explain runs the same planner (and, mode="analyze", the executor)
    # as run(): it shares the full query surface, including lazy
    # recover-on-access fault points reachable from index listing.
    "hyperspace_tpu.hyperspace.Hyperspace.explain": _QUERY_SURFACE,
    "hyperspace_tpu.actions.base.Action.run": _QUERY_SURFACE,
    # Advisor plane (docs/advisor.md). recommend() replays observed plans
    # through the rules/validator (planner surface) and reads the index
    # log; sweep() additionally executes lifecycle actions — individual
    # apply failures are absorbed (recorded, sweep continues), but the
    # recommendation pass, CrashPoint, and policy programming errors
    # escape with the standard query surface.
    "hyperspace_tpu.advisor.whatif.WhatIfAnalyzer.recommend": _QUERY_SURFACE,
    # sweep absorbs per-apply Exceptions (recorded, the sweep continues),
    # so the typed framework surface does not statically escape it — what
    # remains is injected IO faults at advisor.* fault points, CrashPoint,
    # and the programming-error surface.
    "hyperspace_tpu.advisor.lifecycle.LifecyclePolicy.sweep": (
        "OSError", "CrashPoint", "ValueError", "KeyError", "NotImplementedError",
    ),
    # Self-driving operations controller (serve/controller.py). One
    # reconciliation step actuates through the SAME facade methods an
    # operator would call (recover/refresh/lifecycle), so it shares the
    # full query surface: at runtime `_actuate` absorbs per-mutation
    # Exceptions (recorded as controller.actuation_failed, the step
    # continues), but the declared surface stays the honest upper bound
    # on what the actuator lambdas can raise — plus the injected
    # IO-fault surface at the controller.actuate fault point and
    # CrashPoint (a dying process does not keep reconciling).
    "hyperspace_tpu.serve.controller.OpsController.step": _QUERY_SURFACE,
    # Fleet plane (docs/serving.md "fleet topology"). The shared caches
    # are advisory by contract — IO failures are counted and answered
    # with a miss — so what escapes is the injected hard-death surface
    # (CrashPoint via the fleet.* fault points) plus, for the plan
    # cache, the planner surface its cold path runs. Tenant quota
    # admission is exactly one typed rejection. SingleFlight.run's own
    # protocol raises nothing — whatever the caller's build() raises
    # passes through it (the scheduler's contracts cover those).
    # (KeyError is the declared-registry surface: stats.increment raises
    # it for an undeclared counter name — a programming error.)
    # Rejections emit telemetry, so the journal.seal crash surface (and
    # the counter-registry KeyError) rides along with the typed verdict.
    "hyperspace_tpu.serve.fleet.quota.TenantQuotas.admit": (
        "QuotaExceeded", "CrashPoint", "KeyError",
    ),
    "hyperspace_tpu.serve.fleet.singleflight.SingleFlight.run": (
        "OSError", "CrashPoint", "KeyError",
    ),
    "hyperspace_tpu.serve.fleet.shared_cache.SharedResultCache.get": (
        "OSError", "CrashPoint", "KeyError",
    ),
    "hyperspace_tpu.serve.fleet.shared_cache.SharedResultCache.put": (
        "OSError", "CrashPoint", "KeyError",
    ),
    "hyperspace_tpu.serve.fleet.shared_cache.SharedPlanCache.get_or_optimize": _QUERY_SURFACE,
    # Scale-out build worker entry points (docs/architecture.md
    # "scale-out build"). These module-level functions ARE process entry
    # points — parallel/procpool.py runs them in spawned workers and the
    # coordinator's typed abort (WorkerFailed/WorkerCrashed) relies on
    # their surface: framework errors and injected IO faults post back
    # through the result queue; CrashPoint deliberately kills the worker
    # (the coordinator's liveness check converts that into WorkerCrashed).
    "hyperspace_tpu.execution.build_exchange.p1_shard": _QUERY_SURFACE,
    "hyperspace_tpu.execution.build_exchange.p2_owner": _QUERY_SURFACE,
    # Continuous-ingestion daemon (hyperspace_tpu/ingest/,
    # docs/ingestion.md). The writer commits through the SAME facade
    # methods an operator would call (refresh/optimize), so it shares
    # the full query surface. One daemon tick absorbs per-index
    # Exceptions (recorded as ingest.commit_failures /
    # ingest.compact_failures, the loop keeps polling the other
    # watches) — what escapes tick() is injected IO faults at the
    # ingest.* fault points, CrashPoint (a dying daemon does not keep
    # committing), and the programming-error surface. The CDC tailer's
    # poll is a contract of its own: the crash window between a batch
    # file landing and the cursor persisting (the ingest.tail fault
    # point) unwinds through it, and the deterministic batch naming is
    # what makes the retry idempotent. `_service_entry` is the
    # processWorker-mode spawn target (procdomain SPAWN_ENTRY_POINTS):
    # its setup (session rebuild, config replay, watch registration)
    # runs before the absorbing loop, so the full surface applies.
    "hyperspace_tpu.ingest.daemon.IngestDaemon.tick": (
        "OSError", "CrashPoint", "ValueError", "KeyError", "NotImplementedError",
    ),
    "hyperspace_tpu.ingest.tailer.CdcTailer.poll": (
        "OSError", "CrashPoint", "ValueError", "KeyError",
    ),
    "hyperspace_tpu.ingest.daemon._service_entry": _QUERY_SURFACE,
    "hyperspace_tpu.ingest.writer.commit_micro_batch": _QUERY_SURFACE,
    "hyperspace_tpu.ingest.writer.maybe_compact": _QUERY_SURFACE,
}


def is_retryable(exc: BaseException) -> bool:
    """Retryable-exception classification for utils/retry.py: transient
    OS-level IO failures retry; everything else (corruption, missing
    files, programming errors) surfaces immediately."""
    if isinstance(exc, TimeoutError):
        return True
    if isinstance(exc, OSError):
        return exc.errno in TRANSIENT_ERRNOS
    return False


@dataclasses.dataclass(frozen=True)
class PlanDiagnostic:
    """One validator finding, anchored to a plan node.

    `path` is the node's provenance from the plan root — child edges
    joined with "/", e.g. "Join.left/Filter" — so a diagnostic names
    WHERE in the plan tree the problem sits, not just what it is.
    `severity` is "error" (the plan cannot execute correctly) or
    "warning" (legal but almost certainly a mistake or a perf hazard,
    e.g. two index scans bucketed on the join keys with mismatched
    bucket counts, which silently falls off the zero-exchange path).
    """

    rule: str  # e.g. "unresolved-column", "join-bucket-mismatch"
    node: str  # plan node type name, e.g. "Filter"
    path: str  # provenance path from the plan root
    message: str
    severity: str = "error"  # "error" | "warning"

    def __str__(self) -> str:
        return f"[{self.rule}] {self.path or self.node}: {self.message}"


class PlanValidationError(HyperspaceError):
    """A plan failed pre-execution validation (analysis/validator.py).

    Carries the full diagnostic list; the message renders every finding
    with its rule id and node path.
    """

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        lines = "\n".join(f"  {d}" for d in self.diagnostics)
        super().__init__(f"plan validation failed:\n{lines}")


class PlanRewriteError(PlanValidationError):
    """An optimizer rewrite (pushdown / column pruning) produced a plan
    that is not equivalent to the original — wrong output schema, a
    reference to a pruned-away column, or a filter pushed beneath the
    null-extended side of an outer join."""
