"""Framework exception type.

Reference parity: com/microsoft/hyperspace/HyperspaceException.scala:17-19 —
a single exception class carrying a message.
"""


class HyperspaceError(Exception):
    """Raised for any user-facing framework error."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.msg = msg
