"""Framework exception types.

Reference parity: com/microsoft/hyperspace/HyperspaceException.scala:17-19 —
a single exception class carrying a message. The static-analysis subsystem
(analysis/) extends this with STRUCTURED diagnostics: plan validation
failures carry one `PlanDiagnostic` per finding, each naming the offending
plan node and its path from the plan root, so a malformed plan fails
before execution with provenance instead of an opaque mid-execution XLA
shape error.
"""

from __future__ import annotations

import dataclasses


class HyperspaceError(Exception):
    """Raised for any user-facing framework error."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.msg = msg


@dataclasses.dataclass(frozen=True)
class PlanDiagnostic:
    """One validator finding, anchored to a plan node.

    `path` is the node's provenance from the plan root — child edges
    joined with "/", e.g. "Join.left/Filter" — so a diagnostic names
    WHERE in the plan tree the problem sits, not just what it is.
    `severity` is "error" (the plan cannot execute correctly) or
    "warning" (legal but almost certainly a mistake or a perf hazard,
    e.g. two index scans bucketed on the join keys with mismatched
    bucket counts, which silently falls off the zero-exchange path).
    """

    rule: str  # e.g. "unresolved-column", "join-bucket-mismatch"
    node: str  # plan node type name, e.g. "Filter"
    path: str  # provenance path from the plan root
    message: str
    severity: str = "error"  # "error" | "warning"

    def __str__(self) -> str:
        return f"[{self.rule}] {self.path or self.node}: {self.message}"


class PlanValidationError(HyperspaceError):
    """A plan failed pre-execution validation (analysis/validator.py).

    Carries the full diagnostic list; the message renders every finding
    with its rule id and node path.
    """

    def __init__(self, diagnostics):
        self.diagnostics = list(diagnostics)
        lines = "\n".join(f"  {d}" for d in self.diagnostics)
        super().__init__(f"plan validation failed:\n{lines}")


class PlanRewriteError(PlanValidationError):
    """An optimizer rewrite (pushdown / column pruning) produced a plan
    that is not equivalent to the original — wrong output schema, a
    reference to a pruned-away column, or a filter pushed beneath the
    null-extended side of an outer join."""
