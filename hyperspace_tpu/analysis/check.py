"""Unified static-analysis driver: lint + whole-program rules + baseline.

    python -m hyperspace_tpu.analysis.check [paths...] [--format json]

One command runs everything the analysis subsystem knows how to check,
parsing every file exactly ONCE and feeding the same AST to the
per-file linter (HSL001-HSL008, analysis/lint.py) and the whole-program
engine (analysis/program.py → callgraph.py → locks.py):

- **HSL009 lock-order inversion** — the static lock-acquisition graph
  (lock held → locks reachable through the call graph inside the
  ``with`` body) must be cycle-free; findings carry a two-chain witness.
- **HSL010 config-key drift** — every ``hyperspace.*`` key that is
  get/set (or declared as a module constant) must be declared in
  ``config.KNOWN_KEYS`` (typo suggestions via edit distance); declared
  keys never read anywhere are dead and reported; the generated key
  table in docs/configuration.md must match the registry
  (``--write-config-docs`` regenerates it).
- **HSL011 resource/exception safety** — locks/spans/files acquired
  outside ``with``/``try-finally`` on a path that can raise.
- **HSL012 fault-point coverage** — ``faults.KNOWN_POINTS`` and the
  ``fault_point()``/``inject()`` call sites must agree in both
  directions.
- **HSL013 lockset data race** — shared state accessed under
  inconsistent locksets with a write in play, over the effect
  summaries (analysis/effects.py → races.py); two-path witness.
- **HSL014 atomicity violation** — torn check-then-act across released
  and re-acquired locks (memo-fill and re-check idioms exempt).
- **HSL015 jit-cache hygiene** — jit call sites manufacturing a fresh
  cache key per call (recompile storm / executable leak).
- **HSL016 error-contract drift** — every public entry point's
  statically observed escape set (analysis/raises.py) must be covered
  by its declared ``exceptions.ERROR_CONTRACTS`` entry, modulo the
  exception hierarchy; dead entries/types are findings and the
  generated docs/errors.md table is verified (``--write-error-docs``).
- **HSL017 swallowed crash/fault** — except clauses absorbing
  CrashPoint/FaultError/everything without re-raise or signal, and the
  retry-classification bypass.
- **HSL018 unwind safety** — every ``faults.KNOWN_POINTS`` entry must
  have a static propagation path to a recovery construct (witness
  chains land in the report's ``unwind_proof``), and ``+= 1``/``-= 1``
  pairs on shared state must be finally-balanced on raising paths.
- **HSL019-022 process domains** (analysis/procdomain.py) — the
  multi-process invariants over the inferred spawn domain
  (``SPAWN_ENTRY_POINTS``): spawn-import purity (no module a worker
  imports at start may import jax at module level), exchange-surface
  typing (only picklable plain data crosses TaskPool/ProcessHost/fleet
  boundaries), the shared-file protocol (atomic publish + TTL-reaped
  O_EXCL leases on exchange/fleet paths), and cross-boundary
  fault/telemetry continuity. The inferred domain graph lands in the
  report's ``process_domains``.
- **HSL023-026 trace domains** (analysis/tracedomain.py) — the
  device-plane invariants over the inferred trace domain (the closure
  of every function object handed to ``compat.jit``, ``shard_map``, or
  a Pallas ``pallas_call``): traced-effect purity (no host effect
  anywhere in a traced closure), signature-space boundedness (jit keys,
  static arguments and pad widths derive from declared bounded domains
  — ``compat.KNOWN_STATIC_DOMAINS``), donation/aliasing safety
  (zero-copy staged views are never mutated or donated; callers go
  through ``ColumnTable.own_arrays``), and kernel fallback-ladder
  completeness (``ops.KNOWN_KERNELS``: every Pallas engagement proves
  an exactness gate, a permanent per-shape fallback and its
  ``device.kernel.*`` counters). The inferred trace graph, donation
  proof and per-kernel ladder proofs land in the report's
  ``trace_domains``.
- **HSL027-030 durability domains** (analysis/duradomain.py) — the
  crash-consistency invariants over the inferred durability domain (the
  call-graph closure writing under a declared ``DURABLE_ROOTS`` plane):
  atomic-publish completeness (every durable write reaches the
  mkstemp + fsync + ``os.replace`` idiom, generalizing HSL021 beyond
  lease/fleet paths — sites HSL027 claims are deduplicated out of
  HSL021), torn-window ordering (every ``TORN_WINDOWS`` exactly-once
  protocol statically orders its two writes AND arms a
  ``faults.KNOWN_POINTS`` entry inside the window, so the crash sweeps
  provably exercise each torn state), replay idempotence (durable file
  names reachable from ``REPLAY_ROOTS`` recovery/re-poll/takeover
  paths derive from cursor/log-id/generation values, never wall clock,
  pid or RNG), and snapshot-stamp discipline (pinned-snapshot contexts
  never read the live version vector). The inferred durability graph —
  roots, write sites, window proofs with their in-window fault-point
  witnesses, replay closures — lands in the report's
  ``durable_domains``.
- **Validator corpus** — a small set of known-good / known-bad logical
  plans is pushed through the plan validator (analysis/validator.py) as
  a self-test; skipped (with a note) when numpy isn't installed, so the
  dependency-free CI lint job still runs everything else.

Default paths: the package itself plus ``benchmarks/``, ``bench.py``
and ``tests/conftest.py`` (the satellite surfaces that feed CI), with a
narrow, justified allowlist for findings that are correct-but-benign in
single-threaded benchmark code (:data:`TEST_ALLOWLIST`).

**Baseline.** CI fails only on findings not present in the committed
``ANALYSIS_BASELINE.json`` (``--write-baseline`` refreshes it), so a
newly added rule with pre-existing findings can land without blocking
every unrelated PR, while any NEW finding fails immediately.

``--format sarif`` renders the same findings as SARIF 2.1.0 (the CI
code-scanning artifact); ``--changed`` restricts *reporting* to files
changed vs origin/main while the engine still indexes the whole program
(the fast local pre-push mode). Exit codes are format-independent:
0 = clean (no new findings), 1 = new findings, 2 = the analyzer itself
crashed.
"""

from __future__ import annotations

import argparse
import ast
import json
import pathlib
import sys

from hyperspace_tpu.analysis import lint as lint_mod
from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.effects import Effects
from hyperspace_tpu.analysis.lint import (
    EXIT_CLEAN,
    EXIT_FINDINGS,
    EXIT_INTERNAL_ERROR,
    Finding,
    RULES,
)
from hyperspace_tpu.analysis.duradomain import DurabilityDomains
from hyperspace_tpu.analysis.locks import LockGraph, resource_findings
from hyperspace_tpu.analysis.procdomain import ProcessDomains
from hyperspace_tpu.analysis.tracedomain import TraceDomains
from hyperspace_tpu.analysis.program import Program, _index_module, _module_name
from hyperspace_tpu.analysis.races import (
    atomicity_findings,
    jit_hygiene_findings,
    lockset_race_findings,
)
from hyperspace_tpu.analysis.raises import (
    DYNAMIC,
    Raises,
    declared_contracts,
    error_contract_findings,
    swallowed_findings,
    unwind_findings,
)

CONFIG_DRIFT = "HSL010"
FAULT_COVERAGE = "HSL012"
CONTRACT_DRIFT = "HSL016"

BASELINE_NAME = "ANALYSIS_BASELINE.json"
DOCS_BEGIN = "<!-- KNOWN_KEYS:begin (generated from config.KNOWN_KEYS — edit config.py, then run python -m hyperspace_tpu.analysis.check --write-config-docs) -->"
DOCS_END = "<!-- KNOWN_KEYS:end -->"
ERRORS_BEGIN = "<!-- ERROR_CONTRACTS:begin (generated from exceptions.ERROR_CONTRACTS + the HSL016 escape analysis — edit exceptions.py, then run python -m hyperspace_tpu.analysis.check --write-error-docs) -->"
ERRORS_END = "<!-- ERROR_CONTRACTS:end -->"

# (path suffix, rule) -> justification. The narrow test-only allowlist:
# entries must name code that is single-threaded by construction or
# otherwise exempt BY DESIGN — anything else gets fixed, not listed.
TEST_ALLOWLIST: dict[tuple[str, str], str] = {
    # TPC-DS datagen memoizes generated sales tables in a module dict.
    # Benchmarks are one process, one thread, by construction (the
    # harness forks fresh processes per scale) — the HSL008 race cannot
    # occur, and locking the datagen would suggest it is serve-safe when
    # it is not meant to be.
    ("benchmarks/tpcds.py", "HSL008"): "single-threaded benchmark datagen memo",
    # The load-harness client threads collect every error (BaseException
    # included — a CrashPoint must fail the bench) into a list the main
    # thread re-raises after join(); nothing is swallowed, the re-raise
    # just lives outside the handler.
    ("benchmarks/bench_serve.py", "HSL017"): "client threads store errors; main re-raises after join",
}


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parent.parent.parent


def default_paths(root: pathlib.Path) -> list[pathlib.Path]:
    out = []
    for rel in ("hyperspace_tpu", "benchmarks", "bench.py", "tests/conftest.py"):
        p = root / rel
        if p.exists():
            out.append(p)
    return out


# -- shared-parse loading -----------------------------------------------------

def load_sources(paths: list[pathlib.Path]) -> tuple[list, list[Finding]]:
    """Parse every .py under `paths` once. Returns ([(name, path, source,
    tree)], findings-for-unparseable-files)."""
    sources, findings = [], []
    for p in paths:
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            try:
                src = f.read_text()
            except OSError as e:
                findings.append(Finding(str(f), 0, 0, "HSL000", f"unreadable: {e}"))
                continue
            try:
                tree = ast.parse(src, filename=str(f))
            except SyntaxError as e:
                findings.append(Finding(str(f), e.lineno or 0, e.offset or 0,
                                        "HSL000", f"syntax error: {e.msg}"))
                continue
            sources.append((_module_name(f), str(f), src, tree))
    return sources, findings


def build_program(sources: list) -> Program:
    modules = {name: _index_module(name, path, src, tree)
               for name, path, src, tree in sources}
    return Program(modules)


# -- HSL010: config-key drift -------------------------------------------------

def config_key_findings(program: Program, usage_dirs: list[pathlib.Path]) -> list[Finding]:
    from hyperspace_tpu import config as config_mod

    declared = set(config_mod.KNOWN_KEYS)
    findings: list[Finding] = []
    config_module_names = {m.name for m in program.modules.values()
                           if m.path.endswith("hyperspace_tpu/config.py")}
    used: set[str] = set()
    # get/set call sites
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules[fn.module]
        for acc in fn.config_accesses:
            used.add(acc.key)
            if acc.key in declared:
                continue
            if _suppressed(mod, acc.line, CONFIG_DRIFT):
                continue
            import difflib

            close = difflib.get_close_matches(acc.key, declared, n=1, cutoff=0.6)
            hint = f" — did you mean {close[0]!r}?" if close else ""
            findings.append(Finding(
                mod.path, acc.line, 0, CONFIG_DRIFT,
                f"config {'set' if acc.write else 'get'} of undeclared key "
                f"{acc.key!r}{hint} (declare it in config.KNOWN_KEYS; the "
                f"runtime rejects it too)",
            ))
    # hyperspace.* constants declared outside config.py
    for mod in program.modules.values():
        if mod.name in config_module_names:
            continue
        for name, val in sorted(mod.const_strings.items()):
            if val.startswith("hyperspace.") and val not in declared:
                findings.append(Finding(
                    mod.path, 0, 0, CONFIG_DRIFT,
                    f"module constant {name} declares key {val!r} outside "
                    f"config.KNOWN_KEYS — every hyperspace.* key lives in the "
                    f"one registry (move the declaration to config.py)",
                ))
    # dead keys: declared in KNOWN_KEYS but consumed by NOTHING — not
    # wired into the conf get/set dispatch, never get/set by key, never
    # referenced by constant name in another module, and never spelled
    # literally in the usage scan (tests). The registry-only key is the
    # drift this catches: documented, settable, and ignored. Only
    # meaningful when config.py itself is in the scanned set (a corpus
    # file scanned alone must not report the whole registry dead).
    if not config_module_names:
        return findings
    const_of_key = {}
    wired: set[str] = set()
    for mname in config_module_names:
        mod = program.modules[mname]
        for cname, val in mod.const_strings.items():
            const_of_key[val] = cname
        wired |= {const for const in _dispatch_references(mod.tree)}
    other_sources = [m.source for m in program.modules.values()
                     if m.name not in config_module_names]
    for d in usage_dirs:
        for f in sorted(d.rglob("*.py")) if d.is_dir() else [d]:
            try:
                other_sources.append(f.read_text())
            except OSError:
                continue
    config_paths = [program.modules[m].path for m in config_module_names]
    for key in sorted(declared - used):
        cname = const_of_key.get(key)
        if cname is not None and cname in wired:
            continue
        if any(
            (cname is not None and cname in src) or key in src
            for src in other_sources
        ):
            continue
        findings.append(Finding(
            config_paths[0] if config_paths else "hyperspace_tpu/config.py", 0, 0,
            CONFIG_DRIFT,
            f"declared key {key!r} is dead: not wired into the conf get/set "
            f"dispatch and never referenced anywhere — wire it up or delete "
            f"it from KNOWN_KEYS",
        ))
    return findings


def _dispatch_references(config_tree: ast.Module) -> set[str]:
    """Constant names config.py references OUTSIDE their own definition
    and the KNOWN_KEYS literal — i.e. names the get/set dispatch (or any
    other real code) actually consumes."""
    skip_ids: set[int] = set()
    for node in ast.walk(config_tree):
        if isinstance(node, ast.Assign):
            is_const_def = any(
                isinstance(t, ast.Name) and t.id.isupper() for t in node.targets
            )
            is_registry = any(
                isinstance(t, ast.Name) and t.id == "KNOWN_KEYS" for t in node.targets
            )
            if is_registry:
                for sub in ast.walk(node.value):
                    skip_ids.add(id(sub))
            elif is_const_def and isinstance(node.value, ast.Constant):
                for t in node.targets:
                    skip_ids.add(id(t))
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name) and node.target.id == "KNOWN_KEYS":
                for sub in ast.walk(node.value):
                    skip_ids.add(id(sub))
    return {
        node.id
        for node in ast.walk(config_tree)
        if isinstance(node, ast.Name)
        and isinstance(node.ctx, ast.Load)
        and node.id.isupper()
        and id(node) not in skip_ids
    }


def docs_findings(root: pathlib.Path) -> list[Finding]:
    """The generated key table in docs/configuration.md must match
    config.KNOWN_KEYS exactly."""
    from hyperspace_tpu import config as config_mod

    doc = root / "docs" / "configuration.md"
    if not doc.exists():
        return []
    text = doc.read_text()
    if DOCS_BEGIN not in text or DOCS_END not in text:
        return [Finding(str(doc), 0, 0, CONFIG_DRIFT,
                        "docs/configuration.md has no KNOWN_KEYS generated-table "
                        "markers — run python -m hyperspace_tpu.analysis.check "
                        "--write-config-docs")]
    current = text.split(DOCS_BEGIN, 1)[1].split(DOCS_END, 1)[0].strip()
    if current != config_mod.docs_table().strip():
        return [Finding(str(doc), 0, 0, CONFIG_DRIFT,
                        "docs/configuration.md key table is stale relative to "
                        "config.KNOWN_KEYS — run python -m "
                        "hyperspace_tpu.analysis.check --write-config-docs")]
    return []


def write_config_docs(root: pathlib.Path) -> bool:
    from hyperspace_tpu import config as config_mod

    doc = root / "docs" / "configuration.md"
    text = doc.read_text()
    if DOCS_BEGIN not in text or DOCS_END not in text:
        return False
    head, rest = text.split(DOCS_BEGIN, 1)
    _, tail = rest.split(DOCS_END, 1)
    doc.write_text(f"{head}{DOCS_BEGIN}\n{config_mod.docs_table()}\n{DOCS_END}{tail}")
    return True


# -- HSL016: docs/errors.md error-contract table ------------------------------

def errors_table(program, raises_obj: Raises, contracts: dict) -> str:
    """The generated contract table: one row per entry point, declared
    surface next to the statically observed escape set (``(dynamic)``
    marks re-raises of stored/registered exception objects the static
    analysis cannot type)."""
    lines = [
        "| entry point | declared contract | statically observed escapes |",
        "|---|---|---|",
    ]
    for qname in sorted(contracts):
        types, _, _ = contracts[qname]
        esc = raises_obj.escapes.get(qname, {})
        observed = sorted(t for t in esc if t != DYNAMIC)
        if DYNAMIC in esc:
            observed.append("(dynamic)")
        lines.append(
            f"| `{qname}` | {', '.join(f'`{t}`' for t in types) or '—'} "
            f"| {', '.join(f'`{t}`' for t in observed) or '—'} |"
        )
    return "\n".join(lines)


_ERRORS_DOC_SKELETON = """# Error contracts

The typed error surface of every public entry point, declared in
`exceptions.ERROR_CONTRACTS` and statically verified on every push by
rule HSL016 (see docs/static_analysis.md): any exception type that can
escape an entry point without being covered by its declared contract —
modulo the exception hierarchy — fails the build, and so does a declared
program-local type that covers nothing. The table below is generated;
edit `exceptions.py`, then run
`python -m hyperspace_tpu.analysis.check --write-error-docs`.

{begin}
{table}
{end}

An entry covers its subclasses: `HyperspaceError` covers
`IndexCorruptionError`, `PlanValidationError`, `AdmissionRejected`,
`QueryTimeout`; `OSError` covers real disk failures and the injected
`FaultError`. `CrashPoint` (a `BaseException`) is the simulated hard
process death — it appears in the contracts because it must escape
these APIs untouched (docs/fault_tolerance.md). `(dynamic)` marks a
re-raise of a stored exception object the static analysis cannot type.
"""


def errors_docs_findings(root: pathlib.Path, program, raises_obj: Raises,
                         contracts: dict) -> list[Finding]:
    """docs/errors.md must exist and its generated table must match the
    registry + analysis exactly (the HSL010 config-docs pattern)."""
    if not any(q.startswith("hyperspace_tpu.") for q in contracts):
        return []  # scanning a corpus subset, not the package
    doc = root / "docs" / "errors.md"
    stale = Finding(str(doc), 0, 0, CONTRACT_DRIFT,
                    "docs/errors.md error-contract table is missing or stale "
                    "relative to exceptions.ERROR_CONTRACTS — run python -m "
                    "hyperspace_tpu.analysis.check --write-error-docs")
    if not doc.exists():
        return [stale]
    text = doc.read_text()
    if ERRORS_BEGIN not in text or ERRORS_END not in text:
        return [stale]
    current = text.split(ERRORS_BEGIN, 1)[1].split(ERRORS_END, 1)[0].strip()
    if current != errors_table(program, raises_obj, contracts).strip():
        return [stale]
    return []


def write_error_docs(root: pathlib.Path, program, raises_obj: Raises,
                     contracts: dict) -> bool:
    doc = root / "docs" / "errors.md"
    table = errors_table(program, raises_obj, contracts)
    if not doc.exists() or ERRORS_BEGIN not in doc.read_text():
        doc.write_text(_ERRORS_DOC_SKELETON.format(
            begin=ERRORS_BEGIN, table=table, end=ERRORS_END,
        ))
        return True
    text = doc.read_text()
    head, rest = text.split(ERRORS_BEGIN, 1)
    _, tail = rest.split(ERRORS_END, 1)
    doc.write_text(f"{head}{ERRORS_BEGIN}\n{table}\n{ERRORS_END}{tail}")
    return True


# -- dead-symbol report (informational) ---------------------------------------

def dead_symbol_report(program, callgraph, raises_obj: Raises, contracts: dict) -> dict:
    """Functions unreachable from any public entry point through the
    dispatch-augmented call graph. Informational ONLY — the resolver is
    deliberately under-approximate (dynamic dispatch through untyped
    locals, higher-order uses), so a listing here is a lead for a human,
    never a finding."""
    roots = {
        q for q, fn in program.functions.items() if not fn.name.startswith("_")
    }
    roots |= {q for q in contracts if q in program.functions}
    adj: dict[str, set[str]] = {}
    for e in callgraph.edges:
        slot = adj.setdefault(e.caller, set())
        for t in raises_obj.dispatch_targets(e.callee):
            slot.add(t)
    reach = set(roots)
    stack = list(roots)
    while stack:
        q = stack.pop()
        for nxt in adj.get(q, ()):
            if nxt not in reach:
                reach.add(nxt)
                stack.append(nxt)
    dead = sorted(
        q for q, fn in program.functions.items()
        if q not in reach and not fn.name.startswith("__")
    )
    return {"count": len(dead), "functions": dead}


# -- HSL012: fault-point coverage ---------------------------------------------

def fault_point_findings(program: Program) -> list[Finding]:
    from hyperspace_tpu import faults as faults_mod

    declared = set(faults_mod.KNOWN_POINTS)
    findings: list[Finding] = []
    threaded: set[str] = set()
    faults_path = None
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules[fn.module]
        if mod.path.endswith("hyperspace_tpu/faults.py"):
            faults_path = mod.path
            continue  # the harness's own docstrings/validation, not call sites
        for name, line, kind in fn.fault_refs:
            if kind == "point" and fn.module.startswith("hyperspace_tpu."):
                threaded.add(name)
            if name not in declared and not _suppressed(mod, line, FAULT_COVERAGE):
                findings.append(Finding(
                    mod.path, line, 0, FAULT_COVERAGE,
                    f"fault point {name!r} is not declared in "
                    f"faults.KNOWN_POINTS — an undeclared name can never fire "
                    f"a registered rule (fix the typo or declare it)",
                ))
    for mod in program.modules.values():
        if mod.path.endswith("hyperspace_tpu/faults.py"):
            faults_path = mod.path
    if not any(m.startswith("hyperspace_tpu.") for m in program.modules):
        # Coverage direction needs the package in the scanned set; a
        # corpus file scanned alone must not report every point missing.
        return findings
    for point in sorted(declared - threaded):
        findings.append(Finding(
            faults_path or "hyperspace_tpu/faults.py", 0, 0, FAULT_COVERAGE,
            f"declared fault point {point!r} is never threaded through a "
            f"fault_point() call site — the crash sweep cannot exercise it; "
            f"thread it or remove it from KNOWN_POINTS",
        ))
    return findings


def _suppressed(mod, line: int, rule: str) -> bool:
    lines = mod.lines
    text = lines[line - 1] if 0 < line <= len(lines) else ""
    if "# noqa" not in text:
        return False
    tail = text.split("# noqa", 1)[1]
    return not tail.strip().startswith(":") or rule in tail


# -- validator corpus ---------------------------------------------------------

def validator_corpus() -> dict:
    """Self-test the plan validator over a tiny known-good/known-bad
    corpus. Returns a JSON-able status dict; `failures` non-empty means
    the validator regressed."""
    try:
        from hyperspace_tpu.analysis.validator import validate_plan
        from hyperspace_tpu.plan.expr import col
        from hyperspace_tpu.plan.nodes import Filter, Join, Scan, Sort
        from hyperspace_tpu.schema import Field, Schema
    except ImportError as e:
        return {"status": "skipped", "reason": f"dependencies unavailable: {e}"}
    schema = Schema.of(Field("k", "int32"), Field("v", "float64"),
                       Field("emb", "vector", dim=4))
    right = Scan("/corpus/u", "parquet", Schema.of(Field("k", "int32")))
    base = Scan("/corpus/t", "parquet", schema)
    corpus = [
        ("clean-filter", Filter(base, col("k") > 1), []),
        ("unresolved-column", Filter(base, col("zz") > 1), ["unresolved-column"]),
        ("dtype-predicate", Filter(base, col("emb") > 1), ["dtype-incompatible-predicate"]),
        ("unsortable-key", Sort(base, [("emb", True)]), ["unsortable-key"]),
        ("bucket-mismatch",
         Join(Scan("/corpus/t", "parquet", schema, bucket_spec=(8, ["k"])),
              Scan("/corpus/u", "parquet", Schema.of(Field("k", "int32")),
                   bucket_spec=(16, ["k"])),
              ["k"], ["k"]),
         ["join-bucket-mismatch"]),
        ("clean-join", Join(base, right, ["k"], ["k"]), []),
    ]
    failures = []
    for name, plan, expect in corpus:
        got = [d.rule for d in validate_plan(plan)]
        if got != expect:
            failures.append({"case": name, "expected": expect, "got": got})
    return {"status": "ok" if not failures else "failed",
            "cases": len(corpus), "failures": failures}


# -- SARIF --------------------------------------------------------------------

def to_sarif(findings: list[Finding], baseline: set[tuple], root: pathlib.Path) -> dict:
    """SARIF 2.1.0 form of the findings — the code-scanning artifact CI
    uploads next to the JSON report. Baseline-known findings carry
    ``baselineState: unchanged`` so scanners triage only what's new."""
    rules = [
        {
            "id": r.rule,
            "name": r.slug,
            "shortDescription": {"text": r.summary},
            "properties": {"scope": r.scope},
        }
        for r in sorted(RULES.values(), key=lambda r: r.rule)
    ]
    results = []
    for f in findings:
        path = _finding_key(f, root)[1]
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": path},
                    "region": {"startLine": max(1, f.line)},
                },
            }],
            "baselineState": (
                "unchanged" if tuple(_finding_key(f, root)) in baseline else "new"
            ),
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "hyperspace-analysis",
                    "informationUri": "docs/static_analysis.md",
                    "rules": rules,
                },
            },
            "results": results,
        }],
    }


# -- --changed: restrict findings to files changed vs origin/main -------------

def changed_files(root: pathlib.Path) -> tuple[str, set[str]] | None:
    """(base ref, changed .py paths relative to root) from git, trying
    ``origin/main`` then ``main`` then ``HEAD``; None when git (or the
    repo) is unavailable — the caller falls back to a full run."""
    import subprocess

    for base in ("origin/main", "main", "HEAD"):
        try:
            proc = subprocess.run(
                ["git", "diff", "--name-only", base, "--", "*.py"],
                cwd=root, capture_output=True, text=True, timeout=30,
            )
        except (OSError, subprocess.SubprocessError):
            return None
        if proc.returncode == 0:
            files = {line.strip() for line in proc.stdout.splitlines() if line.strip()}
            return base, files
    return None


def restrict_findings(findings: list[Finding], changed: set[str], root: pathlib.Path) -> list[Finding]:
    """Findings whose (root-relative) path — or ANY file on the witness
    chain — is in the changed set. The engine still indexed the WHOLE
    program; only the reporting surface narrows. Witness files count
    because a cross-module finding is often CAUSED by the edited callee
    while its report line sits in an unchanged caller: dropping those
    made --changed blind to exactly the regressions the whole-program
    rules exist for."""

    def _rel(path: str) -> str:
        try:
            return str(pathlib.Path(path).resolve().relative_to(root))
        except ValueError:
            return path

    return [
        f for f in findings
        if _finding_key(f, root)[1] in changed
        or any(_rel(w) in changed for w in f.witness_paths)
    ]


# -- baseline -----------------------------------------------------------------

def _finding_key(f: Finding, root: pathlib.Path) -> list:
    path = f.path
    try:
        path = str(pathlib.Path(f.path).resolve().relative_to(root))
    except ValueError:
        pass
    return [f.rule, path, f.message]


def load_baseline(path: pathlib.Path) -> set[tuple]:
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return set()
    return {tuple(entry) for entry in data.get("findings", [])}


# -- driver -------------------------------------------------------------------

def run_check(
    paths: list[pathlib.Path],
    root: pathlib.Path,
    usage_dirs: list[pathlib.Path],
    allowlist: dict | None = None,
) -> dict:
    """Everything except baseline comparison and rendering: returns the
    full report dict (findings as Finding objects under '_findings')."""
    allowlist = TEST_ALLOWLIST if allowlist is None else allowlist
    sources, findings = load_sources(paths)
    for name, path, src, tree in sources:
        findings.extend(lint_mod.lint_source(src, path, tree=tree))
    program = build_program(sources)
    callgraph = CallGraph(program)
    lockgraph = LockGraph(program, callgraph)
    effects = Effects(program, callgraph)
    raises_obj = Raises(program, callgraph)
    contracts = declared_contracts(program)
    findings.extend(lockgraph.inversions())
    findings.extend(resource_findings(program))
    findings.extend(config_key_findings(program, usage_dirs))
    findings.extend(docs_findings(root))
    findings.extend(fault_point_findings(program))
    findings.extend(lockset_race_findings(program, effects))
    findings.extend(atomicity_findings(program, effects))
    findings.extend(jit_hygiene_findings(program))
    findings.extend(error_contract_findings(program, raises_obj, contracts))
    findings.extend(errors_docs_findings(root, program, raises_obj, contracts))
    findings.extend(swallowed_findings(program, raises_obj))
    unwind, unwind_proof = unwind_findings(program, callgraph, raises_obj, contracts)
    findings.extend(unwind)
    domains = ProcessDomains(program, callgraph, raises_obj)
    tdomains = TraceDomains(program, callgraph, raises_obj)
    ddomains = DurabilityDomains(program, callgraph, raises_obj)
    # HSL021 vs HSL027 dedupe: a lease/fleet write site HSL027 now
    # checks reports ONCE, under the newer rule — otherwise every
    # --changed run would double-report the shared sites.
    findings.extend(
        f for f in domains.findings()
        if not (f.rule == "HSL021" and (f.path, f.line) in ddomains.claimed_sites)
    )
    findings.extend(tdomains.findings())
    findings.extend(ddomains.findings())
    allowed = []
    kept = []
    for f in findings:
        just = next(
            (why for (suffix, rule), why in allowlist.items()
             if f.rule == rule and f.path.endswith(suffix)),
            None,
        )
        (allowed if just is not None else kept).append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    corpus = validator_corpus()
    if corpus.get("failures"):
        for fail in corpus["failures"]:
            kept.append(Finding(
                "hyperspace_tpu/analysis/validator.py", 0, 0, "HSL000",
                f"validator corpus case {fail['case']!r} regressed: expected "
                f"{fail['expected']}, got {fail['got']}",
            ))
    total_calls = len(callgraph.edges) + len(callgraph.unresolved)
    dead = dead_symbol_report(program, callgraph, raises_obj, contracts)
    return {
        "_findings": kept,
        "_engine": (program, callgraph, raises_obj, contracts),
        "summary": {
            "files": len(sources),
            "findings": len(kept),
            "allowlisted": len(allowed),
            "functions": len(program.functions),
            "call_edges": len(callgraph.edges),
            # Resolution-quality accounting: the engine's blind spots.
            # A rising unresolved ratio silently weakens every
            # whole-program rule, so tests pin a regression bound on it.
            "calls_unresolved": len(callgraph.unresolved),
            "calls_unresolved_ratio": round(
                len(callgraph.unresolved) / total_calls, 4
            ) if total_calls else 0.0,
            "locks": len(program.locks),
            "lock_edges": len(lockgraph.order_edges()),
            "shared_states": len(effects.by_state),
            "entry_guaranteed_fns": len(effects.entry_locks),
            "contract_entry_points": len(contracts),
            "fault_points_proven": sum(
                1 for e in unwind_proof.values() if e["covered"]
            ),
            "dead_symbols": dead["count"],
            # Process-domain accounting (HSL019-022): CI asserts the
            # rules actually RAN — a zero entry-point count on the real
            # repo would mean the registry extraction silently broke.
            "spawn_entry_points": len(domains.entry_points),
            "spawn_domain_functions": len(domains.task_fns),
            "spawn_domain_modules": len(domains.domain_modules),
            "spawn_boundary_sites": len(domains.boundary_sites),
            "lease_acquire_sites": len(domains.lease_acquires),
            # Trace-domain accounting (HSL023-026): same CI contract —
            # a zero trace-entry count on the real repo would mean jit
            # site detection silently broke.
            "trace_entry_points": len({e.traced for e in tdomains.entries}),
            "trace_domain_functions": len(tdomains.trace_fns),
            "trace_kernels_proven": sum(
                1 for lad in tdomains._kernel_ladders if lad["proven"]
            ),
            # The trace closure's own blind-spot accounting: traced
            # bodies call mostly jnp/lax (external, unresolvable by
            # design), so this ratio runs high — the bound pins it from
            # drifting higher, like calls_unresolved_ratio above.
            "trace_domain_unresolved_ratio": tdomains.unresolved_ratio(),
            # Durability-domain accounting (HSL027-030): same CI
            # contract — zero roots/sites/windows on the real repo
            # would mean the registry extraction or write-site
            # detection silently broke.
            "durable_roots": len(ddomains.roots or {}),
            "durable_write_sites": len(ddomains.sites),
            "durable_domain_functions": len(ddomains.domain_fns),
            "torn_windows": len(ddomains.windows or {}),
            "torn_windows_proven": sum(
                1 for p in ddomains._window_proofs.values() if p["proven"]
            ),
            "replay_roots": len(ddomains.replay_roots or {}),
            "replay_closure_functions": len(ddomains.replay_fns),
            "durable_domain_unresolved_ratio": ddomains.unresolved_ratio(),
        },
        "validator_corpus": corpus,
        "lock_graph": lockgraph.to_json(),
        # The HSL018 witness chains: per fault point, the recovery
        # construct that statically reaches each threading site.
        "unwind_proof": unwind_proof,
        # The HSL019-022 substrate: the inferred process-domain graph
        # (entries, task closure, domain modules, boundary sites, lease
        # reap proofs) — procdemo pins its exact shape in a golden.
        "process_domains": domains.to_json(),
        # The HSL023-026 substrate: the inferred trace-domain graph
        # (entries, traced closure, donation proof, per-kernel fallback
        # ladders) — jitdemo pins its exact shape in a golden.
        "trace_domains": tdomains.to_json(),
        # The HSL027-030 substrate: the inferred durability-domain
        # graph (durable roots + write sites, torn-window proofs with
        # their in-window fault-point witnesses, replay closures,
        # snapshot carriers) — durademo pins its exact shape in a
        # golden.
        "durable_domains": ddomains.to_json(),
        # Informational (never gated): private functions no public entry
        # point reaches through the resolved call graph.
        "dead_symbols": dead,
        "allowlisted": [
            {"rule": f.rule, "path": f.path, "line": f.line} for f in allowed
        ],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m hyperspace_tpu.analysis.check",
        description="Unified static analysis: per-file lint (HSL001-HSL008), "
                    "whole-program rules (HSL009-HSL030), validator corpus, "
                    "findings baseline.",
    )
    ap.add_argument("paths", nargs="*", help="files/directories (default: the "
                    "package + benchmarks + bench.py + tests/conftest.py)")
    ap.add_argument("--format", choices=("text", "json", "sarif"), default="text")
    ap.add_argument("--output", help="also write the report to this file")
    ap.add_argument("--changed", action="store_true",
                    help="report findings only for files changed vs origin/main "
                         "(the engine still indexes the whole program) — the "
                         "fast local pre-push mode")
    ap.add_argument("--baseline", help=f"baseline file (default: {BASELINE_NAME} "
                    "at the repo root when present)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write the current findings as the new baseline")
    ap.add_argument("--write-config-docs", action="store_true",
                    help="regenerate the docs/configuration.md key table from "
                         "config.KNOWN_KEYS and exit")
    ap.add_argument("--write-error-docs", action="store_true",
                    help="regenerate the docs/errors.md contract table from "
                         "exceptions.ERROR_CONTRACTS + the escape analysis "
                         "and exit")
    ap.add_argument("--no-baseline", action="store_true",
                    help="fail on ALL findings, ignoring any baseline")
    args = ap.parse_args(argv)
    try:
        root = _repo_root()
        if args.write_config_docs:
            ok = write_config_docs(root)
            print("docs/configuration.md key table "
                  + ("regenerated" if ok else "markers missing — not rewritten"))
            return EXIT_CLEAN if ok else EXIT_INTERNAL_ERROR
        paths = [pathlib.Path(p) for p in args.paths] or default_paths(root)
        usage_dirs = [root / "tests"] if (root / "tests").exists() else []
        report = run_check(paths, root, usage_dirs)
        findings: list[Finding] = report.pop("_findings")
        program, _cg, raises_obj, contracts = report.pop("_engine")
        if args.write_error_docs:
            write_error_docs(root, program, raises_obj, contracts)
            print("docs/errors.md error-contract table regenerated")
            return EXIT_CLEAN
        if args.changed:
            got = changed_files(root)
            if got is None:
                print("--changed: git unavailable — running on everything",
                      file=sys.stderr)
            else:
                base, files = got
                findings = restrict_findings(findings, files, root)
                report["changed"] = {"base": base, "files": sorted(files)}
        baseline_path = pathlib.Path(args.baseline) if args.baseline else root / BASELINE_NAME
        if args.write_baseline:
            baseline_path.write_text(json.dumps(
                {"findings": sorted(_finding_key(f, root) for f in findings)},
                indent=2, sort_keys=True,
            ) + "\n")
            print(f"baseline written: {baseline_path} ({len(findings)} finding(s))")
            return EXIT_CLEAN
        baseline = set() if args.no_baseline else (
            load_baseline(baseline_path) if baseline_path.exists() else set()
        )
        new = [f for f in findings if tuple(_finding_key(f, root)) not in baseline]
        stale = len(baseline) - (len(findings) - len(new))
        report["findings"] = [
            {"rule": f.rule, "slug": RULES[f.rule].slug if f.rule in RULES else f.rule,
             "path": f.path, "line": f.line, "message": f.message,
             "new": tuple(_finding_key(f, root)) not in baseline}
            for f in findings
        ]
        report["baseline"] = {
            "path": str(baseline_path) if baseline_path.exists() else None,
            "known": len(baseline), "stale": max(0, stale), "new": len(new),
        }
        report["summary"]["new_findings"] = len(new)
        if args.format == "sarif":
            rendered = json.dumps(to_sarif(findings, baseline, root), indent=2)
        else:
            rendered = json.dumps(report, indent=2, sort_keys=True)
        if args.output:
            pathlib.Path(args.output).write_text(rendered + "\n")
        if args.format in ("json", "sarif"):
            print(rendered)
        else:
            for f in findings:
                marker = "" if tuple(_finding_key(f, root)) in baseline else " [new]"
                print(f"{f}{marker}")
            s = report["summary"]
            print(
                f"{s['files']} files, {s['functions']} functions, "
                f"{s['locks']} locks ({s['lock_edges']} order edges, cycle-free="
                f"{not any(f.rule == 'HSL009' for f in findings)}); "
                f"{s['findings']} finding(s), {len(new)} new, "
                f"{s['allowlisted']} allowlisted; validator corpus: "
                f"{report['validator_corpus']['status']}",
                file=sys.stderr,
            )
        return EXIT_FINDINGS if new else EXIT_CLEAN
    except SystemExit:
        raise
    except Exception as e:
        print(f"internal error: {type(e).__name__}: {e}", file=sys.stderr)
        return EXIT_INTERNAL_ERROR


if __name__ == "__main__":
    sys.exit(main())
