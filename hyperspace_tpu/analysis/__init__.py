"""Static analysis: pre-execution plan validation + trace-safety lint.

Two layers (the analog of Catalyst's analyzer, which the Spark reference
leans on to reject malformed plans before execution — Armbrust et al.,
SIGMOD 2015; the reference inherits it wholesale):

- `validator` — walks the logical plan IR before the executor touches a
  device, checking schema/dtype resolution of every expression, join
  bucket-spec compatibility, sort-key legality, and rewrite
  (pushdown/prune) equivalence. Raises `PlanValidationError` with
  structured `PlanDiagnostic`s naming the offending node.
- `lint` — an AST lint over the package source flagging the bug classes
  that actually bite a jax codebase: version-fragile jax imports outside
  `compat.py`, host synchronization inside jitted code, Python control
  flow on traced values, unhashable static args, unseeded randomness.
  Run as `python -m hyperspace_tpu.analysis.lint <paths>`.
"""

from hyperspace_tpu.analysis.validator import (
    check_plan,
    validate_plan,
    validate_rewrite,
)

__all__ = ["check_plan", "validate_plan", "validate_rewrite"]
