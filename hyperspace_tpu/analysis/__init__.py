"""Static analysis: plan validation, per-file lint, whole-program engine.

Three layers (the analog of Catalyst's analyzer, which the Spark
reference leans on to reject malformed plans before execution —
Armbrust et al., SIGMOD 2015; the reference inherits it wholesale):

- `validator` — walks the logical plan IR before the executor touches a
  device, checking schema/dtype resolution of every expression, join
  bucket-spec compatibility, sort-key legality, and rewrite
  (pushdown/prune) equivalence. Raises `PlanValidationError` with
  structured `PlanDiagnostic`s naming the offending node.
- `lint` — the per-file AST rules (HSL001-HSL008) for the bug classes
  that actually bite a jax codebase: version-fragile jax imports outside
  `compat.py`, host synchronization inside jitted code, Python control
  flow on traced values, unhashable static args, unseeded randomness,
  metadata-write bypass, wall-clock durations / undeclared counters,
  unlocked global mutation. Run as
  `python -m hyperspace_tpu.analysis.lint <paths>`.
- the **whole-program engine** — `program` (module/symbol index +
  single-pass function summaries), `callgraph` (cross-module call
  resolution), `locks` (the static lock-acquisition graph), `effects`
  (per-function shared-state effect summaries with locksets), `raises`
  (per-function exception escape sets over the same call graph), and
  the rules only it can express: HSL009 lock-order inversion with
  two-chain witnesses, HSL010 config-key drift against
  `config.KNOWN_KEYS`, HSL011 resource/exception safety, HSL012
  fault-point coverage against `faults.KNOWN_POINTS`, HSL013 lockset
  data races with two-path witnesses, HSL014 torn check-then-act
  atomicity violations, HSL015 jit-cache hygiene (recompile-storm /
  executable-leak call sites), HSL016 error-contract drift against
  `exceptions.ERROR_CONTRACTS` (generated docs/errors.md), HSL017
  swallowed crash/fault handlers, HSL018 the static unwind-safety
  proof over `faults.KNOWN_POINTS`, and the process-domain layer
  (`procdomain`): HSL019 spawn-import purity over the
  `SPAWN_ENTRY_POINTS` registry's inferred worker domain, HSL020
  exchange-surface typing at every process boundary, HSL021 the
  shared-file protocol (atomic publish + TTL-reaped leases), HSL022
  cross-boundary fault/telemetry continuity. The
  unified driver — lint + whole-program rules + validator corpus +
  findings baseline — is `python -m hyperspace_tpu.analysis.check`
  (docs/static_analysis.md).
"""

from hyperspace_tpu.analysis.validator import (
    check_plan,
    validate_plan,
    validate_rewrite,
)

__all__ = [
    "check_plan",
    "validate_plan",
    "validate_rewrite",
    "CallGraph",
    "Effects",
    "LockGraph",
    "Program",
    "Raises",
]


def __getattr__(name):
    # Lazy: the engine is only needed by the check driver and tests.
    if name == "Program":
        from hyperspace_tpu.analysis.program import Program

        return Program
    if name == "CallGraph":
        from hyperspace_tpu.analysis.callgraph import CallGraph

        return CallGraph
    if name == "LockGraph":
        from hyperspace_tpu.analysis.locks import LockGraph

        return LockGraph
    if name == "Effects":
        from hyperspace_tpu.analysis.effects import Effects

        return Effects
    if name == "Raises":
        from hyperspace_tpu.analysis.raises import Raises

        return Raises
    raise AttributeError(name)
