"""Whole-program index: modules, symbols, and per-function summaries.

The per-file linter (analysis/lint.py) sees one AST at a time; the rules
that actually guard the concurrent serving plane need to see the whole
package at once — a lock acquired in ``serve/scheduler.py`` while a call
chain reaches into ``hyperspace.py`` holding the session RLock is
invisible to any single-file walk. This module builds the shared
substrate every cross-module rule runs on:

- :class:`ModuleInfo` — one parsed module: dotted name, AST, imports
  (alias → dotted target), module-level string constants, module-level
  lock definitions, and variable → class type bindings.
- :class:`FunctionInfo` — one function/method summary extracted in a
  SINGLE visitor pass: calls made (with the stack of locks held AND the
  try/except guards enclosing each call site), locks acquired via
  ``with`` (with the locks already held), raise sites with their guard
  stacks (the raw material of the exception-flow layer,
  analysis/raises.py), config get/set keys, fault-point references, and
  the raw AST node for rules that need a closer look (resource safety,
  HSL011).
- :class:`Program` — the package-wide index: symbol tables, lock
  definitions (module-level and ``self.X = threading.Lock()`` class
  attributes), attribute/variable type bindings, and the name-resolution
  machinery the call graph builds on (analysis/callgraph.py).

Everything here is stdlib-``ast`` only and never imports the analyzed
code — the CI check job runs without the package's dependencies
installed, exactly like the per-file linter always has.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

_LOCK_CTORS = {
    "Lock": "Lock",
    "RLock": "RLock",
    "Condition": "Condition",
}


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a Name/Attribute chain ('' otherwise)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _lock_kind(value: ast.expr) -> str | None:
    """'Lock' / 'RLock' / 'Condition' when `value` is a threading lock
    constructor call (``threading.Lock()`` or a bare imported ``Lock()``),
    else None."""
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func).split(".")[-1]
    return _LOCK_CTORS.get(name)


@dataclasses.dataclass(frozen=True)
class LockRef:
    """An unresolved lock reference as spelled at a ``with``/call site.

    kind: 'name' (bare module-level name), 'self' (``self.<attr>``), or
    'attr' (``<expr>.<attr>`` where the base is not self). Resolution to
    a program-wide lock id happens in :meth:`Program.resolve_lock`.
    """

    kind: str
    name: str  # the bare name or the attribute name
    line: int


@dataclasses.dataclass(frozen=True)
class Guard:
    """The handlers of ONE enclosing ``try`` statement, as seen from a
    site inside its body: for each ``except`` clause, the raw caught
    type texts (``()`` = bare ``except:``) and whether the handler
    re-raises what it caught (a bare ``raise`` / ``raise <bound name>``
    anywhere in its body). The raise-propagation layer
    (analysis/raises.py) subtracts escaping exception types against
    these, narrowed by the exception hierarchy."""

    handlers: tuple[tuple[tuple[str, ...], bool], ...]


@dataclasses.dataclass(frozen=True)
class RaiseSite:
    """One ``raise`` statement: the raw dotted text of the raised
    expression (``None`` for a bare re-raise), the stack of enclosing
    try guards (outermost first), and — when the site re-raises the
    exception an enclosing ``except`` clause bound — that clause's
    caught type texts."""

    raw: str | None
    line: int
    guards: tuple[Guard, ...]
    handler_types: tuple[str, ...] | None = None


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call expression: the raw dotted callee text plus the stack of
    lock references held (lexically, via enclosing ``with``) at the
    call, and the stack of try/except guards enclosing it (the raise
    analysis subtracts callee escapes against those)."""

    raw: str
    line: int
    held: tuple[LockRef, ...]
    guards: tuple[Guard, ...] = ()


@dataclasses.dataclass(frozen=True)
class Acquire:
    """One ``with <lock>`` entry and the locks already held around it."""

    ref: LockRef
    line: int
    held: tuple[LockRef, ...]


@dataclasses.dataclass(frozen=True)
class AttrAccess:
    """One shared-state access: a ``self.<attr>`` load/store or a
    module-global load/store, with the stack of lock references lexically
    held at the access site (the raw material of the effect summaries in
    analysis/effects.py and the HSL013 lockset race rule).

    kind: 'self' (instance attribute through ``self``) or 'global'
    (module-level name in this module's shared-global candidate set).
    write covers rebinds, augmented assigns, subscript stores, ``del``,
    and in-place mutator calls (``.append``/``.update``/...); ``keyed``
    marks subscript/keyed-mutator forms (``S[k] = v``, ``S.pop(k)``) —
    the memo-fill shape the atomicity rule treats differently from a
    whole-value rebind."""

    kind: str
    attr: str
    line: int
    write: bool
    held: tuple[LockRef, ...]
    keyed: bool = False
    in_init: bool = False


@dataclasses.dataclass
class ConfigAccess:
    """One conf ``get``/``set`` whose key resolves (constant or named
    constant) to a ``hyperspace.*`` string. `key` may still be None
    right after the per-module pass when the site spells the key through
    an imported constant (``conf.set(JOIN_VENUE, ...)``); Program._index
    resolves those against the merged constant table of every indexed
    module."""

    key: str | None
    line: int
    write: bool
    pending_name: str | None = None


@dataclasses.dataclass
class FunctionInfo:
    qname: str
    module: str
    cls: str | None
    name: str
    line: int
    node: ast.AST
    calls: list[CallSite] = dataclasses.field(default_factory=list)
    acquires: list[Acquire] = dataclasses.field(default_factory=list)
    config_accesses: list[ConfigAccess] = dataclasses.field(default_factory=list)
    fault_refs: list[tuple[str, int, str]] = dataclasses.field(default_factory=list)
    attr_accesses: list[AttrAccess] = dataclasses.field(default_factory=list)
    raises: list[RaiseSite] = dataclasses.field(default_factory=list)
    returns_type: str | None = None  # raw annotation text, when a simple name
    # Local name -> the raw expression that first bound it, when that is
    # a constructor call ("Executor") or a self-rooted attribute chain
    # ("self.session.manager") — the call graph types receiver locals
    # through these (`executor = Executor(...); executor.execute(...)`).
    local_types: dict[str, str] = dataclasses.field(default_factory=dict)
    # Function-LEVEL imports (alias -> dotted target): the deferred-import
    # idiom the heavy modules use; resolve_symbol consults these before
    # the module-level map so `_prefetch.prefetch_plan(...)` resolves.
    imports: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClassInfo:
    qname: str
    module: str
    name: str
    line: int
    bases: list[str]
    is_protocol: bool = False  # typing.Protocol seam (structural dispatch)
    methods: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    attr_locks: dict[str, str] = dataclasses.field(default_factory=dict)  # attr -> kind
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)  # attr -> raw ctor ref
    attr_names: set[str] = dataclasses.field(default_factory=set)  # every self.X assigned


@dataclasses.dataclass
class ModuleInfo:
    name: str
    path: str
    tree: ast.Module
    source: str
    imports: dict[str, str] = dataclasses.field(default_factory=dict)  # alias -> dotted target
    functions: dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    module_locks: dict[str, str] = dataclasses.field(default_factory=dict)  # name -> kind
    var_types: dict[str, str] = dataclasses.field(default_factory=dict)  # name -> raw ctor ref
    const_strings: dict[str, str] = dataclasses.field(default_factory=dict)
    # Module-level names whose loads/stores count as shared-state
    # accesses: mutable containers assigned at module level plus any
    # name some function rebinds through `global` (analysis/effects.py).
    shared_globals: set[str] = dataclasses.field(default_factory=set)

    @property
    def lines(self) -> list[str]:
        # Memoized: the durability sweep reads node segments against
        # this table for every call expression in the program, and
        # re-splitting the source each access made that quadratic.
        got = self.__dict__.get("_lines")
        if got is None:
            got = self.__dict__["_lines"] = self.source.splitlines()
        return got


class _FunctionPass(ast.NodeVisitor):
    """The single per-function visitor pass: collects calls, lock
    acquisitions (with the held stack), config accesses, and fault-point
    references in one walk."""

    _INIT_NAMES = ("__init__", "__new__", "__post_init__")

    def __init__(self, info: FunctionInfo, module: ModuleInfo):
        self.info = info
        self.module = module
        self._held: list[LockRef] = []
        self._in_init = info.cls is not None and info.name in self._INIT_NAMES
        self._global_decls: set[str] = set()
        # Exception-flow context (analysis/raises.py): the stack of
        # enclosing try guards, the stack of enclosing except-handler
        # (types, bound name) pairs, and whether we are inside a nested
        # def/lambda (whose raises execute later, in some other frame —
        # they never unwind THIS function's callers, so they are not
        # recorded as this function's raise sites).
        self._guards: list[Guard] = []
        self._handler_ctx: list[tuple[tuple[str, ...], str | None]] = []
        self._nested_fn_depth = 0
        # Attribute/Name nodes already accounted for by an enclosing
        # write form (mutator call, subscript store) — their Load visit
        # must not double-record a read.
        self._claimed: set[int] = set()
        # Lambdas that run under the current lock stack despite being
        # nested functions: predicates passed to Condition.wait_for are
        # evaluated while the condition's lock is held.
        self._inherit_held: set[int] = set()

    def _lock_ref(self, ctx: ast.expr, line: int) -> LockRef | None:
        """A LockRef when the with-item context expression *could* be a
        lock: a bare name or a terminal attribute access. Whether it IS
        one is decided at resolution time against the program-wide lock
        definitions — so ``with open(...)`` or ``with span(...)`` never
        produce a ref (calls are not lock expressions)."""
        if isinstance(ctx, ast.Name):
            return LockRef("name", ctx.id, line)
        if isinstance(ctx, ast.Attribute):
            base = ctx.value
            if isinstance(base, ast.Name) and base.id == "self":
                return LockRef("self", ctx.attr, line)
            return LockRef("attr", ctx.attr, line)
        return None

    def visit_With(self, node: ast.With) -> None:
        refs: list[LockRef] = []
        for item in node.items:
            ref = self._lock_ref(item.context_expr, node.lineno)
            if ref is not None:
                self.info.acquires.append(Acquire(ref, node.lineno, tuple(self._held)))
                refs.append(ref)
                self._held.append(ref)
            # Context expressions that are calls (span(...), open(...))
            # still contain visitable sub-calls.
            if isinstance(item.context_expr, ast.Call):
                self.visit(item.context_expr)
                # `with Ctor(...) as x:` types the bound local exactly
                # like `x = Ctor(...)` — the context-manager classes here
                # (TaskPool, the servers) return self from __enter__, and
                # this is how every pooled-build call site is spelled.
                if isinstance(item.optional_vars, ast.Name):
                    ctor = _dotted(item.context_expr.func)
                    if ctor and ctor != "super":
                        self.info.local_types.setdefault(
                            item.optional_vars.id, ctor + "()"
                        )
        for stmt in node.body:
            self.visit(stmt)
        for _ in refs:
            self._held.pop()

    visit_AsyncWith = visit_With

    def _visit_nested_fn(self, node) -> None:
        # Nested defs/lambdas run later, not at the enclosing call site —
        # but the serving plane's closures (QueryServer._body) DO run
        # with no lock held, so walk them with an empty held stack.
        # Exception: wait_for predicates (marked in _inherit_held) are
        # evaluated by Condition.wait_for WITH the lock held.
        # The try/except context resets the same way: an enclosing
        # handler does not guard the closure's later execution, and the
        # closure's own raises unwind some other frame (not recorded).
        saved = self._held
        saved_guards, saved_ctx = self._guards, self._handler_ctx
        if id(node) not in self._inherit_held:
            self._held = []
        self._guards, self._handler_ctx = [], []
        self._nested_fn_depth += 1
        try:
            for stmt in getattr(node, "body", []) if not isinstance(node, ast.Lambda) else [node.body]:
                self.visit(stmt)
        finally:
            self._nested_fn_depth -= 1
            self._held = saved
            self._guards, self._handler_ctx = saved_guards, saved_ctx

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.info.imports[alias.asname or alias.name.split(".")[0]] = alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            if node.level == 0:
                base = node.module
            else:
                base = ".".join(self.module.name.split(".")[: -node.level] + [node.module])
            for alias in node.names:
                self.info.imports[alias.asname or alias.name] = f"{base}.{alias.name}"

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_nested_fn(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_nested_fn(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_nested_fn(node)

    # -- exception flow ----------------------------------------------------
    @staticmethod
    def _handler_types(handler: ast.ExceptHandler) -> tuple[str, ...]:
        """Raw dotted texts of the types one except clause catches;
        ``()`` = bare ``except:`` (catches everything)."""
        t = handler.type
        if t is None:
            return ()
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        return tuple(filter(None, (_dotted(e) for e in elts)))

    @staticmethod
    def _handler_reraises(handler: ast.ExceptHandler) -> bool:
        """True when the handler re-raises what it caught: a bare
        ``raise`` or ``raise <bound name>`` anywhere in its body (a
        conditional re-raise still means the caught types MAY escape)."""
        for sub in ast.walk(handler):
            if not isinstance(sub, ast.Raise):
                continue
            if sub.exc is None:
                return True
            if (
                handler.name is not None
                and isinstance(sub.exc, ast.Name)
                and sub.exc.id == handler.name
            ):
                return True
        return False

    def visit_Try(self, node: ast.Try) -> None:
        guard = Guard(tuple(
            (self._handler_types(h), self._handler_reraises(h))
            for h in node.handlers
        ))
        if node.handlers:
            self._guards.append(guard)
        for stmt in node.body:
            self.visit(stmt)
        if node.handlers:
            self._guards.pop()
        # Handler bodies are guarded only by OUTER tries; `else` and
        # `finally` bodies are never covered by this try's handlers.
        for h in node.handlers:
            self._handler_ctx.append((self._handler_types(h), h.name))
            for stmt in h.body:
                self.visit(stmt)
            self._handler_ctx.pop()
        for stmt in (*node.orelse, *node.finalbody):
            self.visit(stmt)

    visit_TryStar = visit_Try

    def visit_Raise(self, node: ast.Raise) -> None:
        if self._nested_fn_depth == 0:
            guards = tuple(self._guards)
            if node.exc is None:
                # Bare re-raise: legal only inside a handler; record the
                # caught types so the raise analysis knows what escapes.
                if self._handler_ctx:
                    types, _ = self._handler_ctx[-1]
                    self.info.raises.append(
                        RaiseSite(None, node.lineno, guards, handler_types=types)
                    )
            else:
                exc = node.exc
                raw = _dotted(exc.func) if isinstance(exc, ast.Call) else _dotted(exc)
                handler_types = None
                if isinstance(exc, ast.Name):
                    # `raise e` of a bound handler name is a re-raise of
                    # the caught types, not a raise of a type named `e`.
                    for types, bound in reversed(self._handler_ctx):
                        if bound == exc.id:
                            handler_types = types
                            break
                self.info.raises.append(RaiseSite(
                    raw or None, node.lineno, guards, handler_types=handler_types,
                ))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        raw = _dotted(node.func)
        if not raw and isinstance(node.func, ast.Attribute):
            base = node.func.value
            # `super().m(...)`: the base is a Call, so _dotted sees
            # nothing — record it as `super.m` and let the call graph
            # resolve it through the base classes.
            if (
                isinstance(base, ast.Call)
                and isinstance(base.func, ast.Name)
                and base.func.id == "super"
                and not base.args
            ):
                raw = f"super.{node.func.attr}"
            # `Ctor(...).m(...)` — the immediate-invoke shape every
            # manager method uses (`CreateAction(...).run()`): record as
            # `Ctor().m` so the call graph can type the receiver.
            elif isinstance(base, ast.Call):
                ctor = _dotted(base.func)
                if ctor:
                    raw = f"{ctor}().{node.func.attr}"
        if raw:
            self.info.calls.append(
                CallSite(raw, node.lineno, tuple(self._held), tuple(self._guards))
            )
            # retry_call(fn, ...) invokes its first argument synchronously
            # — record the function REFERENCE as a call at this site, so
            # retried IO primitives stay visible to the exception-flow
            # and lock analyses (utils/retry.py is the one sanctioned
            # higher-order invoker on the metadata plane).
            if raw.split(".")[-1] == "retry_call" and node.args:
                inner = _dotted(node.args[0])
                if inner:
                    self.info.calls.append(CallSite(
                        inner, node.lineno, tuple(self._held), tuple(self._guards)
                    ))
        self._check_config_access(node, raw)
        self._check_fault_ref(node, raw)
        # In-place mutator call on shared state: self.X.append(...) /
        # GLOBAL.update(...) is a WRITE to X / GLOBAL.
        if isinstance(node.func, ast.Attribute) and node.func.attr in _MUTATOR_METHODS:
            keyed = node.func.attr in _KEYED_MUTATORS and bool(node.args)
            self._record_target(node.func.value, node.lineno, write=True, keyed=keyed)
        # wait_for predicates run under the condition's lock — mark the
        # lambda so _visit_nested_fn keeps the held stack for it.
        if isinstance(node.func, ast.Attribute) and node.func.attr == "wait_for":
            for arg in node.args:
                if isinstance(arg, (ast.Lambda, ast.Name)):
                    self._inherit_held.add(id(arg))
        self.generic_visit(node)

    # -- shared-state accesses ---------------------------------------------
    def visit_Global(self, node: ast.Global) -> None:
        self._global_decls.update(node.names)

    def _record_target(self, base: ast.expr, line: int, write: bool, keyed: bool) -> None:
        """Record a write through an access base: ``self.X`` or a shared
        module-global name (claiming the base node so its Load visit
        doesn't double-record a read)."""
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            self._claimed.add(id(base))
            self.info.attr_accesses.append(AttrAccess(
                "self", base.attr, line, write, tuple(self._held),
                keyed=keyed, in_init=self._in_init,
            ))
        elif isinstance(base, ast.Name) and base.id in self.module.shared_globals:
            self._claimed.add(id(base))
            self.info.attr_accesses.append(AttrAccess(
                "global", base.id, line, write, tuple(self._held),
                keyed=keyed, in_init=self._in_init,
            ))

    def _record_store(self, tgt: ast.expr, line: int) -> None:
        if isinstance(tgt, ast.Attribute):
            self._record_target(tgt, line, write=True, keyed=False)
        elif isinstance(tgt, ast.Subscript):
            self._record_target(tgt.value, line, write=True, keyed=True)
        elif isinstance(tgt, ast.Name):
            if tgt.id in self._global_decls and tgt.id in self.module.shared_globals:
                self.info.attr_accesses.append(AttrAccess(
                    "global", tgt.id, line, write=True, held=tuple(self._held),
                    in_init=self._in_init,
                ))
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._record_store(el, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._record_store(tgt, node.lineno)
        # Local receiver types: `x = Ctor(...)` / `x = self.a.b` (first
        # binding wins; a rebound local stays conservative).
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            if isinstance(node.value, ast.Call):
                ctor = _dotted(node.value.func)
                if ctor and ctor != "super":
                    self.info.local_types.setdefault(name, ctor + "()")
            elif isinstance(node.value, ast.Attribute):
                path = _dotted(node.value)
                if path.startswith("self."):
                    self.info.local_types.setdefault(name, path)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_store(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._record_store(tgt, node.lineno)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.ctx, ast.Load)
            and id(node) not in self._claimed
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            self.info.attr_accesses.append(AttrAccess(
                "self", node.attr, node.lineno, write=False,
                held=tuple(self._held), in_init=self._in_init,
            ))
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if (
            isinstance(node.ctx, ast.Load)
            and id(node) not in self._claimed
            and node.id in self.module.shared_globals
        ):
            self.info.attr_accesses.append(AttrAccess(
                "global", node.id, node.lineno, write=False,
                held=tuple(self._held), in_init=self._in_init,
            ))

    # -- config get/set ----------------------------------------------------
    def _check_config_access(self, node: ast.Call, raw: str) -> None:
        attr = raw.split(".")[-1]
        if attr not in ("get", "set") or not node.args:
            return
        expr = node.args[0]
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            if expr.value.startswith("hyperspace."):
                self.info.config_accesses.append(
                    ConfigAccess(expr.value, node.lineno, write=(attr == "set"))
                )
            return
        name = None
        if isinstance(expr, ast.Name):
            name = expr.id
        elif isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            name = expr.attr
        if name is None:
            return
        val = self.module.const_strings.get(name)
        if val is not None:
            if val.startswith("hyperspace."):
                self.info.config_accesses.append(
                    ConfigAccess(val, node.lineno, write=(attr == "set"))
                )
            return
        # Imported constant: leave the name pending; Program._index
        # resolves it against every indexed module's constants.
        self.info.config_accesses.append(
            ConfigAccess(None, node.lineno, write=(attr == "set"), pending_name=name)
        )

    # -- fault points ------------------------------------------------------
    def _check_fault_ref(self, node: ast.Call, raw: str) -> None:
        tail = raw.split(".")[-1]
        if tail == "fault_point":
            kind = "point"
        elif tail in ("inject", "injected") and (
            raw.split(".")[0] in ("faults",) or tail == raw
        ):
            kind = "inject"
        else:
            return
        arg: ast.expr | None = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "point":
                arg = kw.value
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            self.info.fault_refs.append((arg.value, node.lineno, kind))


# Container constructors whose module-level instances count as shared
# state, and the in-place method names that mutate shared state (the
# keyed subset is the memo-fill shape: S[k]=v / S.pop(k) / S.setdefault).
_CONTAINER_CTORS = {"dict", "list", "set", "deque", "defaultdict", "OrderedDict", "Counter"}
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "clear",
    "remove", "discard",
}
_KEYED_MUTATORS = {"pop", "setdefault"}


def _shared_global_names(tree: ast.Module) -> set[str]:
    """Module-level names whose cross-thread accesses matter: mutable
    containers assigned at the top level, plus every name declared
    ``global`` inside some function (rebound module state)."""
    out: set[str] = set()
    for node in tree.body:
        targets: list[ast.expr] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            continue
        is_container = isinstance(
            value, (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp, ast.SetComp)
        ) or (
            isinstance(value, ast.Call)
            and _dotted(value.func).split(".")[-1] in _CONTAINER_CTORS
        )
        if not is_container:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                out.add(tgt.id)
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def _local_bound_names(fn_node: ast.AST) -> set[str]:
    """Names bound locally anywhere in a function (params, assignment /
    loop / with / except targets, comprehension vars) — a module-global
    load is only a shared read when the name is NOT shadowed locally."""
    bound: set[str] = set()
    global_names: set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, (ast.Store, ast.Del)):
            bound.add(sub.id)
        elif isinstance(sub, ast.arg):
            bound.add(sub.arg)
        elif isinstance(sub, ast.ExceptHandler) and sub.name:
            bound.add(sub.name)
        elif isinstance(sub, ast.Global):
            global_names.update(sub.names)
    return bound - global_names


def _index_module(name: str, path: str, source: str, tree: ast.Module) -> ModuleInfo:
    mod = ModuleInfo(name=name, path=path, tree=tree, source=source)
    mod.shared_globals = _shared_global_names(tree)
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                mod.imports[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                mod.imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"
        elif isinstance(node, ast.ImportFrom) and node.module and node.level > 0:
            # Relative import: resolve against this module's package.
            pkg_parts = name.split(".")[: -node.level]
            base = ".".join(pkg_parts + [node.module])
            for alias in node.names:
                mod.imports[alias.asname or alias.name] = f"{base}.{alias.name}"
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                kind = _lock_kind(node.value)
                if kind is not None:
                    mod.module_locks[tgt.id] = kind
                elif isinstance(node.value, ast.Constant) and isinstance(node.value.value, str):
                    mod.const_strings[tgt.id] = node.value.value
                elif isinstance(node.value, ast.Call):
                    mod.var_types[tgt.id] = _dotted(node.value.func)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mod.functions[node.name] = _index_function(mod, None, node)
        elif isinstance(node, ast.ClassDef):
            mod.classes[node.name] = _index_class(mod, node)
    return mod


def _index_function(mod: ModuleInfo, cls: str | None, node) -> FunctionInfo:
    qname = f"{mod.name}.{cls}.{node.name}" if cls else f"{mod.name}.{node.name}"
    info = FunctionInfo(
        qname=qname, module=mod.name, cls=cls, name=node.name,
        line=node.lineno, node=node,
    )
    ret = getattr(node, "returns", None)
    if isinstance(ret, ast.Name):
        info.returns_type = ret.id
    elif isinstance(ret, ast.Constant) and isinstance(ret.value, str):
        info.returns_type = ret.value.strip("'\"")
    _FunctionPass(info, mod).generic_visit(node)
    # A module-global load shadowed by a local binding of the same name
    # is not a shared access after all.
    shadowed = _local_bound_names(node)
    if shadowed:
        info.attr_accesses = [
            a for a in info.attr_accesses
            if not (a.kind == "global" and not a.write and a.attr in shadowed)
        ]
    return info


def _index_class(mod: ModuleInfo, node: ast.ClassDef) -> ClassInfo:
    bases = [_dotted(b) for b in node.bases if _dotted(b)]
    cls = ClassInfo(
        qname=f"{mod.name}.{node.name}", module=mod.name, name=node.name,
        line=node.lineno, bases=bases,
        is_protocol=any(b.split(".")[-1] == "Protocol" for b in bases),
    )
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls.methods[item.name] = _index_function(mod, node.name, item)
            # Attribute locks / attribute types: `self.X = threading.Lock()`
            # and `self.X = SomeClass(...)` anywhere in the class's methods
            # (constructors usually, but lazy init counts too). A plain
            # `self.X = param` where the parameter carries a simple type
            # annotation types the attribute too (`def __init__(self,
            # session: HyperspaceSession)` — the facade-wiring shape).
            param_anns: dict[str, str] = {}
            for a in (*item.args.posonlyargs, *item.args.args, *item.args.kwonlyargs):
                ann = a.annotation
                if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                    param_anns[a.arg] = ann.value.strip("'\"")
                else:
                    txt = _dotted(ann) if ann is not None else ""
                    if txt:
                        param_anns[a.arg] = txt
            for sub in ast.walk(item):
                if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                    continue
                tgt = sub.targets[0]
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                cls.attr_names.add(tgt.attr)
                kind = _lock_kind(sub.value)
                if kind is not None:
                    cls.attr_locks[tgt.attr] = kind
                elif isinstance(sub.value, ast.Call):
                    cls.attr_types.setdefault(tgt.attr, _dotted(sub.value.func))
                elif isinstance(sub.value, ast.Name) and sub.value.id in param_anns:
                    cls.attr_types.setdefault(tgt.attr, param_anns[sub.value.id])
    return cls


@dataclasses.dataclass(frozen=True)
class LockDef:
    """One lock *class* in the program: a module-level lock object or a
    (class, attribute) pair. Static analysis treats every instance of a
    class as holding the same lock id — the standard lockset
    abstraction, and exactly right for the singleton caches/sessions
    this codebase locks."""

    lock_id: str
    kind: str  # Lock | RLock | Condition
    module: str
    attr: str  # bare name for module locks, attribute name for class locks
    cls: str | None


class Program:
    """The whole-program index: every module parsed once, plus the
    symbol tables name resolution needs."""

    def __init__(self, modules: dict[str, ModuleInfo]):
        self.modules = modules
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.locks: dict[str, LockDef] = {}
        self._locks_by_attr: dict[str, list[LockDef]] = {}
        self._classes_by_method: dict[str, list[str]] = {}
        self._classes_by_name: dict[str, list[str]] = {}
        self._index()

    # -- construction ------------------------------------------------------
    @classmethod
    def load(cls, paths: list[str | pathlib.Path], package_roots: dict[str, str] | None = None) -> "Program":
        """Parse every ``*.py`` under `paths` (files or directories).

        Module names are derived from the path relative to the nearest
        named package root (default: a directory holding an
        ``__init__.py`` chain), so ``hyperspace_tpu/serve/scheduler.py``
        indexes as ``hyperspace_tpu.serve.scheduler`` and stray files
        (``bench.py``) index under their stem.
        """
        modules: dict[str, ModuleInfo] = {}
        for p in paths:
            root = pathlib.Path(p)
            files = sorted(root.rglob("*.py")) if root.is_dir() else [root]
            for f in files:
                try:
                    source = f.read_text()
                    tree = ast.parse(source, filename=str(f))
                except (OSError, SyntaxError):
                    continue  # the linter reports these; the index skips
                name = _module_name(f)
                modules[name] = _index_module(name, str(f), source, tree)
        return cls(modules)

    def _index(self) -> None:
        # hyperspace.* key constants importable across modules: the
        # merged constant table resolves `conf.set(JOIN_VENUE, ...)`
        # sites whose constant lives in config.py.
        key_constants: dict[str, str] = {}
        for mod in self.modules.values():
            for cname, val in mod.const_strings.items():
                if val.startswith("hyperspace."):
                    key_constants.setdefault(cname, val)
        for mod in self.modules.values():
            for fn in mod.functions.values():
                self.functions[fn.qname] = fn
            for name, lk in mod.module_locks.items():
                d = LockDef(f"{mod.name}.{name}", lk, mod.name, name, None)
                self.locks[d.lock_id] = d
            for cls in mod.classes.values():
                self.classes[cls.qname] = cls
                self._classes_by_name.setdefault(cls.name, []).append(cls.qname)
                for m, fn in cls.methods.items():
                    self.functions[fn.qname] = fn
                    self._classes_by_method.setdefault(m, []).append(cls.qname)
                for attr, lk in cls.attr_locks.items():
                    d = LockDef(f"{cls.qname}.{attr}", lk, mod.name, attr, cls.name)
                    self.locks[d.lock_id] = d
        for d in self.locks.values():
            self._locks_by_attr.setdefault(d.attr, []).append(d)
        for fn in self.functions.values():
            for acc in fn.config_accesses:
                if acc.key is None and acc.pending_name is not None:
                    acc.key = key_constants.get(acc.pending_name)
            # A pending name that resolves to nothing was not a config
            # key after all (dict.get(x), conf.get(other_var), ...).
            fn.config_accesses = [a for a in fn.config_accesses if a.key is not None]

    # -- lock resolution ---------------------------------------------------
    def resolve_lock(self, ref: LockRef, module: str, cls: str | None) -> LockDef | None:
        """The LockDef a with-site reference names, or None.

        - ``with _lock:`` → the module-level lock of the same module
          (or the one it was imported from).
        - ``with self._lock:`` → the enclosing class's attribute lock
          (walking base classes by name when the class itself doesn't
          define it).
        - ``with obj._state_lock:`` → resolved by attribute name when
          exactly ONE class in the program defines a lock attribute with
          that name; ambiguous attribute names stay unresolved
          (conservative: no false edges from `_lock`-vs-`_lock`).
        """
        mod = self.modules.get(module)
        if ref.kind == "name":
            if mod is not None and ref.name in mod.module_locks:
                return self.locks.get(f"{module}.{ref.name}")
            if mod is not None and ref.name in mod.imports:
                return self.locks.get(mod.imports[ref.name])
            return None
        if ref.kind == "self" and cls is not None:
            for cq in self._mro(f"{module}.{cls}"):
                c = self.classes.get(cq)
                if c is not None and ref.name in c.attr_locks:
                    return self.locks.get(f"{cq}.{ref.name}")
        candidates = [d for d in self._locks_by_attr.get(ref.name, []) if d.cls is not None]
        if len(candidates) == 1:
            return candidates[0]
        return None

    def _mro(self, cls_qname: str) -> list[str]:
        """The class plus program-local bases (by simple name), depth-first."""
        out, stack, seen = [], [cls_qname], set()
        while stack:
            q = stack.pop(0)
            if q in seen:
                continue
            seen.add(q)
            out.append(q)
            c = self.classes.get(q)
            if c is None:
                continue
            for b in c.bases:
                base_name = b.split(".")[-1]
                mod = self.modules.get(c.module)
                if mod is not None and b in mod.imports:
                    stack.append(mod.imports[b])
                elif mod is not None and base_name in mod.classes:
                    stack.append(f"{c.module}.{base_name}")
                elif len(self._classes_by_name.get(base_name, [])) == 1:
                    stack.append(self._classes_by_name[base_name][0])
        return out

    # -- type/symbol resolution (used by the call graph) -------------------
    def resolve_symbol(self, module: str, name: str, fn: "FunctionInfo | None" = None) -> str | None:
        """A dotted program qname for a bare name used in `module`:
        a local function/class, or an imported one. With `fn`, the
        function's OWN imports are consulted first — idiomatic deferred
        imports (`from hyperspace_tpu.execution import prefetch as
        _prefetch` inside a method) shadow module-level bindings for
        that function exactly like at runtime."""
        mod = self.modules.get(module)
        if mod is None:
            return None
        if fn is not None and name in fn.imports:
            got = self._import_target(fn.imports[name])
            if got is not None:
                return got
        if name in mod.functions:
            return mod.functions[name].qname
        if name in mod.classes:
            return mod.classes[name].qname
        if name in mod.imports:
            return self._import_target(mod.imports[name])
        return None

    def _import_target(self, target: str) -> str | None:
        """Resolve one import's dotted target to a known program symbol
        or module (shared by module- and function-level imports)."""
        if target in self.functions or target in self.classes or target in self.modules:
            return target
        # Package re-export: `from hyperspace_tpu.actions import
        # CreateAction` maps to hyperspace_tpu.actions.CreateAction,
        # which the package __init__ itself imports from the real
        # defining module — follow one aliasing hop.
        pkg, _, leaf = target.rpartition(".")
        if pkg in self.modules and leaf in self.modules[pkg].imports:
            t2 = self.modules[pkg].imports[leaf]
            if t2 in self.functions or t2 in self.classes or t2 in self.modules:
                return t2
        # `from hyperspace_tpu.obs import trace as obs_trace` maps the
        # alias to hyperspace_tpu.obs.trace: also try the module map by
        # suffix (modules index under their file-derived dotted name).
        for mname in self.modules:
            if mname == target or mname.endswith("." + target.split(".")[-1]) and target.endswith(mname.split(".")[-1]):
                if target == mname or target.endswith(mname) or mname.endswith(target):
                    return mname
        return None

    def class_of_ctor(self, module: str, ctor_raw: str, fn: "FunctionInfo | None" = None) -> str | None:
        """The class qname `ctor_raw` (a dotted ctor/factory expression)
        constructs: a direct class reference, or a function whose return
        annotation names a program class. With `fn`, the function's own
        deferred imports are consulted first (resolve_symbol) — `from
        ...procpool import TaskPool` inside a method types a
        `with TaskPool(...) as pool:` local exactly like at runtime."""
        parts = ctor_raw.split(".")
        target = self.resolve_symbol(module, parts[0], fn=fn)
        if target is None:
            return None
        for p in parts[1:]:
            if target in self.modules:
                mod = self.modules[target]
                if p in mod.classes:
                    target = mod.classes[p].qname
                elif p in mod.functions:
                    target = mod.functions[p].qname
                elif p in mod.var_types:
                    inner = self.class_of_ctor(target, mod.var_types[p])
                    target = inner if inner else None
                else:
                    return None
            else:
                return None
            if target is None:
                return None
        if target in self.classes:
            return target
        fn = self.functions.get(target)
        if fn is not None and fn.returns_type:
            mod = self.modules.get(fn.module)
            if mod is not None and fn.returns_type in mod.classes:
                return mod.classes[fn.returns_type].qname
        return None

    def classes_defining(self, method: str) -> list[str]:
        return self._classes_by_method.get(method, [])


def _module_name(path: pathlib.Path) -> str:
    """Dotted module name from the filesystem: walk up while
    ``__init__.py`` exists, so any package nesting maps correctly."""
    path = path.resolve()
    parts = [path.stem] if path.name != "__init__.py" else []
    d = path.parent
    while (d / "__init__.py").exists():
        parts.insert(0, d.name)
        d = d.parent
    if not parts:
        parts = [path.parent.name]
    return ".".join(parts)
