"""Exception-flow analysis: raise/propagate dataflow + rules HSL016-018.

PR 5 proved the lock graph cycle-free and PR 6 proved the locksets
consistent; this layer proves the third leg of the serving-plane
contract: **where errors go**. The raw material is the ``RaiseSite`` /
``Guard`` records the single-pass function visitor already collects
(analysis/program.py): every ``raise`` with the raw type text and the
stack of enclosing try/except guards, and every call site with the
guards enclosing it. This module turns those into:

- **An exception hierarchy.** Program-local exception classes
  (``exceptions.py``, ``faults.py``) resolved through the class index
  and grafted onto the builtin exception MRO (``FaultError`` ⊆
  ``OSError`` ⊆ ``Exception``; ``CrashPoint`` ⊆ ``BaseException``
  only — the whole point of a simulated hard crash).
- **Per-function escape sets.** ``E(f)`` = the types f's own raise
  sites can throw past f's handlers, ∪ over call sites the callee's
  escapes minus the types the guards at the site absorb — handler
  subtraction is narrowed by the hierarchy (an ``except OSError``
  absorbs ``FaultError`` but never ``CrashPoint``), and a handler that
  re-raises absorbs nothing. Propagated over the resolved call graph to
  a fixpoint with shortest witness chains, mirroring how effects.py
  propagates locksets. Unresolvable raise expressions (``raise
  rule.error``) become the ``<dynamic>`` pseudo-type: recorded for
  visibility, excluded from contract drift (the engine never invents a
  finding from what it cannot name).
- **HSL016 error-contract drift.** ``exceptions.ERROR_CONTRACTS``
  declares the typed error surface of every public entry point; the
  registry is AST-extracted from any scanned module (so fixture
  packages declare their own). Any statically observed escape not
  covered by the declared contract (modulo hierarchy) is a finding;
  dead contract entries (naming no scanned function) and dead declared
  program-local types (covering no observed escape) are findings too.
  The generated ``docs/errors.md`` table is verified by check.py
  exactly like HSL010 verifies the config-key table.
- **HSL017 swallowed crash/fault.** Except clauses that absorb what
  must never be absorbed: bare ``except:``, a
  ``BaseException``/``CrashPoint`` catch with no re-raise (a dying
  writer handled back to life), an explicit ``FaultError`` catch with
  no re-raise, an ``except Exception: pass`` (the silent-swallow
  shape), and the retry-classification bypass — catching ``OSError``
  inside a retry loop wider than ``is_retryable`` without re-raising
  the non-retryable remainder.
- **HSL018 unwind-safety proof.** Every fault point in
  ``faults.KNOWN_POINTS`` must sit in a function statically reachable
  from a *recovery construct* — ``Action.run``'s rollback handler, a
  ``recover()`` method, or a declared error-contract entry point — so
  an injected crash provably unwinds into code that repairs or
  surfaces it. Error paths must also stay balanced: a ``+= 1`` /
  ``-= 1`` pair on shared state (in-flight gauges, refcounts) whose
  decrement is not in a ``finally`` leaks the count on the first
  exception between the two (the raise-aware extension of HSL011).

Everything here is stdlib-only and never imports analyzed code, same
as the rest of the engine.
"""

from __future__ import annotations

import ast
import builtins
import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.lint import Finding, _dotted
from hyperspace_tpu.analysis.program import FunctionInfo, Guard, Program

CONTRACT_DRIFT = "HSL016"
SWALLOWED = "HSL017"
UNWIND_SAFETY = "HSL018"

#: Pseudo-type for raise expressions the resolver cannot name
#: (``raise rule.error``, ``raise self.error``). Recorded in summaries
#: and witness chains, excluded from contract-drift comparisons.
DYNAMIC = "<dynamic>"

# fn qname -> the one dynamic raise the analysis is allowed to treat as
# a KNOWN type set. Every entry must explain why the dynamic raise has a
# statically known type surface — anything else stays <dynamic>.
DYNAMIC_RAISES: dict[str, tuple[tuple[str, ...], str]] = {
    # _hit re-raises the rule's registered error object/type. inject()
    # defaults it to FaultError and every crash goes through the typed
    # `raise CrashPoint(...)` two lines above; the registered-object
    # form is test-supplied and always a FaultError in the sweep.
    "hyperspace_tpu.faults._hit": (
        ("FaultError",),
        "rule.error defaults to FaultError (faults.inject); crashes use the typed CrashPoint raise",
    ),
    # result() re-raises the exact exception object the worker stored:
    # QueryServer._body catches BaseException around run_query, whose
    # declared surface this mirrors (HyperspaceError ∪ OSError ∪
    # CrashPoint; the programming-error tail surfaces as-is too).
    "hyperspace_tpu.serve.scheduler.QueryHandle.result": (
        ("HyperspaceError", "OSError", "CrashPoint"),
        "re-raises the stored worker error; the worker wraps run_query, whose typed surface this is",
    ),
}


def _suppressed(mod, line: int, rule: str) -> bool:
    lines = mod.lines
    text = lines[line - 1] if 0 < line <= len(lines) else ""
    if "# noqa" not in text:
        return False
    tail = text.split("# noqa", 1)[1]
    return not tail.strip().startswith(":") or rule in tail


def _builtin_exception_mro() -> dict[str, tuple[str, ...]]:
    """Simple name -> exception-MRO simple names, for every builtin
    exception type of the running interpreter (the analyzer runs on the
    same Python the analyzed code does, so e.g. TimeoutError ⊆ OSError
    comes out right without a hand-maintained table)."""
    out: dict[str, tuple[str, ...]] = {}
    for name in dir(builtins):
        obj = getattr(builtins, name, None)
        if isinstance(obj, type) and issubclass(obj, BaseException):
            out[obj.__name__] = tuple(
                c.__name__ for c in obj.__mro__ if issubclass(c, BaseException)
            )
    return out


@dataclasses.dataclass(frozen=True)
class Escape:
    """One entry of a propagated escape set: `chain[0]` can leak
    `etype` raised at `chain[-1]`:`line` (shortest witness)."""

    etype: str
    line: int
    chain: tuple[str, ...]


class Raises:
    """Exception hierarchy + per-function escape sets over a Program."""

    def __init__(self, program: Program, callgraph: CallGraph | None = None):
        self.program = program
        self.callgraph = callgraph or CallGraph(program)
        self._builtin = _builtin_exception_mro()
        #: simple type name -> ancestor simple names (self first)
        self.ancestors: dict[str, tuple[str, ...]] = dict(self._builtin)
        #: simple names of exception classes DEFINED in the program
        self.local_types: set[str] = set()
        #: fn qname -> {etype: Escape} (the fixpoint result)
        self.escapes: dict[str, dict[str, Escape]] = {}
        #: fn qname -> {etype: line} (own raises surviving own handlers)
        self.direct: dict[str, dict[str, int]] = {}
        #: base-method qname -> subclass overrides. The call graph is
        #: deliberately under-approximate (a resolved edge names ONE
        #: callee); exception flow is a may-analysis, so a call resolved
        #: to `Action.op` may raise whatever ANY override raises —
        #: class-hierarchy dispatch, applied here (and in the HSL018
        #: reachability) without touching the lock/race graphs.
        self.overrides: dict[str, tuple[str, ...]] = {}
        self._build_hierarchy()
        self._build_overrides()
        self._build_escapes()

    # -- hierarchy ---------------------------------------------------------
    def _build_hierarchy(self) -> None:
        for qname, cls in self.program.classes.items():
            chain: list[str] = []
            tail: tuple[str, ...] = ()
            for cq in self.program._mro(qname):
                c = self.program.classes.get(cq)
                if c is None:
                    continue
                if c.name not in chain:
                    chain.append(c.name)
                for b in c.bases:
                    tb = b.split(".")[-1]
                    if tb in self._builtin and len(self._builtin[tb]) > len(tail):
                        tail = self._builtin[tb]
            if not tail:
                continue  # not an exception class
            anc = tuple(dict.fromkeys((*chain, *tail)))
            self.ancestors.setdefault(cls.name, anc)
            self.local_types.add(cls.name)

    def _build_overrides(self) -> None:
        out: dict[str, list[str]] = {}
        for d_q, d_cls in self.program.classes.items():
            for anc_q in self.program._mro(d_q)[1:]:
                a_cls = self.program.classes.get(anc_q)
                if a_cls is None:
                    continue
                for m, fn_d in d_cls.methods.items():
                    if m.startswith("__") or m not in a_cls.methods:
                        continue
                    base = a_cls.methods[m].qname
                    if fn_d.qname != base:
                        out.setdefault(base, []).append(fn_d.qname)
        # Structural dispatch through typing.Protocol seams: a call
        # resolved to a Protocol stub (IndexWriter.write) may run any
        # program class that implements EVERY method the protocol
        # declares (the all-methods bar keeps common names like `write`
        # from fanning out to unrelated classes).
        for p_q, p_cls in self.program.classes.items():
            if not p_cls.is_protocol:
                continue
            wanted = {m for m in p_cls.methods if not m.startswith("__")}
            if not wanted:
                continue
            for c_q, c_cls in self.program.classes.items():
                if c_cls.is_protocol or c_q == p_q:
                    continue
                if wanted <= set(c_cls.methods):
                    for m in wanted:
                        out.setdefault(p_cls.methods[m].qname, []).append(
                            c_cls.methods[m].qname
                        )
        self.overrides = {k: tuple(sorted(set(v))) for k, v in out.items()}

    def dispatch_targets(self, callee: str) -> tuple[str, ...]:
        """The resolved callee plus every override that may actually run."""
        return (callee, *self.overrides.get(callee, ()))

    def canonical(self, module: str, raw: str) -> str | None:
        """The simple exception-class name `raw` denotes inside
        `module`, or None when it resolves to nothing the hierarchy
        knows (a third-party type, a variable)."""
        parts = raw.split(".")
        prog = self.program
        target = prog.resolve_symbol(module, parts[0])
        if target is not None:
            node = target
            for p in parts[1:]:
                if node in prog.modules and p in prog.modules[node].classes:
                    node = prog.modules[node].classes[p].qname
                elif node in prog.modules and f"{node}.{p}" in prog.modules:
                    node = f"{node}.{p}"
                else:
                    node = ""
                    break
            if node in prog.classes:
                name = prog.classes[node].name
                return name if name in self.ancestors else None
            # An exception FACTORY: `raise _corruption(...)` where the
            # function's return annotation names an exception class.
            fn2 = prog.functions.get(node or "")
            if fn2 is not None and fn2.returns_type in self.ancestors:
                return fn2.returns_type
        tail = parts[-1]
        return tail if tail in self.ancestors else None

    def covers(self, declared: str, etype: str) -> bool:
        """True when an escape of `etype` is within a contract entry (or
        handler) declaring `declared` — i.e. etype ⊆ declared."""
        return declared in self.ancestors.get(etype, (etype,))

    # -- escape computation ------------------------------------------------
    def _survives(self, module: str, etype: str, guards: tuple[Guard, ...]) -> bool:
        """True when an exception of `etype` raised under `guards`
        escapes the enclosing try statements: no non-re-raising handler
        catches it (bare ``except:`` catches everything; a typed
        handler catches subclasses only)."""
        anc = set(self.ancestors.get(etype, ()))
        for g in guards:
            for types, reraises in g.handlers:
                if reraises:
                    continue
                if not types:
                    return False
                for h_raw in types:
                    h = self.canonical(module, h_raw)
                    if h == "BaseException":
                        return False  # absorbs everything, <dynamic> included
                    if h is not None and h in anc:
                        return False
        return True

    def _direct_escapes(self, fn: FunctionInfo) -> dict[str, int]:
        out: dict[str, int] = {}
        for rs in fn.raises:
            # Bare re-raises (and `raise e` of a handler-bound name) are
            # pass-throughs: modeled by guard NON-subtraction, never as
            # a fresh raise of the handler's (wider) caught type.
            if rs.raw is None or rs.handler_types is not None:
                continue
            etype = self.canonical(fn.module, rs.raw) or DYNAMIC
            if etype == DYNAMIC and fn.qname in DYNAMIC_RAISES:
                for t in DYNAMIC_RAISES[fn.qname][0]:
                    if self._survives(fn.module, t, rs.guards):
                        out.setdefault(t, rs.line)
                continue
            if self._survives(fn.module, etype, rs.guards):
                out.setdefault(etype, rs.line)
        return out

    def _build_escapes(self) -> None:
        prog, cg = self.program, self.callgraph
        esc: dict[str, dict[str, Escape]] = {}
        for q, fn in prog.functions.items():
            self.direct[q] = self._direct_escapes(fn)
            esc[q] = {
                t: Escape(t, line, (q,)) for t, line in self.direct[q].items()
            }
        changed = True
        while changed:
            changed = False
            for fn in prog.functions.values():
                mine = esc[fn.qname]
                for call in fn.calls:
                    callee = cg.resolve_call(fn, call.raw)
                    if callee is None or callee == fn.qname:
                        continue
                    for target in self.dispatch_targets(callee):
                        for e in list(esc.get(target, {}).values()):
                            if not self._survives(fn.module, e.etype, call.guards):
                                continue
                            chain = (fn.qname, *e.chain)
                            cur = mine.get(e.etype)
                            if cur is None or len(chain) < len(cur.chain):
                                mine[e.etype] = Escape(e.etype, e.line, chain)
                                changed = True
        self.escapes = esc

    # -- report ------------------------------------------------------------
    def to_json(self) -> dict:
        """Stable JSON form (raisedemo golden, --format json report):
        per function the direct raises and the propagated escape set
        with witness chains, plus the program-local exception hierarchy
        (builtins excluded — their MRO belongs to the interpreter, not
        the golden)."""
        per_fn: dict[str, dict] = {}
        for q in sorted(self.program.functions):
            direct = self.direct.get(q, {})
            esc = self.escapes.get(q, {})
            if not direct and not esc:
                continue
            per_fn[q] = {
                "raises": {t: direct[t] for t in sorted(direct)},
                "escapes": {
                    t: list(esc[t].chain) for t in sorted(esc)
                },
            }
        return {
            "functions": per_fn,
            "exceptions": {
                name: list(self.ancestors[name])
                for name in sorted(self.local_types)
            },
        }


# -- ERROR_CONTRACTS extraction ------------------------------------------------

def declared_contracts(program: Program) -> dict[str, tuple[tuple[str, ...], str, int]]:
    """qname -> (declared types, declaring path, line), AST-extracted
    from every scanned module's ``ERROR_CONTRACTS`` dict literal (the
    real registry lives in exceptions.py; fixture packages and corpus
    files declare their own the same way)."""
    out: dict[str, tuple[tuple[str, ...], str, int]] = {}
    for mod in program.modules.values():
        # Module-level tuple-of-string constants (the shared-surface
        # spelling: `_QUERY_SURFACE = (...)` referenced by name below).
        str_tuples: dict[str, tuple[str, ...]] = {}
        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                vals = [
                    e.value for e in node.value.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                if len(vals) == len(node.value.elts):
                    str_tuples[node.targets[0].id] = tuple(vals)
        for node in mod.tree.body:
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target, value = node.target, node.value
            if not (isinstance(target, ast.Name) and target.id == "ERROR_CONTRACTS"):
                continue
            if not isinstance(value, ast.Dict):
                continue
            for k, v in zip(value.keys, value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                if isinstance(v, ast.Name) and v.id in str_tuples:
                    types = str_tuples[v.id]
                else:
                    types = tuple(
                        e.value
                        for e in (v.elts if isinstance(v, (ast.Tuple, ast.List, ast.Set)) else [])
                        if isinstance(e, ast.Constant) and isinstance(e.value, str)
                    )
                out[k.value] = (types, mod.path, k.lineno or node.lineno)
    return out


# -- HSL016: error-contract drift ---------------------------------------------

def error_contract_findings(
    program: Program,
    raises: Raises,
    contracts: dict | None = None,
) -> list[Finding]:
    contracts = declared_contracts(program) if contracts is None else contracts
    findings: list[Finding] = []
    for qname, (types, decl_path, decl_line) in sorted(contracts.items()):
        fn = program.functions.get(qname)
        decl_mod = next(
            (m for m in program.modules.values() if m.path == decl_path), None
        )
        suppressed = decl_mod is not None and _suppressed(decl_mod, decl_line, CONTRACT_DRIFT)
        if fn is None:
            in_scope = any(qname.startswith(m + ".") for m in program.modules)
            if in_scope and not suppressed:
                findings.append(Finding(
                    decl_path, decl_line, 0, CONTRACT_DRIFT,
                    f"dead contract entry: {qname!r} names no function in the "
                    f"analyzed program — the declared error surface covers "
                    f"nothing (fix the qname or delete the entry)",
                ))
            continue
        for d in types:
            if d not in raises.ancestors and not suppressed:
                findings.append(Finding(
                    decl_path, decl_line, 0, CONTRACT_DRIFT,
                    f"contract for {qname} declares unknown exception type "
                    f"{d!r} — neither a builtin exception nor a class the "
                    f"program defines (typo?)",
                ))
        mod = program.modules.get(fn.module)
        esc = raises.escapes.get(qname, {})
        for t in sorted(esc):
            if t == DYNAMIC:
                continue
            if any(raises.covers(d, t) for d in types):
                continue
            e = esc[t]
            if mod is not None and _suppressed(mod, fn.line, CONTRACT_DRIFT):
                continue
            if suppressed:
                continue
            witness = tuple(dict.fromkeys(
                program.modules[program.functions[q].module].path
                for q in e.chain
                if q in program.functions
                and program.functions[q].module in program.modules
            ))
            findings.append(Finding(
                mod.path if mod is not None else fn.module, fn.line, 0,
                CONTRACT_DRIFT,
                f"error-contract drift on {qname}: {t} escapes (witness: "
                f"{' -> '.join(e.chain)} raises it at line {e.line}) but the "
                f"declared contract only covers {list(types)} — declare {t} "
                f"(or a superclass) in exceptions.ERROR_CONTRACTS, or handle "
                f"it inside",
                witness_paths=witness,
            ))
        # Dead declared types: a program-local exception the analysis can
        # see every raise site of, declared but covering no observed
        # escape. Builtins are exempt — they arrive through stdlib calls
        # the under-approximate propagation cannot see.
        observed = [t for t in esc if t != DYNAMIC]
        for d in types:
            if d not in raises.local_types or suppressed:
                continue
            if not any(raises.covers(d, t) for t in observed):
                findings.append(Finding(
                    decl_path, decl_line, 0, CONTRACT_DRIFT,
                    f"contract for {qname} declares {d!r} but no statically "
                    f"observed escape is covered by it — the declared surface "
                    f"is wider than reality; drop it or add the raise path",
                ))
    return findings


# -- HSL017: swallowed crash/fault --------------------------------------------

_CRASH_TYPES = {"BaseException", "CrashPoint"}


def swallowed_findings(program: Program, raises: Raises) -> list[Finding]:
    findings: list[Finding] = []
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules.get(fn.module)
        if mod is None:
            continue
        findings.extend(_scan_handlers(fn, mod, raises))
    return findings


def _scan_handlers(fn: FunctionInfo, mod, raises: Raises) -> list[Finding]:
    findings: list[Finding] = []
    # Retry loops only: `while ...` and `for ... in range(...)` iterate
    # ATTEMPTS of one operation; a `for f in files` loop iterates
    # different work items, and skipping a bad one is not a retry.
    loops = [
        (sub.lineno, getattr(sub, "end_lineno", sub.lineno) or sub.lineno)
        for sub in ast.walk(fn.node)
        if isinstance(sub, ast.While)
        or (
            isinstance(sub, ast.For)
            and isinstance(sub.iter, ast.Call)
            and _dotted(sub.iter.func).split(".")[-1] == "range"
        )
    ]

    def _report(line: int, msg: str, types: set[str] = frozenset()) -> None:
        if _suppressed(mod, line, SWALLOWED):
            return
        # Witness: the module defining each swallowed program-local
        # exception type — `--changed` mode keeps the finding when the
        # type's definition moves, not just when the handler does.
        prog = raises.program
        witness = tuple(dict.fromkeys(
            [mod.path] + [
                prog.modules[c.module].path
                for c in prog.classes.values()
                if c.name in types and c.module in prog.modules
            ]
        ))
        findings.append(
            Finding(mod.path, line, 0, SWALLOWED, msg, witness_paths=witness)
        )

    for sub in ast.walk(fn.node):
        if not isinstance(sub, ast.Try):
            continue
        for h in sub.handlers:
            line = h.lineno
            has_raise = any(isinstance(n, ast.Raise) for n in ast.walk(h))
            body_is_pass = all(isinstance(s, ast.Pass) for s in h.body)
            if h.type is None:
                if not has_raise:
                    _report(
                        line,
                        f"bare `except:` in {fn.qname} swallows EVERYTHING — "
                        f"including CrashPoint (a simulated dying writer) and "
                        f"KeyboardInterrupt; name the exception types, or "
                        f"re-raise",
                    )
                continue
            elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
            canon = {
                raises.canonical(fn.module, _dotted(e)) or _dotted(e).split(".")[-1]
                for e in elts
                if _dotted(e)
            }
            if canon & _CRASH_TYPES and not has_raise:
                which = sorted(canon & _CRASH_TYPES)[0]
                _report(
                    line,
                    f"except {which} in {fn.qname} with no re-raise — a "
                    f"CrashPoint is a BaseException PRECISELY so dying "
                    f"writers get no cleanup (faults.py); handling it here "
                    f"lets a 'dead' process keep running; re-raise it, or "
                    f"`# noqa: HSL017` with the isolation argument",
                    canon,
                )
            elif "FaultError" in canon and not has_raise:
                _report(
                    line,
                    f"except FaultError in {fn.qname} with no re-raise — an "
                    f"injected fault silently absorbed never reaches the "
                    f"retry layer or the crash sweep; let it propagate (or "
                    f"classify via is_retryable and re-raise the rest)",
                    canon,
                )
            elif body_is_pass and "Exception" in canon:
                _report(
                    line,
                    f"`except Exception: pass` in {fn.qname} silently "
                    f"swallows every software failure — record it (counter / "
                    f"trace event / log) or narrow the type; a best-effort "
                    f"path still owes the operator a signal",
                    canon,
                )
            elif (
                "OSError" in canon
                and not has_raise
                and any(a <= line <= b for (a, b) in loops)
                and not _mentions_retryable(h)
                # A handler that returns/breaks EXITS the retry loop and
                # reports the outcome in-band — not a silent re-attempt.
                and not any(
                    isinstance(n, (ast.Return, ast.Break)) for n in ast.walk(h)
                )
            ):
                _report(
                    line,
                    f"retry-classification bypass in {fn.qname}: `except "
                    f"OSError` inside a loop retries NON-retryable errors "
                    f"too (corruption, missing files) — classify with "
                    f"exceptions.is_retryable and re-raise the non-retryable "
                    f"remainder (utils/retry.py does this for you)",
                    canon,
                )
    return findings


def _mentions_retryable(handler: ast.ExceptHandler) -> bool:
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Name) and sub.id == "is_retryable":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "is_retryable":
            return True
    return False


# -- HSL018: unwind-safety proof ----------------------------------------------

def known_fault_points(program: Program) -> tuple[set[str], str | None]:
    """(declared fault points, declaring path) AST-extracted from any
    scanned module with a top-level ``KNOWN_POINTS`` tuple — the real
    ``faults.KNOWN_POINTS`` when the package is scanned, a fixture's or
    corpus file's own when not."""
    points: set[str] = set()
    path = None
    for mod in program.modules.values():
        for node in mod.tree.body:
            if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                continue
            tgt = node.targets[0]
            if not (isinstance(tgt, ast.Name) and tgt.id == "KNOWN_POINTS"):
                continue
            if isinstance(node.value, (ast.Tuple, ast.List)):
                for e in node.value.elts:
                    if isinstance(e, ast.Constant) and isinstance(e.value, str):
                        points.add(e.value)
                path = mod.path
    return points, path


def recovery_roots(program: Program, contracts: dict | None = None) -> dict[str, str]:
    """qname -> why it counts as a recovery construct: a declared
    error-contract entry point (the typed surface), a ``recover()``
    method, or a function whose except handler invokes a rollback."""
    contracts = declared_contracts(program) if contracts is None else contracts
    roots: dict[str, str] = {}
    for q in contracts:
        if q in program.functions:
            roots[q] = "declared error contract"
    for q, fn in program.functions.items():
        if fn.name == "recover":
            roots.setdefault(q, "recover()")
            continue
        for sub in ast.walk(fn.node):
            if not isinstance(sub, ast.Try):
                continue
            for h in sub.handlers:
                for inner in ast.walk(h):
                    if isinstance(inner, ast.Call) and "rollback" in _dotted(inner.func).lower():
                        roots.setdefault(q, "rollback handler")
    return roots


def unwind_findings(
    program: Program,
    callgraph: CallGraph,
    raises: Raises,
    contracts: dict | None = None,
) -> tuple[list[Finding], dict]:
    """(findings, proof). The proof maps every declared fault point to
    one witness chain from a recovery construct down to a function that
    threads it — the static guarantee that an injected FaultError or
    CrashPoint unwinds into rollback/recover()/a declared contract."""
    contracts = declared_contracts(program) if contracts is None else contracts
    points, faults_path = known_fault_points(program)
    findings: list[Finding] = []
    if points:
        roots = recovery_roots(program, contracts)
        # Reachability over the dispatch-augmented graph: a call resolved
        # to a base method (Action.run -> self.op) may run any override,
        # so the proof follows those edges too.
        adj: dict[str, set[str]] = {}
        for e in callgraph.edges:
            slot = adj.setdefault(e.caller, set())
            for t in raises.dispatch_targets(e.callee):
                slot.add(t)
        covered: dict[str, str] = {}  # fn qname -> root that reaches it
        for r in sorted(roots):
            if r in covered:
                continue
            stack = [r]
            covered[r] = r
            while stack:
                q = stack.pop()
                for nxt in adj.get(q, ()):
                    if nxt not in covered:
                        covered[nxt] = r
                        stack.append(nxt)
        sites: dict[str, list[tuple[str, int]]] = {}
        for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
            mod = program.modules.get(fn.module)
            if mod is not None and mod.name.split(".")[-1] == "faults":
                continue  # the harness itself, not a threaded site
            for name, line, kind in fn.fault_refs:
                if kind == "point" and name in points:
                    sites.setdefault(name, []).append((fn.qname, line))
        proof: dict[str, dict] = {}
        for point in sorted(points):
            entry: dict = {"sites": [], "covered": True}
            for fq, line in sites.get(point, []):
                root = covered.get(fq)
                site: dict = {"fn": fq, "line": line}
                if root is None:
                    entry["covered"] = False
                    fn = program.functions[fq]
                    mod = program.modules.get(fn.module)
                    if mod is not None and not _suppressed(mod, line, UNWIND_SAFETY):
                        findings.append(Finding(
                            mod.path, line, 0, UNWIND_SAFETY,
                            f"fault point {point!r} in {fq} has no static "
                            f"propagation path to a recovery construct — no "
                            f"Action.run rollback, recover(), or declared "
                            f"error contract can reach it, so an injected "
                            f"crash here unwinds into nothing that repairs "
                            f"or surfaces it",
                            witness_paths=tuple(dict.fromkeys(
                                p for p in (mod.path, faults_path)
                                if p is not None
                            )),
                        ))
                else:
                    site["via"] = f"{root} ({roots.get(root, '?')})"
                    site["chain"] = _bfs_path(adj, root, fq) or [fq]
                entry["sites"].append(site)
            proof[point] = entry
    else:
        proof = {}
    findings.extend(_balance_findings(program))
    return findings, proof


def _bfs_path(adj: dict[str, set[str]], start: str, target: str) -> list[str] | None:
    """Shortest chain start -> target over the augmented adjacency
    (witness material for the per-point unwind proof)."""
    if start == target:
        return [start]
    prev: dict[str, str] = {}
    seen = {start}
    queue = [start]
    while queue:
        q = queue.pop(0)
        for nxt in sorted(adj.get(q, ())):
            if nxt in seen:
                continue
            prev[nxt] = q
            if nxt == target:
                path = [nxt]
                while path[-1] != start:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            seen.add(nxt)
            queue.append(nxt)
    return None


def _balance_findings(program: Program) -> list[Finding]:
    """The raise-aware balance half of HSL018: ``X += 1`` on shared
    state (an in-flight gauge, a refcount) later ``X -= 1``'d outside
    any ``finally``, with a call between that can raise — the first
    exception skews the count forever."""
    findings: list[Finding] = []
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules.get(fn.module)
        if mod is None:
            continue
        finally_ids: set[int] = set()
        for sub in ast.walk(fn.node):
            if isinstance(sub, ast.Try) and sub.finalbody:
                for stmt in sub.finalbody:
                    for inner in ast.walk(stmt):
                        finally_ids.add(id(inner))
        incs: dict[str, int] = {}
        decs: dict[str, tuple[int, bool]] = {}
        for sub in ast.walk(fn.node):
            if not (
                isinstance(sub, ast.AugAssign)
                and isinstance(sub.value, ast.Constant)
                and sub.value.value == 1
            ):
                continue
            key = _balance_key(sub.target, mod)
            if key is None:
                continue
            if isinstance(sub.op, ast.Add):
                incs.setdefault(key, sub.lineno)
            elif isinstance(sub.op, ast.Sub):
                cur = decs.get(key)
                if cur is None or sub.lineno < cur[0]:
                    decs[key] = (sub.lineno, id(sub) in finally_ids)
        for key, i in sorted(incs.items()):
            dec = decs.get(key)
            if dec is None or dec[1] or dec[0] <= i:
                continue
            j = dec[0]
            has_call_between = any(
                isinstance(c, ast.Call) and i < c.lineno < j
                for c in ast.walk(fn.node)
            )
            if not has_call_between or _suppressed(mod, i, UNWIND_SAFETY):
                continue
            findings.append(Finding(
                mod.path, i, 0, UNWIND_SAFETY,
                f"unbalanced unwind in {fn.qname}: {key} += 1 at line {i} is "
                f"decremented at line {j} outside any finally — an exception "
                f"in between skews the count forever (a stuck in-flight "
                f"gauge / leaked refcount); move the decrement into a "
                f"try/finally around the raising region",
                witness_paths=(mod.path,),
            ))
    return findings


def _balance_key(target: ast.expr, mod) -> str | None:
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return f"self.{target.attr}"
    if isinstance(target, ast.Name) and target.id in mod.shared_globals:
        return target.id
    return None
