"""Pre-execution plan validator.

The Spark reference never validates plans itself — Catalyst's analyzer
rejects malformed trees before any Hyperspace rule sees them. Our IR has
no Catalyst in front of it, so a malformed plan (a typo'd column, a
string compared to a number, two indexes bucketed differently on the
join keys) used to surface as an opaque mid-execution KeyError or XLA
shape error. This pass walks the logical plan BEFORE the executor runs
and reports every problem at once as structured `PlanDiagnostic`s with
node provenance.

Severities:
- **error** — the plan cannot execute correctly (unresolved column,
  dtype-incompatible predicate, unsortable key, string arithmetic).
  `Executor.execute` refuses these up front.
- **warning** — legal but almost certainly a mistake or a silent perf
  cliff (join over two index scans bucketed on the join keys whose
  bucket specs disagree: the executor quietly falls off the
  zero-exchange path and re-shuffles). Surfaced by `validate_plan`;
  `check_plan(fail_on="warning")` promotes them to failures.

`validate_rewrite(original, optimized)` additionally guards the
optimizer: the rewritten plan must resolve, keep the original output
schema, and must not have pushed a filter beneath the null-extended
side of an outer join (which would drop rows that should null-extend).
"""

from __future__ import annotations

import json

from hyperspace_tpu.exceptions import PlanDiagnostic, PlanRewriteError, PlanValidationError
from hyperspace_tpu.plan.expr import (
    And,
    BinOp,
    Case,
    Col,
    DatePart,
    Expr,
    InList,
    IsNull,
    Like,
    Lit,
    MathFn,
    Not,
    Or,
    Substr,
    expr_dtype,
    split_conjuncts,
)
from hyperspace_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    LogicalPlan,
    Project,
    Scan,
    Sort,
    Union,
    Window,
)
from hyperspace_tpu.schema import Schema

_STRINGY = ("string",)
_SORTABLE = ("int32", "int64", "float32", "float64", "bool", "string", "date", "timestamp")


# -- public API --------------------------------------------------------------

def validate_plan(plan: LogicalPlan) -> list[PlanDiagnostic]:
    """All diagnostics for `plan`, most severe first."""
    diags: list[PlanDiagnostic] = []
    _walk(plan, type(plan).__name__, diags)
    diags.sort(key=lambda d: (d.severity != "error", d.path))
    return diags


def check_plan(plan: LogicalPlan, fail_on: str = "error") -> None:
    """Raise `PlanValidationError` if `plan` has diagnostics at or above
    `fail_on` severity ("error" | "warning")."""
    diags = validate_plan(plan)
    bad = [d for d in diags if d.severity == "error" or fail_on == "warning"]
    if bad:
        raise PlanValidationError(bad)


def validate_rewrite(original: LogicalPlan, optimized: LogicalPlan) -> None:
    """Guard an optimizer rewrite: `optimized` must validate error-free,
    keep `original`'s output schema, and must not have introduced a
    filter beneath the null-extended side of an outer join. Raises
    `PlanRewriteError` naming the offending node."""
    diags = [d for d in validate_plan(optimized) if d.severity == "error"]
    if diags:
        raise PlanRewriteError(diags)
    try:
        orig_schema, opt_schema = original.schema, optimized.schema
    except Exception as e:  # schema errors already surfaced above for optimized
        raise PlanRewriteError(
            [PlanDiagnostic("rewrite-schema-change", type(optimized).__name__, "",
                            f"cannot resolve rewritten schema: {e}")]
        )
    if not _schemas_equivalent(orig_schema, opt_schema):
        raise PlanRewriteError(
            [PlanDiagnostic(
                "rewrite-schema-change",
                type(optimized).__name__,
                type(optimized).__name__,
                f"rewrite changed the output schema: "
                f"{[(f.name, f.dtype) for f in orig_schema.fields]} -> "
                f"{[(f.name, f.dtype) for f in opt_schema.fields]}",
            )]
        )
    before = _filters_below_null_extended(original)
    pushed = {
        key: (path, pred)
        for key, (path, pred) in _filters_below_null_extended(optimized).items()
        if key not in before
    }
    if pushed:
        raise PlanRewriteError(
            [
                PlanDiagnostic(
                    "illegal-pushdown",
                    "Filter",
                    path,
                    f"predicate {pred} was pushed beneath the null-extended "
                    f"side of an outer join; rows it drops should null-extend "
                    f"instead",
                )
                for path, pred in pushed.values()
            ]
        )


# -- node walk ---------------------------------------------------------------

def _walk(node: LogicalPlan, path: str, diags: list[PlanDiagnostic]) -> None:
    try:
        _check_node(node, path, diags)
    except Exception as e:
        # A node whose schema cannot even be computed (ambiguous join
        # columns, malformed children) is itself the diagnostic.
        diags.append(PlanDiagnostic(
            "schema-error", type(node).__name__, path, str(e)
        ))
    for edge, child in _edges(node):
        _walk(child, f"{path}/{edge}:{type(child).__name__}", diags)


def _edges(node: LogicalPlan):
    if isinstance(node, Join):
        return [("left", node.left), ("right", node.right)]
    if isinstance(node, Union):
        return [(f"inputs[{i}]", c) for i, c in enumerate(node.inputs)]
    return [("child", c) for c in node.children()]


def _check_node(node: LogicalPlan, path: str, diags: list[PlanDiagnostic]) -> None:
    name = type(node).__name__
    if isinstance(node, Scan):
        _check_scan(node, path, diags)
        return
    if isinstance(node, Filter):
        schema = node.child.schema
        dt = _check_expr(node.predicate, schema, name, path, diags)
        if dt is not None and dt != "bool":
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", name, path,
                f"filter predicate has dtype {dt!r}, expected bool",
            ))
        return
    if isinstance(node, Project):
        schema = node.child.schema
        for c in node.columns:
            if isinstance(c, str):
                if c not in schema:
                    diags.append(PlanDiagnostic(
                        "unresolved-column", name, path,
                        f"projected column {c!r} does not exist in the input "
                        f"schema {schema.names}",
                    ))
            else:
                _check_expr(c[1], schema, name, path, diags, what=f"computed column {c[0]!r}")
        return
    if isinstance(node, Join):
        _check_join(node, path, diags)
        return
    if isinstance(node, Aggregate):
        _check_aggregate(node, path, diags)
        return
    if isinstance(node, Window):
        _check_window(node, path, diags)
        return
    if isinstance(node, Sort):
        schema = node.child.schema
        for c, _asc in node.by:
            _check_sort_key(c, schema, name, path, diags)
        return
    if isinstance(node, Union):
        _check_union(node, path, diags)
        return
    if isinstance(node, Limit):
        if node.n < 0:
            diags.append(PlanDiagnostic(
                "bad-limit", name, path, f"limit must be >= 0, got {node.n}"
            ))
        return
    # Unknown node kinds (internal leaves like the executor's _TableLeaf)
    # have nothing structural to check beyond their children.


def _check_scan(node: Scan, path: str, diags: list[PlanDiagnostic]) -> None:
    if node.bucket_spec is None:
        return
    num_buckets, cols = node.bucket_spec
    if num_buckets < 1:
        diags.append(PlanDiagnostic(
            "bad-bucket-spec", "Scan", path,
            f"bucket count must be >= 1, got {num_buckets}",
        ))
    for c in cols:
        if c not in node.scan_schema:
            diags.append(PlanDiagnostic(
                "unresolved-column", "Scan", path,
                f"bucket column {c!r} does not exist in the scan schema "
                f"{node.scan_schema.names}",
            ))
        elif node.scan_schema.field(c).is_vector:
            diags.append(PlanDiagnostic(
                "bad-bucket-spec", "Scan", path,
                f"bucket column {c!r} has vector dtype; vectors have no "
                f"hash-bucket semantics",
            ))


def _check_join(node: Join, path: str, diags: list[PlanDiagnostic]) -> None:
    ls, rs = node.left.schema, node.right.schema
    ok = True
    for side, keys, schema in (("left", node.left_on, ls), ("right", node.right_on, rs)):
        for k in keys:
            if k not in schema:
                diags.append(PlanDiagnostic(
                    "unresolved-column", "Join", path,
                    f"{side} join key {k!r} does not exist in the {side} "
                    f"schema {schema.names}",
                ))
                ok = False
    if ok:
        for lk, rk in zip(node.left_on, node.right_on):
            lf, rf = ls.field(lk), rs.field(rk)
            if lf.is_vector or rf.is_vector:
                diags.append(PlanDiagnostic(
                    "join-key-type-mismatch", "Join", path,
                    f"join key {lk!r}/{rk!r} has vector dtype; vectors "
                    f"cannot be equi-join keys",
                ))
            elif lf.is_string != rf.is_string:
                diags.append(PlanDiagnostic(
                    "join-key-type-mismatch", "Join", path,
                    f"join keys {lk!r} ({lf.dtype}) and {rk!r} ({rf.dtype}) "
                    f"live in different comparison domains; equal values "
                    f"can never match",
                ))
    if node.condition is not None:
        _check_expr(node.condition, node.match_schema, "Join", path, diags,
                    what="join condition")
    # Null-sentinel consistency: the null-extended side's columns must be
    # null-extendable — vector columns have no null representation on
    # device (execution/exec_common._null_field refuses them at runtime).
    extended = {"left": [("right", rs)], "right": [("left", ls)],
                "full": [("left", ls), ("right", rs)]}.get(node.how, [])
    keysets = {"left": {k.lower() for k in node.left_on},
               "right": {k.lower() for k in node.right_on}}
    for side, schema in extended:
        for f in schema.fields:
            if f.name.lower() in keysets[side]:
                continue  # key columns coalesce across sides, never extended
            if f.is_vector:
                diags.append(PlanDiagnostic(
                    "null-extension-vector", "Join", path,
                    f"{node.how} outer join null-extends {side} column "
                    f"{f.name!r}, but vector columns have no null "
                    f"representation",
                    severity="warning",
                ))
    _check_bucket_alignment(node, path, diags)


def _check_bucket_alignment(node: Join, path: str, diags: list[PlanDiagnostic]) -> None:
    """Both sides bucketed on the join keys is the zero-exchange shape —
    but only when the specs AGREE (same count, same hash dtype domain).
    A disagreement is legal (the executor falls back to a re-shuffle)
    yet almost always a mis-built index pair, so it warns."""
    lscan = _aligned_scan(node.left)
    rscan = _aligned_scan(node.right)
    if lscan is None or rscan is None:
        return
    if not (_keyed_on(lscan, node.left_on) and _keyed_on(rscan, node.right_on)):
        return
    if lscan.bucket_spec[0] != rscan.bucket_spec[0]:
        diags.append(PlanDiagnostic(
            "join-bucket-mismatch", "Join", path,
            f"both sides are index scans bucketed on the join keys but "
            f"with different bucket counts ({lscan.bucket_spec[0]} vs "
            f"{rscan.bucket_spec[0]}); the zero-exchange join path cannot "
            f"apply and the right side will be re-shuffled at query time",
            severity="warning",
        ))
        return
    if _hash_domain(lscan) != _hash_domain(rscan):
        diags.append(PlanDiagnostic(
            "join-bucket-mismatch", "Join", path,
            f"both sides are bucketed on the join keys with equal counts "
            f"but over different hash dtype domains "
            f"({_hash_domain(lscan)} vs {_hash_domain(rscan)}); equal key "
            f"values bucket differently, so the aligned join path cannot "
            f"apply",
            severity="warning",
        ))


def _aligned_scan(plan: LogicalPlan) -> Scan | None:
    """The bucketed Scan beneath a linear Project/Filter chain — the same
    descent the executor's `_aligned_side` performs when deciding the
    zero-exchange path (execution/exec_side.py)."""
    node = plan
    while isinstance(node, (Project, Filter)):
        if isinstance(node, Project) and not node.is_simple:
            return None
        node = node.child
    if isinstance(node, Scan) and node.bucket_spec is not None:
        return node
    return None


def _keyed_on(scan: Scan, join_on: list[str]) -> bool:
    return [c.lower() for c in scan.bucket_spec[1]] == [c.lower() for c in join_on]


def _hash_domain(scan: Scan) -> tuple[str, ...]:
    """The hash dtype domain of a scan's bucket columns (mirrors
    execution/exec_side.JoinSidesMixin._bucket_hash_dtypes: the canonical
    row hash is dtype-sensitive, so equal key VALUES bucket identically
    only when the bucket column dtypes agree)."""
    import numpy as np

    out = []
    for c in scan.bucket_spec[1]:
        f = scan.scan_schema.field(c)
        out.append("string" if f.is_string else str(np.dtype(f.device_dtype)))
    return tuple(out)


def _check_aggregate(node: Aggregate, path: str, diags: list[PlanDiagnostic]) -> None:
    schema = node.child.schema
    for c in node.group_by:
        if c not in schema:
            diags.append(PlanDiagnostic(
                "unresolved-column", "Aggregate", path,
                f"group-by column {c!r} does not exist in the input schema "
                f"{schema.names}",
            ))
        elif schema.field(c).is_vector:
            diags.append(PlanDiagnostic(
                "dtype-incompatible-aggregate", "Aggregate", path,
                f"group-by column {c!r} has vector dtype; vectors have no "
                f"grouping semantics",
            ))
    for a in node.aggs:
        if a.expr is None:
            continue
        dt = _check_expr(a.expr, schema, "Aggregate", path, diags,
                         what=f"aggregate {a.alias!r}")
        if dt in _STRINGY and a.fn in ("sum", "mean"):
            diags.append(PlanDiagnostic(
                "dtype-incompatible-aggregate", "Aggregate", path,
                f"{a.fn}({a.alias}) aggregates a string-typed expression; "
                f"strings cannot be summed or averaged",
            ))


def _check_window(node: Window, path: str, diags: list[PlanDiagnostic]) -> None:
    schema = node.child.schema
    for c in node.partition_by:
        if c not in schema:
            diags.append(PlanDiagnostic(
                "unresolved-column", "Window", path,
                f"partition column {c!r} does not exist in the input schema "
                f"{schema.names}",
            ))
    for c, _asc in node.order_by:
        _check_sort_key(c, schema, "Window", path, diags)
    for f in node.funcs:
        if f.expr is None:
            continue
        dt = _check_expr(f.expr, schema, "Window", path, diags,
                         what=f"window function {f.alias!r}")
        if dt in _STRINGY and f.fn in ("sum", "mean"):
            diags.append(PlanDiagnostic(
                "dtype-incompatible-aggregate", "Window", path,
                f"{f.fn}({f.alias}) aggregates a string-typed expression",
            ))


def _check_sort_key(c: str, schema: Schema, node: str, path: str,
                    diags: list[PlanDiagnostic]) -> None:
    if c not in schema:
        diags.append(PlanDiagnostic(
            "unresolved-column", node, path,
            f"sort key {c!r} does not exist in the input schema {schema.names}",
        ))
        return
    f = schema.field(c)
    if f.dtype not in _SORTABLE:
        diags.append(PlanDiagnostic(
            "unsortable-key", node, path,
            f"sort key {c!r} has dtype {f.dtype!r}, which has no total "
            f"order (sortable: {_SORTABLE})",
        ))


def _check_union(node: Union, path: str, diags: list[PlanDiagnostic]) -> None:
    first = node.inputs[0].schema
    for i, child in enumerate(node.inputs[1:], start=1):
        for lf, rf in zip(first.fields, child.schema.fields):
            if lf.is_string != rf.is_string:
                diags.append(PlanDiagnostic(
                    "union-type-mismatch", "Union", path,
                    f"column {lf.name!r} is {lf.dtype} in inputs[0] but "
                    f"{rf.dtype} in inputs[{i}]; branches cannot concatenate",
                ))


# -- expression checks -------------------------------------------------------

def _check_expr(e: Expr, schema: Schema, node: str, path: str,
                diags: list[PlanDiagnostic], what: str = "expression") -> str | None:
    """Type-check one expression against `schema`. Returns the result
    dtype, or None when resolution failed (diagnostics appended)."""
    missing = sorted(r for r in e.references() if r not in schema)
    if missing:
        for m in missing:
            diags.append(PlanDiagnostic(
                "unresolved-column", node, path,
                f"{what} references column {m!r}, which does not exist in "
                f"the input schema {schema.names}",
            ))
        return None
    vec = sorted(r for r in e.references() if schema.field(r).is_vector)
    if vec:
        diags.append(PlanDiagnostic(
            "dtype-incompatible-predicate", node, path,
            f"{what} references vector column(s) {vec}; vectors cannot "
            f"appear in scalar expressions",
        ))
        return None
    before = len(diags)
    _expr_structure(e, schema, node, path, diags, what)
    if len(diags) > before:
        return None
    try:
        return expr_dtype(e, schema)
    except ValueError as err:
        diags.append(PlanDiagnostic(
            "dtype-incompatible-predicate", node, path, f"{what}: {err}"
        ))
        return None


def _dtype_or_none(e: Expr, schema: Schema) -> str | None:
    try:
        return expr_dtype(e, schema)
    except ValueError:
        return None


def _expr_structure(e: Expr, schema: Schema, node: str, path: str,
                    diags: list[PlanDiagnostic], what: str) -> None:
    """Structural dtype rules `expr_dtype` is too permissive to catch:
    cross-domain comparisons, string arithmetic, LIKE/SUBSTRING over
    non-strings, date-part extraction from non-dates, IN lists whose
    literals live in a different domain than the probe."""
    if isinstance(e, BinOp):
        _expr_structure(e.left, schema, node, path, diags, what)
        _expr_structure(e.right, schema, node, path, diags, what)
        lt, rt = _dtype_or_none(e.left, schema), _dtype_or_none(e.right, schema)
        if lt is None or rt is None:
            return
        if e.op in ("eq", "ne", "lt", "le", "gt", "ge"):
            if (lt in _STRINGY) != (rt in _STRINGY):
                diags.append(PlanDiagnostic(
                    "dtype-incompatible-predicate", node, path,
                    f"{what}: cannot compare {lt} with {rt} — string and "
                    f"numeric values live in different comparison domains",
                ))
        else:  # arithmetic
            if lt in _STRINGY or rt in _STRINGY:
                diags.append(PlanDiagnostic(
                    "dtype-incompatible-predicate", node, path,
                    f"{what}: arithmetic op {e.op!r} is undefined over "
                    f"string operands ({lt} {e.op} {rt})",
                ))
        return
    if isinstance(e, (And, Or)):
        for side in (e.left, e.right):
            _expr_structure(side, schema, node, path, diags, what)
            dt = _dtype_or_none(side, schema)
            if dt is not None and dt != "bool":
                diags.append(PlanDiagnostic(
                    "dtype-incompatible-predicate", node, path,
                    f"{what}: AND/OR operand has dtype {dt!r}, expected bool",
                ))
        return
    if isinstance(e, Not):
        _expr_structure(e.child, schema, node, path, diags, what)
        dt = _dtype_or_none(e.child, schema)
        if dt is not None and dt != "bool":
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: NOT operand has dtype {dt!r}, expected bool",
            ))
        return
    if isinstance(e, Like):
        _expr_structure(e.child, schema, node, path, diags, what)
        dt = _dtype_or_none(e.child, schema)
        if dt is not None and dt not in _STRINGY:
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: LIKE applies to string columns, got {dt!r}",
            ))
        return
    if isinstance(e, Substr):
        _expr_structure(e.child, schema, node, path, diags, what)
        dt = _dtype_or_none(e.child, schema)
        if dt is not None and dt not in _STRINGY:
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: SUBSTRING applies to string columns, got {dt!r}",
            ))
        return
    if isinstance(e, DatePart):
        _expr_structure(e.child, schema, node, path, diags, what)
        dt = _dtype_or_none(e.child, schema)
        if dt is not None and dt != "date":
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: {e.part}() extracts from date columns, got {dt!r}",
            ))
        return
    if isinstance(e, InList):
        _expr_structure(e.child, schema, node, path, diags, what)
        dt = _dtype_or_none(e.child, schema)
        if dt is None:
            return
        str_vals = [v for v in e.values if isinstance(v, str)]
        if dt in _STRINGY and len(str_vals) != len(e.values):
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: IN list over a string column contains non-string "
                f"literals {[v for v in e.values if not isinstance(v, str)]}",
            ))
        elif dt not in _STRINGY and str_vals:
            diags.append(PlanDiagnostic(
                "dtype-incompatible-predicate", node, path,
                f"{what}: IN list over a {dt} column contains string "
                f"literals {str_vals}",
            ))
        return
    if isinstance(e, Case):
        for cond, val in e.branches:
            _expr_structure(cond, schema, node, path, diags, what)
            _expr_structure(val, schema, node, path, diags, what)
            dt = _dtype_or_none(cond, schema)
            if dt is not None and dt != "bool":
                diags.append(PlanDiagnostic(
                    "dtype-incompatible-predicate", node, path,
                    f"{what}: CASE condition has dtype {dt!r}, expected bool",
                ))
        _expr_structure(e.default, schema, node, path, diags, what)
        return
    if isinstance(e, (IsNull, Not, MathFn)):
        _expr_structure(e.child, schema, node, path, diags, what)
        return
    if isinstance(e, (Col, Lit)):
        return
    # Unknown expression kinds: nothing structural to check.


# -- rewrite guard helpers ---------------------------------------------------

def _schemas_equivalent(a: Schema, b: Schema) -> bool:
    if len(a.fields) != len(b.fields):
        return False
    return all(
        fa.name.lower() == fb.name.lower() and fa.dtype == fb.dtype
        for fa, fb in zip(a.fields, b.fields)
    )


def _filters_below_null_extended(plan: LogicalPlan) -> dict[str, tuple[str, str]]:
    """Conjuncts sitting directly beneath a null-extended outer-join side
    (through linear Project/Filter chains), keyed by canonical predicate
    JSON -> (node path, predicate repr). Used to detect rewrites that
    PUSHED a filter where null-extension semantics forbid it: a conjunct
    present in the optimized tree's map but not the original's was moved
    there by the rewrite."""
    acc: dict[str, tuple[str, str]] = {}
    _collect_null_extended(plan, type(plan).__name__, acc)
    return acc


def _collect_null_extended(plan: LogicalPlan, path: str, acc: dict) -> None:
    if isinstance(plan, Join):
        sides = {"left": [("right", plan.right)], "right": [("left", plan.left)],
                 "full": [("left", plan.left), ("right", plan.right)]}.get(plan.how, [])
        for edge, side in sides:
            node, spath = side, f"{path}/{edge}:{type(side).__name__}"
            while isinstance(node, (Project, Filter)):
                if isinstance(node, Filter):
                    for c in split_conjuncts(node.predicate):
                        key = json.dumps(c.to_json(), sort_keys=True)
                        acc[key] = (spath, repr(c))
                node = node.child
                spath = f"{spath}/child:{type(node).__name__}"
    for edge, child in _edges(plan):
        _collect_null_extended(child, f"{path}/{edge}:{type(child).__name__}", acc)
