"""Race rules over the effect summaries (HSL013 / HSL014 / HSL015).

**HSL013 lockset data race.** For each shared state (class attribute or
module global) the rule infers the *expected guard*: the lock contained
in a strict majority of the state's effective locksets (RacerD-style
guarded-by inference). A state whose every access holds the guard is
consistent; a state with NO dominant lock has no locking discipline to
violate (cross-thread safety there is somebody else's argument — e.g.
``QueryHandle`` synchronizes through an ``Event``). The finding is the
in-between case: a discipline exists and an access breaks it, with at
least one write in play. Reported with a **two-path witness**: the
guarded access (naming the lock and, when the guarantee comes from a
caller, the providing call site) and the conflicting unguarded access.
``__init__``-time writes are exempt (the object is not shared yet);
:data:`RACE_ALLOWLIST` + ``# noqa: HSL013`` cover deliberately
unguarded state.

**HSL014 atomicity violation.** A value read under a lock, the lock
released, then the same state written under the SAME lock where the
write (or the branch guarding it) depends on the stale read — torn
check-then-act. Two shapes are deliberately exempt because they
revalidate or converge: the *memo-fill* idiom (keyed read → keyed
insert: worst case is duplicate idempotent work, the pattern every
cache in this codebase uses) and the *re-check* idiom (the second
region re-reads the state before writing — double-checked locking).
The call-chain form is covered through the propagated summaries: a
post-region call whose callee writes the state back under the lock.

**HSL015 jit-cache hygiene.** ``jax.jit`` caches on the identity of the
jitted callable and the values of static args; every distinct key
compiles a NEW executable whose LLVM code mappings live as long as the
jit cache. A call site that manufactures a fresh key per call — a
lambda/``functools.partial``/locally-defined closure jitted inside a
function body, or an f-string flowing into a jitted call — is a
recompile storm that leaks executables until mmap exhaustion (the
XLA:CPU map-count segfault ``utils/jit_memory.py`` mitigates at
runtime; this rule removes the cause statically). Factories whose
enclosing function is ``functools.lru_cache``-decorated, and jitted
callables stored into a memo container (``CACHE[key] = jit(fn)``), are
the sanctioned bounded patterns and exempt.
"""

from __future__ import annotations

import ast
import dataclasses

from hyperspace_tpu.analysis.callgraph import CallGraph
from hyperspace_tpu.analysis.effects import Effects, ResolvedAccess
from hyperspace_tpu.analysis.lint import Finding, _dotted
from hyperspace_tpu.analysis.program import FunctionInfo, LockRef, Program

LOCKSET_RACE = "HSL013"
ATOMICITY = "HSL014"
JIT_HYGIENE = "HSL015"

# state id -> justification. Deliberately unguarded shared state: every
# entry must explain why the inconsistent lockset is correct BY DESIGN
# (init-only publication, benign last-writer-wins config, double-checked
# monotonic publish) — anything else gets a lock, not a listing.
RACE_ALLOWLIST: dict[str, str] = {
    # Lazy singleton with the classic double-checked shape: the bare
    # read is the lock-free hot path, losers re-check under _pool_lock,
    # and the name is never reassigned after publication.
    "hyperspace_tpu.parallel.x64._pool":
        "double-checked lazy singleton; monotonic publish under _pool_lock",
}

_MEMO_DECORATORS = {"lru_cache", "cache", "cached_property"}


def _suppressed(mod, line: int, rule: str) -> bool:
    lines = mod.lines
    text = lines[line - 1] if 0 < line <= len(lines) else ""
    if "# noqa" not in text:
        return False
    tail = text.split("# noqa", 1)[1]
    return not tail.strip().startswith(":") or rule in tail


# -- HSL013: lockset data races -----------------------------------------------

def lockset_race_findings(
    program: Program,
    effects: Effects,
    allowlist: dict[str, str] | None = None,
) -> list[Finding]:
    allowlist = RACE_ALLOWLIST if allowlist is None else allowlist
    findings: list[Finding] = []
    for state in sorted(effects.by_state):
        if state in allowlist:
            continue
        accesses = [
            a for a in effects.by_state[state]
            if not a.in_init and not _access_suppressed(program, a, LOCKSET_RACE)
        ]
        if len(accesses) < 2 or not any(a.write for a in accesses):
            continue
        guard = _inferred_guard(accesses)
        if guard is None:
            continue
        unguarded = [a for a in accesses if guard not in a.locks]
        if not unguarded:
            continue
        guarded = [a for a in accesses if guard in a.locks]
        pair = _conflict_pair(unguarded, guarded)
        if pair is None:
            continue
        bare, locked = pair
        findings.append(Finding(
            _path_of(program, bare.fn), bare.line, 0, LOCKSET_RACE,
            witness_paths=(_path_of(program, locked.fn),),
            message=f"lockset race on {state}: inferred guard {guard} (held at "
            f"{len(guarded)}/{len(accesses)} accesses) — "
            f"path 1: {_describe(effects, locked)}; "
            f"path 2: {_describe(effects, bare)} — two threads interleaving "
            f"these paths tear the state; hold {guard} at every access (or "
            f"annotate `# noqa: HSL013` / RACE_ALLOWLIST for init-only "
            f"publication)",
        ))
    return findings


def _inferred_guard(accesses: list[ResolvedAccess]) -> str | None:
    """The lock held at a strict majority of accesses (the guarded-by
    inference); None when every access holds it (consistent) or no lock
    dominates (no discipline to violate)."""
    counts: dict[str, int] = {}
    for a in accesses:
        for lock in a.locks:
            counts[lock] = counts.get(lock, 0) + 1
    if not counts:
        return None
    guard = max(sorted(counts), key=lambda k: counts[k])
    n = counts[guard]
    if n == len(accesses) or n * 2 <= len(accesses):
        return None
    return guard


def _conflict_pair(unguarded, guarded):
    """(unguarded, guarded) witness pair with at least one write —
    prefer the pair that shows a write on the unguarded side."""
    bare_w = [a for a in unguarded if a.write]
    lock_w = [a for a in guarded if a.write]
    if bare_w:
        return bare_w[0], (lock_w[0] if lock_w else guarded[0])
    if lock_w:
        return unguarded[0], lock_w[0]
    return None


def _describe(effects: Effects, a: ResolvedAccess) -> str:
    what = "write" if a.write else "read"
    if not a.locks:
        return f"{what} at {a.fn}:{a.line} holding no lock"
    vias = []
    for lock in sorted(a.locks):
        if lock in a.lexical:
            vias.append(lock)
        else:
            provider = effects.entry_provider.get(a.fn, {}).get(lock)
            vias.append(f"{lock} (guaranteed by caller {provider})" if provider else lock)
    return f"{what} at {a.fn}:{a.line} holding {', '.join(vias)}"


def _access_suppressed(program: Program, a: ResolvedAccess, rule: str) -> bool:
    fn = program.functions.get(a.fn)
    mod = program.modules.get(fn.module) if fn is not None else None
    return mod is not None and _suppressed(mod, a.line, rule)


def _path_of(program: Program, fn_qname: str) -> str:
    fn = program.functions.get(fn_qname)
    if fn is None:
        return "<unknown>"
    mod = program.modules.get(fn.module)
    return mod.path if mod is not None else fn.module


# -- HSL014: torn check-then-act ----------------------------------------------

@dataclasses.dataclass
class _Region:
    """One ``with <lock>`` region in a function: the states it reads and
    writes, and the local names it binds from reads of each state."""

    lock: str
    node: ast.With
    start: int
    end: int
    binds: dict[str, str] = dataclasses.field(default_factory=dict)  # name -> state
    keyed_binds: set[str] = dataclasses.field(default_factory=set)
    reads: dict[str, int] = dataclasses.field(default_factory=dict)  # state -> first line
    # (state, line, keyed, value_names)
    writes: list[tuple[str, int, bool, frozenset[str]]] = dataclasses.field(default_factory=list)


def atomicity_findings(program: Program, effects: Effects) -> list[Finding]:
    findings: list[Finding] = []
    for fn in sorted(program.functions.values(), key=lambda f: (f.module, f.line)):
        mod = program.modules.get(fn.module)
        if mod is None:
            continue
        findings.extend(_scan_atomicity(fn, mod, program, effects))
    return findings


def _scan_atomicity(fn: FunctionInfo, mod, program: Program, effects: Effects) -> list[Finding]:
    regions = _lock_regions(fn, program, effects)
    if not regions:
        return []
    findings: list[Finding] = []
    guards = _guard_tests(fn.node)
    assigns = _name_assign_lines(fn.node)
    for i, ri in enumerate(regions):
        for name, state in ri.binds.items():
            for rj in regions[i + 1:]:
                if rj.lock != ri.lock or rj.start <= ri.end:
                    continue
                f = _torn_pair(fn, mod, ri, rj, name, state, guards, assigns)
                if f is not None:
                    findings.append(f)
            f = _torn_call(fn, mod, ri, name, state, guards, assigns, effects)
            if f is not None:
                findings.append(f)
    return findings


def _torn_pair(fn, mod, ri: _Region, rj: _Region, name: str, state: str,
               guards, assigns) -> Finding | None:
    """A write to `state` in region `rj` that depends on the value bound
    to `name` from region `ri`'s read — unless revalidated."""
    if _killed(assigns, name, ri.end, rj.start):
        return None
    for w_state, w_line, w_keyed, w_names in rj.writes:
        if w_state != state:
            continue
        depends = name in w_names
        decided = any(
            start <= rj.start and end >= rj.end and name in names
            for (start, end, names) in guards
        )
        if not depends and not decided:
            continue
        # memo-fill: keyed read then keyed insert — duplicate idempotent
        # work at worst, the sanctioned cache idiom.
        if w_keyed and name in ri.keyed_binds and not depends:
            continue
        # re-check: region j re-reads the state before writing
        # (double-checked locking) — the decision is revalidated.
        if state in rj.reads and rj.reads[state] <= w_line:
            continue
        if _suppressed(mod, w_line, ATOMICITY):
            return None
        return Finding(
            mod.path, w_line, 0, ATOMICITY,
            f"torn check-then-act on {state}: {name!r} read under "
            f"{ri.lock} at {fn.qname}:{ri.start}, lock released, then "
            f"written back under the re-acquired lock at line {w_line} "
            f"{'using the stale value' if depends else 'behind a decision on the stale value'}"
            f" — another thread can update {state} between the two "
            f"critical sections; widen the lock to cover both, or "
            f"re-validate inside the second",
        )
    return None


def _torn_call(fn, mod, ri: _Region, name: str, state: str, guards, assigns,
               effects: Effects) -> Finding | None:
    """The call-chain form: after region `ri`, a call guarded by a
    decision on the stale read whose callee writes `state` back under
    the same lock."""
    for call in fn.calls:
        if call.line <= ri.end or _killed(assigns, name, ri.end, call.line):
            continue
        # A call made while still holding the lock is not torn — the
        # read and the callee's write share one critical section.
        if ri.lock in effects._resolve_held(fn, call.held):
            continue
        decided = any(
            start < call.line <= end and name in names
            for (start, end, names) in guards
        )
        if not decided:
            continue
        callee = effects.callgraph.resolve_call(fn, call.raw)
        if callee is None:
            continue
        for eff in effects.writes_reachable(callee):
            if eff.state == state and ri.lock in eff.locks:
                if _suppressed(mod, call.line, ATOMICITY):
                    return None
                chain = " -> ".join((fn.qname, *eff.chain))
                witness = tuple(dict.fromkeys(
                    _path_of(effects.program, q) for q in (fn.qname, *eff.chain)
                ))
                return Finding(
                    mod.path, call.line, 0, ATOMICITY,
                    witness_paths=witness,
                    message=f"torn check-then-act on {state} across a call chain: "
                    f"{name!r} read under {ri.lock} at {fn.qname}:{ri.start}, "
                    f"lock released, then {chain} re-acquires it and writes "
                    f"{state} behind a decision on the stale value — widen "
                    f"the critical section or re-validate in the callee",
                )
    return None


def _lock_regions(fn: FunctionInfo, program: Program, effects: Effects) -> list[_Region]:
    regions: list[_Region] = []
    for sub in ast.walk(fn.node):
        if not isinstance(sub, (ast.With, ast.AsyncWith)):
            continue
        for item in sub.items:
            ref = _as_lock_ref(item.context_expr, sub.lineno)
            if ref is None:
                continue
            d = program.resolve_lock(ref, fn.module, fn.cls)
            if d is None:
                continue
            region = _Region(
                lock=d.lock_id, node=sub, start=sub.lineno,
                end=getattr(sub, "end_lineno", sub.lineno) or sub.lineno,
            )
            _fill_region(region, sub, fn, effects)
            regions.append(region)
    regions.sort(key=lambda r: r.start)
    return regions


def _as_lock_ref(ctx: ast.expr, line: int) -> LockRef | None:
    if isinstance(ctx, ast.Name):
        return LockRef("name", ctx.id, line)
    if isinstance(ctx, ast.Attribute):
        base = ctx.value
        if isinstance(base, ast.Name) and base.id == "self":
            return LockRef("self", ctx.attr, line)
        return LockRef("attr", ctx.attr, line)
    return None


def _fill_region(region: _Region, with_node: ast.With, fn: FunctionInfo,
                 effects: Effects) -> None:
    # binds: x = self.attr / x = NAME / x = S.get(...) / x = S[k]
    for sub in ast.walk(with_node):
        if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                and isinstance(sub.targets[0], ast.Name):
            tgt = sub.targets[0].id
            src, keyed = _read_source(sub.value)
            if src is not None:
                state = effects.state_of(fn, *src)
                if state is not None:
                    region.binds[tgt] = state
                    if keyed:
                        region.keyed_binds.add(tgt)
    # reads / writes: the recorded accesses that fall inside the region
    start, end = region.start, region.end
    for acc in fn.attr_accesses:
        if not (start <= acc.line <= end):
            continue
        state = effects.state_of(fn, acc.kind, acc.attr)
        if state is None:
            continue
        if acc.write:
            names = _write_value_names(with_node, acc.line)
            region.writes.append((state, acc.line, acc.keyed, names))
        else:
            region.reads.setdefault(state, acc.line)


def _read_source(value: ast.expr) -> tuple[tuple[str, str] | None, bool]:
    """((kind, attr), keyed) when `value` reads shared state into a
    name: ``self.attr`` / ``NAME`` / ``<those>.get(...)`` /
    ``<those>[k]``; (None, False) otherwise."""
    keyed = False
    node = value
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get":
        node = node.func.value
        keyed = True
    elif isinstance(node, ast.Subscript):
        node = node.value
        keyed = True
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return ("self", node.attr), keyed
    if isinstance(node, ast.Name):
        return ("global", node.id), keyed
    return None, False


def _write_value_names(scope: ast.AST, line: int) -> frozenset[str]:
    """Names appearing in the RHS of assignment statements on `line`
    inside `scope` (the dependency test for stale-value write-back)."""
    names: set[str] = set()
    for sub in ast.walk(scope):
        if isinstance(sub, (ast.Assign, ast.AugAssign)) and sub.lineno == line:
            value = sub.value
            for inner in ast.walk(value):
                if isinstance(inner, ast.Name):
                    names.add(inner.id)
        elif isinstance(sub, ast.Call) and sub.lineno == line:
            for arg in sub.args:
                for inner in ast.walk(arg):
                    if isinstance(inner, ast.Name):
                        names.add(inner.id)
    return frozenset(names)


def _guard_tests(fn_node: ast.AST) -> list[tuple[int, int, frozenset[str]]]:
    """(start, end, names-in-test) for every if/while in the function —
    the 'decision based on the stale read' test."""
    out = []
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.If, ast.While)):
            names = frozenset(
                n.id for n in ast.walk(sub.test) if isinstance(n, ast.Name)
            )
            if names:
                out.append((
                    sub.lineno,
                    getattr(sub, "end_lineno", sub.lineno) or sub.lineno,
                    names,
                ))
    return out


def _name_assign_lines(fn_node: ast.AST) -> dict[str, list[int]]:
    out: dict[str, list[int]] = {}
    for sub in ast.walk(fn_node):
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            for tgt in targets:
                for inner in ast.walk(tgt):
                    if isinstance(inner, ast.Name):
                        out.setdefault(inner.id, []).append(sub.lineno)
        elif isinstance(sub, ast.For):
            for inner in ast.walk(sub.target):
                if isinstance(inner, ast.Name):
                    out.setdefault(inner.id, []).append(sub.lineno)
    return out


def _killed(assigns: dict[str, list[int]], name: str, after: int, before: int) -> bool:
    """True when `name` is re-bound strictly between the two lines —
    the stale value is gone, so no torn write-back."""
    return any(after < line < before for line in assigns.get(name, []))


# -- HSL015: jit-cache hygiene ------------------------------------------------

def jit_hygiene_findings(program: Program) -> list[Finding]:
    findings: list[Finding] = []
    for mod in sorted(program.modules.values(), key=lambda m: m.name):
        jitted = _module_jitted_names(mod.tree)
        fns = list(mod.functions.values())
        for cls in mod.classes.values():
            fns.extend(cls.methods.values())
        for fn in sorted(fns, key=lambda f: f.line):
            findings.extend(_scan_jit_sites(fn, mod, jitted))
    return findings


def _module_jitted_names(tree: ast.Module) -> set[str]:
    """Function names that are jit-compiled at module level: decorated
    with a jit-family transform, or wrapped via ``X = jax.jit(f)``."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_mentions_jit(d) for d in node.decorator_list):
                out.add(node.name)
        elif isinstance(node, ast.Call) and _is_jit_callee(node.func) and node.args:
            first = node.args[0]
            if isinstance(first, ast.Name):
                out.add(first.id)
    return out


def _mentions_jit(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in ("jit", "pmap"):
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in ("jit", "pmap"):
            return True
    return False


def _is_jit_callee(func: ast.expr) -> bool:
    return _dotted(func).split(".")[-1] in ("jit", "pmap")


def _scan_jit_sites(fn: FunctionInfo, mod, jitted: set[str]) -> list[Finding]:
    node = fn.node
    memoized_fn = any(
        _dotted(d.func if isinstance(d, ast.Call) else d).split(".")[-1] in _MEMO_DECORATORS
        for d in getattr(node, "decorator_list", [])
    )
    local_defs = {
        sub.name for sub in ast.walk(node)
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) and sub is not node
    }
    memo_stored = _memo_stored_names(node)
    findings: list[Finding] = []

    def _report(line: int, msg: str) -> None:
        if not _suppressed(mod, line, JIT_HYGIENE):
            findings.append(Finding(mod.path, line, 0, JIT_HYGIENE, msg))

    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        # fresh callable jitted per call
        if _is_jit_callee(sub.func) and sub.args:
            arg = sub.args[0]
            fresh = None
            if isinstance(arg, ast.Lambda):
                fresh = "a fresh lambda"
            elif isinstance(arg, ast.Call) and _dotted(arg.func).split(".")[-1] == "partial":
                fresh = "a fresh functools.partial"
            elif isinstance(arg, ast.Name) and arg.id in local_defs:
                fresh = f"the per-call closure {arg.id!r}"
            if fresh is not None and not memoized_fn \
                    and not _feeds_memo(node, sub, memo_stored):
                _report(
                    sub.lineno,
                    f"jit of {fresh} inside {fn.qname} — jit caches on "
                    f"callable IDENTITY, so every call compiles a new "
                    f"executable whose code mappings live until the cache "
                    f"dies (recompile storm -> mmap exhaustion, the "
                    f"XLA:CPU map-count segfault); hoist the jitted fn, "
                    f"lru_cache the factory, or memoize the result",
                )
        # per-call string flowing into a jitted call as a (static) arg
        callee_tail = _dotted(sub.func).split(".")[-1]
        if callee_tail in jitted:
            for arg in [*sub.args, *[kw.value for kw in sub.keywords]]:
                if isinstance(arg, ast.JoinedStr):
                    _report(
                        arg.lineno,
                        f"f-string passed to jitted {callee_tail!r} — every "
                        f"distinct string is a distinct static-arg cache key, "
                        f"compiling (and leaking) a new executable per call; "
                        f"pass a stable token or hoist the formatting out of "
                        f"the jitted call",
                    )
    return findings


def _memo_stored_names(fn_node: ast.AST) -> set[str]:
    """Names that are stored into a subscripted container somewhere in
    the function (``CACHE[key] = name`` — the bounded memo pattern)."""
    out: set[str] = set()
    for sub in ast.walk(fn_node):
        if isinstance(sub, ast.Assign):
            for tgt in sub.targets:
                if isinstance(tgt, ast.Subscript) and isinstance(sub.value, ast.Name):
                    out.add(sub.value.id)
    return out


def _feeds_memo(fn_node: ast.AST, jit_call: ast.Call, memo_stored: set[str]) -> bool:
    """True when the jit call's result lands in a memo container:
    ``CACHE[k] = jit(f)`` directly, or ``g = jit(f)`` with ``g`` later
    stored under a key."""
    for sub in ast.walk(fn_node):
        if not isinstance(sub, ast.Assign) or sub.value is not jit_call:
            continue
        for tgt in sub.targets:
            if isinstance(tgt, ast.Subscript):
                return True
            if isinstance(tgt, ast.Name) and tgt.id in memo_stored:
                return True
    return False
